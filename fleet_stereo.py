#!/usr/bin/env python
"""graftfleet CLI — N supervised ``serve_stereo`` instances behind one
router (DESIGN.md "Fleet operations (r20)").

Usage:

    # four instances, shared warm-state dir, fleet port 8080
    python fleet_stereo.py --instances 4 --fleet_port 8080 \
        --cache_dir /var/tmp/raft-cache -- \
        --restore_ckpt ckpt.npz --max_batch 8 --warmup 544x960

Everything after ``--`` is passed verbatim to every instance's
``serve_stereo.py`` launch (the per-instance model/serving recipe); the
flags before it shape the FLEET.  Each instance binds ``--http_port 0``
and hands its port back through the ``RAFT_HTTP_PORT=<n>`` stdout
handshake; clients talk only to the fleet port:

    POST /v1/stereo      — routed to the healthiest instance
                           (headroom-weighted; X-Raft-Session pinned)
    GET  /fleet/healthz  — aggregated fleet health + the router's books
    GET  /fleet/metrics  — raft_fleet_* counters (Prometheus text)

Operations:

- SIGHUP triggers a zero-downtime rolling deploy (relaunch every slot
  on the current recipe — the upgrade path after swapping a checkpoint
  file or env);
- SIGTERM/SIGINT drains every instance under RAFT_DRAIN_GRACE_MS and
  exits 0 (second signal: default disposition, immediate);
- a killed/crashed/hung instance is replaced automatically under
  RAFT_FLEET_RESTART_BUDGET per slot.

Event lines on stdout are single JSON objects (the serve_stereo.py
convention), plus this CLI's own ``RAFT_FLEET_PORT=<n>`` handshake for
supervisors-of-supervisors.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="fleet supervisor for serve_stereo instances",
        epilog="arguments after -- are passed to every instance's "
               "serve_stereo.py")
    parser.add_argument("--instances", type=int, default=None,
                        help="fleet width (default RAFT_FLEET_INSTANCES "
                        "or 2)")
    parser.add_argument("--fleet_port", type=int, default=0,
                        help="fleet ingress port (default 0 = "
                        "ephemeral, reported via RAFT_FLEET_PORT=<n>)")
    parser.add_argument("--fleet_host", default="127.0.0.1",
                        help="fleet ingress bind address (default "
                        "loopback; widen to 0.0.0.0 deliberately)")
    parser.add_argument("--cache_dir", default=None,
                        help="shared RAFT_CACHE_DIR handed to every "
                        "instance (incl. replacements) so the disk-"
                        "spilled exact tier survives instance deaths")
    parser.add_argument("--restart_budget", type=int, default=None,
                        help="per-slot launch retries + replacements "
                        "per generation (default "
                        "RAFT_FLEET_RESTART_BUDGET or 3)")
    parser.add_argument("--probe_ms", type=float, default=None,
                        help="health-probe period, ms (default "
                        "RAFT_FLEET_PROBE_MS or 500)")
    parser.add_argument("--warmup_timeout_ms", type=float, default=None,
                        help="per-launch readiness deadline, ms "
                        "(default RAFT_FLEET_WARMUP_TIMEOUT_MS or "
                        "600 s)")
    parser.add_argument("--drain_grace_ms", type=float, default=None,
                        help="SIGTERM drain grace per retiring "
                        "instance (default RAFT_DRAIN_GRACE_MS or "
                        "10 s; overrun escalates to SIGKILL, counted)")
    # graftheal: the fleet rung of the recovery plane — restart budgets
    # refill on a decay clock so a degraded slot re-enters probation
    # (one handshake-verified relaunch per refill) instead of staying
    # dark until the next deploy.
    parser.add_argument("--restart_refill_ms", type=float, default=None,
                        help="restart-budget decay: one spent charge "
                        "refunds per this interval (default "
                        "RAFT_HEAL_REFILL_MS or 60 s)")
    parser.add_argument("--no_heal", action="store_true",
                        help="disable the recovery plane (RAFT_HEAL=0 "
                        "equivalent): exhausted slots stay degraded "
                        "until the next deploy")
    # graftpod: forwarded to every instance (incl. replacements) so a
    # rolling deploy can widen/narrow the per-instance mesh in one
    # place; equivalent to putting --mesh_data N after --.
    parser.add_argument("--mesh_data", type=int, default=None,
                        help="per-instance data-mesh width: each "
                        "serve_stereo instance shards its device batch "
                        "over this many chips and advertises N-chip "
                        "headroom to the router (default: whatever the "
                        "instance recipe / RAFT_SERVE_MESH_DATA says)")
    return parser


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        fleet_argv, instance_args = argv[:split], argv[split + 1:]
    else:
        fleet_argv, instance_args = argv, []
    args = build_parser().parse_args(fleet_argv)
    if args.mesh_data is not None:
        instance_args = instance_args + ["--mesh_data",
                                         str(args.mesh_data)]

    from raft_stereo_tpu.serve.fleet import (FleetConfig, FleetFrontend,
                                             FleetSupervisor)

    supervisor = FleetSupervisor(FleetConfig(
        instances=args.instances,
        restart_budget=args.restart_budget,
        probe_ms=args.probe_ms,
        warmup_timeout_ms=args.warmup_timeout_ms,
        drain_grace_ms=args.drain_grace_ms,
        heal=False if args.no_heal else None,
        restart_refill_ms=args.restart_refill_ms,
        cache_dir=args.cache_dir,
        instance_args=tuple(instance_args)))

    stop_requested = threading.Event()
    roll_requested = threading.Event()

    def _request_stop(signum, frame):  # noqa: ARG001 — signal signature
        if stop_requested.is_set():
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        stop_requested.set()

    def _request_roll(signum, frame):  # noqa: ARG001 — signal signature
        roll_requested.set()

    prev = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev[sig] = signal.signal(sig, _request_stop)
        except ValueError:
            pass
    try:
        signal.signal(signal.SIGHUP, _request_roll)
    except (ValueError, AttributeError):
        pass

    print(json.dumps({"event": "fleet_starting",
                      "instances": supervisor.n,
                      "instance_args": instance_args}), flush=True)
    supervisor.start()
    frontend = FleetFrontend(supervisor, host=args.fleet_host,
                             port=args.fleet_port).start()
    try:
        print(json.dumps({
            "event": "fleet_listening",
            "endpoint": f"http://{frontend.host}:{frontend.port}",
            "routes": ["POST /v1/stereo", "GET /fleet/healthz",
                       "GET /fleet/metrics"],
            "ready": int(supervisor.registry.value("raft_fleet_ready")),
        }), flush=True)
        print(f"RAFT_FLEET_PORT={frontend.port}", flush=True)
        while not stop_requested.wait(0.2):
            if roll_requested.is_set():
                roll_requested.clear()
                print(json.dumps({"event": "rolling_deploy",
                                  "reason": "SIGHUP"}), flush=True)
                report = supervisor.deploy()
                print(json.dumps({"event": "rolled", **report}),
                      flush=True)
        print(json.dumps({"event": "fleet_draining",
                          "reason": "signal received"}), flush=True)
    finally:
        frontend.stop()
        supervisor.stop()
        for sig, handler in prev.items():
            signal.signal(sig, handler)
    print(json.dumps({"event": "fleet_stopped",
                      "status": supervisor.status()}, default=str),
          flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

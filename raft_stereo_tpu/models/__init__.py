"""Model layer: functional modules over explicit param pytrees.

Design stance (SURVEY.md §7): the model is a pure function
``(params, image1, image2) -> predictions``; parameters live in a plain nested
dict pytree (trivially shardable, checkpointable, and transplantable from the
reference's torch state_dict); the GRU refinement loop is a ``jax.lax.scan``.
"""

from raft_stereo_tpu.models.raft_stereo import (  # noqa: F401
    init_raft_stereo,
    raft_stereo_epilogue,
    raft_stereo_forward,
    raft_stereo_inference,
    raft_stereo_prepare,
    raft_stereo_segment,
    raft_stereo_segment_carry,
    stack_refinement_states,
    take_refinement_rows,
)

"""Parameterized layer primitives: conv + norms + residual blocks.

Initialization matches the reference: Kaiming-normal (fan_out, relu) conv
weights with torch-default uniform biases (``core/extractor.py:155-162`` — the
reference overrides weights only, so biases keep ``nn.Conv2d``'s default
U(-1/sqrt(fan_in), 1/sqrt(fan_in))); norm scales 1, biases 0.

Params are nested dicts; convs are ``{"w": HWIO, "b": (C,)}``; norms carry
state per ``norm_fn`` ('batch' is permanently frozen — see ops.basic).
"""

from __future__ import annotations

import math
from typing import Dict, Tuple, Union

import jax
import jax.numpy as jnp

from raft_stereo_tpu.ops.basic import (
    conv2d, frozen_batch_norm, group_norm, instance_norm)

Params = Dict


def init_conv(key: jax.Array, kh: int, kw: int, cin: int, cout: int,
              bias: bool = True) -> Params:
    kw_key, b_key = jax.random.split(key)
    fan_out = cout * kh * kw
    std = math.sqrt(2.0 / fan_out)
    p = {"w": std * jax.random.normal(kw_key, (kh, kw, cin, cout), jnp.float32)}
    if bias:
        bound = 1.0 / math.sqrt(cin * kh * kw)
        p["b"] = jax.random.uniform(b_key, (cout,), jnp.float32, -bound, bound)
    return p


def apply_conv(p: Params, x: jax.Array, *, stride: Union[int, Tuple[int, int]] = 1,
               padding: Union[int, Tuple[int, int]] = 0) -> jax.Array:
    return conv2d(x, p["w"], p.get("b"), stride=stride, padding=padding)


def init_norm(norm_fn: str, c: int) -> Params:
    if norm_fn == "batch":
        z, o = jnp.zeros((c,), jnp.float32), jnp.ones((c,), jnp.float32)
        return {"scale": o, "bias": z, "mean": z, "var": o}
    if norm_fn == "group":
        return {"scale": jnp.ones((c,), jnp.float32),
                "bias": jnp.zeros((c,), jnp.float32)}
    # instance / none: stateless
    return {}


def apply_norm(norm_fn: str, p: Params, x: jax.Array, *,
               num_groups: int | None = None) -> jax.Array:
    if norm_fn == "batch":
        return frozen_batch_norm(x, p)
    if norm_fn == "group":
        return group_norm(x, p, num_groups)
    if norm_fn == "instance":
        return instance_norm(x)
    return x  # 'none'


def init_residual_block(key: jax.Array, in_planes: int, planes: int,
                        norm_fn: str, stride: int = 1) -> Params:
    """Reference ``ResidualBlock`` (``core/extractor.py:6-60``)."""
    k1, k2, k3 = jax.random.split(key, 3)
    p = {
        "conv1": init_conv(k1, 3, 3, in_planes, planes),
        "conv2": init_conv(k2, 3, 3, planes, planes),
        "norm1": init_norm(norm_fn, planes),
        "norm2": init_norm(norm_fn, planes),
    }
    if not (stride == 1 and in_planes == planes):
        p["downsample"] = {"conv": init_conv(k3, 1, 1, in_planes, planes),
                           "norm": init_norm(norm_fn, planes)}
    return p


def apply_residual_block(p: Params, x: jax.Array, norm_fn: str,
                         stride: int = 1) -> jax.Array:
    planes = p["conv1"]["w"].shape[-1]
    groups = planes // 8
    y = apply_conv(p["conv1"], x, stride=stride, padding=1)
    y = jax.nn.relu(apply_norm(norm_fn, p["norm1"], y, num_groups=groups))
    y = apply_conv(p["conv2"], y, padding=1)
    y = jax.nn.relu(apply_norm(norm_fn, p["norm2"], y, num_groups=groups))
    if "downsample" in p:
        x = apply_conv(p["downsample"]["conv"], x, stride=stride)
        x = apply_norm(norm_fn, p["downsample"]["norm"], x, num_groups=groups)
    return jax.nn.relu(x + y)


def apply_residual_block_packed(p: Params, xp: jax.Array,
                                norm_fn: str) -> jax.Array:
    """Stride-2 ``ResidualBlock`` whose entry convs read the parity-packed
    (H, W/2, 128) fused-trunk exit in place (``ops/pallas_encoder.py``):
    stride 2 over true columns is stride 1 over packed columns, so the
    interleaving unpack copy never materializes. Matches
    ``apply_residual_block(p, unpack(xp), norm_fn, stride=2)``."""
    from raft_stereo_tpu.ops.pallas_encoder import (
        packed_entry_conv, packed_entry_w1, packed_entry_w3)
    # Stride-2 blocks ALWAYS carry a downsample shortcut (init_residual_block
    # creates one unless stride == 1 and widths match), so its absence means
    # these params came from a stride-1 block — a packed (stride-2-only)
    # apply would silently compute the wrong shortcut; fail with the cause.
    assert "downsample" in p, (
        "apply_residual_block_packed needs stride-2 block params (with a "
        "'downsample' shortcut); got a stride-1 block's params")
    planes = p["conv1"]["w"].shape[-1]
    groups = planes // 8
    y = packed_entry_conv(xp, packed_entry_w3(p["conv1"]["w"]),
                          p["conv1"].get("b"), window_w=2)
    y = jax.nn.relu(apply_norm(norm_fn, p["norm1"], y, num_groups=groups))
    y = apply_conv(p["conv2"], y, padding=1)
    y = jax.nn.relu(apply_norm(norm_fn, p["norm2"], y, num_groups=groups))
    x = packed_entry_conv(xp, packed_entry_w1(p["downsample"]["conv"]["w"]),
                          p["downsample"]["conv"].get("b"), window_w=1)
    x = apply_norm(norm_fn, p["downsample"]["norm"], x, num_groups=groups)
    return jax.nn.relu(x + y)


def init_bottleneck_block(key: jax.Array, in_planes: int, planes: int,
                          norm_fn: str, stride: int = 1) -> Params:
    """Reference ``BottleneckBlock`` (``core/extractor.py:64-120``; unused by
    the stereo configs but part of the reference API surface)."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    p = {
        "conv1": init_conv(k1, 1, 1, in_planes, planes // 4),
        "conv2": init_conv(k2, 3, 3, planes // 4, planes // 4),
        "conv3": init_conv(k3, 1, 1, planes // 4, planes),
        "norm1": init_norm(norm_fn, planes // 4),
        "norm2": init_norm(norm_fn, planes // 4),
        "norm3": init_norm(norm_fn, planes),
    }
    if stride != 1:
        p["downsample"] = {"conv": init_conv(k4, 1, 1, in_planes, planes),
                           "norm": init_norm(norm_fn, planes)}
    return p


def apply_bottleneck_block(p: Params, x: jax.Array, norm_fn: str,
                           stride: int = 1) -> jax.Array:
    planes = p["conv3"]["w"].shape[-1]
    groups = planes // 8
    y = apply_conv(p["conv1"], x)
    y = jax.nn.relu(apply_norm(norm_fn, p["norm1"], y, num_groups=groups))
    y = apply_conv(p["conv2"], y, stride=stride, padding=1)
    y = jax.nn.relu(apply_norm(norm_fn, p["norm2"], y, num_groups=groups))
    y = apply_conv(p["conv3"], y)
    y = jax.nn.relu(apply_norm(norm_fn, p["norm3"], y, num_groups=groups))
    if "downsample" in p:
        x = apply_conv(p["downsample"]["conv"], x, stride=stride)
        x = apply_norm(norm_fn, p["downsample"]["norm"], x, num_groups=groups)
    return jax.nn.relu(x + y)

"""RAFT-Stereo assembly: encoders -> correlation -> scanned GRU refinement.

Reference ``core/raft_stereo.py:22-141``. TPU-first restructuring:

- the iteration loop is a ``jax.lax.scan`` over a pure step function — one
  compiled program regardless of ``iters`` (the reference re-traces a Python
  loop; ``unroll=True`` reproduces that for debugging/parity);
- truncated BPTT is ``lax.stop_gradient`` on the coordinates at the top of each
  iteration (reference ``coords1.detach()``, :109);
- the epipolar projection zeroes the y-component of every delta (:120);
- mixed precision is bf16-compute / fp32-params (no grad scaler needed — bf16
  keeps fp32's exponent range); correlation math stays fp32, mirroring the
  reference's ``.float()`` casts for the non-CUDA paths (:92-95).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.corr import make_corr_fn
from raft_stereo_tpu.models.extractor import (
    apply_basic_encoder, apply_multi_basic_encoder,
    init_basic_encoder, init_multi_basic_encoder)
from raft_stereo_tpu.models.layers import (
    Params, apply_conv, apply_residual_block, init_conv, init_residual_block)
from raft_stereo_tpu.models.update import (
    apply_mask_head, apply_update_block, init_update_block)
from raft_stereo_tpu.ops.coords import coords_grid
from raft_stereo_tpu.ops.upsample import convex_upsample


# Above this many pixels, eval runs the two images through fnet sequentially
# (lax.map) instead of batch-concatenated — see _context_and_features. Module
# constant so tests can exercise the sequential path at small shapes.
FNET_SEQUENTIAL_MIN_PIXELS = 1 << 21


# -- narrow-lane (r24, RAFT_LANE_PACK8) state containers ---------------------
# The iteration-invariant tensors the serving carry re-reads — the
# three-scale post-zqr context and the fmap pair the corr volume rebuilds
# from — ride the state pytree as width-group int8 container dicts
# ``{"pk", "scale"}`` (corr/pallas_reg.py seam) instead of bf16 planes.
# ``net`` deliberately stays bf16: it is MUTATED every iteration, so a
# container would pay quantize+dequantize per step for zero reuse.
# Engagement is inference-only (test-mode forward / prepare / advance;
# the spatial-shard path is excluded) and the test-mode forward
# fake-quantizes through the SAME helpers, so forward == prepare+advance
# stays bitwise by construction.


def _lane_pack_feature(x: jax.Array) -> dict:
    """(B, H, W, C) activation -> {"pk": (B, H, ceil(W/4), C) fp32
    container, "scale": (B, 1, 1, 1) fp32 per-sample dequant scale}."""
    from raft_stereo_tpu.corr.pallas_reg import (feature_scale8,
                                                 quantize_pack_feature8)
    scale = feature_scale8(x)
    return {"pk": quantize_pack_feature8(x, scale), "scale": scale}


def _lane_unpack_feature(packed: dict, width: int, dtype) -> jax.Array:
    """Container dict -> (B, H, width, C) activation in ``dtype``."""
    from raft_stereo_tpu.corr.pallas_reg import unpack_feature8
    return unpack_feature8(packed["pk"], packed["scale"],
                           width).astype(dtype)


def _is_lane_packed(leaf) -> bool:
    """STRUCTURAL packed-container detection — the advance path keys on
    what the carry actually holds, not on the env knob at trace time, so
    a breaker trip or ladder walk that flips RAFT_LANE_PACK8 between
    prepare and advance still dequantizes (or passes through) correctly."""
    return isinstance(leaf, dict) and "pk" in leaf


def _packed_context_level(conv: dict, x: jax.Array, dtype) -> dict:
    """One zqr level as a packed container: the streamed quantize-on-exit
    epilogue (ops/pallas_encoder.py, tentpole b) when the geometry
    supports it, else a host-side pack of the SAME conv producer's output
    — bitwise-identical bytes either way (the epilogue quantizes the
    bf16-rounded rows with the same masked amax scale; pinned in
    tests/test_lane_pack8.py), so the container contract never depends on
    which branch ran."""
    from raft_stereo_tpu.ops.pallas_encoder import (
        head_conv_q8_streamable, head_conv_streamable, stream_head_conv,
        stream_head_conv_q8)
    if head_conv_q8_streamable(conv, x):
        pk, scale = stream_head_conv_q8(conv, x)
        return {"pk": pk, "scale": scale}
    y = (stream_head_conv(conv, x) if head_conv_streamable(conv, x)
         else apply_conv(conv, x, padding=1))
    return _lane_pack_feature(y.astype(dtype))


def init_raft_stereo(key: jax.Array, cfg: RAFTStereoConfig) -> Params:
    """Build the parameter pytree (reference ctor, ``core/raft_stereo.py:23-39``)."""
    ks = jax.random.split(key, 4 + cfg.n_gru_layers)
    params: Params = {
        "cnet": init_multi_basic_encoder(
            ks[0], output_dim=[list(cfg.hidden_dims), list(cfg.context_dims)],
            norm_fn="batch", downsample=cfg.n_downsample),
        "update_block": init_update_block(ks[1], cfg),
        "context_zqr_convs": [
            init_conv(ks[4 + i], 3, 3, cfg.context_dims[i], cfg.hidden_dims[i] * 3)
            for i in range(cfg.n_gru_layers)],
    }
    if cfg.shared_backbone:
        params["conv2"] = {
            "res": init_residual_block(ks[2], 128, 128, "instance", stride=1),
            "conv": init_conv(ks[3], 3, 3, 128, 256)}
    else:
        params["fnet"] = init_basic_encoder(ks[2], output_dim=256,
                                            norm_fn="instance",
                                            downsample=cfg.n_downsample)
    return params


def _context_and_features(params: Params, cfg: RAFTStereoConfig,
                          image1: jax.Array, image2: jax.Array,
                          compute_dtype,
                          fused: bool = True,
                          pack_ctx: bool = False) -> Tuple[list, list, jax.Array, jax.Array]:
    """Run context + feature networks (reference forward :76-88).

    ``pack_ctx`` (RAFT_LANE_PACK8): return each post-zqr context level as
    a packed ``{"pk", "scale"}`` container instead of a (z, r, q) triple —
    the forward and the prepare half both route through this switch, so
    the bytes the serving carry stores are the bytes the forward consumed.
    """
    image1 = (2 * (image1 / 255.0) - 1.0).astype(compute_dtype)
    image2 = (2 * (image2 / 255.0) - 1.0).astype(compute_dtype)

    if cfg.shared_backbone:
        # dual_inp runs both images through one stem by construction, so
        # the sequential-fnet memory treatment below does not apply here;
        # the shared backbone is the realtime (n_downsample=3) config,
        # which never runs at the full-resolution sizes where it matters.
        *cnet_list, x = apply_multi_basic_encoder(
            params["cnet"], jnp.concatenate([image1, image2], axis=0),
            norm_fn="batch", downsample=cfg.n_downsample,
            num_layers=cfg.n_gru_layers, dual_inp=True)
        x = apply_residual_block(params["conv2"]["res"], x, "instance", stride=1)
        x = apply_conv(params["conv2"]["conv"], x, padding=1)
        fmap1, fmap2 = jnp.split(x, 2, axis=0)
    else:
        cnet_list = apply_multi_basic_encoder(
            params["cnet"], image1, norm_fn="batch", downsample=cfg.n_downsample,
            num_layers=cfg.n_gru_layers, fused=fused)
        if image1.shape[1] * image1.shape[2] >= FNET_SEQUENTIAL_MIN_PIXELS:
            # Full-resolution inputs (>=2M px): run the two images through
            # the feature net SEQUENTIALLY (lax.map reuses the stem buffers
            # between steps). The reference's batch-concat (:83) is a GPU
            # throughput trick; at Middlebury-F the stride-1 stem's
            # space-to-depth intermediates are ~1.5 GB per image, and
            # batching both doubles peak HBM for zero win on a
            # latency-bound B=1 eval. Instance norm is per-sample, so the
            # outputs are identical.
            fmaps = lax.map(
                lambda im: apply_basic_encoder(
                    params["fnet"], im, norm_fn="instance",
                    downsample=cfg.n_downsample, fused=fused),
                jnp.stack([image1, image2]))
            fmap1, fmap2 = fmaps[0], fmaps[1]
        else:
            fmaps = apply_basic_encoder(
                params["fnet"], jnp.concatenate([image1, image2], axis=0),
                norm_fn="instance", downsample=cfg.n_downsample)
            fmap1, fmap2 = jnp.split(fmaps, 2, axis=0)

    net_list = [jnp.tanh(x[0]) for x in cnet_list]
    inp_list = [jax.nn.relu(x[1]) for x in cnet_list]
    # GRU gate biases from context, computed once outside the loop (:87-88).
    if pack_ctx:
        inp_list = [
            _packed_context_level(conv, i, compute_dtype)
            for i, conv in zip(inp_list, params["context_zqr_convs"])]
    else:
        inp_list = [
            tuple(jnp.split(apply_conv(conv, i, padding=1), 3, axis=-1))
            for i, conv in zip(inp_list, params["context_zqr_convs"])]
    return net_list, inp_list, fmap1, fmap2


def _refinement_closures(params: Params, cfg: RAFTStereoConfig,
                         net, inp, fmap1: jax.Array, fmap2: jax.Array, *,
                         compute_dtype, test_mode: bool,
                         flow_init: Optional[jax.Array] = None,
                         space_mesh=None):
    """Scan-body machinery shared by the single-scan forward and the
    segmented inference path (:func:`raft_stereo_segment`).

    ``net`` is the hidden-state tuple (used only for kernel fusability
    shape/dtype checks — its values are carried by the caller); ``inp`` is
    the post-zqr context triple list already cast to ``compute_dtype``;
    ``fmap1``/``fmap2`` are the feature maps at 1/``downsample_factor``
    resolution. Builds the correlation lookup, the loop-invariant
    streaming-GRU context, and the ``one_iteration`` / ``upsampled``
    closures — everything that, given a carried ``(net, coords1)``,
    advances the refinement by one step. Returns
    ``(coords0, one_iteration, upsampled, fused_engaged)`` where
    ``fused_engaged`` says whether any streaming kernel context was built
    (the train scan picks its remat policy from it).
    """
    corr_fp32 = cfg.corr_implementation in ("reg", "alt")
    corr_dtype = jnp.float32 if corr_fp32 else compute_dtype
    # out_dtype = compute dtype: the Pallas kernels downcast in-kernel (an
    # external astype on a custom-call output is a separate full-tensor
    # pass), so the scan body consumes corr_fn's output directly.
    # For reg_tpu the volume/container build is exposed as an operand
    # struct: the classic lookup closure AND the r19 resident-iteration
    # kernel (ops/pallas_resident.py) share it, so both paths cost one
    # build and XLA DCEs whichever a given program never calls.
    corr_ops = None
    if cfg.corr_implementation in ("reg_tpu", "reg_cuda"):
        from raft_stereo_tpu.corr.pallas_reg import (build_corr_operands,
                                                     corr_fn_from_operands)
        corr_ops = build_corr_operands(
            fmap1.astype(corr_dtype), fmap2.astype(corr_dtype),
            num_levels=cfg.corr_levels, radius=cfg.corr_radius,
            out_dtype=compute_dtype)
        corr_fn = corr_fn_from_operands(corr_ops)
    else:
        corr_fn = make_corr_fn(
            cfg.corr_implementation,
            fmap1.astype(corr_dtype), fmap2.astype(corr_dtype),
            num_levels=cfg.corr_levels, radius=cfg.corr_radius,
            out_dtype=compute_dtype)

    b, h, w, _ = fmap1.shape
    coords0 = coords_grid(b, h, w)
    factor = cfg.downsample_factor

    # Pre-folded per-level GRU context for the streaming Pallas kernels —
    # loop-invariant, so built ONCE here rather than inside the scan.
    # cfg.fused_update=False (spatially-sharded eval) leaves every entry
    # None, keeping the whole scan body on partitionable XLA ops.
    from raft_stereo_tpu.ops.pallas_stream import (
        gru_is_fusable, prepare_gru_context, spatial_gru_is_fusable)
    # The streaming kernels engage in test mode by default. Training
    # engages them only under cfg.fused_train: r4 measured (batch-6
    # 320x720 crops on the v5e) that the remat'd scan runs each kernel
    # forward twice while the backward still pays the full XLA oracle,
    # and at crop shapes the row streams are too short to amortize —
    # 0.64 -> 0.13 steps/s. fused_train adds a remat policy that saves
    # the kernel outputs (one forward each); see the scan below.
    fuse = cfg.fused_update and (test_mode or cfg.fused_train)
    if space_mesh is not None:
        # Per-shard czrq (halo-exchanged, bias-folded, pre-padded) —
        # hoisted out of the scan exactly like the unsharded entries.
        from raft_stereo_tpu.ops.pallas_stream import (
            spatial_prepare_gru_context)
        ns = space_mesh.shape.get("space", 1)
        fused_ctx = [
            spatial_prepare_gru_context(
                space_mesh,
                params["update_block"][("gru08", "gru16", "gru32")[i]],
                inp[i])
            if (fuse and ns > 1 and spatial_gru_is_fusable(net[i], ns))
            else None
            for i in range(cfg.n_gru_layers)]
    else:
        # Training engagement (fused_train) fuses at any batch size — the
        # B>1 crossover (stream_batch_crossover) is an eval heuristic
        # (see gru_is_fusable).
        any_batch = not test_mode and cfg.fused_train
        # Inference additionally packs the pre-folded czrq into an int8
        # container when RAFT_LANE_PACK8 is armed (prepare_gru_context_any
        # is a pass-through otherwise) — the per-iteration context stream
        # is the largest unnarrowed lane. Train numerics are untouched.
        from raft_stereo_tpu.ops.pallas_stream import prepare_gru_context_any
        ctx_builder = (prepare_gru_context_any if test_mode
                       else prepare_gru_context)
        fused_ctx = [
            ctx_builder(
                params["update_block"][("gru08", "gru16", "gru32")[i]],
                inp[i], compute_dtype)
            if fuse and gru_is_fusable(net[i], any_batch=any_batch) else None
            for i in range(cfg.n_gru_layers)]

    # r19 resident iteration (ops/pallas_resident.py): corr lookup +
    # motion encoder + gru08 + FlowHead in ONE streaming kernel, engaged
    # only in the compute_mask=False test-mode scan body (the serving
    # advance/segment programs and the test-mode forward) — bit-identical
    # to the serial fused composition by construction, so nothing about
    # the segment/epilogue pins moves. Engagement needs the reg_tpu
    # operand struct, the gru08 stream's own fusability (incl. the r19
    # batch crossover) and no caller-supplied flow_init (the fused motion
    # encoder's y==0 weight drop, exactly like fuse_motion below).
    resident_ok = False
    if (test_mode and space_mesh is None and flow_init is None
            and corr_ops is not None and fuse):
        from raft_stereo_tpu.ops.pallas_resident import iter_is_fusable
        resident_ok = (fused_ctx[0] is not None
                       and iter_is_fusable(net[0], corr_ops))

    def one_iteration(net, coords1, compute_mask=True):
        coords1 = lax.stop_gradient(coords1)  # truncated BPTT (:109)
        use_resident = resident_ok and not compute_mask
        flow = (coords1 - coords0).astype(compute_dtype)
        fuse_any_batch = not test_mode and cfg.fused_train
        if cfg.n_gru_layers == 3 and cfg.slow_fast_gru:  # low-res GRU only
            net = apply_update_block(params["update_block"], cfg, net, inp,
                                     iter32=True, iter16=False, iter08=False,
                                     update=False, fused_ctx=fused_ctx,
                                     space_mesh=space_mesh,
                                     fuse_any_batch=fuse_any_batch)
        if cfg.n_gru_layers >= 2 and cfg.slow_fast_gru:  # low+mid-res GRUs
            net = apply_update_block(params["update_block"], cfg, net, inp,
                                     iter32=cfg.n_gru_layers == 3, iter16=True,
                                     iter08=False, update=False,
                                     fused_ctx=fused_ctx,
                                     space_mesh=space_mesh,
                                     fuse_any_batch=fuse_any_batch)
        if use_resident:
            from raft_stereo_tpu.ops.pallas_resident import fused_iter_fwd_impl
            from raft_stereo_tpu.ops.resize import interp_align_corners
            # Coarse GRUs first (the SAME composition apply_update_block's
            # iter32/iter16 section runs — fused_gru1632 co-schedule
            # included), then the resident kernel replaces the serial
            # corr -> motion -> gru08+head chain. Splitting the call is a
            # pure reorganization of the same ops.
            net = apply_update_block(
                params["update_block"], cfg, net, inp,
                iter32=cfg.n_gru_layers == 3, iter16=cfg.n_gru_layers >= 2,
                iter08=False, update=False, fused_ctx=fused_ctx)
            ub = params["update_block"]
            xs2 = ((interp_align_corners(net[1], net[0].shape[1:3]),)
                   if cfg.n_gru_layers > 1 else ())
            net0, delta_x = fused_iter_fwd_impl(
                ub["encoder"], ub["gru08"], ub["flow_head"], corr_ops,
                net[0], fused_ctx[0], coords1[..., 0], flow, *xs2)
            net = (net0,) + tuple(net[1:])
            # The kernel omits conv2.b[0]; adding it here keeps the
            # fused_gru_head contract (models/update.py does the same).
            delta_x = delta_x + ub["flow_head"]["conv2"]["b"][0]
            delta_flow = jnp.concatenate(
                [delta_x, jnp.zeros_like(delta_x)], axis=-1)
            up_mask = None
        else:
            corr = corr_fn(coords1[..., 0])  # compute_dtype (out_dtype)
            # Named so the fused-train remat policy saves the lookup
            # output (its custom_vjp backward needs only the residual
            # coords/volume, never a kernel re-run). No-op outside that
            # policy.
            corr = checkpoint_name(corr, "stream_kernel")
            net, up_mask, delta_flow = apply_update_block(
                params["update_block"], cfg, net, inp, corr, flow,
                iter32=cfg.n_gru_layers == 3, iter16=cfg.n_gru_layers >= 2,
                compute_mask=compute_mask, fused_ctx=fused_ctx,
                fuse_motion=flow_init is None, space_mesh=space_mesh,
                fuse_any_batch=fuse_any_batch)
        # Stereo: project the update onto the epipolar line (:120).
        delta_flow = delta_flow.astype(jnp.float32).at[..., 1].set(0.0)
        coords1 = coords1 + delta_flow
        return net, coords1, up_mask

    def upsampled(coords1, up_mask):
        # Only x (disparity) survives (:134); slicing BEFORE the upsample
        # halves its einsum and write bytes. Identical output: the convex
        # combination is per-channel independent, so dropping y before or
        # after upsampling cannot change channel 0.
        flow_x = (coords1 - coords0)[..., :1].astype(jnp.float32)
        return convex_upsample(flow_x, up_mask.astype(jnp.float32), factor)

    fused_engaged = any(c is not None for c in fused_ctx)
    return coords0, one_iteration, upsampled, fused_engaged


def raft_stereo_forward(params: Params, cfg: RAFTStereoConfig,
                        image1: jax.Array, image2: jax.Array, *,
                        iters: int = 12,
                        flow_init: Optional[jax.Array] = None,
                        test_mode: bool = False,
                        unroll: bool = False,
                        space_mesh=None):
    """Estimate disparity for a rectified stereo pair.

    image1/image2: (B, H, W, 3) in [0, 255].
    Train mode returns per-iteration upsampled predictions
    ``(iters, B, H, W, 1)``; test mode returns ``(low_res_flow, final_up)``
    (reference :126-141). Disparity is ``-flow[..., 0]``.

    ``space_mesh``: the mesh whose ``space`` axis shards image height in
    the enclosing jit. The streaming scan-body kernels then run their
    halo-exchange shard_map variants (the encoder kernels stay XLA —
    their global instance-norm stats and full-H row streams do not cut).
    """
    compute_dtype = jnp.bfloat16 if cfg.mixed_precision else jnp.float32
    from raft_stereo_tpu.corr.pallas_reg import lane_pack8
    pack_ctx = test_mode and space_mesh is None and lane_pack8()
    net_list, inp_list, fmap1, fmap2 = _context_and_features(
        params, cfg, image1, image2, compute_dtype,
        fused=cfg.fused_update and space_mesh is None, pack_ctx=pack_ctx)

    net = tuple(x.astype(compute_dtype) for x in net_list)
    if pack_ctx:
        # Fake-quantize through the SAME containers the prepare half
        # stores: the forward consumes the exact dequantized bytes the
        # segment path will, so forward == prepare+segments stays bitwise
        # under the knob (pinned by tests/test_lane_pack8.py).
        inp = [tuple(jnp.split(
            _lane_unpack_feature(lvl, n.shape[2], compute_dtype),
            3, axis=-1)) for lvl, n in zip(inp_list, net)]
        fmap1 = _lane_unpack_feature(
            _lane_pack_feature(fmap1), fmap1.shape[2], fmap1.dtype)
        fmap2 = _lane_unpack_feature(
            _lane_pack_feature(fmap2), fmap2.shape[2], fmap2.dtype)
    else:
        inp = [tuple(c.astype(compute_dtype) for c in triple)
               for triple in inp_list]
    coords0, one_iteration, upsampled, fused_engaged = _refinement_closures(
        params, cfg, net, inp, fmap1, fmap2, compute_dtype=compute_dtype,
        test_mode=test_mode, flow_init=flow_init, space_mesh=space_mesh)
    coords1 = coords0
    if flow_init is not None:
        coords1 = coords1 + flow_init

    if unroll:  # reference-style Python loop, for debugging and parity checks
        flow_predictions = []
        up_mask = None
        for _ in range(iters):
            net, coords1, up_mask = one_iteration(net, coords1)
            flow_predictions.append(upsampled(coords1, up_mask))
        if test_mode:
            return coords1 - coords0, flow_predictions[-1]
        return jnp.stack(flow_predictions)

    if test_mode:
        # The mask feeds only the upsampler — and test mode upsamples only
        # the final iteration (reference :126-127) — so the mask head runs
        # ONCE after the scan instead of every iteration (the reference
        # computes-and-discards it 31 times; identical outputs here).
        def step(carry, _):
            net, coords1 = carry
            net, coords1, _ = one_iteration(net, coords1, compute_mask=False)
            return (net, coords1), None

        (net, coords1), _ = lax.scan(
            step, (net, coords1), None, length=iters)
        up_mask = apply_mask_head(params["update_block"], net[0])
        return coords1 - coords0, upsampled(coords1, up_mask)

    def step(carry, _):
        net, coords1 = carry
        net, coords1, up_mask = one_iteration(net, coords1)
        return (net, coords1), upsampled(coords1, up_mask)

    # Rematerialize each iteration's internals in the backward pass instead
    # of storing them: without this the scan saves every iteration's GRU /
    # corr / upsample intermediates (~8 GB over the reference's 22-iter
    # batch-6 training config — past a v5e chip's HBM). The reference's
    # truncated BPTT means each step's backward needs only that step's
    # activations, so remat trades ~1/3 extra backward FLOPs for O(1-step)
    # memory. When the streaming kernels are engaged (fused_train), the
    # policy additionally saves their tagged outputs so each kernel
    # forward runs ONCE — remat would otherwise re-run every pallas_call
    # on top of the XLA-oracle backward.
    if fused_engaged:
        ckpt = jax.checkpoint(
            step, policy=jax.checkpoint_policies.save_only_these_names(
                "stream_kernel"))
    else:
        ckpt = jax.checkpoint(step)
    (net, coords1), flow_predictions = lax.scan(
        ckpt, (net, coords1), None, length=iters)
    return flow_predictions


# ---------------------------------------------------------------------------
# Segmented (anytime) inference. RAFT-Stereo's refinement is an anytime
# algorithm — every GRU iteration yields a valid disparity field — and the
# serving layer (raft_stereo_tpu/serve/) exploits that for deadline-aware
# degradation: the scan runs as k host-visible segments of m iterations, the
# wall clock is checked between segments, and the best-so-far upsampled field
# is returned when the budget runs out. The split point is the refinement
# carry ``(net, coords1)``: the segment program below runs the SAME scan body
# as the single-scan test-mode forward, so k segments of m iters compose
# bit-identically to one k*m-iter scan (pinned by tests/test_serve.py).

def raft_stereo_prepare(params: Params, cfg: RAFTStereoConfig,
                        image1: jax.Array, image2: jax.Array, *,
                        flow_init: Optional[jax.Array] = None):
    """Encoder half of test-mode inference: everything outside the GRU scan.

    Runs the context/feature networks and the zqr context convs, and builds
    the initial refinement carry. Returns a dict pytree of arrays only —
    ``net`` (tuple of hidden states), ``inp`` (tuple of context (z, r, q)
    triples), ``fmap1``/``fmap2`` (feature maps the correlation volume is
    rebuilt from), ``coords1`` — so it crosses ``jax.jit`` boundaries and
    feeds :func:`raft_stereo_segment`.

    Warm-start contract (streaming, serve/stream.py): ``flow_init`` seeds
    ``coords1 = coords0 + flow_init``.  The serving ``prepare_warm``
    program constructs ``flow_init`` from an x-only operand with a ZERO y
    channel baked into the program, so the carried flow's y component is
    exactly 0 forever (every iteration's delta-y is zeroed by the
    epipolar projection).  That invariant is what lets warm carries ride
    the SAME compiled advance program as cold ones: ``fuse_motion=False``
    exists only to protect the fused motion encoder from a
    caller-supplied flow_init with nonzero y (models/update.py), which
    the x-only construction rules out.
    """
    compute_dtype = jnp.bfloat16 if cfg.mixed_precision else jnp.float32
    from raft_stereo_tpu.corr.pallas_reg import lane_pack8
    pack_ctx = lane_pack8()
    net_list, inp_list, fmap1, fmap2 = _context_and_features(
        params, cfg, image1, image2, compute_dtype, fused=cfg.fused_update,
        pack_ctx=pack_ctx)
    net = tuple(x.astype(compute_dtype) for x in net_list)
    b, h, w, _ = fmap1.shape
    if pack_ctx:
        # Narrow-lane carry: context levels arrive packed from
        # _context_and_features; the fmap pair packs here. Every leaf
        # keeps its leading batch dim, so stack/take row composition is
        # untouched.
        inp = tuple(inp_list)
        fmap1 = _lane_pack_feature(fmap1)
        fmap2 = _lane_pack_feature(fmap2)
    else:
        inp = tuple(tuple(c.astype(compute_dtype) for c in triple)
                    for triple in inp_list)
    coords1 = coords_grid(b, h, w)
    if flow_init is not None:
        coords1 = coords1 + flow_init
    return {"net": net, "inp": inp, "fmap1": fmap1, "fmap2": fmap2,
            "coords1": coords1}


def _advance_carry(params: Params, cfg: RAFTStereoConfig, state, *,
                   iters: int, warm_start: bool):
    """Shared segment core: run the scan body ``iters`` steps from a carried
    state. Returns ``(new_state, coords0, upsampled)`` — the caller decides
    whether to pay for the mask-head epilogue (:func:`raft_stereo_segment`
    does; the continuous-batching scheduler advances many carries per tick
    and runs :func:`raft_stereo_epilogue` only for the rows that exit)."""
    compute_dtype = jnp.bfloat16 if cfg.mixed_precision else jnp.float32
    net = tuple(state["net"])
    # Narrow-lane carries (RAFT_LANE_PACK8) hold packed containers;
    # dequantize ONCE here, outside the scan. XLA keeps only what the
    # scan body actually streams per iteration: the packed czrq container
    # (prepare_gru_context_any re-packs from these dequantized values —
    # bitwise the same container prepare built, pinned by the segment
    # tests) and the once-per-segment corr-volume build.
    inp = [
        tuple(jnp.split(
            _lane_unpack_feature(lvl, n.shape[2], compute_dtype),
            3, axis=-1))
        if _is_lane_packed(lvl) else tuple(lvl)
        for lvl, n in zip(state["inp"], net)]
    fmap1, fmap2 = state["fmap1"], state["fmap2"]
    if _is_lane_packed(fmap1):
        w8 = state["coords1"].shape[2]
        fmap1 = _lane_unpack_feature(fmap1, w8, compute_dtype)
        fmap2 = _lane_unpack_feature(fmap2, w8, compute_dtype)
    # flow_init only steers the fuse_motion flag here; the carried coords1
    # already contains any warm-start offset.
    fake_init = state["coords1"] if warm_start else None
    coords0, one_iteration, upsampled, _ = _refinement_closures(
        params, cfg, net, inp, fmap1, fmap2,
        compute_dtype=compute_dtype, test_mode=True, flow_init=fake_init)

    def step(carry, _):
        net, coords1 = carry
        net, coords1, _ = one_iteration(net, coords1, compute_mask=False)
        return (net, coords1), None

    (net, coords1), _ = lax.scan(step, (net, state["coords1"]), None,
                                 length=iters)
    return dict(state, net=net, coords1=coords1), coords0, upsampled


def raft_stereo_segment(params: Params, cfg: RAFTStereoConfig, state, *,
                        iters: int, warm_start: bool = False):
    """Advance the refinement scan ``iters`` steps from a carried state.

    ``state`` is the carry from :func:`raft_stereo_prepare` or a previous
    segment. The scan body is the one the single-scan test-mode forward
    compiles — the correlation pyramid is rebuilt from the carried feature
    maps by the same deterministic ops, so composing segments never changes
    a bit relative to one long scan. Returns ``(new_state, flow_low,
    flow_up)``: the low-res flow and the convex-upsampled disparity field
    after these iterations (the mask head runs once at the segment end,
    exactly like the single-scan path runs it once after its scan).

    ``warm_start`` mirrors ``flow_init is not None`` in the single-scan
    forward (it disables motion-encoder fusion the same way).
    """
    new_state, coords0, upsampled = _advance_carry(
        params, cfg, state, iters=iters, warm_start=warm_start)
    up_mask = apply_mask_head(params["update_block"], new_state["net"][0])
    coords1 = new_state["coords1"]
    return new_state, coords1 - coords0, upsampled(coords1, up_mask)


def raft_stereo_segment_carry(params: Params, cfg: RAFTStereoConfig, state, *,
                              iters: int, warm_start: bool = False):
    """:func:`raft_stereo_segment` minus the mask-head epilogue: advance the
    carry only. The continuous-batching scheduler runs this once per tick
    over the whole device batch and pays the epilogue (mask head + convex
    upsample) only for the rows that exit at this segment boundary —
    ``raft_stereo_epilogue(segment_carry(state))`` is bit-identical to
    ``raft_stereo_segment(state)[2]`` because the mask head reads the
    carried hidden state and never feeds back into it.

    Returns ``(new_state, dnorm)`` where ``dnorm`` is the per-row
    convergence monitor: the segment's mean per-iteration
    ``|delta_flow_x|`` (``mean|coords1_out - coords1_in| / iters``,
    px/iter at 1/``downsample_factor`` res), shape ``(B,)`` fp32.
    Computed OUTSIDE the scan from its endpoint coords — the scan body
    and its carry are byte-for-byte the ones :func:`raft_stereo_segment`
    compiles, so the epilogue∘segment_carry == segment bitwise pin is
    untouched (an in-carry last-iteration monitor measurably perturbed
    XLA:CPU's scan codegen).  The serving layers compare ``dnorm``
    against ``RAFT_CONVERGE_TOL`` on the HOST at segment boundaries
    (serve/stream.py) — the tolerance never enters the compiled program,
    so it stays out of the program fingerprint."""
    new_state, _, _ = _advance_carry(
        params, cfg, state, iters=iters, warm_start=warm_start)
    dnorm = jnp.mean(jnp.abs(
        (new_state["coords1"] - state["coords1"]).astype(
            jnp.float32)[..., 0]), axis=(1, 2)) / float(iters)
    return new_state, dnorm


def raft_stereo_epilogue(params: Params, cfg: RAFTStereoConfig, state):
    """Mask head + convex upsample from a carried state, without advancing.

    Exactly the segment-end output computation: the same
    ``apply_mask_head`` call and the same channel-0-sliced fp32 upsample
    the single-scan test-mode forward and :func:`raft_stereo_segment`
    perform — so for any carry, ``raft_stereo_epilogue`` returns the same
    bytes a segment ending at that carry would have. Returns
    ``(flow_low, flow_up)``.
    """
    # coords1 carries the refinement geometry directly — state["fmap1"]
    # may be a packed {"pk","scale"} container (RAFT_LANE_PACK8) whose
    # width axis is the quad-packed ceil(W/4).
    b, h, w = state["coords1"].shape[:3]
    coords0 = coords_grid(b, h, w)
    coords1 = state["coords1"]
    up_mask = apply_mask_head(params["update_block"], tuple(state["net"])[0])
    # Mirror of _refinement_closures.upsampled: slice x before upsampling.
    flow_x = (coords1 - coords0)[..., :1].astype(jnp.float32)
    flow_up = convex_upsample(flow_x, up_mask.astype(jnp.float32),
                              cfg.downsample_factor)
    return coords1 - coords0, flow_up


# -- carry-batch composition -------------------------------------------------
# The serving scheduler composes per-request carries into one device batch
# (and back) with the two helpers below. Every leaf of the carry dict has a
# leading batch dim and every op in the scan body is batch-row independent
# (convs, the corr gather, the epipolar .at[..., 1] update chain), so row i
# of a stacked carry advances bit-identically to the same carry alone —
# pinned by tests/test_batch_serve.py.


def stack_refinement_states(states):
    """Concatenate carry dicts along the batch axis (rows keep order)."""
    if not states:
        raise ValueError("stack_refinement_states needs >= 1 state")
    if len(states) == 1:
        return states[0]
    return jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=0), *states)


def take_refinement_rows(state, rows: Sequence[int]):
    """Gather batch rows of a carry dict (repeats allowed — padding a batch
    to its power-of-two bucket replicates a live row, so pad rows are
    always well-formed finite carries that are simply never read back)."""
    idx = jnp.asarray(tuple(int(r) for r in rows), dtype=jnp.int32)
    return jax.tree_util.tree_map(lambda x: jnp.take(x, idx, axis=0), state)


def raft_stereo_inference(params: Params, cfg: RAFTStereoConfig,
                          image1: jax.Array, image2: jax.Array, *,
                          iters: int = 32, segments: int = 1,
                          flow_init: Optional[jax.Array] = None):
    """Test-mode forward with the scan split into ``segments`` chunks.

    ``segments=1`` delegates to :func:`raft_stereo_forward` in test mode —
    the exact single-scan program, byte-identical outputs. ``segments=k``
    chains k scans of ``iters // k`` steps through the carried state
    (``iters`` must divide evenly). Traceable either way, so callers can
    jit the whole thing; the serving layer instead jits prepare and segment
    separately to get host control between segments. Returns
    ``(flow_low, flow_up)``.
    """
    if segments < 1:
        raise ValueError(f"segments must be >= 1, got {segments}")
    if segments == 1:
        return raft_stereo_forward(params, cfg, image1, image2, iters=iters,
                                   flow_init=flow_init, test_mode=True)
    if iters % segments:
        raise ValueError(
            f"iters ({iters}) must be divisible by segments ({segments})")
    state = raft_stereo_prepare(params, cfg, image1, image2,
                                flow_init=flow_init)
    flow_low = flow_up = None
    for _ in range(segments):
        state, flow_low, flow_up = raft_stereo_segment(
            params, cfg, state, iters=iters // segments,
            warm_start=flow_init is not None)
    return flow_low, flow_up

"""Feature and context encoders.

Reference ``core/extractor.py``:
- ``BasicEncoder`` (:122-197) — feature net: 7x7 stem (stride ``1 + (downsample
  > 2)``) -> 3 stages of 2 ResidualBlocks at 64/96/128 channels (strides 1,
  ``1+(downsample>1)``, ``1+(downsample>0)``) -> 1x1 conv to output_dim. For the
  default ``n_downsample=2`` the output is 1/4 resolution.
- ``MultiBasicEncoder`` (:199-300) — context net: same trunk plus ``layer4``/
  ``layer5`` at stride 2 producing three scales, with per-scale output heads.
  Index convention preserved from the reference: head ``outputs08`` (finest)
  emits ``dim[2]`` channels, ``outputs32`` (coarsest) emits ``dim[0]``
  (:231,240,247). ``dual_inp`` (shared-backbone mode) runs both images through
  the trunk and also returns the full-batch trunk features (:283-285).

Images are fed as a single batch (the reference concatenates the image list
along batch to share one pass, :173-179); on TPU this keeps one big MXU-friendly
conv stream.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from raft_stereo_tpu.models.layers import (
    Params, apply_conv, apply_residual_block, init_conv, init_residual_block)


def _init_stage(key, in_planes: int, dim: int, norm_fn: str, stride: int):
    k1, k2 = jax.random.split(key)
    return [init_residual_block(k1, in_planes, dim, norm_fn, stride=stride),
            init_residual_block(k2, dim, dim, norm_fn, stride=1)]


def _apply_stage(stage: list, x: jax.Array, norm_fn: str, stride: int) -> jax.Array:
    x = apply_residual_block(stage[0], x, norm_fn, stride=stride)
    return apply_residual_block(stage[1], x, norm_fn, stride=1)


def _maybe_stream_block(blk: Params, x: jax.Array, norm_fn: str) -> jax.Array:
    """Stride-1 second block of a stage: streamed Pallas passes when the
    shape/dtype allow (ops/pallas_encoder.py streamed tail), XLA otherwise."""
    from raft_stereo_tpu.ops.pallas_encoder import (
        resblock_streamable, stream_resblock)
    if resblock_streamable(blk, x, norm_fn):
        return stream_resblock(norm_fn, blk, x)
    return apply_residual_block(blk, x, norm_fn, stride=1)


def _apply_stage_fused(stage: list, x: jax.Array, norm_fn: str,
                       stride: int) -> jax.Array:
    """Stage application on the FUSED encoder path: the stride-2 entry
    block stays XLA (its strided reads don't fit the row-ring geometry);
    the stride-1 second block streams. The ``fused=False`` oracle path
    keeps using the all-XLA ``_apply_stage``."""
    x = apply_residual_block(stage[0], x, norm_fn, stride=stride)
    return _maybe_stream_block(stage[1], x, norm_fn)


def _trunk_strides(downsample: int) -> Tuple[int, int, int]:
    return (1 + (downsample > 2), 1 + (downsample > 1), 1 + (downsample > 0))


def _packed_l2_enabled() -> bool:
    import os
    return os.environ.get("RAFT_PACKED_L2", "1").strip().lower() not in (
        "0", "false", "no", "off")


def _fused_trunk_then_layer2(p: Params, x: jax.Array, norm_fn: str, s2: int,
                             trunk_packed, trunk_unpacked) -> jax.Array:
    """Fused stem+layer1 followed by layer2, shared by both encoders.

    When layer2 opens with stride 2, its entry convs consume the trunk's
    parity-packed (H, W/2, 128) exit in place (the full-res interleaving
    unpack copy never materializes); otherwise the trunk unpacks and
    layer2 runs the plain stage. RAFT_PACKED_L2=0 forces the unpacked
    handoff (A/B knob)."""
    from raft_stereo_tpu.models.layers import apply_residual_block_packed
    if s2 == 2 and _packed_l2_enabled():
        xp = trunk_packed(p, x)
        x = apply_residual_block_packed(p["layer2"][0], xp, norm_fn)
        return _maybe_stream_block(p["layer2"][1], x, norm_fn)
    x = trunk_unpacked(p, x)
    return _apply_stage_fused(p["layer2"], x, norm_fn, s2)


def init_basic_encoder(key: jax.Array, output_dim: int = 128,
                       norm_fn: str = "instance", downsample: int = 3) -> Params:
    from raft_stereo_tpu.models.layers import init_norm
    ks = jax.random.split(key, 5)
    return {
        "conv1": init_conv(ks[0], 7, 7, 3, 64),
        "norm1": init_norm(norm_fn, 64),
        "layer1": _init_stage(ks[1], 64, 64, norm_fn, 1),
        "layer2": _init_stage(ks[2], 64, 96, norm_fn, 1 + (downsample > 1)),
        "layer3": _init_stage(ks[3], 96, 128, norm_fn, 1 + (downsample > 0)),
        "conv2": init_conv(ks[4], 1, 1, 128, output_dim),
    }


def apply_basic_encoder(p: Params, x: jax.Array, *, norm_fn: str,
                        downsample: int, fused: bool = True) -> jax.Array:
    from raft_stereo_tpu.models.layers import apply_norm
    from raft_stereo_tpu.ops.pallas_encoder import (
        fused_in_stem_layer1, fused_in_stem_layer1_packed,
        in_stem_layer1_is_fusable)
    s_stem, s2, s3 = _trunk_strides(downsample)
    if fused and in_stem_layer1_is_fusable(p, x, norm_fn, s_stem):
        # Full-resolution stem + layer1 streamed one-pass-per-conv with
        # inline instance normalization (see ops/pallas_encoder.py).
        x = _fused_trunk_then_layer2(p, x, norm_fn, s2,
                                     fused_in_stem_layer1_packed,
                                     fused_in_stem_layer1)
    else:
        x = apply_conv(p["conv1"], x, stride=s_stem, padding=3)
        # Stem GroupNorm uses 8 groups (extractor.py:129), unlike blocks
        # (planes//8).
        x = jax.nn.relu(apply_norm(norm_fn, p["norm1"], x, num_groups=8))
        x = _apply_stage(p["layer1"], x, norm_fn, 1)
        x = (_apply_stage_fused if fused else _apply_stage)(
            p["layer2"], x, norm_fn, s2)
    x = (_apply_stage_fused if fused else _apply_stage)(
        p["layer3"], x, norm_fn, s3)
    return apply_conv(p["conv2"], x)


def init_multi_basic_encoder(key: jax.Array, output_dim: Sequence[Sequence[int]],
                             norm_fn: str = "batch", downsample: int = 3) -> Params:
    from raft_stereo_tpu.models.layers import init_norm
    ks = jax.random.split(key, 6 + 3 * len(output_dim))
    p = {
        "conv1": init_conv(ks[0], 7, 7, 3, 64),
        "norm1": init_norm(norm_fn, 64),
        "layer1": _init_stage(ks[1], 64, 64, norm_fn, 1),
        "layer2": _init_stage(ks[2], 64, 96, norm_fn, 1 + (downsample > 1)),
        "layer3": _init_stage(ks[3], 96, 128, norm_fn, 1 + (downsample > 0)),
        "layer4": _init_stage(ks[4], 128, 128, norm_fn, 2),
        "layer5": _init_stage(ks[5], 128, 128, norm_fn, 2),
    }
    ki = iter(ks[6:])
    outputs08, outputs16, outputs32 = [], [], []
    for dim in output_dim:
        k1, k2 = jax.random.split(next(ki))
        outputs08.append({"res": init_residual_block(k1, 128, 128, norm_fn, 1),
                          "conv": init_conv(k2, 3, 3, 128, dim[2])})
    for dim in output_dim:
        k1, k2 = jax.random.split(next(ki))
        outputs16.append({"res": init_residual_block(k1, 128, 128, norm_fn, 1),
                          "conv": init_conv(k2, 3, 3, 128, dim[1])})
    for dim in output_dim:
        outputs32.append({"conv": init_conv(next(ki), 3, 3, 128, dim[0])})
    p["outputs08"], p["outputs16"], p["outputs32"] = outputs08, outputs16, outputs32
    return p


def apply_multi_basic_encoder(p: Params, x: jax.Array, *, norm_fn: str,
                              downsample: int, num_layers: int = 3,
                              dual_inp: bool = False, fused: bool = True):
    """Returns a tuple of per-scale lists (finest first), plus the full-batch
    trunk features when ``dual_inp``."""
    from raft_stereo_tpu.models.layers import apply_norm
    from raft_stereo_tpu.ops.pallas_encoder import (
        fused_stem_layer1, fused_stem_layer1_packed, stem_layer1_is_fusable)
    s_stem, s2, s3 = _trunk_strides(downsample)
    if fused and stem_layer1_is_fusable(p, x, norm_fn, s_stem):
        # Full-resolution stem + layer1 as ONE streaming Pallas pass
        # (frozen-BN folded into the convs) — the XLA chain materializes
        # five ~770 MB activations per frame at Middlebury-F.
        x = _fused_trunk_then_layer2(p, x, norm_fn, s2,
                                     fused_stem_layer1_packed,
                                     fused_stem_layer1)
    else:
        x = apply_conv(p["conv1"], x, stride=s_stem, padding=3)
        x = jax.nn.relu(apply_norm(norm_fn, p["norm1"], x, num_groups=8))
        x = _apply_stage(p["layer1"], x, norm_fn, 1)
        x = (_apply_stage_fused if fused else _apply_stage)(
            p["layer2"], x, norm_fn, s2)
    x = (_apply_stage_fused if fused else _apply_stage)(
        p["layer3"], x, norm_fn, s3)
    if dual_inp:
        v = x
        x = x[: x.shape[0] // 2]

    def head(h, feat, streamed=False):
        from raft_stereo_tpu.ops.pallas_encoder import (
            head_conv_streamable, stream_head_conv)
        if "res" in h:
            feat = (_maybe_stream_block(h["res"], feat, norm_fn) if streamed
                    else apply_residual_block(h["res"], feat, norm_fn,
                                              stride=1))
        if streamed and head_conv_streamable(h["conv"], feat):
            return stream_head_conv(h["conv"], feat)
        return apply_conv(h["conv"], feat, padding=1)

    # Only the finest (1/4-res) heads stream: they carry ~16x the pixels
    # of outputs16/32, whose XLA convs are already cheap — and each
    # streamed pass is one more Mosaic kernel in an already
    # compile-time-bound program. The r24 quantize-on-exit epilogues
    # (stream_head_conv_q8 / stream_resblock_q8) are NOT wired at these
    # heads either: the tensors that ride as packed containers are the
    # zqr gate levels, produced at raft_stereo._packed_context_level
    # (which picks the q8 epilogue per-geometry and host-packs
    # bitwise-identically otherwise), while apply_basic_encoder's fmap
    # tail ends in a 1x1 conv — the wrong seam for a width-group
    # packing epilogue, so fmaps pack host-side in raft_stereo_prepare.
    outputs08 = [head(h, x, streamed=fused) for h in p["outputs08"]]
    if num_layers == 1:
        return (outputs08, v) if dual_inp else (outputs08,)
    y = _apply_stage(p["layer4"], x, norm_fn, 2)
    outputs16 = [head(h, y) for h in p["outputs16"]]
    if num_layers == 2:
        return (outputs08, outputs16, v) if dual_inp else (outputs08, outputs16)
    z = _apply_stage(p["layer5"], y, norm_fn, 2)
    outputs32 = [head(h, z) for h in p["outputs32"]]
    return (outputs08, outputs16, outputs32, v) if dual_inp else (outputs08, outputs16, outputs32)

"""Per-iteration refinement: motion encoder, ConvGRU cascade, flow/mask heads.

Reference ``core/update.py``. The multilevel update runs coarse-to-fine:
the coarse GRU consumes pooled mid-scale state, the mid GRU consumes pooled
fine state + upsampled coarse state, the fine GRU consumes motion features +
upsampled mid state (:115-129). Context features enter as per-gate additive
biases (cz, cr, cq) precomputed once outside the iteration loop
(``core/raft_stereo.py:87-88``).

GRU hidden-dim convention preserved from the reference (:104-106):
``hidden_dims[2]`` is the finest scale (gru08), ``hidden_dims[0]`` the coarsest.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models.layers import Params, apply_conv, init_conv
from raft_stereo_tpu.ops.pooling import pool2x
from raft_stereo_tpu.ops.resize import interp_align_corners


def init_flow_head(key, input_dim=128, hidden_dim=256, output_dim=2) -> Params:
    k1, k2 = jax.random.split(key)
    return {"conv1": init_conv(k1, 3, 3, input_dim, hidden_dim),
            "conv2": init_conv(k2, 3, 3, hidden_dim, output_dim)}


def apply_flow_head(p: Params, x: jax.Array) -> jax.Array:
    return apply_conv(p["conv2"], jax.nn.relu(apply_conv(p["conv1"], x, padding=1)),
                      padding=1)


def init_conv_gru(key, hidden_dim: int, input_dim: int, kernel_size: int = 3) -> Params:
    kz, kr, kq = jax.random.split(key, 3)
    cin = hidden_dim + input_dim
    return {"convz": init_conv(kz, kernel_size, kernel_size, cin, hidden_dim),
            "convr": init_conv(kr, kernel_size, kernel_size, cin, hidden_dim),
            "convq": init_conv(kq, kernel_size, kernel_size, cin, hidden_dim)}


def _split_conv(w: jax.Array, b, parts: Sequence[jax.Array],
                pad: int, out_dtype=None) -> jax.Array:
    """conv(concat(parts), w) as a sum of per-part convs.

    Algebraically identical (channel-blocked matmul), but never materializes
    the concatenated input: at Middlebury-F resolution the concat + layout
    copy + pad for each gate conv accounted for ~25% of frame time in the
    profile (HBM-bound data movement the MXU waits on).

    The per-part results stay in the fp32 accumulator and are downcast ONCE
    at the end — summing bf16 partials would double the rounding error vs
    the single concat conv this replaces (measured 0.11 vs 0.05 max error
    on gate pre-activations). ``out_dtype=jnp.float32`` hands the caller
    the raw accumulator (for summing with other split-conv results before
    the single downcast).
    """
    from raft_stereo_tpu.ops.basic import conv2d
    off = 0
    out = None
    for t in parts:
        c = t.shape[-1]
        y = conv2d(t, jax.lax.slice_in_dim(w, off, off + c, axis=2), None,
                   padding=pad, out_dtype=jnp.float32)
        out = y if out is None else out + y
        off += c
    if b is not None:
        out = out + b.astype(jnp.float32)
    return out if out_dtype == jnp.float32 else out.astype(parts[0].dtype)


def apply_conv_gru(p: Params, h: jax.Array, context: Sequence[jax.Array],
                   *x_list: jax.Array) -> jax.Array:
    """context = (cz, cr, cq) additive gate biases (``core/update.py:23-32``).

    TPU formulation: the z and r gates share one fused conv pair (their
    weights concatenated along the output channels) and every gate conv is
    split over its input parts instead of concatenating them — same
    arithmetic, no materialized ``[h; x]`` tensors in the scan body.
    (Measured at Middlebury-F: materializing the x concat + a single wide
    conv is SLOWER — XLA emits the concat, its conv-layout pad, and the
    fp32 upcast of the output as three extra full-tensor passes.)
    """
    cz, cr, cq = context
    pad = p["convz"]["w"].shape[0] // 2
    ch = h.shape[-1]
    wz, wr, wq = p["convz"]["w"], p["convr"]["w"], p["convq"]["w"]
    wx = jnp.concatenate([jax.lax.slice_in_dim(w, ch, w.shape[2], axis=2)
                          for w in (wz, wr, wq)], axis=-1)
    ax = _split_conv(wx, None, x_list, pad, out_dtype=jnp.float32)
    wzr_h = jnp.concatenate(
        [jax.lax.slice_in_dim(w, 0, ch, axis=2) for w in (wz, wr)], axis=-1)
    bzr = jnp.concatenate([p["convz"]["b"], p["convr"]["b"]])
    ah = _split_conv(wzr_h, bzr, (h,), pad, out_dtype=jnp.float32)
    zr = (ah + ax[..., :2 * ch]).astype(h.dtype)
    z = jax.nn.sigmoid(zr[..., :ch] + cz)
    r = jax.nn.sigmoid(zr[..., ch:] + cr)
    aq = _split_conv(jax.lax.slice_in_dim(wq, 0, ch, axis=2), p["convq"]["b"],
                     (r * h,), pad, out_dtype=jnp.float32)
    q = jnp.tanh((aq + ax[..., 2 * ch:]).astype(h.dtype) + cq)
    return (1 - z) * h + z * q


def init_sep_conv_gru(key, hidden_dim: int = 128, input_dim: int = 192 + 128) -> Params:
    """Reference ``SepConvGRU`` (``core/update.py:34-62``; unused by the stereo
    configs, kept for API parity)."""
    ks = jax.random.split(key, 6)
    cin = hidden_dim + input_dim
    return {"convz1": init_conv(ks[0], 1, 5, cin, hidden_dim),
            "convr1": init_conv(ks[1], 1, 5, cin, hidden_dim),
            "convq1": init_conv(ks[2], 1, 5, cin, hidden_dim),
            "convz2": init_conv(ks[3], 5, 1, cin, hidden_dim),
            "convr2": init_conv(ks[4], 5, 1, cin, hidden_dim),
            "convq2": init_conv(ks[5], 5, 1, cin, hidden_dim)}


def apply_sep_conv_gru(p: Params, h: jax.Array, *x_list: jax.Array) -> jax.Array:
    x = jnp.concatenate(x_list, axis=-1) if len(x_list) > 1 else x_list[0]
    for suffix, pad in (("1", (0, 2)), ("2", (2, 0))):
        hx = jnp.concatenate([h, x], axis=-1)
        z = jax.nn.sigmoid(apply_conv(p["convz" + suffix], hx, padding=pad))
        r = jax.nn.sigmoid(apply_conv(p["convr" + suffix], hx, padding=pad))
        q = jnp.tanh(apply_conv(p["convq" + suffix],
                                jnp.concatenate([r * h, x], axis=-1), padding=pad))
        h = (1 - z) * h + z * q
    return h


def init_motion_encoder(key, cfg: RAFTStereoConfig) -> Params:
    """Reference ``BasicMotionEncoder`` (``core/update.py:64-85``)."""
    ks = jax.random.split(key, 5)
    return {"convc1": init_conv(ks[0], 1, 1, cfg.cor_planes, 64),
            "convc2": init_conv(ks[1], 3, 3, 64, 64),
            "convf1": init_conv(ks[2], 7, 7, 2, 64),
            "convf2": init_conv(ks[3], 3, 3, 64, 64),
            "conv": init_conv(ks[4], 3, 3, 128, 126)}


def apply_motion_encoder(p: Params, flow: jax.Array,
                         corr: jax.Array) -> jax.Array:
    cor = jax.nn.relu(apply_conv(p["convc1"], corr))
    cor = jax.nn.relu(apply_conv(p["convc2"], cor, padding=1))
    flo = jax.nn.relu(apply_conv(p["convf1"], flow, padding=3))
    flo = jax.nn.relu(apply_conv(p["convf2"], flo, padding=1))
    out = jax.nn.relu(_split_conv(p["conv"]["w"], p["conv"]["b"], (cor, flo),
                                  pad=1))
    # Motion features are (fused 126ch ‖ raw 2ch flow), reference channel
    # order (update.py:85). Emitting the 128ch concat here (one fused copy
    # pass) lets the consuming gate conv read ONE lane-aligned tensor —
    # the alternative, a separate 2-channel conv partial, costs a full
    # (H, W, 3*hidden) fp32 write+read per iteration for two channels of
    # input (profiled ~1 ms/iter at Middlebury-F).
    return jnp.concatenate([out, flow.astype(out.dtype)], axis=-1)


def init_update_block(key, cfg: RAFTStereoConfig) -> Params:
    hd = cfg.hidden_dims
    n = cfg.n_gru_layers
    encoder_output_dim = 128
    ks = jax.random.split(key, 6)
    p = {
        "encoder": init_motion_encoder(ks[0], cfg),
        # Input dims per reference core/update.py:104-106.
        "gru08": init_conv_gru(ks[1], hd[2],
                               encoder_output_dim + hd[1] * (n > 1)),
        "gru16": init_conv_gru(ks[2], hd[1], hd[0] * (n == 3) + hd[2]),
        "gru32": init_conv_gru(ks[3], hd[0], hd[1]),
        "flow_head": init_flow_head(ks[4], hd[2], hidden_dim=256, output_dim=2),
    }
    km1, km2 = jax.random.split(ks[5])
    factor = cfg.downsample_factor
    p["mask"] = {"conv1": init_conv(km1, 3, 3, hd[2], 256),
                 "conv2": init_conv(km2, 1, 1, 256, factor * factor * 9)}
    return p


def apply_mask_head(p: Params, net0: jax.Array) -> jax.Array:
    """Convex-upsampling mask from the finest hidden state, scaled 0.25
    "to balance gradients" (``core/update.py:136-137``)."""
    return 0.25 * apply_conv(p["mask"]["conv2"],
                             jax.nn.relu(apply_conv(p["mask"]["conv1"], net0,
                                                    padding=1)))


def apply_update_block(p: Params, cfg: RAFTStereoConfig,
                       net: Tuple[jax.Array, ...], inp: Sequence[Sequence[jax.Array]],
                       corr: jax.Array | None = None, flow: jax.Array | None = None,
                       iter08: bool = True, iter16: bool = True, iter32: bool = True,
                       update: bool = True, compute_mask: bool = True,
                       fused_ctx: Sequence | None = None,
                       fuse_motion: bool = True,
                       space_mesh=None,
                       fuse_any_batch: bool = False):
    """Reference ``BasicMultiUpdateBlock.forward`` (``core/update.py:115-138``).

    net: per-scale hidden states, finest first. inp: per-scale (cz, cr, cq).
    Returns the new net tuple, and ``(net, mask, delta_flow)`` when ``update``.

    ``compute_mask=False`` skips the mask head and returns ``None`` for it:
    the mask feeds only the upsampler, never the recurrent state, so
    test-mode callers that upsample only the final iteration
    (``raft_stereo.py:126-127`` semantics) can hoist the mask convs out of
    the iteration loop — identical outputs, ~2/33 of the per-iteration conv
    FLOPs saved (the reference computes-and-discards it every iteration).

    ``fused_ctx``: per-level pre-folded context from
    ``pallas_stream.prepare_gru_context_any`` (hoisted out of the scan);
    non-None entries route that level through the streaming Pallas GRU
    kernel. Each entry is OPAQUE here: bf16 rows, or under
    RAFT_LANE_PACK8 a ``(container, scale)`` pair the kernels
    dequantize in-register (r24 narrow lanes) — this module never
    inspects which, so the lane format can evolve behind the
    ``prepare_gru_context_any`` seam. In the test-mode scan (``compute_mask=False``) the FlowHead is
    chained into the finest kernel and the x-delta comes back with it.
    ``space_mesh``: when the jit is sharded over a mesh ``space`` axis,
    non-None entries instead route through the halo-exchange shard_map
    variants (fused_ctx then holds True flags — the gate context is
    folded per shard).
    """
    from jax.ad_checkpoint import checkpoint_name
    from raft_stereo_tpu.ops.pallas_stream import (
        fused_conv_gru, fused_conv_gru_spatial, fused_gru1632,
        fused_gru_head, fused_gru_head_spatial, fused_motion,
        fused_motion_spatial, gru1632_is_fusable, gru_is_fusable,
        motion_is_fusable, spatial_motion_is_fusable)
    fc = list(fused_ctx) if fused_ctx is not None else []
    fc += [None] * (3 - len(fc))

    # Kernel outputs are checkpoint-named so the fused-train remat policy
    # (save_only_these_names in raft_stereo.py) saves them: without the
    # tag, jax.checkpoint re-runs every pallas_call forward in the
    # backward pass. No-op outside that policy (and in test mode).
    def kname(x):
        return checkpoint_name(x, "stream_kernel")

    def gru(idx, h, ctx, *xs):
        gp = p[("gru08", "gru16", "gru32")[idx]]
        # bf16 single-sample steps run the streaming Pallas kernel (gate
        # convs + nonlinearities + state update fused in VMEM); other
        # shapes/dtypes use the XLA formulation.
        if fc[idx] is not None and space_mesh is not None:
            return kname(fused_conv_gru_spatial(space_mesh, gp, h, fc[idx],
                                                ctx, *xs))
        if fc[idx] is not None and gru_is_fusable(
                h, *xs, any_batch=fuse_any_batch):
            return kname(fused_conv_gru(gp, h, fc[idx], ctx, *xs))
        return apply_conv_gru(gp, h, ctx, *xs)

    net = list(net)
    n = cfg.n_gru_layers
    # The two coarse GRUs co-schedule in ONE streaming kernel when both
    # fire in this call: gru32's fresh state feeds gru16's upsampled
    # x-input straight from VMEM (bit-identical to the serial kernels +
    # XLA interp — see pallas_stream.fused_gru1632). Their small spatial
    # extents make the serial dispatch latency-bound (r5: 126 ms/frame
    # vs ~50 MXU-bound at Middlebury-F).
    if (iter32 and iter16 and n == 3 and space_mesh is None
            and fc[1] is not None and fc[2] is not None
            and gru1632_is_fusable(net[1], net[2],
                                   any_batch=fuse_any_batch)):
        x1p = pool2x(net[1])
        x0p = pool2x(net[0])
        net[1], net[2] = fused_gru1632(
            p["gru16"], p["gru32"], net[1], net[2], fc[1], fc[2],
            inp[1], inp[2], x0p, x1p)
        net[1], net[2] = kname(net[1]), kname(net[2])
    else:
        if iter32:
            net[2] = gru(2, net[2], inp[2], pool2x(net[1]))
        if iter16:
            if n > 2:
                net[1] = gru(1, net[1], inp[1], pool2x(net[0]),
                             interp_align_corners(net[2], net[1].shape[1:3]))
            else:
                net[1] = gru(1, net[1], inp[1], pool2x(net[0]))
    delta_x = None
    if iter08:
        # fuse_motion=False when a caller-supplied flow_init could carry a
        # nonzero y component — the fused motion encoder drops convf1's
        # flow-y weights on the strength of the y==0 invariant, which only
        # the default zero-init coords guarantee.
        if (fuse_motion and fc[0] is not None and space_mesh is not None
                and spatial_motion_is_fusable(
                    corr, space_mesh.shape.get("space", 1))):
            motion = kname(fused_motion_spatial(space_mesh, p["encoder"],
                                                flow, corr))
        elif (fuse_motion and fc[0] is not None
                and motion_is_fusable(corr, any_batch=fuse_any_batch)):
            motion = kname(fused_motion(p["encoder"], flow, corr))
        else:
            motion = apply_motion_encoder(p["encoder"], flow, corr)
        xs = (motion, interp_align_corners(net[1], net[0].shape[1:3])) \
            if n > 1 else (motion,)
        if (update and not compute_mask and fc[0] is not None
                and space_mesh is not None):
            net[0], delta_x = fused_gru_head_spatial(
                space_mesh, p["gru08"], p["flow_head"], net[0], fc[0],
                inp[0], *xs)
            net[0], delta_x = kname(net[0]), kname(delta_x)
        elif (update and not compute_mask and fc[0] is not None
                and gru_is_fusable(net[0], *xs, any_batch=fuse_any_batch)):
            net[0], delta_x = fused_gru_head(
                p["gru08"], p["flow_head"], net[0], fc[0], inp[0], *xs)
            net[0], delta_x = kname(net[0]), kname(delta_x)
        else:
            net[0] = gru(0, net[0], inp[0], *xs)
    net = tuple(net)
    if not update:
        return net

    if delta_x is not None:
        # Kernel emits the x-delta without conv2's bias; adding b[0] here
        # keeps its gradient path. The y-delta is identically zero after
        # the epipolar projection (raft_stereo.py:120), so it is never
        # computed.
        delta_x = delta_x + p["flow_head"]["conv2"]["b"][0]
        delta_flow = jnp.concatenate(
            [delta_x, jnp.zeros_like(delta_x)], axis=-1)
        return net, None, delta_flow

    delta_flow = apply_flow_head(p["flow_head"], net[0])
    mask = apply_mask_head(p, net[0]) if compute_mask else None
    return net, mask, delta_flow

"""Configuration for the TPU RAFT-Stereo framework.

One dataclass shared by all entry points (the reference passes a raw argparse
namespace straight into the model — ``train_stereo.py:214-248`` /
``core/raft_stereo.py:25-39``; here the config is typed and validated once).
Flag names are kept identical to the reference CLIs so scripts run unmodified,
plus the TPU-native correlation choices ``reg_tpu`` / ``alt_tpu``.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import List, Optional, Sequence, Tuple

CORR_IMPLEMENTATIONS = ("reg", "alt", "reg_tpu", "alt_tpu", "reg_cuda", "alt_cuda")


@dataclasses.dataclass
class RAFTStereoConfig:
    """Architecture + precision configuration (reference: the `args` namespace)."""

    # Architecture choices (reference train_stereo.py:231-239)
    corr_implementation: str = "reg"
    shared_backbone: bool = False
    corr_levels: int = 4
    corr_radius: int = 4
    n_downsample: int = 2
    slow_fast_gru: bool = False
    n_gru_layers: int = 3
    hidden_dims: Tuple[int, ...] = (128, 128, 128)
    # Precision. The reference uses torch.cuda.amp autocast (fp16); on TPU the
    # native fast dtype is bfloat16, whose fp32-range exponent removes the need
    # for loss scaling entirely. Correlation math stays fp32 (the reference
    # casts fmaps .float() for non-CUDA corr, core/raft_stereo.py:92-95).
    mixed_precision: bool = False
    # Streaming Pallas kernels for the scan body (fused ConvGRU / motion
    # encoder / flow head; ops/pallas_stream.py). Engaged only for bf16
    # single-sample steps; spatially-sharded eval sets this False — compiled
    # Mosaic kernels have no SPMD partitioning rule, so a jit sharded over a
    # real multi-chip mesh cannot split the pallas_call.
    fused_update: bool = True
    # Engage the streaming kernels in TRAINING too (forward only; backward
    # stays the XLA-oracle custom_vjp). The train scan then remats with
    # ``save_only_these_names('stream_kernel')`` so each kernel forward runs
    # ONCE instead of twice. Default off: at the reference's small crop
    # shapes the row streams are too short to amortize kernel fixed costs
    # (r4 measured 0.64 -> 0.13 steps/s without the policy; see BASELINE.md
    # for the policy-on measurement) — profitable only for large-crop /
    # full-res fine-tuning.
    fused_train: bool = False

    def __post_init__(self):
        self.hidden_dims = tuple(self.hidden_dims)
        if self.corr_implementation not in CORR_IMPLEMENTATIONS:
            raise ValueError(
                f"corr_implementation must be one of {CORR_IMPLEMENTATIONS}, "
                f"got {self.corr_implementation!r}")
        if self.n_gru_layers not in (1, 2, 3):
            raise ValueError(f"n_gru_layers must be 1, 2 or 3, got {self.n_gru_layers}")
        if len(self.hidden_dims) != 3:
            raise ValueError(f"hidden_dims must have 3 entries, got {self.hidden_dims}")
        if self.n_downsample not in (2, 3):
            raise ValueError(f"n_downsample must be 2 or 3, got {self.n_downsample}")

    @property
    def context_dims(self) -> Tuple[int, ...]:
        # Reference: context_dims = args.hidden_dims (core/raft_stereo.py:27)
        return self.hidden_dims

    @property
    def downsample_factor(self) -> int:
        return 2 ** self.n_downsample

    @property
    def cor_planes(self) -> int:
        # core/update.py:69
        return self.corr_levels * (2 * self.corr_radius + 1)

    @classmethod
    def from_namespace(cls, ns: argparse.Namespace) -> "RAFTStereoConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in vars(ns).items() if k in fields})


@dataclasses.dataclass
class TrainConfig:
    """Training parameters (reference train_stereo.py:215-229, 241-246)."""

    name: str = "raft-stereo"
    restore_ckpt: Optional[str] = None
    batch_size: int = 6
    train_datasets: Tuple[str, ...] = ("sceneflow",)
    lr: float = 0.0002
    num_steps: int = 100000
    image_size: Tuple[int, int] = (320, 720)
    train_iters: int = 16
    valid_iters: int = 32
    wdecay: float = 1e-5
    # Data augmentation
    img_gamma: Optional[Tuple[float, float]] = None
    saturation_range: Optional[Tuple[float, float]] = None
    do_flip: Optional[str] = None  # False/'h'/'v' in the reference CLI
    spatial_scale: Tuple[float, float] = (0.0, 0.0)
    noyjitter: bool = False
    # TPU-framework extensions (not in the reference CLI). num_workers=None
    # means "size from SLURM_CPUS_PER_TASK - 2" like the reference loader.
    num_workers: Optional[int] = None
    seed: int = 1234
    ckpt_every: int = 10000  # reference validation/ckpt cadence, train_stereo.py:153
    # Profile one steady-state step into this directory (jax.profiler trace,
    # SURVEY §5 tracing; same hook bench.py exposes as RAFT_BENCH_TRACE).
    trace_dir: Optional[str] = None
    # Shard each sample's height over this many devices (the mesh `space`
    # axis) in addition to batch data parallelism — the big-crop/full-res
    # training enabler, mirroring evaluate's --spatial_shard.
    spatial_shard: int = 1
    # Fault tolerance (DESIGN.md "Failure recovery"). A non-finite step is
    # skipped (params/opt_state untouched via optax.apply_if_finite) and the
    # run aborts only after this many CONSECUTIVE bad steps; 0 restores the
    # reference's abort-on-first behavior. `restore_ckpt` may also name a
    # checkpoint DIRECTORY: resume from its newest valid bundle
    # (checkpoint.find_latest_checkpoint), skipping truncated/corrupt ones.
    max_bad_steps: int = 5
    # Keep-last-K retention over periodic checkpoints; 0 keeps all.
    # Preempt/epoch/final bundles are never pruned.
    keep_ckpts: int = 3
    # Per-sample IO/decode retries before quarantine + substitution, and
    # the base seconds of the loader's exponential retry backoff.
    data_retries: int = 2
    data_retry_backoff: float = 0.05

    def __post_init__(self):
        self.train_datasets = tuple(self.train_datasets)
        self.image_size = tuple(self.image_size)
        self.spatial_scale = tuple(self.spatial_scale)
        if self.do_flip is False:
            self.do_flip = None

    @classmethod
    def from_namespace(cls, ns: argparse.Namespace) -> "TrainConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in vars(ns).items() if k in fields})


def eval_mixed_precision(cfg: RAFTStereoConfig) -> bool:
    """The inference-CLI bf16 policy, in ONE place (evaluate/demo/serve all
    call this — reference ``evaluate_stereo.py:227-230``): full-network
    mixed precision is safe when explicitly requested or when a
    kernel-backed corr implementation is selected (their lookups
    accumulate in fp32 in-kernel)."""
    return (cfg.mixed_precision
            or cfg.corr_implementation.endswith(("_cuda", "_tpu")))


def with_eval_precision(cfg: RAFTStereoConfig) -> RAFTStereoConfig:
    """``cfg`` with :func:`eval_mixed_precision` applied (same object when
    nothing changes)."""
    mp = eval_mixed_precision(cfg)
    if mp == cfg.mixed_precision:
        return cfg
    return type(cfg)(**{**cfg.__dict__, "mixed_precision": mp})


def add_model_args(parser: argparse.ArgumentParser) -> None:
    """Architecture flags, identical to the reference CLIs plus TPU corr choices."""
    parser.add_argument('--corr_implementation', choices=list(CORR_IMPLEMENTATIONS),
                        default="reg", help="correlation volume implementation")
    parser.add_argument('--shared_backbone', action='store_true',
                        help="use a single backbone for the context and feature encoders")
    parser.add_argument('--corr_levels', type=int, default=4,
                        help="number of levels in the correlation pyramid")
    parser.add_argument('--corr_radius', type=int, default=4,
                        help="width of the correlation pyramid")
    parser.add_argument('--n_downsample', type=int, default=2,
                        help="resolution of the disparity field (1/2^K)")
    parser.add_argument('--slow_fast_gru', action='store_true',
                        help="iterate the low-res GRUs more frequently")
    parser.add_argument('--n_gru_layers', type=int, default=3,
                        help="number of hidden GRU levels")
    parser.add_argument('--hidden_dims', nargs='+', type=int, default=[128] * 3,
                        help="hidden state and context dimensions")
    parser.add_argument('--mixed_precision', action='store_true',
                        help='use mixed precision (bfloat16 compute on TPU)')

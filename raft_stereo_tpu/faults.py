"""Deterministic fault injection for the reliability layer (DESIGN.md
"Failure recovery").

Long schedules (100k+ steps, SURVEY §5) meet every failure domain
eventually: unreadable/corrupt samples, non-finite steps, truncated
checkpoints, preemption. Each recovery path in ``data/loader.py``,
``engine/train.py`` and ``engine/checkpoint.py`` is proven under test by
the injectors here. Everything is driven by an explicit :class:`FaultPlan`
value — no environment-variable side channels, no wall-clock, no global
state — so an injected fault fires at exactly the same sample/step/byte on
every run, every host, every worker-thread schedule.

The four training injectors map one-to-one onto the recovery paths:

- ``io_errors``      -> loader retry + quarantine + deterministic substitution;
- ``nan_at_steps``   -> ``optax.apply_if_finite`` skip policy + bounded abort;
- ``truncate_file``  -> checkpoint hash validation + ``find_latest_checkpoint``
  fallback to the previous good bundle;
- ``sigterm_at_step``-> ``PreemptGuard`` checkpoint-and-exit + schedule-exact
  resume.

The serving injectors (:class:`ServeFaultPlan` et al., bottom of this
module) do the same for ``raft_stereo_tpu/serve/``: plan-driven compile
failures / RESOURCE_EXHAUSTED on the Nth program build, injected slow
forwards on a deterministic :class:`FakeClock` (deadline overruns),
NaN-poisoned outputs, and malformed-input generators.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
from typing import Dict, Iterable, Iterator, Mapping, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Declarative fault schedule; all coordinates are deterministic keys.

    io_errors: dataset index -> number of injected load failures for that
        sample (``-1`` = fail every attempt, i.e. permanently corrupt).
        Counted per *attempt*, so a budget of 1 models a transient fault
        that succeeds on the loader's first retry.
    nan_at_steps: global step numbers whose batch is NaN-poisoned before
        the compiled step (exercises the skip-if-nonfinite policy).
    sigterm_at_step: deliver SIGTERM to this process at that step boundary
        (exercises the PreemptGuard checkpoint-and-exit path).
    """

    io_errors: Mapping[int, int] = dataclasses.field(default_factory=dict)
    nan_at_steps: Tuple[int, ...] = ()
    sigterm_at_step: Optional[int] = None


class FaultyDataset:
    """Dataset wrapper raising injected IO errors per :class:`FaultPlan`.

    Attempt counts are per dataset index and lock-protected: the loader's
    thread pool may probe the same quarantined index concurrently, and a
    lost increment would turn a configured-transient fault permanent.
    """

    def __init__(self, dataset, plan: FaultPlan):
        self.dataset = dataset
        self.plan = plan
        self.attempts: Dict[int, int] = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.dataset)

    def __getitem__(self, index, rng=None):
        budget = self.plan.io_errors.get(int(index))
        if budget is not None:
            with self._lock:
                n = self.attempts.get(int(index), 0)
                self.attempts[int(index)] = n + 1
            if budget < 0 or n < budget:
                raise OSError(
                    f"injected IO fault for sample {index} (attempt {n + 1})")
        return self.dataset.__getitem__(index, rng=rng)


def poisoned_batches(batches: Iterable, plan: Optional[FaultPlan],
                     start_step: int = 0) -> Iterator:
    """Yield host batches, NaN-poisoning those for steps in ``nan_at_steps``.

    Applied to the *host* loader before ``device_prefetch`` so the poison
    rides the normal transfer path (including the bf16 image downcast,
    which preserves NaN). Batch ``i`` of this iterator feeds global step
    ``start_step + i`` — prefetch depth does not change that mapping, only
    when the decode happens.
    """
    for i, batch in enumerate(batches):
        if plan is not None and (start_step + i) in plan.nan_at_steps:
            batch = dict(batch)
            img = np.array(batch["image1"], copy=True)
            img[(0,) * img.ndim] = np.nan
            batch["image1"] = img
        yield batch


def fire_step_faults(plan: Optional[FaultPlan], step: int) -> None:
    """Step-boundary injections (currently: SIGTERM at a configured step)."""
    if plan is not None and plan.sigterm_at_step == step:
        os.kill(os.getpid(), signal.SIGTERM)


def truncate_file(path: str, keep_bytes: Optional[int] = None,
                  keep_frac: float = 0.5) -> int:
    """Truncate ``path`` (default: to half its size), modeling a checkpoint
    write cut off by a crash that bypassed the atomic-rename path (partial
    NFS flush, disk-full copy, ...). Returns the retained byte count."""
    size = os.path.getsize(path)
    keep = int(size * keep_frac) if keep_bytes is None else keep_bytes
    keep = max(0, min(size, keep))
    with open(path, "rb+") as f:
        f.truncate(keep)
    return keep


# ---------------------------------------------------------------------------
# Serving-layer injectors (raft_stereo_tpu/serve/). Same stance as the
# training injectors above: every fault is driven by an explicit plan value
# keyed on deterministic ordinals — no env vars, no randomness — so a storm
# replays identically on every run. The three injectors map onto the three
# serving recovery paths:
#
# - ``compile_errors``  -> circuit-breaker trip + fallback-ladder rebuild
#                          (serve/guard.py);
# - ``slow_forwards``   -> deadline-aware anytime degradation
#                          (serve/degrade.py best-so-far early return);
# - ``poison_outputs`` / ``malformed_pairs`` -> output validation + parity
#                          canary, and admission control (serve/validate.py).


class InjectedKernelError(RuntimeError):
    """Stands in for the compile/runtime failures a TPU fast path can
    throw (Mosaic lowering failure, XLA ``RESOURCE_EXHAUSTED``). The
    message carries the same marker substrings the circuit breaker
    classifies real failures by."""

    def __init__(self, kind: str, detail: str = ""):
        self.kind = kind
        messages = {
            "mosaic": "Mosaic lowering failed (injected)",
            "oom": "RESOURCE_EXHAUSTED: out of memory while allocating "
                   "(injected)",
        }
        msg = messages.get(kind, kind)
        if detail:
            msg = f"{msg} [{detail}]"
        super().__init__(msg)


class InjectedWorkerCrash(RuntimeError):
    """Stands in for a host-side bug that kills a serving worker thread
    (the scheduler tick loop, the uploader).  Deliberately NOT a kernel
    failure: the circuit breaker must never see it — thread death is the
    supervision layer's territory (serve/supervise.py watchdogs), not a
    fallback-ladder rung."""


@dataclasses.dataclass(frozen=True)
class ServeFaultPlan:
    """Declarative fault schedule for one :class:`~raft_stereo_tpu.serve.
    session.InferenceSession`; all coordinates are deterministic ordinals.

    compile_errors: program-build ordinal (0-based count of compile
        attempts in the session, across breaker rebuilds) -> failure kind:
        ``'mosaic'`` / ``'oom'``, optionally suffixed ``':<detail>'``
        whose detail text lets the breaker's matchers attribute the
        failure to a specific fast path (e.g. ``'mosaic:gru1632'``).
    slow_builds: program-build ordinal -> real seconds to sleep inside the
        (per-bucket-locked) compile, widening the race window the compile
        locks must close.
    slow_forwards: device-invocation ordinal (0-based count of program
        executions: warmups, canary runs and request forwards all count)
        -> seconds of injected device-time, advanced on the session's
        clock (a :class:`FakeClock` makes deadline tests instantaneous
        and exact).
    poison_outputs: device-invocation ordinals whose disparity output is
        NaN-corrupted after the forward — models a silently wrong kernel;
        must be caught by output validation or the parity canary, never
        served.
    """

    compile_errors: Mapping[int, str] = dataclasses.field(default_factory=dict)
    slow_builds: Mapping[int, float] = dataclasses.field(default_factory=dict)
    slow_forwards: Mapping[int, float] = dataclasses.field(default_factory=dict)
    poison_outputs: Tuple[int, ...] = ()


@dataclasses.dataclass(frozen=True)
class ChaosPlan(ServeFaultPlan):
    """Supervision-layer chaos schedule (serve/supervise.py + the
    ``scratch/chaos_serve.py`` soak): extends :class:`ServeFaultPlan`
    with the fault classes only a watchdog can recover from.  Same
    stance as every other plan here — deterministic ordinals, no
    randomness, no env side channels — so a chaos storm replays
    identically on every run.

    hang_invokes: device-invocation ordinal (0-based count of *invoke
        entries* — a separate ordinal space from ``slow_forwards``'
        post-execution count, though the two coincide whenever every
        invoke completes) -> fake seconds the hang appears to take.  The
        invocation first advances the session clock by that many seconds
        (so a FakeClock watchdog sees it overdue immediately), then
        parks the invoking thread on a real condition until
        :meth:`ServeFaults.release_hangs` (the generation bounce calls
        it) or the ``hang_cap_s`` real-time safety cap.
    crash_uploads: upload ordinals (0-based count of rows the uploader
        thread picks up) whose processing kills the uploader thread —
        the injected form of the mid-run uploader crash that used to
        strand its joiners' Futures forever.
    crash_ticks: scheduler work-tick ordinals (0-based count of ticks
        that did work) AFTER which the tick-loop thread crashes.
    hang_chips: mesh chip ordinals whose post-bounce health probe
        (``InferenceSession.probe_chips`` -> ``on_chip_probe``) parks —
        the injected form of ONE chip of a data mesh staying wedged
        while its siblings answer, so the chip-local quarantine path is
        CPU-testable.  Parked probes ride the same release
        epoch/real-time cap as ``hang_invokes``.
    hang_cap_s: real-seconds safety cap on any injected hang, so a test
        that never bounces cannot deadlock the suite.
    clear_after_invokes: graftheal (r22) transient-fault window — the
        plan's HANG faults (``hang_invokes`` parks and ``hang_chips``
        probe parks) stop firing once this many device invocations have
        entered since the plan was installed on its
        :class:`ServeFaults`.  Models a fault that clears under load;
        the ordinal-keyed faults (``compile_errors``, ``slow_forwards``,
        ``poison_outputs``) are already self-limiting by ordinal and are
        NOT gated, so existing storm ordinals stay byte-stable (the
        PR 14 stance).
    clear_after_ms: same window on the injectable session clock: hang
        faults stop firing once the clock has advanced this many ms past
        plan installation.  Either bound clearing the window clears it.
    """

    hang_invokes: Mapping[int, float] = dataclasses.field(
        default_factory=dict)
    crash_uploads: Tuple[int, ...] = ()
    crash_ticks: Tuple[int, ...] = ()
    hang_chips: Tuple[int, ...] = ()
    hang_cap_s: float = 30.0
    clear_after_invokes: Optional[int] = None
    clear_after_ms: Optional[float] = None


class ServeFaults:
    """Lock-protected ordinal counters binding a :class:`ServeFaultPlan`
    to one session (mirrors :class:`FaultyDataset` for the loader)."""

    def __init__(self, plan: Optional[ServeFaultPlan], clock=None):
        self.clock = clock
        self.builds = 0
        self.forwards = 0
        self.invokes = 0
        self.uploads = 0
        self.ticks = 0
        self._lock = threading.Lock()
        # Injected hangs park on this condition until release_hangs()
        # (the watchdog bounce) bumps the epoch, or the plan's real-time
        # cap expires.  ``hangs_entered`` lets tests wait until the
        # victim thread is provably parked before advancing the clock.
        self._hang_cv = threading.Condition()
        self.hangs_entered = 0
        self._hang_epoch = 0
        # graftheal transient-fault windows are measured from plan
        # INSTALLATION (the property setter below re-bases them), so a
        # test that swaps plans mid-run gets a fresh window — assigned
        # last: the setter reads the counters above.
        self._window_invokes0 = 0
        self._window_t0: Optional[float] = None
        self.plan = plan

    @property
    def plan(self) -> Optional[ServeFaultPlan]:
        return self._plan

    @plan.setter
    def plan(self, plan: Optional[ServeFaultPlan]) -> None:
        # Plans stay reassignable mid-run (storms swap them); each
        # install re-bases the transient window's invoke/clock origin.
        with self._lock:
            self._plan = plan
            self._window_invokes0 = self.invokes
            self._window_t0 = (self.clock.now()
                               if self.clock is not None else None)

    def _cleared(self, ordinal: Optional[int] = None) -> bool:
        """True when the plan's transient-fault window has expired —
        hang faults (invoke parks, chip-probe parks) stop firing.  The
        ordinal counters themselves are NEVER gated: deterministic fault
        ordinals survive the window (the PR 14 storm stance)."""
        plan = self._plan
        n_clear = getattr(plan, "clear_after_invokes", None)
        if n_clear is not None:
            with self._lock:
                count = (ordinal if ordinal is not None
                         else self.invokes) - self._window_invokes0
            if count >= n_clear:
                return True
        ms_clear = getattr(plan, "clear_after_ms", None)
        if ms_clear is not None and self.clock is not None \
                and self._window_t0 is not None:
            if self.clock.now() - self._window_t0 >= ms_clear / 1e3:
                return True
        return False

    def on_build(self) -> int:
        """Fire at each program-compile attempt; raises the injected
        compile failure for this ordinal, if any."""
        with self._lock:
            n = self.builds
            self.builds = n + 1
        if self.plan is None:
            return n
        slow = self.plan.slow_builds.get(n)
        if slow:
            import time
            time.sleep(slow)
        kind = self.plan.compile_errors.get(n)
        if kind is not None:
            base, _, detail = kind.partition(":")
            raise InjectedKernelError(base, detail)
        return n

    def on_forward(self) -> int:
        """Fire after each device-program invocation; advances the
        session clock by any injected slowness. Returns the ordinal so
        the caller can apply ``poisoned()``."""
        with self._lock:
            n = self.forwards
            self.forwards = n + 1
        if self.plan is not None:
            slow = self.plan.slow_forwards.get(n)
            if slow and self.clock is not None:
                self.clock.sleep(slow)
        return n

    def poisoned(self, ordinal: int) -> bool:
        return self.plan is not None and ordinal in self.plan.poison_outputs

    # -- supervision-layer injectors (ChaosPlan; plain ServeFaultPlans
    # have none of these fields, so every hook is a counted no-op) ------

    def on_invoke(self) -> int:
        """Fire at each device-invocation ENTRY (before the program
        runs, inside the session's invocation watch window); parks the
        calling thread on an injected hang for this ordinal, if any."""
        with self._lock:
            n = self.invokes
            self.invokes = n + 1
        hang = getattr(self.plan, "hang_invokes", None)
        if not hang or n not in hang or self._cleared(ordinal=n):
            return n
        # Capture the release epoch BEFORE the clock advance below: the
        # advance is what makes this hang detectable, so a supervisor
        # sweep (and its release_hangs) can land in the gap between the
        # sleep and the park — an epoch read after that release would
        # miss it and park the victim for the full real-time cap.
        with self._hang_cv:
            epoch = self._hang_epoch
        # The hang's apparent duration lands on the session clock FIRST:
        # a FakeClock watchdog sees the invocation overdue the moment the
        # victim parks, with zero real sleeping in the deadline math.
        if self.clock is not None and hang[n]:
            self.clock.sleep(hang[n])
        import time
        cap = time.monotonic() + getattr(self.plan, "hang_cap_s", 30.0)
        with self._hang_cv:
            self.hangs_entered += 1
            self._hang_cv.notify_all()
            while self._hang_epoch == epoch and time.monotonic() < cap:
                self._hang_cv.wait(0.05)
        return n

    def on_chip_probe(self, chip: int) -> None:
        """Fire inside each mesh chip-health probe thread
        (``InferenceSession.probe_chips``); parks the probe for a chip in
        the plan's ``hang_chips`` — modeling a chip that stays wedged
        after the bounce freed the invoke-level hang.  Parked probes use
        the SAME epoch condition as ``on_invoke`` hangs, so they respect
        ``release_hangs`` and the real-time cap; a probe that parks past
        its caller's join timeout reads as a hung chip, which is the
        point."""
        if chip not in getattr(self.plan, "hang_chips", ()) \
                or self._cleared():
            return
        with self._hang_cv:
            epoch = self._hang_epoch
        import time
        cap = time.monotonic() + getattr(self.plan, "hang_cap_s", 30.0)
        with self._hang_cv:
            self.hangs_entered += 1
            self._hang_cv.notify_all()
            while self._hang_epoch == epoch and time.monotonic() < cap:
                self._hang_cv.wait(0.05)

    def release_hangs(self) -> None:
        """Unpark every currently-hung invocation (the generation bounce
        calls this so an abandoned victim thread can run to its no-op
        completion instead of leaking until the real-time cap)."""
        with self._hang_cv:
            self._hang_epoch += 1
            self._hang_cv.notify_all()

    def wait_hang_entered(self, n: int = 1, timeout: float = 30.0) -> bool:
        """Block (real time) until at least ``n`` injected hangs have
        parked their victims — the test-side rendezvous."""
        import time
        deadline = time.monotonic() + timeout
        with self._hang_cv:
            while self.hangs_entered < n:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._hang_cv.wait(min(0.05, remaining))
        return True

    def on_upload(self) -> int:
        """Fire as the uploader thread picks up each row; raises the
        injected thread-killing crash for this ordinal, if any."""
        with self._lock:
            n = self.uploads
            self.uploads = n + 1
        if n in getattr(self.plan, "crash_uploads", ()):
            raise InjectedWorkerCrash(
                f"injected uploader crash at upload {n}")
        return n

    def on_tick(self) -> int:
        """Fire after each scheduler work-tick; raises the injected
        tick-loop crash for this ordinal, if any."""
        with self._lock:
            n = self.ticks
            self.ticks = n + 1
        if n in getattr(self.plan, "crash_ticks", ()):
            raise InjectedWorkerCrash(
                f"injected tick-loop crash after work tick {n}")
        return n


# ---------------------------------------------------------------------------
# Wire-level injectors (graftwire, serve/http.py + scratch/chaos_serve.py
# --wire). Unlike every plan above, these describe CLIENT behavior: the
# hostile things a network peer does to an ingress — truncating an upload,
# stalling a socket at a chosen byte, flooding headers, disconnecting
# mid-request, sending garbage or a decompression bomb. The storm driver
# plays them over real loopback sockets; the server side is entirely
# unmodified production code, which is the point.


#: Every hostile client behavior the wire storm can inject, with the
#: structured code (or connection outcome) the ingress must answer.
WIRE_FAULT_KINDS: Tuple[str, ...] = (
    "ok",                        # well-formed request -> 200
    "truncated_body",            # short body + half-close -> 400
    "stalled_body",              # stop sending mid-body -> 408
    "garbage_image",             # undecodable part bytes -> 400
    "bomb_image",                # crafted huge-header PNG -> 413
    "header_flood",              # >100 headers -> 431
    "disconnect_mid_request",    # close without reading the response
    "oversize_content_length",   # declared length > cap -> 413
    "empty_body",                # Content-Length: 0 -> 400
    "bad_multipart",             # boundary-less multipart -> 400
    "wrong_route",               # POST /v1/nope -> 404
    "bad_method",                # DELETE /v1/stereo -> 405
)


@dataclasses.dataclass(frozen=True)
class WireChaosPlan:
    """Deterministic client-side fault schedule for the network storm.

    faults: request ordinal -> fault kind (one of
        :data:`WIRE_FAULT_KINDS`); ordinals absent from the map are
        well-formed requests. Same stance as every plan here: explicit
        values keyed on deterministic ordinals, so a storm replays
        identically on every run.
    truncate_frac / stall_frac: the deterministic BYTE ordinal (as a
        fraction of the encoded body) at which a truncating client stops
        sending / a stalling client goes silent.
    stall_hold_s: how long a stalled client keeps its socket open
        waiting for the server's verdict (must exceed the ingress
        per-read timeout for the fault to be non-vacuous).
    flood_headers: header count for the flood fault (the stdlib parser
        rejects past 100).
    """

    faults: Mapping[int, str] = dataclasses.field(default_factory=dict)
    truncate_frac: float = 0.5
    stall_frac: float = 0.25
    stall_hold_s: float = 5.0
    flood_headers: int = 150

    @staticmethod
    def seeded(seed: int, n: int, hostile_frac: float = 0.5,
               kinds: Optional[Tuple[str, ...]] = None) -> "WireChaosPlan":
        """A reproducible storm: ``hostile_frac`` of ``n`` ordinals get a
        fault kind drawn round-robin-shuffled from ``kinds`` (default:
        every kind except ``ok``), the rest stay well-formed."""
        kinds = tuple(kinds if kinds is not None else
                      [k for k in WIRE_FAULT_KINDS if k != "ok"])
        rng = np.random.default_rng(seed)
        n_hostile = int(n * hostile_frac)
        ordinals = rng.choice(n, size=n_hostile, replace=False)
        # Every kind appears before any repeats (shuffled blocks), so a
        # small storm still exercises the full fault surface.
        assignment = []
        while len(assignment) < n_hostile:
            block = list(kinds)
            rng.shuffle(block)
            assignment.extend(block)
        faults = {int(o): assignment[i]
                  for i, o in enumerate(sorted(int(x) for x in ordinals))}
        return WireChaosPlan(faults=faults)


def bomb_png(width: int, height: int) -> bytes:
    """A syntactically valid PNG whose IHDR declares ``width x height``
    pixels backed by almost no data — the crafted decompression bomb the
    ingress guard must reject from the HEADER alone (a real decode of a
    100 MP declaration would allocate ~300 MB from these few hundred
    bytes)."""
    import struct
    import zlib

    def chunk(typ: bytes, data: bytes) -> bytes:
        return (struct.pack(">I", len(data)) + typ + data
                + struct.pack(">I", zlib.crc32(typ + data) & 0xFFFFFFFF))

    ihdr = struct.pack(">IIBBBBB", width, height, 8, 2, 0, 0, 0)
    idat = zlib.compress(b"\x00")
    return (b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr)
            + chunk(b"IDAT", idat) + chunk(b"IEND", b""))


def poison_disparity(arr: np.ndarray) -> np.ndarray:
    """NaN-corrupt a disparity field (injected silently-wrong kernel).
    Poisons the CENTER pixel — corner pixels sit in the bucket padding and
    would be sliced away before output validation ever saw them."""
    out = np.array(arr, copy=True)
    out[tuple(s // 2 for s in out.shape)] = np.nan
    return out


class FakeClock:
    """Deterministic clock for deadline tests: ``now()`` advances only via
    ``sleep()``, so an injected 10-second overrun costs zero wall time and
    deadline arithmetic is exact on any machine. The serving layer takes
    any object with this interface; production uses :class:`RealClock`."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        with self._lock:
            self._now += float(seconds)


class RealClock:
    """Monotonic wall clock (the serving default)."""

    @staticmethod
    def now() -> float:
        import time
        return time.monotonic()

    @staticmethod
    def sleep(seconds: float) -> None:
        import time
        time.sleep(seconds)


def malformed_pairs(h: int = 48, w: int = 64,
                    oversize_pixels: Optional[int] = None) -> Dict[str, Tuple]:
    """Generators for the admission-control test battery: each entry is a
    ``name -> (left, right)`` pair that a serving session must REJECT with
    a structured error (never crash on, never silently serve).

    ``oversize_pixels``: admission limit to exceed for the ``oversized``
    case (omitted when None — building a >limit array may be expensive)."""
    rng = np.random.default_rng(7)

    def img(hh=h, ww=w, c=3):
        return rng.uniform(0, 255, size=(hh, ww, c)).astype(np.float32)

    good = img()
    nan_img = img()
    nan_img[0, 0, 0] = np.nan
    inf_img = img()
    inf_img[-1, -1, -1] = np.inf
    pairs: Dict[str, Tuple] = {
        "nan_pixels": (nan_img, img()),
        "inf_pixels": (good, inf_img),
        "five_channel": (img(c=5), img(c=5)),
        "zero_area": (img(hh=0), img(hh=0)),
        "mismatched_shapes": (img(), img(ww=w + 4)),
        "wrong_rank": (rng.uniform(0, 255, size=(h, w)).astype(np.float32),) * 2,
        "not_an_array": ([[1.0, 2.0], [3.0, 4.0]], good),
    }
    if oversize_pixels is not None:
        side = int(np.ceil(np.sqrt(oversize_pixels))) + 1
        pairs["oversized"] = (img(hh=side, ww=side), img(hh=side, ww=side))
    return pairs

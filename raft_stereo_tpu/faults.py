"""Deterministic fault injection for the reliability layer (DESIGN.md
"Failure recovery").

Long schedules (100k+ steps, SURVEY §5) meet every failure domain
eventually: unreadable/corrupt samples, non-finite steps, truncated
checkpoints, preemption. Each recovery path in ``data/loader.py``,
``engine/train.py`` and ``engine/checkpoint.py`` is proven under test by
the injectors here. Everything is driven by an explicit :class:`FaultPlan`
value — no environment-variable side channels, no wall-clock, no global
state — so an injected fault fires at exactly the same sample/step/byte on
every run, every host, every worker-thread schedule.

The four injectors map one-to-one onto the recovery paths:

- ``io_errors``      -> loader retry + quarantine + deterministic substitution;
- ``nan_at_steps``   -> ``optax.apply_if_finite`` skip policy + bounded abort;
- ``truncate_file``  -> checkpoint hash validation + ``find_latest_checkpoint``
  fallback to the previous good bundle;
- ``sigterm_at_step``-> ``PreemptGuard`` checkpoint-and-exit + schedule-exact
  resume.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
from typing import Dict, Iterable, Iterator, Mapping, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Declarative fault schedule; all coordinates are deterministic keys.

    io_errors: dataset index -> number of injected load failures for that
        sample (``-1`` = fail every attempt, i.e. permanently corrupt).
        Counted per *attempt*, so a budget of 1 models a transient fault
        that succeeds on the loader's first retry.
    nan_at_steps: global step numbers whose batch is NaN-poisoned before
        the compiled step (exercises the skip-if-nonfinite policy).
    sigterm_at_step: deliver SIGTERM to this process at that step boundary
        (exercises the PreemptGuard checkpoint-and-exit path).
    """

    io_errors: Mapping[int, int] = dataclasses.field(default_factory=dict)
    nan_at_steps: Tuple[int, ...] = ()
    sigterm_at_step: Optional[int] = None


class FaultyDataset:
    """Dataset wrapper raising injected IO errors per :class:`FaultPlan`.

    Attempt counts are per dataset index and lock-protected: the loader's
    thread pool may probe the same quarantined index concurrently, and a
    lost increment would turn a configured-transient fault permanent.
    """

    def __init__(self, dataset, plan: FaultPlan):
        self.dataset = dataset
        self.plan = plan
        self.attempts: Dict[int, int] = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.dataset)

    def __getitem__(self, index, rng=None):
        budget = self.plan.io_errors.get(int(index))
        if budget is not None:
            with self._lock:
                n = self.attempts.get(int(index), 0)
                self.attempts[int(index)] = n + 1
            if budget < 0 or n < budget:
                raise OSError(
                    f"injected IO fault for sample {index} (attempt {n + 1})")
        return self.dataset.__getitem__(index, rng=rng)


def poisoned_batches(batches: Iterable, plan: Optional[FaultPlan],
                     start_step: int = 0) -> Iterator:
    """Yield host batches, NaN-poisoning those for steps in ``nan_at_steps``.

    Applied to the *host* loader before ``device_prefetch`` so the poison
    rides the normal transfer path (including the bf16 image downcast,
    which preserves NaN). Batch ``i`` of this iterator feeds global step
    ``start_step + i`` — prefetch depth does not change that mapping, only
    when the decode happens.
    """
    for i, batch in enumerate(batches):
        if plan is not None and (start_step + i) in plan.nan_at_steps:
            batch = dict(batch)
            img = np.array(batch["image1"], copy=True)
            img[(0,) * img.ndim] = np.nan
            batch["image1"] = img
        yield batch


def fire_step_faults(plan: Optional[FaultPlan], step: int) -> None:
    """Step-boundary injections (currently: SIGTERM at a configured step)."""
    if plan is not None and plan.sigterm_at_step == step:
        os.kill(os.getpid(), signal.SIGTERM)


def truncate_file(path: str, keep_bytes: Optional[int] = None,
                  keep_frac: float = 0.5) -> int:
    """Truncate ``path`` (default: to half its size), modeling a checkpoint
    write cut off by a crash that bypassed the atomic-rename path (partial
    NFS flush, disk-full copy, ...). Returns the retained byte count."""
    size = os.path.getsize(path)
    keep = int(size * keep_frac) if keep_bytes is None else keep_bytes
    keep = max(0, min(size, keep))
    with open(path, "rb+") as f:
        f.truncate(keep)
    return keep

"""Mesh construction and sharding helpers.

Axes:
- ``data``  — batch data parallelism (the reference's DataParallel equivalent,
  ``train_stereo.py:134``);
- ``space`` — intra-sample sharding along image height H. Correlation rows are
  independent (the 1D corr volume ``(B, H, W1, W2)`` and its lookup partition
  trivially along H), and XLA's SPMD partitioner inserts the halo exchanges
  the convolutions need — so one sharding annotation scales full-resolution
  eval (Middlebury-F) past a single chip's HBM. This is the framework's
  sequence/context-parallel analog: the "sequence" is the epipolar scanline
  grid (SURVEY §5 long-context).

Multi-host: call ``maybe_distributed_init()`` before device queries; mesh axes
are laid out so ``space`` stays inside the ICI domain (halo exchanges and
volume traffic ride ICI) and ``data`` spans hosts over DCN.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def maybe_distributed_init() -> None:
    """Initialize jax.distributed when launched multi-host (no-op otherwise).

    Opt-in via ``COORDINATOR_ADDRESS``. On cloud TPU pods the remaining
    topology is auto-detected; manual launchers (including the 2-process CPU
    distributed test, ``tests/test_multihost.py``) pass ``PROCESS_ID`` and
    ``NUM_PROCESSES`` explicitly.
    """
    addr = os.environ.get("COORDINATOR_ADDRESS")
    if not addr:
        return
    pid, num = os.environ.get("PROCESS_ID"), os.environ.get("NUM_PROCESSES")
    if (pid is None) != (num is None):
        raise RuntimeError(
            "PROCESS_ID and NUM_PROCESSES must be set together (manual "
            "multi-host launch needs COORDINATOR_ADDRESS, PROCESS_ID and "
            f"NUM_PROCESSES); got PROCESS_ID={pid!r} NUM_PROCESSES={num!r}")
    if pid is not None:
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=int(num),
                                   process_id=int(pid))
    else:
        jax.distributed.initialize()


def validate_spatial_shard(n_space: int, n_devices: int,
                           local_devices: Optional[int] = None) -> None:
    """Shared checks for the ``space`` (height) axis extent.

    Raises ValueError (CLIs turn it into their exit style). The /32 rule:
    every input is padded to a /32-multiple height (train crops and eval
    padding alike), so a shard count dividing 32 shards every feature scale
    evenly. ``local_devices`` (multi-host): the space axis must fit within
    one process's devices so halo exchanges and corr-volume traffic ride
    ICI, not DCN (the layout invariant this module's docstring promises).
    """
    if n_space <= 1:
        return
    if n_devices % n_space:
        raise ValueError(
            f"spatial_shard {n_space} does not divide the "
            f"{n_devices} available device(s)")
    if 32 % n_space:
        raise ValueError(
            f"spatial_shard {n_space} must divide 32 so every /32-multiple "
            "input height shards evenly at all scales")
    if local_devices is not None and local_devices % n_space:
        raise ValueError(
            f"spatial_shard {n_space} must divide the {local_devices} "
            "devices local to each host, or the space axis would span "
            "hosts and its halo/volume traffic would ride DCN instead of "
            "ICI")


def make_mesh(n_data: Optional[int] = None, n_space: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // n_space
    use = n_data * n_space
    dev_array = np.asarray(devices[:use]).reshape(n_data, n_space)
    return Mesh(dev_array, axis_names=("data", "space"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis (batch) sharding for NHWC arrays."""
    return NamedSharding(mesh, P("data"))


def spatial_sharding(mesh: Mesh) -> NamedSharding:
    """Batch over ``data`` and image height over ``space`` (NHWC axis 1)."""
    return NamedSharding(mesh, P("data", "space"))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """The batch-input sharding for this mesh: batch over ``data``, plus H
    over ``space`` when that axis is real (>1). Correlation rows are
    independent along H and XLA inserts conv halo exchanges, so the corr
    volume — the memory hog — is split 1/n_space per device (the
    full-resolution eval enabler; SURVEY §5 long-context)."""
    if mesh.shape.get("space", 1) > 1:
        return spatial_sharding(mesh)
    return batch_sharding(mesh)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_config_overrides(cfg, mesh: Optional[Mesh]) -> dict:
    """Config overrides required to run ``cfg`` under ``mesh`` — none,
    since r4. Every Pallas kernel now has an SPMD story: the correlation
    kernels carry a custom_partitioning row rule
    (``corr/pallas_reg.py``), the streaming scan-body kernels partition
    along batch and run halo-exchange shard_map variants under a real
    ``space`` axis (``ops/pallas_stream.py``), and the full-resolution
    encoder kernels — whose global instance-norm stats and full-H row
    streams genuinely cannot cut — are gated off per-trace via the
    ``space_mesh`` argument to ``raft_stereo_forward``, not by config
    mutation. Kept (returning {}) as the single place a future
    kernel-vs-mesh incompatibility would live, and because the CLIs call
    ``mesh_safe_cfg`` unconditionally."""
    return {}


def space_mesh_of(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """``mesh`` when it has a real (>1) ``space`` axis, else None — the
    single gate every engine passes to ``raft_stereo_forward`` as
    ``space_mesh``."""
    if mesh is not None and mesh.shape.get("space", 1) > 1:
        return mesh
    return None


def mesh_safe_cfg(cfg, mesh: Optional[Mesh], **extra):
    """``cfg`` with ``mesh_config_overrides`` (+ any ``extra`` overrides)
    applied; returns the same config class, or ``cfg`` itself unchanged."""
    ov = {**mesh_config_overrides(cfg, mesh), **extra}
    return cfg if not ov else type(cfg)(**{**cfg.__dict__, **ov})


def local_batch_rows(mesh: Mesh, batch_size: int) -> Optional[slice]:
    """Rows of the global batch whose shards live on THIS process's devices.

    The pod input pipeline decodes only these rows (the reference runs one
    DataLoader per process, ``core/stereo_datasets.py:311-312``; a pod
    where every host decodes the global batch turns the input pipeline
    into the bottleneck at scale). Returns None when the assignment is not
    a contiguous row range (unusual topology) — callers then fall back to
    decoding everything, which is correct but redundant.
    """
    n_data = mesh.shape.get("data", 1)
    if batch_size % n_data:
        return None
    rows_per = batch_size // n_data
    pidx = jax.process_index()
    mine = sorted({int(i) for i in range(mesh.devices.shape[0])
                   if any(d.process_index == pidx
                          for d in np.atleast_1d(mesh.devices[i]).flat)})
    if not mine:
        return None
    if mine != list(range(mine[0], mine[-1] + 1)):
        return None
    return slice(mine[0] * rows_per, (mine[-1] + 1) * rows_per)


def shard_batch(batch, mesh: Mesh, spatial: Optional[bool] = None):
    """Device-put a pytree of batch-leading arrays onto the mesh.

    By default the sharding follows ``data_sharding`` (H sharded over
    ``space`` whenever the mesh has that axis); pass ``spatial`` to force.
    """
    if spatial is None:
        sharding = data_sharding(mesh)
    else:
        sharding = spatial_sharding(mesh) if spatial else batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)

"""Mesh construction and sharding helpers.

Axes:
- ``data``  — batch data parallelism (the reference's DataParallel equivalent);
- ``width`` — optional intra-sample sharding of the correlation volume along
  image width for full-resolution eval (each output row/column block is
  independent; collectives only at the einsum boundary).

Multi-host: call ``maybe_distributed_init()`` before device queries; mesh axes
are laid out so ``data`` spans hosts (DCN) last and ``width`` stays inside the
ICI domain.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def maybe_distributed_init() -> None:
    """Initialize jax.distributed when launched multi-host (no-op otherwise)."""
    if os.environ.get("COORDINATOR_ADDRESS"):
        jax.distributed.initialize()


def make_mesh(n_data: Optional[int] = None, n_width: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // n_width
    use = n_data * n_width
    dev_array = np.asarray(devices[:use]).reshape(n_data, n_width)
    return Mesh(dev_array, axis_names=("data", "width"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis (batch) sharding for NHWC arrays."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Device-put a pytree of batch-leading arrays with batch sharded on 'data'."""
    sharding = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)

"""``alt`` correlation: on-the-fly lookup, no materialized W^2 volume.

Reference ``PytorchAlternateCorrBlock1D`` (``core/corr.py:64-107``): per level,
sample ``2r+1`` feature vectors from (width-pooled) fmap2 around the current
coordinate and dot them with fmap1. This is the memory-efficient path for
full-resolution inputs — the reference's "long-context" strategy (recompute
instead of materialize, ``README.md:121``).

Equivalence note: pooling fmap2 then dotting equals pooling the precomputed
volume (the dot is linear), so ``alt`` matches ``reg`` bit-for-bit up to
floating-point association — property-tested in ``tests/test_corr.py``.

Memory per lookup: O(B * H * W * (2r+1) * D) — linear in W.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from raft_stereo_tpu.ops.chunked import map_chunked
from raft_stereo_tpu.ops.pooling import avg_pool_w2
from raft_stereo_tpu.ops.sampler import sample_rows_zeros


def make_alt_corr_fn(fmap1: jax.Array, fmap2: jax.Array, *,
                     out_dtype=None,
                     num_levels: int, radius: int):
    f1 = fmap1.astype(jnp.float32)
    pyramid2 = [fmap2.astype(jnp.float32)]
    for _ in range(num_levels - 1):
        pyramid2.append(avg_pool_w2(pyramid2[-1]))
    d = fmap1.shape[-1]
    scale = 1.0 / math.sqrt(d)
    dx = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    k = 2 * radius + 1

    def row_lookup(args):
        """Per-H-chunk lookup; keeps the one-hot weight tensors bounded."""
        f1_c, coords_c, *pyr_c = args
        out = []
        for i, f2 in enumerate(pyr_c):
            xs = coords_c.astype(jnp.float32)[..., None] / (2 ** i) + dx
            b, hc, w1 = coords_c.shape
            sampled = sample_rows_zeros(f2, xs.reshape(b, hc, w1 * k))
            sampled = sampled.reshape(b, hc, w1, k, d)
            out.append(jnp.einsum("bhwkd,bhwd->bhwk", sampled, f1_c) * scale)
        return jnp.concatenate(out, axis=-1)

    def corr_fn(coords_x: jax.Array, h_chunk: int = 32) -> jax.Array:
        # Map over H chunks: peak memory O(chunk * W1 * (2r+1) * W2) for the
        # one-hot sampling weights instead of O(H * ...) — the point of `alt`.
        out = map_chunked(row_lookup, (f1, coords_x, *pyramid2),
                          chunk=h_chunk, axis=1)
        return out if out_dtype is None else out.astype(out_dtype)

    return corr_fn

"""``reg_tpu``: the reg correlation lookup as a Pallas TPU kernel.

TPU-native analog of the reference's only native component, the CUDA
``corr_sampler`` extension (``sampler/sampler_kernel.cu:20-105`` forward,
``:63-105`` backward; pybind binding ``sampler/sampler.cpp:48-51``): per
output pixel, read the pyramid row ``volume[b, h, w1, :]`` and linearly
interpolate ``2r+2`` integer taps into ``2r+1`` outputs per level, with
out-of-range taps contributing zero.

Kernel design (how a gather maps onto a machine with no per-lane dynamic
addressing):

- Mosaic's one dynamic-gather primitive is ``take_along_axis`` along the
  lane axis of a single vreg — the index and operand must both be
  ``(sublanes, 128)``. The ``2r+2`` taps of one pixel are *contiguous*
  integers, so the whole tap window fits in one 128-lane vreg.
- Per pixel: (1) **coarse align** — select the two vreg-aligned 128-lane
  slabs of the volume row that bracket the tap window ``[i0-r, i0+r+1]``
  (the window may straddle a slab boundary, so both the slab containing
  the first tap and its successor are selected). Each selection is an
  unrolled select-scan over the row's ``W2p/128`` aligned slabs: ~2 VPU
  ops per volume element per scan, versus ~3 ops *per tap* per element
  for the one-hot fallback — an order of magnitude less VPU work.
  (2) **fine gather** — one ``take_along_axis`` per slab with the
  window-relative lane index, then a per-tap select by whether the tap
  falls in the first or second slab, leaving tap ``t`` at lane ``t``.
  (3) mask out-of-range taps to zero (``grid_sample`` zero-padding
  semantics), lerp adjacent lanes.
- Grid is over flattened pixel tiles ``(B*H*W1) / TILE``; pyramid levels
  stream HBM->VMEM via BlockSpec pipelining. Output rows are pixels, so
  partial boundary tiles are safe: garbage rows never contaminate real
  rows (the gather is row-local) and are sliced off at the end.

Width padding: fmap2 is zero-padded to a 128-multiple *before* the
volume einsum, so no post-hoc volume copy is needed; per-level true
widths (successive floor halving of the original W2) bound the tap mask,
which also hides the pooled-boundary artifact when a level width is odd.

Precision: the pyramid is stored in the feature-map dtype (bf16 under the
mixed-precision policy — the analog of the reference's fp16-capable CUDA
sampler, ``sampler_kernel.cu:126``) and upcast to fp32 inside the kernel,
so lerp arithmetic is fp32 and volume HBM traffic — the lookup's cost —
is halved. The fp32 path stores fp32 and is exact.

Backward (training): ``custom_vjp`` — gradient flows to the volume only,
none to coords, exactly like the CUDA sampler (``core/corr.py:24-29``
returns ``None`` for the coords grad; coords are detached upstream each
GRU iteration anyway). The volume-grad scatter is the transpose of a
gather — irregular writes that do not map to TPU vector memory — so the
backward runs the *masked one-hot* formulation in plain XLA (regular
VPU/MXU work in both directions), numerically identical to the kernel.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_stereo_tpu.corr.reg import build_pyramid

LANE = 128
TILE = 512  # pixels per grid cell (swept 128-1024 on v5e: 512 best by ~1%)


def _interpret() -> bool:
    """Compiled Mosaic on TPU; interpreter everywhere else (CPU tests)."""
    return jax.default_backend() not in ("tpu",)


def pad_width(w: int) -> int:
    """Smallest vreg-width (128) multiple >= w."""
    return -(-w // LANE) * LANE


def gather_lerp_taps(vol, cl, radius: int, w2: int):
    """Windowed-gather + lerp over one level's rows held in VMEM/registers.

    vol: (P, W2p) rows, any float dtype (the selects/gathers run in the
    storage dtype — half the vreg traffic for bf16 rows — and the gathered
    taps are upcast so the lerp arithmetic is always fp32); cl: (P, 1)
    fp32 level-scaled positions. Returns (P, 2r+1) fp32 lerped taps with
    zero-pad semantics. Shared by the reg_tpu (volume-resident) and
    alt_tpu (fused on-the-fly) kernels.
    """
    p, w2p = vol.shape
    if w2p % LANE:
        # Lane-pad to a vreg multiple in VMEM (callers with HBM-resident
        # rows pre-pad instead; in-kernel pooled rows land here).
        vol = jnp.concatenate(
            [vol, jnp.zeros((p, LANE - w2p % LANE), vol.dtype)], axis=-1)
        w2p = vol.shape[-1]
    k = 2 * radius + 1
    lane = jax.lax.broadcasted_iota(jnp.int32, (p, LANE), 1)
    i0 = jnp.floor(cl)
    frac = cl - i0  # (P, 1)
    base = i0.astype(jnp.int32) - radius  # first tap position
    xpos = base + lane  # true tap position in the row
    if w2p > LANE:
        # Coarse: select the two vreg-aligned 128-lane slabs bracketing the
        # tap window (select-scans over aligned slices only — no cross-vreg
        # relayouts; ~2 VPU ops per element per scan, once per level).
        nslab = w2p // LANE
        slab = jnp.clip(base // LANE, 0, nslab - 1)
        slab_b = jnp.minimum(slab + 1, nslab - 1)
        win_a = vol[:, 0:LANE]
        win_b = vol[:, (nslab - 1) * LANE:]
        for s in range(1, nslab):
            win_a = jnp.where(slab == s, vol[:, s * LANE:(s + 1) * LANE],
                              win_a)
        for s in range(1, nslab - 1):
            win_b = jnp.where(slab_b == s, vol[:, s * LANE:(s + 1) * LANE],
                              win_b)
        # Fine: Mosaic's take_along_axis works on exactly one 128-lane vreg;
        # the 2r+2-tap window may straddle the slab boundary, so gather both
        # slabs and select per tap. Lane t then holds tap t. The gather
        # operands upcast to fp32 HERE — Mosaic's dynamic_gather requires
        # the index and result bitwidths to match (i32 indices), so only
        # the two selected slabs pay the conversion, not the whole row.
        rel = base - slab * LANE + lane  # [0, 128+2r+1] when in range
        g_a = jnp.take_along_axis(win_a.astype(jnp.float32),
                                  jnp.clip(rel, 0, LANE - 1), axis=-1)
        g_b = jnp.take_along_axis(win_b.astype(jnp.float32),
                                  jnp.clip(rel - LANE, 0, LANE - 1), axis=-1)
        g = jnp.where(rel < LANE, g_a, g_b)
        # rel >= 128 with slab_b == slab reads the wrong slab, but then
        # xpos >= w2p >= w2, so the bounds mask below zeroes it.
    else:
        g = jnp.take_along_axis(vol.astype(jnp.float32),
                                jnp.clip(xpos, 0, LANE - 1), axis=-1)
    g = jnp.where((xpos >= 0) & (xpos < w2), g, 0.0)
    return g[:, :k] * (1.0 - frac) + g[:, 1:k + 1] * frac


def _lookup_kernel(coords_ref, *refs, radius: int, widths: Sequence[int]):
    *vol_refs, out_ref = refs
    k = 2 * radius + 1
    c = coords_ref[:]  # (TILE, 1) fp32
    for lvl, vol_ref in enumerate(vol_refs):
        cl = c * (1.0 / (1 << lvl))
        out_ref[:, lvl * k:(lvl + 1) * k] = gather_lerp_taps(
            vol_ref[:], cl, radius, widths[lvl]).astype(out_ref.dtype)


def _pallas_lookup(pyramid: Sequence[jax.Array], coords_flat: jax.Array,
                   radius: int, widths: Tuple[int, ...],
                   out_dtype) -> jax.Array:
    """pyramid: list of (N, W2p_l) fp32; coords_flat: (N, 1) fp32."""
    n = coords_flat.shape[0]
    k = 2 * radius + 1
    out_ch = len(pyramid) * k
    grid = pl.cdiv(n, TILE)
    kernel = functools.partial(_lookup_kernel, radius=radius, widths=widths)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, out_ch), out_dtype),
        grid=(grid,),
        in_specs=[pl.BlockSpec((TILE, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)] +
                 [pl.BlockSpec((TILE, p.shape[-1]), lambda i: (i, 0),
                               memory_space=pltpu.VMEM) for p in pyramid],
        out_specs=pl.BlockSpec((TILE, out_ch), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(coords_flat, *pyramid)
    return out


def _masked_lookup_xla(pyramid: Sequence[jax.Array], coords_flat: jax.Array,
                       radius: int, widths: Tuple[int, ...]) -> jax.Array:
    """One-hot-reduce lookup over *padded* rows with true-width masking.

    Matches the kernel bit-for-bit in exact arithmetic; exists as (a) the
    custom_vjp backward (its VJP is regular VPU/MXU work — scatters don't
    vectorize on TPU) and (b) an oracle for the kernel tests.
    """
    out = []
    for lvl, vol in enumerate(pyramid):
        w2p = vol.shape[-1]
        w2 = widths[lvl]
        cl = coords_flat * (1.0 / (1 << lvl))
        i0 = jnp.floor(cl)
        frac = cl - i0
        base = i0 - radius
        j = jnp.arange(w2p, dtype=jnp.float32)
        valid_j = j < w2
        vol32 = vol.astype(jnp.float32)  # match the kernel's fp32 lerp
        taps = []
        for t in range(2 * radius + 2):
            onehot = ((j == base + t) & valid_j).astype(jnp.float32)
            taps.append(jnp.sum(vol32 * onehot, axis=-1))
        g = jnp.stack(taps, axis=-1)  # (N, 2r+2)
        out.append(g[:, :-1] * (1.0 - frac) + g[:, 1:] * frac)
    return jnp.concatenate(out, axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _lookup(pyramid: List[jax.Array], coords_flat: jax.Array,
            radius: int, widths: Tuple[int, ...],
            out_dtype=jnp.float32) -> jax.Array:
    return _pallas_lookup(pyramid, coords_flat, radius, widths, out_dtype)


def _lookup_fwd(pyramid, coords_flat, radius, widths, out_dtype):
    return (_lookup(pyramid, coords_flat, radius, widths, out_dtype),
            (pyramid, coords_flat))


def _lookup_bwd(radius, widths, out_dtype, residuals, g):
    pyramid, coords_flat = residuals
    _, vjp = jax.vjp(
        lambda p: _masked_lookup_xla(p, coords_flat, radius, widths), pyramid)
    # The oracle emits fp32; a bf16-out kernel hands back a bf16 cotangent.
    (d_pyramid,) = vjp(g.astype(jnp.float32))
    return d_pyramid, jnp.zeros_like(coords_flat)


_lookup.defvjp(_lookup_fwd, _lookup_bwd)


def level_widths(w2: int, num_levels: int) -> Tuple[int, ...]:
    """True (unpadded) per-level widths: successive floor halving."""
    ws = [w2]
    for _ in range(num_levels - 1):
        ws.append(ws[-1] // 2)
    return tuple(ws)


def make_reg_tpu_corr_fn(fmap1: jax.Array, fmap2: jax.Array, *,
                         num_levels: int, radius: int, out_dtype=None):
    out_dtype = jnp.float32 if out_dtype is None else out_dtype
    b, h, w1, _ = fmap1.shape
    w2 = fmap2.shape[2]
    widths = level_widths(w2, num_levels)
    # Zero-pad fmap2's width before the einsum: the padded volume region is
    # exactly zero, so no post-hoc volume copy; deeper levels whose pooled
    # width falls under one vreg get a (cheap) per-level re-pad. The pyramid
    # is stored in the fmap dtype (bf16 under mixed precision — halves the
    # lookup's HBM traffic; the kernel upcasts rows to fp32 for the lerp).
    f2p = jnp.pad(fmap2, ((0, 0), (0, 0), (0, pad_width(w2) - w2), (0, 0)))
    # The einsum runs — and emits — the fmap dtype (the MXU accumulates
    # fp32 within the single K=256 pass regardless): upcasting the inputs
    # (build_volume) would materialize a full fp32 volume (2.1 GB at
    # Middlebury-F) before the downcast, and requesting an fp32 output
    # type breaks the autodiff transpose for bf16 operands. Identical when
    # fmaps are fp32.
    d = fmap1.shape[-1]
    vol = jnp.einsum("bhid,bhjd->bhij", fmap1, f2p) * (1.0 / d ** 0.5)
    pyramid = build_pyramid(vol, num_levels)
    flat = []
    for lvl, vol in enumerate(pyramid):
        wp = vol.shape[-1]
        want = pad_width(widths[lvl])
        if wp < want:
            vol = jnp.pad(vol, ((0, 0), (0, 0), (0, 0), (0, want - wp)))
        elif wp > want:
            vol = vol[..., :want]
        flat.append(vol.reshape(b * h * w1, -1))

    def corr_fn(coords_x: jax.Array) -> jax.Array:
        n = b * h * w1
        coords_flat = coords_x.astype(jnp.float32).reshape(n, 1)
        out = _lookup(flat, coords_flat, radius, widths, out_dtype)
        return out.reshape(b, h, w1, -1)

    return corr_fn

"""``reg_tpu``: the reg correlation lookup as a Pallas TPU kernel.

TPU-native analog of the reference's only native component, the CUDA
``corr_sampler`` extension (``sampler/sampler_kernel.cu:20-105`` forward,
``:63-105`` backward; pybind binding ``sampler/sampler.cpp:48-51``): per
output pixel, read the pyramid row ``volume[b, h, w1, :]`` and linearly
interpolate ``2r+2`` integer taps into ``2r+1`` outputs per level, with
out-of-range taps contributing zero.

Kernel design (how a gather maps onto a machine with no per-lane dynamic
addressing):

- Mosaic's one dynamic-gather primitive is ``take_along_axis`` along the
  lane axis of a single vreg — the index and operand must both be
  ``(sublanes, 128)``. The ``2r+2`` taps of one pixel are *contiguous*
  integers, so the whole tap window fits in one 128-lane vreg.
- Per pixel: (1) **coarse align** — select the two vreg-aligned 128-lane
  slabs of the volume row that bracket the tap window ``[i0-r, i0+r+1]``
  (the window may straddle a slab boundary, so both the slab containing
  the first tap and its successor are selected). Each selection is an
  unrolled select-scan over the row's ``W2p/128`` aligned slabs: ~2 VPU
  ops per volume element per scan, versus ~3 ops *per tap* per element
  for the one-hot fallback — an order of magnitude less VPU work.
  (2) **fine gather** — one ``take_along_axis`` per slab with the
  window-relative lane index, then a per-tap select by whether the tap
  falls in the first or second slab, leaving tap ``t`` at lane ``t``.
  (3) mask out-of-range taps to zero (``grid_sample`` zero-padding
  semantics), lerp adjacent lanes.
- Grid is over flattened pixel tiles ``(B*H*W1) / TILE``; pyramid levels
  stream HBM->VMEM via BlockSpec pipelining. Output rows are pixels, so
  partial boundary tiles are safe: garbage rows never contaminate real
  rows (the gather is row-local) and are sliced off at the end.

Width padding: fmap2 is zero-padded to a 128-multiple *before* the
volume einsum, so no post-hoc volume copy is needed; per-level true
widths (successive floor halving of the original W2) bound the tap mask,
which also hides the pooled-boundary artifact when a level width is odd.

Packing (bf16): levels pair-pack two taps per 32-bit lane so the gather
needs no upcast pass and the align scan walks half the lanes. A level
whose 128-aligned row is an EVEN number of 128-blocks packs standalone
(container rows are whole vregs at the same byte count); the odd-block
levels — whose standalone containers would pad half a vreg of dead DMA
per row (r5: +17% pyramid traffic at Middlebury-F) — pair up instead:
the widest odd-block level hosts a combined container whose last 64
lanes carry the deepest level's packed rows (``pack_plan``). Total DMA
equals the unpacked layout exactly, every level runs the packed gather,
and the kernel reads one fewer operand. Reads that land in the other
level's lanes (a tap window straddling past a true width) are zeroed by
the same true-width bounds mask that hides stale-slab reads.

Precision: the pyramid is stored in the feature-map dtype (bf16 under the
mixed-precision policy — the analog of the reference's fp16-capable CUDA
sampler, ``sampler_kernel.cu:126``) and upcast to fp32 inside the kernel,
so lerp arithmetic is fp32 and volume HBM traffic — the lookup's cost —
is halved. The fp32 path stores fp32 and is exact.

Backward (training): ``custom_vjp`` — gradient flows to the volume only,
none to coords, exactly like the CUDA sampler (``core/corr.py:24-29``
returns ``None`` for the coords grad; coords are detached upstream each
GRU iteration anyway). The volume-grad scatter is the transpose of a
gather — irregular writes that do not map to TPU vector memory — so the
backward runs the *masked one-hot* formulation in plain XLA (regular
VPU/MXU work in both directions), numerically identical to the kernel.
"""

from __future__ import annotations

import functools
import os
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_stereo_tpu.ops.jax_compat import compiler_params

from raft_stereo_tpu.ops.pooling import avg_pool_last

LANE = 128
# Pixels per grid cell. r3 swept 128-1024 and settled on 512; r4's
# per-step fixed-cost measurement (~5-10 us/step on the remote v5e —
# 732 steps/lookup ~= 4.4 ms against a ~1.4 ms DMA roofline) says the
# step COUNT was the real cost: 2048 cuts it 4x for ~11 MB more VMEM.
# Env override for sweeps (scratch/sweep_tile.py); r5 sweep table in
# BASELINE.md.
_TILE_DEFAULT = 2048


def corr_tile() -> int:
    """Pixels per grid cell, read from ``RAFT_CORR_TILE`` when each corr fn
    is built (i.e. at trace time — the lookup cache is keyed by the tile, so
    sweeps in one process get the tile they set; programs already compiled
    keep the tile they were traced with)."""
    return int(os.environ.get("RAFT_CORR_TILE", _TILE_DEFAULT))


def _interpret() -> bool:
    """Compiled Mosaic on TPU; interpreter everywhere else (CPU tests)."""
    return jax.default_backend() not in ("tpu",)


def pad_width(w: int, align: int = LANE) -> int:
    """Smallest ``align`` (vreg-width 128 by default) multiple >= w."""
    return -(-w // align) * align


def gather_lerp_taps(vol, cl, radius: int, w2: int):
    """Windowed-gather + lerp over one level's rows held in VMEM/registers.

    vol: (P, W2p) rows, any float dtype (the selects/gathers run in the
    storage dtype — half the vreg traffic for bf16 rows — and the gathered
    taps are upcast so the lerp arithmetic is always fp32); cl: (P, 1)
    fp32 level-scaled positions. Returns (P, 2r+1) fp32 lerped taps with
    zero-pad semantics. Shared by the reg_tpu (volume-resident) and
    alt_tpu (fused on-the-fly) kernels.
    """
    p, w2p = vol.shape
    if w2p % LANE:
        # Lane-pad to a vreg multiple in VMEM (callers with HBM-resident
        # rows pre-pad instead; in-kernel pooled rows land here).
        vol = jnp.concatenate(
            [vol, jnp.zeros((p, LANE - w2p % LANE), vol.dtype)], axis=-1)
        w2p = vol.shape[-1]
    k = 2 * radius + 1
    lane = jax.lax.broadcasted_iota(jnp.int32, (p, LANE), 1)
    i0 = jnp.floor(cl)
    frac = cl - i0  # (P, 1)
    base = i0.astype(jnp.int32) - radius  # first tap position
    xpos = base + lane  # true tap position in the row
    if w2p > LANE:
        # Coarse: select the two vreg-aligned 128-lane slabs bracketing the
        # tap window (select-scans over aligned slices only — no cross-vreg
        # relayouts). ONE merged pass: slab s feeds win_a where slab==s and
        # win_b where slab==s-1, so each slab is read once.
        nslab = w2p // LANE
        slab = jnp.clip(base // LANE, 0, nslab - 1)
        win_a = vol[:, 0:LANE]
        win_b = vol[:, LANE:2 * LANE]
        for s in range(1, nslab):
            sl = vol[:, s * LANE:(s + 1) * LANE]
            win_a = jnp.where(slab == s, sl, win_a)
            if s >= 2:
                win_b = jnp.where(slab == s - 1, sl, win_b)
        # slab == nslab-1 leaves win_b stale; any rel >= LANE there implies
        # xpos >= w2p >= w2, zeroed by the bounds mask below.
        # Fine: Mosaic's take_along_axis works on exactly one 128-lane vreg
        # AND only in 32-bit (index/result bitwidths must match, indices
        # are i32 — a bf16 gather was tried in r4 and rejected by Mosaic),
        # so the two selected slabs upcast here; the 2r+2-tap window may
        # straddle the slab boundary, so gather both slabs and select per
        # tap. Lane t then holds tap t.
        rel = base - slab * LANE + lane  # [0, 128+2r+1] when in range
        g_a = jnp.take_along_axis(win_a.astype(jnp.float32),
                                  jnp.clip(rel, 0, LANE - 1), axis=-1)
        g_b = jnp.take_along_axis(win_b.astype(jnp.float32),
                                  jnp.clip(rel - LANE, 0, LANE - 1), axis=-1)
        g = jnp.where(rel < LANE, g_a, g_b)
        # rel >= 128 with slab_b == slab reads the wrong slab, but then
        # xpos >= w2p >= w2, so the bounds mask below zeroes it.
    else:
        g = jnp.take_along_axis(vol.astype(jnp.float32),
                                jnp.clip(xpos, 0, LANE - 1), axis=-1)
    g = jnp.where((xpos >= 0) & (xpos < w2), g, 0.0)
    return g[:, :k] * (1.0 - frac) + g[:, 1:k + 1] * frac


def gather_lerp_taps_packed(vol, cl, radius: int, w2: int):
    """Pair-packed variant of ``gather_lerp_taps`` for bf16 pyramids.

    vol: (P, W2p/2) fp32-CONTAINER rows — each 32-bit lane carries the two
    bf16 taps at true positions (2j, 2j+1), low half = even position (XLA
    bitcast semantics: trailing-dim element 0 is the low-order bits).
    Why: Mosaic's ``take_along_axis`` is 32-bit-only, so the unpacked bf16
    path must upcast both selected slabs to fp32 *before* gathering; here
    the gather fetches two taps per lane with no conversion pass, the
    coarse align scans HALF the lanes, and the bf16->fp32 upcast becomes
    two bit-ops in-register (bf16 bits << 16 ARE the fp32 bits). The two
    deepest pyramid levels drop under one vreg and skip the align
    entirely. Numerically identical to the unpacked path (same fp32 lerp
    on the same bf16 tap values)."""
    p, w2p2 = vol.shape
    if w2p2 % LANE:
        vol = jnp.concatenate(
            [vol, jnp.zeros((p, LANE - w2p2 % LANE), vol.dtype)], axis=-1)
        w2p2 = vol.shape[-1]
    k = 2 * radius + 1
    vi = jax.lax.bitcast_convert_type(vol, jnp.int32)
    lane = jax.lax.broadcasted_iota(jnp.int32, (p, LANE), 1)
    i0 = jnp.floor(cl)
    frac = cl - i0  # (P, 1)
    base = i0.astype(jnp.int32) - radius  # first tap true position
    xpos = base + lane  # true tap position for out lane t
    pidx = xpos >> 1  # containing pair (arithmetic shift = floor)
    if w2p2 > LANE:
        nslab = w2p2 // LANE
        slab = jnp.clip((base >> 1) // LANE, 0, nslab - 1)
        # ONE merged pass: slab s feeds win_a where slab==s and win_b where
        # slab==s-1 (successor), so each slab is read once.
        win_a = vi[:, 0:LANE]
        win_b = vi[:, LANE:2 * LANE]
        for s in range(1, nslab):
            sl = vi[:, s * LANE:(s + 1) * LANE]
            win_a = jnp.where(slab == s, sl, win_a)
            if s >= 2:
                win_b = jnp.where(slab == s - 1, sl, win_b)
        # slab == nslab-1 leaves win_b stale, but any rel >= LANE there
        # implies xpos >= w2p >= w2 — zeroed by the bounds mask.
        rel = pidx - slab * LANE  # pair-relative lane index
        g_a = jnp.take_along_axis(win_a, jnp.clip(rel, 0, LANE - 1), axis=-1)
        g_b = jnp.take_along_axis(win_b, jnp.clip(rel - LANE, 0, LANE - 1),
                                  axis=-1)
        g = jnp.where(rel < LANE, g_a, g_b)
    else:
        g = jnp.take_along_axis(vi, jnp.clip(pidx, 0, LANE - 1), axis=-1)
    lo = jax.lax.bitcast_convert_type(g << 16, jnp.float32)
    hi = jax.lax.bitcast_convert_type(g & jnp.int32(-65536), jnp.float32)
    val = jnp.where((xpos & 1) == 0, lo, hi)
    val = jnp.where((xpos >= 0) & (xpos < w2), val, 0.0)
    return val[:, :k] * (1.0 - frac) + val[:, 1:k + 1] * frac


def gather_lerp_taps_packed_tail(vol, cl, radius: int, w2: int,
                                 lane_base: int):
    """Packed gather for a level riding in the TAIL lanes of a combined
    container operand (see the pairing rule in ``make_reg_tpu_corr_fn``).

    The level's packed rows occupy container lanes ``[lane_base,
    lane_base + pad_width(w2)/2)`` and must fit inside ONE 128-lane slab
    (``lane_base % LANE + pad_width(w2)//2 <= LANE`` — the builder
    asserts it), so the gather is a single ``take_along_axis`` on that
    slab with a static lane offset: no align scan at all, like the
    deepest levels of a standalone packed operand. Out-of-range taps
    (including clipped indices that land in the OTHER level's lanes)
    are zeroed by the true-width bounds mask, exactly like the stale-
    slab reads of the standalone walk."""
    k = 2 * radius + 1
    vi = jax.lax.bitcast_convert_type(vol, jnp.int32)
    lane = jax.lax.broadcasted_iota(jnp.int32, (vol.shape[0], LANE), 1)
    i0 = jnp.floor(cl)
    frac = cl - i0  # (P, 1)
    base = i0.astype(jnp.int32) - radius  # first tap true position
    xpos = base + lane  # true tap position for out lane t
    pidx = xpos >> 1  # containing pair (arithmetic shift = floor)
    sb, off = lane_base // LANE, lane_base % LANE
    slab = vi[:, sb * LANE:(sb + 1) * LANE]
    g = jnp.take_along_axis(slab, jnp.clip(off + pidx, 0, LANE - 1),
                            axis=-1)
    lo = jax.lax.bitcast_convert_type(g << 16, jnp.float32)
    hi = jax.lax.bitcast_convert_type(g & jnp.int32(-65536), jnp.float32)
    val = jnp.where((xpos & 1) == 0, lo, hi)
    val = jnp.where((xpos >= 0) & (xpos < w2), val, 0.0)
    return val[:, :k] * (1.0 - frac) + val[:, 1:k + 1] * frac


def gather_lerp_taps_packed8(vi, cl, radius: int, w2: int, lane_base: int,
                             scale):
    """Quad-packed int8 gather for one level riding lanes ``[lane_base,
    lane_base + pad_width(w2)/4)`` of the combined int8 container.

    vi: (P, C) int32 view of the container (the caller bitcasts ONCE);
    cl: (P, 1) fp32 level-scaled positions; scale: (P, 1) fp32 per-level
    dequant scale (a per-level scalar broadcast onto the coords operand —
    see ``make_reg_tpu_corr_fn``). Each 32-bit lane carries the four int8
    taps at true positions (4j..4j+3), byte 0 = lowest position (XLA
    bitcast semantics). The align walk is the packed gather's merged
    select-scan with the level's static lane offset folded in; byte
    extraction is two arithmetic shifts (sign-extending), selected per
    lane by ``xpos & 3``. Out-of-range taps — including clipped reads
    landing in another level's lanes — are zeroed by the true-width
    bounds mask before the (linear) dequant+lerp, so zero-pad semantics
    survive quantization exactly (symmetric scheme: q==0 <-> 0.0)."""
    p, nlanes = vi.shape
    k = 2 * radius + 1
    lane = jax.lax.broadcasted_iota(jnp.int32, (p, LANE), 1)
    i0 = jnp.floor(cl)
    frac = cl - i0  # (P, 1)
    base = i0.astype(jnp.int32) - radius  # first tap true position
    xpos = base + lane  # true tap position for out lane t
    al = lane_base + (xpos >> 2)  # absolute container lane (floor shift)
    if nlanes > LANE:
        nslab = nlanes // LANE
        slab = jnp.clip((lane_base + (base >> 2)) // LANE, 0, nslab - 1)
        win_a = vi[:, 0:LANE]
        win_b = vi[:, LANE:2 * LANE]
        for s in range(1, nslab):
            sl = vi[:, s * LANE:(s + 1) * LANE]
            win_a = jnp.where(slab == s, sl, win_a)
            if s >= 2:
                win_b = jnp.where(slab == s - 1, sl, win_b)
        rel = al - slab * LANE
        g_a = jnp.take_along_axis(win_a, jnp.clip(rel, 0, LANE - 1),
                                  axis=-1)
        g_b = jnp.take_along_axis(win_b, jnp.clip(rel - LANE, 0, LANE - 1),
                                  axis=-1)
        g = jnp.where(rel < LANE, g_a, g_b)
    else:
        g = jnp.take_along_axis(vi, jnp.clip(al, 0, LANE - 1), axis=-1)
    # Sign-extending byte extract: tap byte b of lane g is (g << (3-b)*8)
    # >> 24 with ARITHMETIC shifts (int32 in jax). b = xpos & 3 per lane.
    b_ = xpos & 3
    q = (g << ((3 - b_) * 8)) >> 24
    val = jnp.where((xpos >= 0) & (xpos < w2),
                    q.astype(jnp.float32) * scale, 0.0)
    return val[:, :k] * (1.0 - frac) + val[:, 1:k + 1] * frac


@jax.custom_vjp
def quantize_pack_rows8(rows: jax.Array, scale: jax.Array) -> jax.Array:
    """(..., Wb) bf16/fp32 rows -> (..., Wb/4) int32 container rows (four
    symmetric-int8 taps per lane): ``q = clip(round(v / scale), -127,
    127)``. Called once per frame at corr-fn build time, like
    ``pack_rows``. The container (and the scale that shaped it) is an
    opaque bit transport with zero cotangent — gradient flows through the
    bf16 pyramid rows operand (straight-through estimator; the pack8 path
    is serving-oriented and default-off, DESIGN.md r19)."""
    wb = rows.shape[-1]
    q = jnp.clip(jnp.round(rows.astype(jnp.float32) / scale),
                 -127.0, 127.0).astype(jnp.int8)
    # fp32 CONTAINER (bit view, like pack_rows): float operands keep the
    # zero-cotangent custom_vjp well-typed; the kernel bitcasts back to
    # int32 before any bit arithmetic, so no float op ever touches the
    # (possibly NaN-patterned) container values.
    return jax.lax.bitcast_convert_type(
        jax.lax.bitcast_convert_type(
            q.reshape(*rows.shape[:-1], wb // 4, 4), jnp.int32),
        jnp.float32)


def _qpack8_fwd(rows, scale):
    return quantize_pack_rows8(rows, scale), None


def _qpack8_bwd(_, g):
    # Bit container (see pack_rows): zero cotangent for the bf16 rows and
    # the (B, 1, 1) per-sample scales — gradient flows through the bf16
    # pyramid operand.
    return (jnp.zeros((*g.shape[:-1], g.shape[-1] * 4), jnp.bfloat16),
            jnp.zeros((g.shape[0], 1, 1), jnp.float32))


quantize_pack_rows8.defvjp(_qpack8_fwd, _qpack8_bwd)


def level_scale8(rows: jax.Array) -> jax.Array:
    """Per-level, PER-SAMPLE symmetric dequant scale ``max|v| / 127``
    over each sample's (padded — zeros can't win) rows, shape (B, 1, 1),
    floored away from zero so an all-zero level quantizes to zeros with
    a well-defined scale.

    Per-sample is load-bearing, not a refinement: a whole-batch amax
    would let one sample's content set its batchmates' quantization grid
    — the same request would return different bytes depending on batch
    composition, breaking the r4 batched-rows == B=1-rows invariant and
    the response cache's bit-identical-to-recompute contract. With
    per-sample scales the container rows of sample i depend on sample i
    alone, so batched pack8 quantization is row-independent by
    construction (regression-pinned in tests/test_corr.py)."""
    amax = jnp.max(jnp.abs(rows.astype(jnp.float32)), axis=(1, 2),
                   keepdims=True)
    return jnp.maximum(amax, 1e-30) / 127.0


PACK8_ALIGN = 4 * LANE  # int8 row width multiple that quad-packs to vregs


def corr_pack8() -> bool:
    """``RAFT_CORR_PACK8=1`` quantizes bf16 pyramid levels to 4-per-lane
    int8 containers with per-level symmetric scales — HALF the pair-packed
    bf16 correlation DMA again (r19). Read at corr-fn build (trace) time
    and registered in ENV_KNOBS, so serving programs key on it; default
    OFF: the path is canary-banded (quantization error budget
    ``scale/2 = amax/254`` per tap, pinned in tests/test_corr.py and
    DESIGN.md r19), not bit-identical, so an operator opts in."""
    return os.environ.get("RAFT_CORR_PACK8", "0").strip().lower() in (
        "1", "true", "yes", "on")


def pack_plan8(widths: Sequence[int]):
    """Lane layout of the ONE combined int8 container all levels share.

    Each level's quantized rows occupy ``pad_width(w)/4`` container lanes
    (4 taps per 32-bit lane) at a static ``lane_base``; concatenating all
    levels and padding the tail to a whole vreg gives the minimum-DMA
    layout (at Middlebury-F: 192+96+64+32 = 384 lanes = 3 whole slabs,
    exactly half the pair-packed bf16 bytes). Returns
    ``([(lane_base, lane_count) per level], total_lanes)``."""
    segs: List[Tuple[int, int]] = []
    base = 0
    for w in widths:
        cnt = pad_width(w) // 4
        segs.append((base, cnt))
        base += cnt
    return segs, pad_width(base)


def plan_dma_bytes(widths: Sequence[int], bf16: bool, pack8: bool
                   ) -> float:
    """Per-PIXEL kernel-operand DMA bytes of one correlation lookup —
    exactly what the BlockSpecs declare (each pixel's grid cell streams
    every level's full operand row). This is the analytic half of the
    r19 ledger story: the ratio ``plan_dma_bytes(int8) /
    plan_dma_bytes(bf16)`` is computable at ANY geometry without a
    compile, and the driver's on-chip run corroborates it with the
    advance rows' compiler ``bytes_est``."""
    if pack8 and bf16:
        _, total = pack_plan8(widths)
        # int8 container lanes (4 B each) + the per-level fp32 scales
        # riding the coords operand.
        return total * 4.0 + len(widths) * 4.0
    if not bf16:
        return float(sum(pad_width(w) * 4 for w in widths))
    plan = pack_plan(widths, True)
    total = 0.0
    for w, p in zip(widths, plan):
        if p == "packed":
            total += pad_width(w, PACK_ALIGN) * 2  # container lanes x 4 B
        elif isinstance(p, tuple) and p[0] == "host":
            total += pad_width(w) * 2  # bloat-free by construction
        elif isinstance(p, tuple) and p[0] == "tail":
            total += pad_width(w) * 2  # rides the host container
        else:
            total += pad_width(w) * 2  # plain bf16 rows
    return total


# ---------------------------------------------------------------------------
# Narrow-lane FEATURE containers (r24, RAFT_LANE_PACK8): the corr pyramid's
# quad-pack seam (above) generalized to the iteration-invariant context /
# feature tensors the GRU scan re-reads every iteration. Layout is
# WIDTH-GROUP, not channel-group: a (..., W, C) tensor packs to
# (..., ceil(W/4), C) fp32 containers where byte b of lane column j holds
# width position ``b * ceil(W/4) + j``. Keeping the minor (lane) axis at the
# original channel count means the container tiles HBM exactly like the
# bf16 tensor it replaces (C = 128-multiples stay 128-multiples), so the
# declared DMA ratio is ~0.5 instead of the ~0.67 a channel-group layout
# pays to lane padding — and the in-kernel unpack is four sign-extending
# byte extracts concatenated on the SUBLANE axis (no minor-dim reshape).
# ---------------------------------------------------------------------------


def lane_pack8() -> bool:
    """``RAFT_LANE_PACK8=1`` quantizes the iteration-invariant context
    streams (the three-scale ``inp`` czrq tensors and the fmap operands the
    state pytree carries) into width-group int8 containers — halving the
    per-iteration context DMA the same way RAFT_CORR_PACK8 halved the
    pyramid's (r24). Read at trace time and registered in ENV_KNOBS so
    serving programs key on it; default OFF: canary-banded (dequant error
    ``scale/2`` per element, pinned in tests/test_lane_pack8.py), not
    bit-identical, so an operator opts in."""
    return os.environ.get("RAFT_LANE_PACK8", "0").strip().lower() in (
        "1", "true", "yes", "on")


def feature_scale8(x: jax.Array) -> jax.Array:
    """PER-SAMPLE symmetric dequant scale ``max|v| / 127`` over every
    non-batch axis of a (B, ...) feature tensor, keepdims (so (B, 1, 1, 1)
    for the 4D activations), floored away from zero. Per-sample for the
    same reason as :func:`level_scale8`: a whole-batch amax would let one
    sample's content set a batchmate's quantization grid, breaking the
    batched-rows == B=1 invariant (regression-pinned in
    tests/test_lane_pack8.py)."""
    axes = tuple(range(1, x.ndim))
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axes, keepdims=True)
    return jnp.maximum(amax, 1e-30) / 127.0


def _qfeat8_impl(x: jax.Array, scale: jax.Array) -> jax.Array:
    w = x.shape[-2]
    wq = -(-w // 4)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                 -127.0, 127.0).astype(jnp.int32)
    if 4 * wq != w:
        pad = [(0, 0)] * x.ndim
        pad[-2] = (0, 4 * wq - w)
        q = jnp.pad(q, pad)  # symmetric: zero pad rows quantize to q == 0
    ax = x.ndim - 2
    qs = [jax.lax.slice_in_dim(q, b * wq, (b + 1) * wq, axis=ax)
          for b in range(4)]
    packed = ((qs[0] & 0xFF) | ((qs[1] & 0xFF) << 8)
              | ((qs[2] & 0xFF) << 16) | ((qs[3] & 0xFF) << 24))
    return jax.lax.bitcast_convert_type(packed, jnp.float32)


@jax.custom_vjp
def quantize_pack_feature8(x: jax.Array, scale: jax.Array) -> jax.Array:
    """(..., W, C) float activations -> (..., ceil(W/4), C) fp32 width-group
    int8 containers: ``q = clip(round(v / scale), -127, 127)``. Zero pad
    rows/columns quantize to exact zero bytes (symmetric grid), so the
    czrq row padding ``prepare_gru_context`` applies survives packing
    bit-exactly. Like :func:`quantize_pack_rows8` the container is an
    opaque bit transport with zero cotangent — the straight-through
    gradient flows through the unpacked ``context`` operand the fused ops
    carry alongside it."""
    return _qfeat8_impl(x, scale)


def _qfeat8_fwd(x, scale):
    return quantize_pack_feature8(x, scale), (
        x.shape, x.dtype, scale.shape, scale.dtype)


def _qfeat8_bwd(res, g):
    # Bit container: zero cotangent for the activation AND its scale, in
    # the operands' own shapes/dtypes (unlike _qpack8_bwd this seam packs
    # arbitrary-rank feature tensors, so nothing is hardcoded).
    x_shape, x_dtype, s_shape, s_dtype = res
    del g
    return jnp.zeros(x_shape, x_dtype), jnp.zeros(s_shape, s_dtype)


quantize_pack_feature8.defvjp(_qfeat8_fwd, _qfeat8_bwd)


def unpack_feature8(pk: jax.Array, scale: jax.Array, width: int) -> jax.Array:
    """(..., Wq, C) container -> (..., width, C) fp32 dequantized rows —
    the pack inverse modulo quantization: four ARITHMETIC-shift byte
    extracts (sign-extending, the gather_lerp_taps_packed8 idiom)
    concatenated on the width axis, sliced to the true width, times the
    broadcastable dequant scale."""
    gi = jax.lax.bitcast_convert_type(pk, jnp.int32)
    parts = [(gi << 24) >> 24, (gi << 16) >> 24, (gi << 8) >> 24, gi >> 24]
    q = jnp.concatenate(parts, axis=-2)
    q = jax.lax.slice_in_dim(q, 0, width, axis=pk.ndim - 2)
    return q.astype(jnp.float32) * scale


PACK_ALIGN = 2 * LANE  # bf16 row width multiple that packs to whole vregs


@jax.custom_vjp
def pack_rows(rows: jax.Array) -> jax.Array:
    """(..., Wb) bf16 rows -> (..., Wb/2) fp32-container rows (two bf16
    taps per 32-bit lane). Called ONCE per frame at corr-fn build time —
    outside the GRU scan — so the kernel reads packed rows every iteration
    for free. The container is an opaque BIT transport: its vjp is zero
    (fp32 addition of bit-packed pairs is meaningless, and JAX SUMS
    cotangents across the loop's 32 lookup calls before any unpack could
    run) — all gradient flows through the bf16 rows operand that
    ``_lookup`` takes alongside the containers."""
    wb = rows.shape[-1]
    return jax.lax.bitcast_convert_type(
        rows.reshape(*rows.shape[:-1], wb // 2, 2), jnp.float32)


def unpack_rows(packed: jax.Array) -> jax.Array:
    """(..., W2) fp32-container -> (..., 2*W2) bf16 rows (pack inverse)."""
    rows = jax.lax.bitcast_convert_type(packed, jnp.bfloat16)
    return rows.reshape(*packed.shape[:-1], packed.shape[-1] * 2)


def _lohi_avg(packed: jax.Array) -> jax.Array:
    """Average the two bf16 taps in each 32-bit lane (elementwise)."""
    vi = jax.lax.bitcast_convert_type(packed, jnp.int32)
    lo = jax.lax.bitcast_convert_type(vi << 16, jnp.float32)
    hi = jax.lax.bitcast_convert_type(vi & jnp.int32(-65536), jnp.float32)
    return ((lo + hi) * 0.5).astype(jnp.bfloat16)


@jax.custom_vjp
def pool_next_level(rows: jax.Array, packed: jax.Array) -> jax.Array:
    """Next pyramid level from a packed level's container — numerically
    identical to ``avg_pool_last(rows)`` (exact fp32 values of both bf16
    taps, fp32 mean, one bf16 round) but pure ELEMENTWISE bit-ops: the
    conventional pool (reshape + mean over a minor size-2 axis) makes XLA
    materialize an fp32 copy of the whole level in a rotated layout
    (measured ~6 ms on the 576 MB headline L0). The custom backward is the
    pooling transpose on the ROWS operand — routing the forward through
    the container's bit-ops alone would silently zero every deeper
    level's gradient (integer bitcasts carry no tangent and pack_rows'
    vjp is deliberately zero)."""
    del rows
    return _lohi_avg(packed)


def _pool_next_fwd(rows, packed):
    return pool_next_level(rows, packed), None


def _pool_next_bwd(_, g):
    # avg_pool_last transpose: input lane i receives 0.5 * g[i // 2].
    d_rows = jnp.repeat(g.astype(jnp.float32) * 0.5, 2, axis=-1)
    return d_rows.astype(jnp.bfloat16), jnp.zeros(g.shape, jnp.float32)


pool_next_level.defvjp(_pool_next_fwd, _pool_next_bwd)


def _pack_fwd(rows):
    return pack_rows(rows), None


def _pack_bwd(_, g):
    # Bit container: no meaningful float cotangent (see pack_rows).
    return (jnp.zeros((*g.shape[:-1], g.shape[-1] * 2), jnp.bfloat16),)


pack_rows.defvjp(_pack_fwd, _pack_bwd)


def _row_sharding(mesh, arg_shapes, ndim: int, n_lead: int = 2):
    """Sharding along the first ``n_lead`` (row) axes, taken from the
    first operand; every other axis replicated (for ``alt_tpu`` the
    third axes disagree between operands — W1 for f1/coords vs the
    search width for f2 — so only batch and height may shard)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = arg_shapes[0].sharding.spec
    lead = [spec[i] if i < len(spec) else None for i in range(n_lead)]
    return NamedSharding(mesh, P(*lead, *([None] * (ndim - n_lead))))


def _make_partitioned(impl, ndims: Sequence[int], rule: str,
                      need_replication_factors: Tuple[str, ...] = ()):
    """Wrap ``impl`` (positional array args) in a custom_partitioning that
    splits every operand and the result along their leading axes.

    This is the SPMD story for the correlation kernels: compiled Mosaic
    kernels have no built-in partitioning rule, but every lookup row
    (pixel for ``reg_tpu``, image row for ``alt_tpu``) is independent, so
    the kernel runs unchanged on each device's row shard — the analog of
    the reference's CUDA sampler running under DataParallel
    (``core/corr.py:17-29``, ``train_stereo.py:134``). ``rule`` is the
    einsum-like Shardy sharding rule; the GSPMD callbacks mirror it.
    """
    from jax.experimental.custom_partitioning import custom_partitioning

    fn = custom_partitioning(impl)

    def infer(mesh, arg_shapes, result_shape):
        return _row_sharding(mesh, arg_shapes, result_shape.ndim)

    def partition(mesh, arg_shapes, result_shape):
        out_sh = _row_sharding(mesh, arg_shapes, result_shape.ndim)
        arg_sh = tuple(_row_sharding(mesh, arg_shapes, nd) for nd in ndims)
        return mesh, impl, out_sh, arg_sh

    from raft_stereo_tpu.ops.jax_compat import def_partition
    def_partition(fn, partition, infer_sharding_from_operands=infer,
                  sharding_rule=rule,
                  need_replication_factors=need_replication_factors)
    return fn


def make_batch_partitioned(impl, batch_in_axes: Sequence,
                           in_ndims: Sequence[int],
                           batch_out_axes: Sequence,
                           out_ndims: Sequence[int]):
    """custom_partitioning that splits ONLY the batch axis (given per
    operand/result; None = fully replicated — weights and other small
    arrays ride along). Used by the streaming scan-body kernels
    (``ops/pallas_stream.py``), whose outer grid dimension IS the batch
    sample — so a data-sharded training step runs them per-shard instead
    of hitting an unpartitionable ``pallas_call``."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    fn = custom_partitioning(impl)
    ops_, results, repl = [], [], []
    fresh = iter(f"f{i}" for i in range(10000))

    def mapping(ax, nd):
        names = []
        for d in range(nd):
            if d == ax:
                names.append("b")
            else:
                names.append(next(fresh))
                repl.append(names[-1])
        return " ".join(names)

    for ax, nd in zip(batch_in_axes, in_ndims):
        ops_.append(mapping(ax, nd))
    for ax, nd in zip(batch_out_axes, out_ndims):
        results.append(mapping(ax, nd))
    rule = ", ".join(ops_) + " -> " + ", ".join(results)

    def _shardings(mesh, arg_shapes):
        b_axis = None
        for ax, s in zip(batch_in_axes, arg_shapes):
            if ax is not None and len(s.sharding.spec) > ax:
                b_axis = s.sharding.spec[ax]
                break

        def sh(ax, nd):
            spec = [None] * nd
            if ax is not None:
                spec[ax] = b_axis
            return NamedSharding(mesh, P(*spec))

        ins = tuple(sh(ax, nd) for ax, nd in zip(batch_in_axes, in_ndims))
        outs = [sh(ax, nd) for ax, nd in zip(batch_out_axes, out_ndims)]
        return ins, (outs[0] if len(outs) == 1 else tuple(outs))

    def infer(mesh, arg_shapes, result_shape):
        return _shardings(mesh, arg_shapes)[1]

    def partition(mesh, arg_shapes, result_shape):
        ins, outs = _shardings(mesh, arg_shapes)
        return mesh, impl, outs, ins

    from raft_stereo_tpu.ops.jax_compat import def_partition
    def_partition(fn, partition, infer_sharding_from_operands=infer,
                  sharding_rule=rule,
                  need_replication_factors=tuple(repl))
    return fn


def gather_level_taps(vol, cl, radius: int, w2: int, mode: str,
                      lane_base: int, scale=None):
    """One level's gather+lerp, dispatched by packing mode — THE shared
    dispatcher of the standalone lookup kernel and the resident-iteration
    kernel (ops/pallas_resident.py): their bit-identity contract is by
    shared code, not parallel copies. ``vol``: the level's 2D operand
    rows ((P, lanes); packed8 callers pass the int32 bitcast view, cast
    once per operand); ``scale``: (P, 1) fp32 dequant column (packed8)."""
    if mode == "plain":
        return gather_lerp_taps(vol, cl, radius, w2)
    if mode == "packed":
        return gather_lerp_taps_packed(vol, cl, radius, w2)
    if mode == "tail":
        return gather_lerp_taps_packed_tail(vol, cl, radius, w2, lane_base)
    if mode == "packed8":
        return gather_lerp_taps_packed8(vol, cl, radius, w2, lane_base,
                                        scale)
    raise ValueError(f"unknown lookup mode {mode!r}")


def _lookup_kernel(coords_ref, *refs, radius: int, widths: Sequence[int],
                   spec: Tuple[Tuple[int, str, int], ...]):
    """``spec``: per level ``(operand_idx, mode, lane_base)`` with mode in
    ``plain | packed | tail | packed8`` — levels may share one operand
    (the combined host+tail bf16 container; ALL levels for the int8
    container), so operands are a separate axis from pyramid levels. The
    coords block's column 0 is the fp32 position; under ``packed8`` the
    per-level dequant scales ride as columns ``1 + lvl`` (broadcast
    per-pixel — see make_reg_tpu_corr_fn)."""
    *vol_refs, out_ref = refs
    k = 2 * radius + 1
    c = coords_ref[:, :1]  # (TILE, 1) fp32 position
    pack8_views = {}
    for lvl, (op, mode, base) in enumerate(spec):
        cl = c * (1.0 / (1 << lvl))
        if mode == "packed8":
            if op not in pack8_views:  # bitcast the container view once
                pack8_views[op] = jax.lax.bitcast_convert_type(
                    vol_refs[op][:], jnp.int32)
            vol = pack8_views[op]
            scale = coords_ref[:, 1 + lvl:2 + lvl]
        else:
            vol = vol_refs[op][:]
            scale = None  # no scale columns exist on non-pack8 coords
        t = gather_level_taps(vol, cl, radius, widths[lvl], mode, base,
                              scale)
        out_ref[:, lvl * k:(lvl + 1) * k] = t.astype(out_ref.dtype)


def _pallas_lookup(pyramid: Sequence[jax.Array], coords_flat: jax.Array,
                   radius: int, widths: Tuple[int, ...],
                   out_dtype, spec: Tuple[Tuple[int, str, int], ...],
                   tile: int = _TILE_DEFAULT) -> jax.Array:
    """pyramid: list of per-OPERAND (N, W2p) rows; coords_flat: (N, U)
    (column 0 = position; packed8 scale columns ride along)."""
    n, cw = coords_flat.shape
    k = 2 * radius + 1
    out_ch = len(spec) * k
    grid = pl.cdiv(n, tile)
    kernel = functools.partial(_lookup_kernel, radius=radius, widths=widths,
                               spec=spec)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, out_ch), out_dtype),
        grid=(grid,),
        in_specs=[pl.BlockSpec((tile, cw), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)] +
                 [pl.BlockSpec((tile, p.shape[-1]), lambda i: (i, 0),
                               memory_space=pltpu.VMEM) for p in pyramid],
        out_specs=pl.BlockSpec((tile, out_ch), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        # The 2048-pixel tile's double-buffered level blocks + fp32
        # gather temporaries need ~28 MB; the default scoped cap is 16.
        compiler_params=compiler_params(vmem_limit_bytes=64 * 2**20),
        interpret=_interpret(),
    )(coords_flat, *pyramid)
    return out


@functools.lru_cache(maxsize=None)
def _partitioned_lookup(radius: int, widths: Tuple[int, ...], out_dtype_name,
                        nops: int,
                        spec: Tuple[Tuple[int, str, int], ...] = (),
                        tile: int = _TILE_DEFAULT):
    """SPMD-partitionable 3D lookup: coords (B, N, 1) + ``nops`` row
    operands (B, N, W2p) -> (B, N, nlev*(2r+1)), independent along (B, N)
    — any mesh sharding of the leading two axes runs the flat kernel
    per-shard. ``spec`` maps pyramid levels onto operands (a combined
    host+tail container serves two levels). ``tile`` is part of the cache
    key, so corr fns built under different ``RAFT_CORR_TILE`` values
    coexist.
    """
    out_dtype = jnp.dtype(out_dtype_name)
    spec = spec or tuple((i, "plain", 0) for i in range(len(widths)))

    def impl(coords3, *pyr3):
        b, n, cw = coords3.shape
        flat = [p.reshape(b * n, p.shape[-1]) for p in pyr3]
        out = _pallas_lookup(flat, coords3.reshape(b * n, cw), radius,
                             widths, out_dtype, spec, tile)
        return out.reshape(b, n, -1)

    rule = ("b n u, " + ", ".join(f"b n w{i}" for i in range(nops))
            + " -> b n k")
    # In rule-appearance order (the Shardy verifier requires it).
    repl = ("u",) + tuple(f"w{i}" for i in range(nops)) + ("k",)
    return _make_partitioned(impl, [3] * (nops + 1), rule,
                             need_replication_factors=repl)


def _masked_lookup_xla(pyramid: Sequence[jax.Array], coords_flat: jax.Array,
                       radius: int, widths: Tuple[int, ...]) -> jax.Array:
    """One-hot-reduce lookup over *padded* rows with true-width masking.

    Matches the kernel bit-for-bit in exact arithmetic; exists as (a) the
    custom_vjp backward (its VJP is regular VPU/MXU work — scatters don't
    vectorize on TPU) and (b) an oracle for the kernel tests. Shape-
    agnostic over leading axes (used with both flat (N, .) and (B, N, .)
    row layouts).
    """
    out = []
    for lvl, vol in enumerate(pyramid):
        w2p = vol.shape[-1]
        w2 = widths[lvl]
        cl = coords_flat * (1.0 / (1 << lvl))
        i0 = jnp.floor(cl)
        frac = cl - i0
        base = i0 - radius
        j = jnp.arange(w2p, dtype=jnp.float32)
        valid_j = j < w2
        vol32 = vol.astype(jnp.float32)  # match the kernel's fp32 lerp
        taps = []
        for t in range(2 * radius + 2):
            onehot = ((j == base + t) & valid_j).astype(jnp.float32)
            taps.append(jnp.sum(vol32 * onehot, axis=-1))
        g = jnp.stack(taps, axis=-1)  # (..., 2r+2)
        out.append(g[..., :-1] * (1.0 - frac) + g[..., 1:] * frac)
    return jnp.concatenate(out, axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _lookup(pyramid: List[jax.Array], kernel_ops: List[jax.Array],
            coords_flat: jax.Array, radius: int, widths: Tuple[int, ...],
            out_dtype=jnp.float32,
            spec: Tuple[Tuple[int, str, int], ...] = (),
            tile: int = _TILE_DEFAULT) -> jax.Array:
    """pyramid: per-level (B, N, W2p_l) bf16/fp32 rows — the DIFFERENTIABLE
    operand (cotangents sum linearly across the loop's 32 lookup calls);
    kernel_ops: the operands the kernel actually reads when any level
    packs — pair-packed fp32-container rows, one per ``spec`` operand
    index (a combined container carries TWO levels; see ``pack_rows``) —
    zero cotangent. Empty when nothing packs (the kernel then reads the
    pyramid rows directly). coords_flat: (B, N, 1).
    """
    fn = _partitioned_lookup(radius, widths, jnp.dtype(out_dtype).name,
                             len(kernel_ops) or len(pyramid), spec, tile)
    rows = kernel_ops if kernel_ops else pyramid
    return fn(coords_flat, *rows)


def _lookup_fwd(pyramid, kernel_ops, coords_flat, radius, widths, out_dtype,
                spec, tile):
    return (_lookup(pyramid, kernel_ops, coords_flat, radius, widths,
                    out_dtype, spec, tile),
            (pyramid, kernel_ops, coords_flat))


def _lookup_bwd(radius, widths, out_dtype, spec, tile, residuals, g):
    pyramid, kernel_ops, coords_flat = residuals
    # Column 0 is the fp32 position; packed8 scale columns (zero
    # cotangent — they shaped only the bit containers) ride behind it.
    cpos = coords_flat[..., :1]
    _, vjp = jax.vjp(
        lambda p: _masked_lookup_xla(p, cpos, radius, widths), pyramid)
    # The oracle emits fp32; a bf16-out kernel hands back a bf16 cotangent.
    (d_pyramid,) = vjp(g.astype(jnp.float32))
    # The containers are loop-invariant bit transports: zero cotangent
    # (all gradient flows through the bf16 pyramid rows).
    d_ops = [jnp.zeros_like(op) for op in kernel_ops]
    return d_pyramid, d_ops, jnp.zeros_like(coords_flat)


_lookup.defvjp(_lookup_fwd, _lookup_bwd)


def level_widths(w2: int, num_levels: int) -> Tuple[int, ...]:
    """True (unpadded) per-level widths: successive floor halving."""
    ws = [w2]
    for _ in range(num_levels - 1):
        ws.append(ws[-1] // 2)
    return tuple(ws)


def pack_plan(widths: Sequence[int], bf16: bool):
    """Per-level packing plan: ``"plain"`` | ``"packed"`` (standalone
    container) | ``("host", tail_lvl)`` | ``("tail", host_lvl)``.

    A bf16 level pair-packs for free only when its 256-aligned pad equals
    its 128-aligned pad (an EVEN number of 128-blocks); an odd-block level
    packed standalone pays an extra zero half-vreg of DMA every grid step
    (r5 measured the bloat eating the win: L1 384->512, L3 128->256 at
    Middlebury-F, +17% pyramid DMA). But every odd-block level's packed
    row is an ODD multiple of 64 container lanes, so TWO odd-block levels
    concatenated are whole vregs with ZERO pad bloat: the deepest level
    (whose packed rows fit one 64-lane tail, w <= 128) rides in the tail
    of the widest odd-block level's container. The combined operand's
    DMA equals the two unpacked levels' exactly, both levels get the
    no-upcast packed gather, and the kernel reads one fewer operand.
    Remaining odd-block levels (a third and beyond) stay plain.
    """
    plan: List = []
    for w in widths:
        if not bf16:
            plan.append("plain")
        elif pad_width(w, PACK_ALIGN) == pad_width(w):
            plan.append("packed")
        else:
            plan.append("odd")  # placeholder, resolved below
    odd = [i for i, p in enumerate(plan) if p == "odd"]
    # Tail candidate: the deepest level overall, iff odd-block and its
    # packed rows fit one 64-lane tail slot inside a slab.
    last = len(widths) - 1
    if (len(odd) >= 2 and odd[-1] == last
            and pad_width(widths[last]) // 2 == 64):
        host = odd[0]  # widest odd-block level hosts the container
        base = pad_width(widths[host]) // 2
        if base % LANE + 64 <= LANE:
            plan[host] = ("host", last)
            plan[last] = ("tail", host)
    return ["plain" if p == "odd" else p for p in plan]


def build_corr_operands(fmap1: jax.Array, fmap2: jax.Array, *,
                        num_levels: int, radius: int, out_dtype=None):
    """Build the correlation volume + the exact operand set the lookup
    kernel reads, WITHOUT closing over a corr_fn.

    Returns a dict: ``flat`` (per-level differentiable rows), ``kernel_ops``
    (packed containers when any level packs — empty means the kernel reads
    ``flat``), ``spec`` (level -> (operand, mode, lane_base)), ``widths``,
    ``scales`` (per-level fp32 dequant scalars under pack8, else None),
    geometry and ``tile``. :func:`make_reg_tpu_corr_fn` wraps this into
    the classic closure; the r19 resident-iteration kernel
    (ops/pallas_resident.py) consumes the same operands directly so the
    in-kernel gather is the SAME arithmetic on the SAME containers as the
    standalone lookup."""
    out_dtype = jnp.float32 if out_dtype is None else out_dtype
    b, h, w1, _ = fmap1.shape
    w2 = fmap2.shape[2]
    widths = level_widths(w2, num_levels)
    # Zero-pad fmap2's width before the einsum: the padded volume region is
    # exactly zero, so no post-hoc volume copy; deeper levels whose pooled
    # width falls under one vreg get a (cheap) per-level re-pad. The pyramid
    # is stored in the fmap dtype (bf16 under mixed precision — halves the
    # lookup's HBM traffic; the kernel upcasts rows to fp32 for the lerp).
    f2p = jnp.pad(fmap2, ((0, 0), (0, 0), (0, pad_width(w2) - w2), (0, 0)))
    # The einsum runs — and emits — the fmap dtype (the MXU accumulates
    # fp32 within the single K=256 pass regardless): upcasting the inputs
    # (build_volume) would materialize a full fp32 volume (2.1 GB at
    # Middlebury-F) before the downcast, and requesting an fp32 output
    # type breaks the autodiff transpose for bf16 operands. Identical when
    # fmaps are fp32.
    d = fmap1.shape[-1]
    vol = jnp.einsum("bhid,bhjd->bhij", fmap1, f2p) * (1.0 / d ** 0.5)
    # bf16 pyramid levels pair-pack into fp32 containers ONCE here (outside
    # the GRU scan — 32 lookups amortize one bitcast pass) so the kernel
    # runs the half-width-scan / no-upcast gather path every iteration.
    # Per-level decision (``pack_plan``): pack standalone when the
    # 256-multiple alignment the container needs pads no further than the
    # plain 128 alignment; the two widest/deepest ODD-block levels (whose
    # standalone containers would bloat, e.g. 372 padding 384 -> 512)
    # share ONE combined container with zero pad bloat. A packed level's
    # successor pools via ``_lohi_avg`` on the container (elementwise);
    # unpacked levels pool conventionally. Padded zero lanes pool to
    # zeros and every consumer masks by the true width, so pooling padded
    # rows is value-identical to the pad-after-pool order.
    # (B, H*W1, W2p_l) rows: batch stays a real axis and H (major) merges
    # with W1 (minor, unsharded) — both mesh axes of a (data, space)
    # sharding survive the reshape, so the partitioned lookup runs
    # per-shard under any row mesh.
    bf16 = vol.dtype == jnp.bfloat16
    pack8 = bf16 and corr_pack8()
    plan = pack_plan(widths, bf16 and not pack8)
    any_packed = any(p != "plain" for p in plan)
    flat, containers = [], {}  # containers: lvl -> packed rows
    cur = vol.reshape(b, h * w1, -1)
    for lvl in range(num_levels):
        wp = cur.shape[-1]
        want = pad_width(widths[lvl],
                         PACK_ALIGN if plan[lvl] == "packed" else LANE)
        if wp < want:
            cur = jnp.pad(cur, ((0, 0), (0, 0), (0, want - wp)))
        elif wp > want:
            cur = cur[..., :want]
        # The kernel reads the containers on packed levels; the bf16 rows
        # stay the differentiable operand (DCE'd from no-grad programs).
        flat.append(cur)
        if plan[lvl] != "plain":
            pk = pack_rows(cur)
            containers[lvl] = pk
            cur = (pool_next_level(cur, pk)
                   if lvl + 1 < num_levels else None)
        else:
            cur = avg_pool_last(cur) if lvl + 1 < num_levels else None

    scales = None
    if pack8:
        # r19 narrow-lane packing: ONE combined int8 container carries
        # every level at a static lane_base (pack_plan8); per-level
        # symmetric scales dequant in-register at the gather. Built once
        # per frame, outside the GRU scan, exactly like pack_rows — and
        # the bf16 ``flat`` rows stay the differentiable operand.
        segs, total = pack_plan8(widths)
        scales = [level_scale8(flat[lvl]) for lvl in range(num_levels)]
        parts = [quantize_pack_rows8(flat[lvl], scales[lvl])
                 for lvl in range(num_levels)]
        used = segs[-1][0] + segs[-1][1]
        if total > used:  # pad the container tail to whole vregs
            parts.append(jnp.zeros((b, h * w1, total - used), jnp.float32))
        kernel_ops = [jnp.concatenate(parts, axis=-1)]
        spec = tuple((0, "packed8", segs[lvl][0])
                     for lvl in range(num_levels))
        any_packed = True
    else:
        # Assemble operands + the level -> (operand, mode, lane_base) spec.
        kernel_ops, spec = [], [None] * num_levels
        for lvl in range(num_levels):
            p = plan[lvl]
            if p == "plain":
                if any_packed:
                    spec[lvl] = (len(kernel_ops), "plain", 0)
                    kernel_ops.append(flat[lvl])
                else:
                    spec[lvl] = (lvl, "plain", 0)
            elif p == "packed":
                spec[lvl] = (len(kernel_ops), "packed", 0)
                kernel_ops.append(containers[lvl])
            elif isinstance(p, tuple) and p[0] == "host":
                tail = p[1]
                base = containers[lvl].shape[-1]
                assert base % LANE + containers[tail].shape[-1] <= LANE, (
                    "tail level must fit one slab slot", base)
                op = len(kernel_ops)
                spec[lvl] = (op, "packed", 0)
                spec[tail] = (op, "tail", base)
                kernel_ops.append(jnp.concatenate(
                    [containers[lvl], containers[tail]], axis=-1))
            # ("tail", host): spec written by its host above.
        spec = tuple(spec)

    tile = corr_tile()  # env override honored per corr-fn build (trace time)
    return {"b": b, "h": h, "w1": w1, "widths": widths, "spec": spec,
            "flat": flat, "kernel_ops": kernel_ops if any_packed else [],
            "scales": scales, "out_dtype": out_dtype, "tile": tile,
            "radius": radius, "pack8": pack8}


def corr_coords_operand(ops, coords_x: jax.Array) -> jax.Array:
    """The lookup's coords operand: column 0 = fp32 x position; under
    pack8 the per-level PER-SAMPLE dequant scales ride as broadcast
    columns (they shard like coords — ``b n u`` — so the SPMD rule is
    untouched; +4 fp32/pixel of DMA against the halved pyramid rows)."""
    b, n = ops["b"], ops["h"] * ops["w1"]
    coords_flat = coords_x.astype(jnp.float32).reshape(b, n, 1)
    if ops["scales"] is None:
        return coords_flat
    cols = [jnp.broadcast_to(s.reshape(b, 1, 1), (b, n, 1))
            for s in ops["scales"]]
    return jnp.concatenate([coords_flat] + cols, axis=-1)


def corr_fn_from_operands(ops):
    """The classic lookup closure over a :func:`build_corr_operands`
    struct — shared with the resident-iteration path so building BOTH (the
    standalone lookup for compute_mask steps, the in-kernel gather for the
    resident scan body) costs one volume/container build; XLA DCEs
    whichever one a given program never calls."""
    b, h, w1 = ops["b"], ops["h"], ops["w1"]

    def corr_fn(coords_x: jax.Array) -> jax.Array:
        coords_flat = corr_coords_operand(ops, coords_x)
        out = _lookup(ops["flat"], ops["kernel_ops"], coords_flat,
                      ops["radius"], ops["widths"], ops["out_dtype"],
                      ops["spec"], ops["tile"])
        return out.reshape(b, h, w1, -1)

    return corr_fn


def make_reg_tpu_corr_fn(fmap1: jax.Array, fmap2: jax.Array, *,
                         num_levels: int, radius: int, out_dtype=None):
    return corr_fn_from_operands(
        build_corr_operands(fmap1, fmap2, num_levels=num_levels,
                            radius=radius, out_dtype=out_dtype))

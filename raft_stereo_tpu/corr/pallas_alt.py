"""``alt_tpu``: blockwise fused build+sample correlation, no W^2 volume.

Fills — properly — the hole the reference leaves: its ``alt_cuda`` choice
crashes at construction (``core/corr.py:159-161`` raises
NotImplementedError). This is the memory path for full-resolution work
(Middlebury-F), the framework's "long-context" strategy: recompute the
correlation on the fly instead of materializing the O(B*H*W^2) volume —
the exact trade blockwise/flash attention makes.

Kernel design: one grid cell per image row (b, h). The cell receives the
f1 row and the level-0 f2 row (width-padded to a vreg multiple) and, per
level,

1. pools the f2 row in VMEM (pairwise width averaging — the whole
   pyramid lives on-chip; nothing per-level ever reaches HBM);
2. computes that row's correlation block on the MXU —
   ``vol = f1_row @ f2_row^T / sqrt(D)`` with fp32 accumulation, shape
   ``(W1, W2p_l)``, living only in VMEM;
3. immediately runs the same windowed-gather + lerp as ``reg_tpu``
   (``pallas_reg.gather_lerp_taps``) and writes the ``(W1, 2r+1)`` taps.

Nothing W^2-sized and no pooled pyramid ever reaches HBM: peak footprint
per cell is the f1/f2 rows plus one ``(W1, W2p)`` VMEM block (~2.3 MB at
Middlebury-F 1/4-res). The MXU rebuilds the volume every lookup — FLOPs
traded for HBM exactly as the reference's ``alt`` trades them for CUDA
memory (``README.md:121``).

Width padding: the single pre-kernel pad to a 128-multiple happens before
pooling — pad zeros pool to zeros, and the one half-real boundary entry an
odd true width produces lands outside ``widths[lvl]`` where the tap mask
zeroes it, so this is identical to the reference's pad-free floor-halving
pyramid. The feature maps keep their dtype (bf16 under mixed precision);
the dot accumulates fp32 on the MXU, so only the inputs — not the
correlation math — are reduced precision, mirroring the reference's
fp16-capable CUDA path (``sampler_kernel.cu:126``).

Math note: sampling fmap2 first and dotting (the reference's ``alt``,
``core/corr.py:72-87``) equals lerping the on-the-fly volume row (the dot
is linear), so this matches ``reg`` bit-for-bit up to fp association —
property-tested against both.

Backward: ``custom_vjp`` to the feature maps via the masked one-hot XLA
formulation (H-chunked to bound the transient volume), no coord grad —
the reference detaches coords each GRU iteration (``raft_stereo.py:109``).
"""

from __future__ import annotations

import functools
import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_stereo_tpu.corr.pallas_reg import (
    _interpret, _make_partitioned, gather_lerp_taps, level_widths, pad_width)
from raft_stereo_tpu.ops.chunked import map_chunked


def _pool_rows(f2: jax.Array) -> jax.Array:
    """Pairwise width pooling: (..., W, D) -> (..., W//2, D).

    The single definition shared by the Pallas kernel and its custom_vjp
    backward — the two must stay numerically identical (the backward IS
    the gradient definition for the forward).
    """
    *lead, w, d = f2.shape
    f2r = f2.reshape(*lead, w // 2, 2, d)
    return (f2r[..., 0, :] + f2r[..., 1, :]) * 0.5


def _alt_kernel(coords_ref, f1_ref, f2_ref, out_ref, *, radius: int,
                num_levels: int, widths: Sequence[int], scale: float):
    # out_ref's dtype is the requested out_dtype; lerp arithmetic stays fp32.
    k = 2 * radius + 1
    c = coords_ref[0]  # (W1, 1)
    f1 = f1_ref[0]     # (W1, D)
    f2 = f2_ref[0]     # (W2p, D) — level 0, width-padded
    for lvl in range(num_levels):
        if lvl:
            f2 = _pool_rows(f2)
        vol = jax.lax.dot_general(
            f1, f2, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (W1, W2p_l)
        cl = c * (1.0 / (1 << lvl))
        out_ref[0, :, lvl * k:(lvl + 1) * k] = gather_lerp_taps(
            vol, cl, radius, widths[lvl]).astype(out_ref.dtype)


def _pallas_alt(f1: jax.Array, f2: jax.Array, coords: jax.Array,
                radius: int, num_levels: int,
                widths: Tuple[int, ...], scale: float,
                out_dtype=jnp.float32) -> jax.Array:
    """f1: (BH, W1, D); f2: (BH, W2p, D) level-0 padded; coords: (BH, W1, 1)."""
    bh, w1, d = f1.shape
    w2p = f2.shape[1]
    k = 2 * radius + 1
    out_ch = num_levels * k
    kernel = functools.partial(_alt_kernel, radius=radius,
                               num_levels=num_levels, widths=widths,
                               scale=scale)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, w1, out_ch), out_dtype),
        grid=(bh,),
        in_specs=[pl.BlockSpec((1, w1, 1), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((1, w1, d), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((1, w2p, d), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, w1, out_ch), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(coords, f1, f2)


def _masked_alt_xla(f1: jax.Array, f2: jax.Array, coords: jax.Array,
                    radius: int, num_levels: int,
                    widths: Tuple[int, ...], scale: float) -> jax.Array:
    """On-the-fly masked one-hot reference — the custom_vjp backward.

    Pools the padded f2 row per level exactly like the kernel, H-chunked
    via lax.map so the transient (chunk, W1, W2p) volume stays bounded;
    regular VPU/MXU work in both directions (scatters don't vectorize on
    TPU).
    """
    def chunk(args):
        f1_c, coords_c, f2_c = args
        out = []
        f2l = f2_c
        for lvl in range(num_levels):
            if lvl:
                f2l = _pool_rows(f2l)
            w2p = f2l.shape[-2]
            vol = jnp.einsum("nwd,nvd->nwv", f1_c, f2l,
                             preferred_element_type=jnp.float32) * scale
            cl = coords_c * (1.0 / (1 << lvl))
            i0 = jnp.floor(cl)
            frac = cl - i0
            base = i0 - radius
            j = jnp.arange(w2p, dtype=jnp.float32)
            valid_j = j < widths[lvl]
            taps = []
            for t in range(2 * radius + 2):
                onehot = ((j == base + t) & valid_j).astype(jnp.float32)
                taps.append(jnp.sum(vol * onehot, axis=-1))
            g = jnp.stack(taps, axis=-1)
            out.append(g[..., :-1] * (1.0 - frac) + g[..., 1:] * frac)
        return jnp.concatenate(out, axis=-1)

    return map_chunked(chunk, (f1, coords, f2), chunk=8, axis=0)


@functools.lru_cache(maxsize=None)
def _partitioned_alt(radius: int, num_levels: int, widths: Tuple[int, ...],
                     scale: float, out_dtype_name):
    """SPMD-partitionable 4D fused build+sample: f1 (B, H, W1, D),
    f2 (B, H, W2p, D), coords (B, H, W1, 1) -> (B, H, W1, C) — image rows
    are independent, so any (batch, height) mesh sharding runs the
    kernel per-shard with no collectives (the feature dim D and the f2
    row axis must stay unsharded; the Shardy rule marks them
    need-replication)."""
    out_dtype = jnp.dtype(out_dtype_name)

    def impl(coords4, f1, f2):
        b, h, w1, d = f1.shape
        out = _pallas_alt(f1.reshape(b * h, w1, d),
                          f2.reshape(b * h, -1, d),
                          coords4.reshape(b * h, w1, 1),
                          radius, num_levels, widths, scale, out_dtype)
        return out.reshape(b, h, w1, -1)

    rule = "b h w u, b h w d, b h v d -> b h w c"
    # Factors in rule-appearance order (the Shardy verifier requires
    # it). W1 ('w') must not shard either: f2's third axis is the search
    # width, not W1, so a w-shard would slice f2's rows out from under
    # full-width coords.
    return _make_partitioned(impl, [4, 4, 4], rule,
                             need_replication_factors=("w", "u", "d", "v",
                                                       "c"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _alt_lookup(f1, f2, coords, radius: int, num_levels: int,
                widths: Tuple[int, ...], scale: float,
                out_dtype=jnp.float32):
    """f1: (B, H, W1, D); f2: (B, H, W2p, D); coords: (B, H, W1, 1)."""
    fn = _partitioned_alt(radius, num_levels, widths, scale,
                          jnp.dtype(out_dtype).name)
    return fn(coords, f1, f2)


def _alt_fwd(f1, f2, coords, radius, num_levels, widths, scale, out_dtype):
    out = _alt_lookup(f1, f2, coords, radius, num_levels, widths, scale,
                      out_dtype)
    return out, (f1, f2, coords)


def _alt_bwd(radius, num_levels, widths, scale, out_dtype, residuals, g):
    f1, f2, coords = residuals
    bsz, h = f1.shape[:2]

    def flat_oracle(a, b):
        out = _masked_alt_xla(a.reshape((bsz * h,) + a.shape[2:]),
                              b.reshape((bsz * h,) + b.shape[2:]),
                              coords.reshape(bsz * h, -1, 1),
                              radius, num_levels, widths, scale)
        return out.reshape((bsz, h) + out.shape[1:])

    _, vjp = jax.vjp(flat_oracle, f1, f2)
    # The oracle emits fp32; a bf16-out kernel hands back a bf16 cotangent.
    df1, df2 = vjp(g.astype(jnp.float32))
    return df1, df2, jnp.zeros_like(coords)


_alt_lookup.defvjp(_alt_fwd, _alt_bwd)


def make_alt_tpu_corr_fn(fmap1: jax.Array, fmap2: jax.Array, *,
                         num_levels: int, radius: int, out_dtype=None):
    out_dtype = jnp.float32 if out_dtype is None else out_dtype
    b, h, w1, d = fmap1.shape
    w2 = fmap2.shape[2]
    widths = level_widths(w2, num_levels)
    scale = 1.0 / math.sqrt(d)
    # One width pad to a 128-multiple divisible by 2^(num_levels-1) so the
    # in-kernel pooling chain stays aligned (128 = 2^7 covers any level
    # count the model uses).
    f2p = jnp.pad(fmap2, ((0, 0), (0, 0), (0, pad_width(w2) - w2), (0, 0)))

    def corr_fn(coords_x: jax.Array) -> jax.Array:
        # 4D end to end: batch and height stay real axes, so a
        # (data, space) mesh sharding of the feature maps flows straight
        # into the partitioned kernel.
        coords4 = coords_x.astype(jnp.float32).reshape(b, h, w1, 1)
        return _alt_lookup(fmap1, f2p, coords4, radius, num_levels,
                           widths, scale, out_dtype)

    return corr_fn

"""``alt_tpu``: blockwise fused build+sample correlation, no W^2 volume.

Fills — properly — the hole the reference leaves: its ``alt_cuda`` choice
crashes at construction (``core/corr.py:159-161`` raises
NotImplementedError). This is the memory path for full-resolution work
(Middlebury-F), the framework's "long-context" strategy: recompute the
correlation on the fly instead of materializing the O(B*H*W^2) volume —
the exact trade blockwise/flash attention makes.

Kernel design: one grid cell per image row (b, h). Per level, the cell

1. computes that row's correlation block on the MXU —
   ``vol = f1_row @ f2_row^T / sqrt(D)`` with fp32 accumulation, shape
   ``(W1, W2p_l)``, living only in VMEM;
2. immediately runs the same windowed-gather + lerp as ``reg_tpu``
   (``pallas_reg.gather_lerp_taps``) and writes the ``(W1, 2r+1)`` taps.

Nothing W^2-sized ever reaches HBM: peak footprint per cell is the f1/f2
rows plus one ``(W1, W2p)`` VMEM block (~2.3 MB at Middlebury-F 1/4-res).
The MXU rebuilds the volume every lookup — FLOPs traded for HBM exactly
as the reference's ``alt`` trades them for CUDA memory (``README.md:121``).

Math note: sampling fmap2 first and dotting (the reference's ``alt``,
``core/corr.py:72-87``) equals lerping the on-the-fly volume row (the dot
is linear), so this matches ``reg`` bit-for-bit up to fp association —
property-tested against both.

Backward: ``custom_vjp`` to the feature maps via the masked one-hot XLA
formulation (H-chunked to bound the transient volume), no coord grad —
the reference detaches coords each GRU iteration (``raft_stereo.py:109``).
"""

from __future__ import annotations

import functools
import math
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_stereo_tpu.corr.pallas_reg import (
    _interpret, gather_lerp_taps, level_widths, pad_width)
from raft_stereo_tpu.ops.chunked import map_chunked
from raft_stereo_tpu.ops.pooling import avg_pool_w2


def _alt_kernel(coords_ref, f1_ref, *refs, radius: int,
                widths: Sequence[int], scale: float):
    *f2_refs, out_ref = refs
    k = 2 * radius + 1
    c = coords_ref[0]  # (W1, 1)
    f1 = f1_ref[0]     # (W1, D)
    for lvl, f2_ref in enumerate(f2_refs):
        f2 = f2_ref[0]  # (W2p_l, D)
        vol = jax.lax.dot_general(
            f1, f2, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (W1, W2p_l)
        cl = c * (1.0 / (1 << lvl))
        out_ref[0, :, lvl * k:(lvl + 1) * k] = gather_lerp_taps(
            vol, cl, radius, widths[lvl])


def _pallas_alt(f1: jax.Array, f2_levels: Sequence[jax.Array],
                coords: jax.Array, radius: int,
                widths: Tuple[int, ...], scale: float) -> jax.Array:
    """f1: (BH, W1, D); f2_levels: (BH, W2p_l, D); coords: (BH, W1, 1)."""
    bh, w1, d = f1.shape
    k = 2 * radius + 1
    out_ch = len(f2_levels) * k
    kernel = functools.partial(_alt_kernel, radius=radius, widths=widths,
                               scale=scale)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, w1, out_ch), jnp.float32),
        grid=(bh,),
        in_specs=[pl.BlockSpec((1, w1, 1), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((1, w1, d), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)] +
                 [pl.BlockSpec((1, f2l.shape[1], d), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)
                  for f2l in f2_levels],
        out_specs=pl.BlockSpec((1, w1, out_ch), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(coords, f1, *f2_levels)


def _masked_alt_xla(f1: jax.Array, f2_levels: Sequence[jax.Array],
                    coords: jax.Array, radius: int,
                    widths: Tuple[int, ...], scale: float) -> jax.Array:
    """On-the-fly masked one-hot reference — the custom_vjp backward.

    H-chunked via lax.map so the transient (chunk, W1, W2p) volume stays
    bounded; regular VPU/MXU work in both directions (scatters don't
    vectorize on TPU).
    """
    def chunk(args):
        f1_c, coords_c, *f2_c = args
        out = []
        for lvl, f2l in enumerate(f2_c):
            w2p = f2l.shape[-2]
            vol = jnp.einsum("nwd,nvd->nwv", f1_c, f2l,
                             preferred_element_type=jnp.float32) * scale
            cl = coords_c * (1.0 / (1 << lvl))
            i0 = jnp.floor(cl)
            frac = cl - i0
            base = i0 - radius
            j = jnp.arange(w2p, dtype=jnp.float32)
            valid_j = j < widths[lvl]
            taps = []
            for t in range(2 * radius + 2):
                onehot = ((j == base + t) & valid_j).astype(vol.dtype)
                taps.append(jnp.sum(vol * onehot, axis=-1))
            g = jnp.stack(taps, axis=-1)
            out.append(g[..., :-1] * (1.0 - frac) + g[..., 1:] * frac)
        return jnp.concatenate(out, axis=-1)

    return map_chunked(chunk, (f1, coords, *f2_levels), chunk=8, axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _alt_lookup(f1, f2_levels: List[jax.Array], coords, radius: int,
                widths: Tuple[int, ...], scale: float):
    return _pallas_alt(f1, f2_levels, coords, radius, widths, scale)


def _alt_fwd(f1, f2_levels, coords, radius, widths, scale):
    out = _alt_lookup(f1, f2_levels, coords, radius, widths, scale)
    return out, (f1, f2_levels, coords)


def _alt_bwd(radius, widths, scale, residuals, g):
    f1, f2_levels, coords = residuals
    _, vjp = jax.vjp(
        lambda a, b: _masked_alt_xla(a, b, coords, radius, widths, scale),
        f1, f2_levels)
    df1, df2 = vjp(g)
    return df1, df2, jnp.zeros_like(coords)


_alt_lookup.defvjp(_alt_fwd, _alt_bwd)


def make_alt_tpu_corr_fn(fmap1: jax.Array, fmap2: jax.Array, *,
                         num_levels: int, radius: int):
    b, h, w1, d = fmap1.shape
    w2 = fmap2.shape[2]
    widths = level_widths(w2, num_levels)
    scale = 1.0 / math.sqrt(d)
    # Pool fmap2 per level on the UNPADDED width (reference semantics),
    # then zero-pad each level's width for the kernel's vreg windows.
    pyr2 = [fmap2.astype(jnp.float32)]
    for _ in range(num_levels - 1):
        pyr2.append(avg_pool_w2(pyr2[-1]))
    f2_levels = []
    for lvl, f2l in enumerate(pyr2):
        wl = f2l.shape[2]
        f2l = jnp.pad(f2l, ((0, 0), (0, 0), (0, pad_width(wl) - wl), (0, 0)))
        f2_levels.append(f2l.reshape(b * h, -1, d))
    f1_flat = fmap1.astype(jnp.float32).reshape(b * h, w1, d)

    def corr_fn(coords_x: jax.Array) -> jax.Array:
        coords_flat = coords_x.astype(jnp.float32).reshape(b * h, w1, 1)
        out = _alt_lookup(f1_flat, f2_levels, coords_flat, radius, widths,
                          scale)
        return out.reshape(b, h, w1, -1)

    return corr_fn

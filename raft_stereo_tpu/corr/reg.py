"""``reg`` correlation: precomputed all-pairs 1D volume + pyramid, XLA lookup.

Reference ``CorrBlock1D`` (``core/corr.py:110-156``): the volume is one big
batched matmul over the feature dim — ideal MXU work — followed by width
halving via 1x2 average pooling. The lookup gathers ``2r+1`` taps per pixel per
level with zero-padded linear interpolation.

Reference quirk reproduced *in effect only*: the torch code appends the base
level plus ``num_levels`` pooled levels (``corr.py:122-125``) but indexes only
the first ``num_levels`` (``corr.py:133``); building the unused last level is
wasted work, so only levels ``0..num_levels-1`` are materialized here (outputs
are identical).

Memory: O(B * H * W^2) fp32 — for full-resolution work use ``alt``/``alt_tpu``
(the reference documents the same guidance, ``README.md:121``).
"""

from __future__ import annotations

import math
from typing import List

import jax
import jax.numpy as jnp

from raft_stereo_tpu.ops.pooling import avg_pool_last


def build_volume(fmap1: jax.Array, fmap2: jax.Array) -> jax.Array:
    """All-pairs 1D correlation along epipolar rows: (B, H, W1, W2), fp32.

    Matches ``CorrBlock1D.corr`` (``core/corr.py:148-156``): dot over the
    feature dim, normalized by sqrt(D).
    """
    d = fmap1.shape[-1]
    vol = jnp.einsum("bhid,bhjd->bhij",
                     fmap1.astype(jnp.float32), fmap2.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return vol / math.sqrt(d)


def build_pyramid(volume: jax.Array, num_levels: int) -> List[jax.Array]:
    """Width-halving pyramid: level i has shape (B, H, W1, W2 // 2^i)."""
    pyramid = [volume]
    for _ in range(num_levels - 1):
        pyramid.append(avg_pool_last(pyramid[-1]))
    return pyramid


def lookup_pyramid(pyramid: List[jax.Array], coords_x: jax.Array,
                   radius: int) -> jax.Array:
    """Sample ``2r+1`` lerped taps around ``coords_x / 2^i`` at every level.

    coords_x: (B, H, W1) fractional x positions at full (1/4-res) width.
    Returns (B, H, W1, num_levels * (2r+1)), level-major then offset -r..r
    (the concat order of ``core/corr.py:132-145``).

    TPU formulation: the taps sit at consecutive integer offsets from one
    fractional base, so the ``2r+1`` samples share ``2r+2`` integer taps and
    one lerp fraction. Each integer tap is a one-hot reduce over the volume
    row (regular VPU work; per-pixel gathers lower to serial loops on TPU and
    measured ~45x slower — see ``ops/sampler.py``).
    """
    out = []
    for i, vol in enumerate(pyramid):
        w2 = vol.shape[-1]
        cl = coords_x.astype(jnp.float32) / (2 ** i)
        i0 = jnp.floor(cl)
        frac = (cl - i0)[..., None]
        j = jnp.arange(w2, dtype=jnp.float32)
        taps = []
        for d in range(-radius, radius + 2):  # 2r+2 integer taps
            onehot = (j == (i0[..., None] + d)).astype(vol.dtype)
            taps.append(jnp.sum(vol * onehot, axis=-1))
        g = jnp.stack(taps, axis=-1)  # (B, H, W1, 2r+2)
        out.append(g[..., :-1] * (1.0 - frac) + g[..., 1:] * frac)
    return jnp.concatenate(out, axis=-1)


def make_reg_corr_fn(fmap1: jax.Array, fmap2: jax.Array, *,
                     out_dtype=None,
                     num_levels: int, radius: int):
    pyramid = build_pyramid(build_volume(fmap1, fmap2), num_levels)

    def corr_fn(coords_x: jax.Array) -> jax.Array:
        out = lookup_pyramid(pyramid, coords_x, radius)
        # XLA fuses this convert into the reduce epilogue (free, unlike a
        # convert on a Pallas custom-call output).
        return out if out_dtype is None else out.astype(out_dtype)

    return corr_fn

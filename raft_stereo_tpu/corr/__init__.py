"""Correlation-volume implementations behind one protocol.

``make_corr_fn(impl, fmap1, fmap2, num_levels, radius)`` returns a traceable
closure ``corr_fn(coords_x) -> (B, H, W1, num_levels * (2r+1))`` where
``coords_x`` is the x-channel of the current matching coordinates, shape
``(B, H, W1)``. The closure is pure, so it can be captured by the GRU
refinement ``lax.scan``; the pyramid (if any) is traced once outside the loop.

Implementations (reference ``core/corr.py`` / ``core/raft_stereo.py:90-100``):

- ``reg``      — precomputed all-pairs volume + pyramid, XLA gather-lerp lookup
                 (CorrBlock1D, ``core/corr.py:110-156``).
- ``alt``      — on-the-fly: no W^2 volume, samples pooled fmap2 rows per lookup
                 (PytorchAlternateCorrBlock1D, ``core/corr.py:64-107``); the
                 memory-efficient path for full-resolution inputs.
- ``reg_tpu``  — ``reg`` with the lookup as a Pallas TPU kernel
                 (``pallas_reg.py``; the analog of the reference's CUDA
                 ``corr_sampler`` extension, ``sampler/``).
- ``alt_tpu``  — blockwise fused build+sample Pallas kernel, no W^2 volume in
                 HBM (``pallas_alt.py``; fills the hole the reference left:
                 its ``alt_cuda`` choice crashes, ``core/corr.py:159-161``).
- ``reg_cuda`` / ``alt_cuda`` — accepted for CLI compatibility, aliased to the
                 TPU-native kernels.

All four implementations produce identical outputs on one protocol
(property-tested in ``tests/test_corr.py``, gradients included); channel order
is level-major, then offset ``-r..r`` — the order the motion encoder's weights
expect.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax

from raft_stereo_tpu.corr.reg import make_reg_corr_fn
from raft_stereo_tpu.corr.alt import make_alt_corr_fn

CorrFn = Callable[[jax.Array], jax.Array]

_ALIASES = {"reg_cuda": "reg_tpu", "alt_cuda": "alt_tpu"}


def make_corr_fn(impl: str, fmap1: jax.Array, fmap2: jax.Array, *,
                 num_levels: int = 4, radius: int = 4,
                 out_dtype=None) -> CorrFn:
    """Build a correlation lookup closure. fmaps are NHWC ``(B, H, W, D)``.

    ``out_dtype`` (default fp32) is the dtype of the returned taps. The
    Pallas kernels downcast INSIDE the kernel — an external
    ``astype`` on a custom-call output is a separate full-tensor XLA pass
    (~8 ms/frame at Middlebury-F), while the XLA paths fuse it for free.
    Lerp arithmetic is fp32 regardless.
    """
    impl = _ALIASES.get(impl, impl)
    kw = dict(num_levels=num_levels, radius=radius, out_dtype=out_dtype)
    if impl == "reg":
        return make_reg_corr_fn(fmap1, fmap2, **kw)
    if impl == "alt":
        return make_alt_corr_fn(fmap1, fmap2, **kw)
    if impl == "reg_tpu":
        from raft_stereo_tpu.corr.pallas_reg import make_reg_tpu_corr_fn
        return make_reg_tpu_corr_fn(fmap1, fmap2, **kw)
    if impl == "alt_tpu":
        from raft_stereo_tpu.corr.pallas_alt import make_alt_tpu_corr_fn
        return make_alt_tpu_corr_fn(fmap1, fmap2, **kw)
    raise ValueError(f"unknown corr implementation {impl!r}")

"""Version-bridging shims for the two JAX APIs this project straddles.

The TPU host runs a current JAX (``pltpu.CompilerParams``,
``custom_partitioning.def_partition(..., sharding_rule=)``); CPU-only CI
images may carry an older release where the params class is still
``TPUCompilerParams`` and ``def_partition`` predates Shardy sharding
rules. Only the names/signatures changed — semantics are identical for
everything this project uses — so each shim resolves the available form
once at import time. Dropping ``sharding_rule`` on old JAX only loses
Shardy-mode propagation, which the GSPMD callbacks (always passed) cover.
"""

from __future__ import annotations

import inspect

from jax.experimental.pallas import tpu as pltpu

_PARAMS_CLS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


def modern_jax() -> bool:
    """True when ``def_partition`` understands Shardy sharding rules —
    the proxy for the JAX generation this project targets. Old releases
    still run the single-device paths correctly (the shims above), but
    their XLA:CPU crashes (hard SIGSEGV, not an exception) compiling
    custom-partitioned Pallas programs under a mesh, so mesh-heavy tests
    skip on them rather than take down the whole pytest process."""
    from jax.experimental.custom_partitioning import custom_partitioning
    return "sharding_rule" in inspect.signature(
        custom_partitioning.def_partition).parameters


def compiler_params(**kwargs):
    """``pltpu.CompilerParams`` on current JAX, ``TPUCompilerParams`` on
    older releases (same fields, e.g. ``vmem_limit_bytes``)."""
    return _PARAMS_CLS(**kwargs)


def def_partition(fn, partition, infer_sharding_from_operands, *,
                  sharding_rule=None, need_replication_factors=()):
    """``custom_partitioning.def_partition`` across the Shardy transition:
    pass the einsum-like rule where supported, silently omit it where the
    signature predates it (GSPMD callbacks carry the semantics there)."""
    params = inspect.signature(fn.def_partition).parameters
    kwargs = {}
    if "sharding_rule" in params and sharding_rule is not None:
        kwargs["sharding_rule"] = sharding_rule
        if "need_replication_factors" in params:
            kwargs["need_replication_factors"] = need_replication_factors
    fn.def_partition(partition,
                     infer_sharding_from_operands=infer_sharding_from_operands,
                     **kwargs)

"""Streaming Pallas passes for the encoders' full-resolution trunks.

The cnet/fnet stem + layer1 run at FULL image resolution (stride-1 stem
for ``n_downsample=2``, reference ``core/extractor.py:122-146,199-225``):
five convs whose activations are ~770 MB each at Middlebury-F. Under XLA
every conv/norm/relu materializes in HBM and the small-channel (3->64,
64ch) shapes run far off roofline (profiled ~340 ms per frame for both
encoders against a ~50 ms bound).

Design: ONE streamed pass per conv (ops/pallas_stream.py ring-window
machinery). Pass k reads conv k-1's RAW output, applies the input
transform inline — for fnet: relu((x - mean) * inv) with the instance-norm
stats pass k-1 accumulated in scratch; for cnet the frozen BatchNorm is
folded into the conv weights (the reference never updates BN —
``freeze_bn``, ``train_stereo.py:151``), so the same kernels run with
mean=0, inv=1 — convolves, and writes conv k's raw output while
accumulating its stats. The global-stats barrier between instance-norm
convs thus costs one HBM round trip per conv, the minimum possible.

Per-pass details that matter on v5e:
- outputs are emitted BLOCK-ALIGNED (a one-block ring delays the write by
  one grid step), so chained passes never pay an unaligned-row slice copy
  of a 770 MB tensor;
- the 7x7 stem runs as 7 per-dy dots with all 7 dx-taps merged into the
  dot's N dimension (4 -> 7*64 channels), then cheap shifted slice-adds —
  49 tiny-K MXU passes would be pipeline-fill-bound;
- row blocks are tall (th<=24): per-step fixed costs (MXU fill, DMA
  issue) dominate these low-arithmetic-intensity convs.

Residual structure (reference ResidualBlock, core/extractor.py:6-60):
x = act(stem); y1 = act(conv1(x)); y2 = conv2(y1);
o1 = relu(x + act0(y2)); y3 = act(conv3(o1)); y4 = conv4(y3);
out = relu(o1 + act0(y4)) — where act = relu(norm(.)) and act0 likewise;
identity shortcuts (stride-1, equal channels) only.

Gradients via custom_vjp through the XLA oracles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_stereo_tpu.ops.pallas_stream import (
    _conv_rows, _dot, _interpret, _row_mask, _shift, _zeros)

_ENC_VMEM = 120 * 2**20  # v5e has 128M physical

# Default-off: the streamed encoder passes are numerically validated
# (tests/test_fused_stream.py) but the 12-kernel program currently drives
# the AOT TPU compiler into multi-ten-minute compiles / OOM at full
# Middlebury-F width, so the production path keeps the XLA encoders.
# RAFT_FUSED_ENCODERS=1 opts in for experimentation.
import os as _os

ENABLE = _os.environ.get("RAFT_FUSED_ENCODERS", "0").lower() not in (
    "0", "false", "no", "")


def _enc_th(hh: int, width: int) -> int:
    """Row-block for the encoder passes (single conv + small scratches:
    tall blocks amortize per-step fixed costs)."""
    for th in (24, 16, 12, 8, 6, 4, 2):
        if hh % th == 0 and th * width <= 72 * 1024:
            return th
    return 0


def _normed(raw, m_ref, v_ref):
    """relu((raw - mean) * inv) in fp32 -> raw.dtype."""
    x = raw.astype(jnp.float32) - m_ref[...].astype(jnp.float32)
    return jax.nn.relu(x * v_ref[...].astype(jnp.float32)).astype(raw.dtype)


def _conv7_rows(scr, w7, th, width):
    """7x7 conv over a (>=th+6, width+6, 4) window: 7 per-dy dots with the
    7 dx-taps stacked along N (4 -> 7*Cout), then shifted slice-adds."""
    cout = w7.shape[-1] // 7
    acc = None
    for dy in range(7):
        r = _dot(scr[dy:dy + th], w7[dy])
        for dx in range(7):
            y = r[:, dx:dx + width, dx * cout:(dx + 1) * cout]
            acc = y if acc is None else acc + y
    return acc


def _aligned_out(out_ref, scr_prev, new, lag: int, th: int):
    """Emit block max(i-1, 0) = true rows [(i-1)T, iT) from the previous
    step's tail + this step's head; keeps outputs block-aligned so chained
    passes never pay an unaligned-row slice copy."""
    out_ref[0:th - lag] = scr_prev[lag:th]
    out_ref[th - lag:th] = new[0:lag]
    scr_prev[...] = new


def _pass_kernel(*refs, kind: str, th: int, nb: int, width: int, hh: int,
                 stats: bool):
    """kind: 'stem7' (7x7 on the raw 4-ch image), 'mid1'
    (relu(norm(x)) -> 3x3), 'mid2' (relu(relu(norm(a)) + relu(norm(b)))
    -> 3x3), 'point3' (relu(relu(relu(norm(s)) + relu(norm(y2)))
    + relu(norm(y4))), no conv)."""
    i = pl.program_id(0)
    k = 0

    def take(n):
        nonlocal k
        r = refs[k:k + n]
        k += n
        return r

    if kind == "stem7":
        (img_ref,), (w_ref, b_ref) = take(1), take(2)
    elif kind == "mid1":
        (x_ref, m_ref, v_ref), (w_ref, b_ref) = take(3), take(2)
    elif kind == "mid2":
        (a_ref, ma_ref, va_ref, b2_ref, mb_ref, vb_ref) = take(6)
        (w_ref, b_ref) = take(2)
    else:  # point3
        (s_ref, ms_ref, vs_ref, y2_ref, m2_ref, v2_ref,
         y4_ref, m4_ref, v4_ref) = take(9)
        (out_ref,) = take(1)
        o1 = jax.nn.relu(
            _normed(s_ref[...], ms_ref, vs_ref).astype(jnp.float32)
            + _normed(y2_ref[...], m2_ref, v2_ref))
        out_ref[...] = jax.nn.relu(
            o1 + _normed(y4_ref[...], m4_ref, v4_ref)).astype(out_ref.dtype)
        return

    out_ref = take(1)[0]
    st_ref = take(1)[0] if stats else None
    scr_in, scr_prev = take(2)
    scr_st = take(1)[0] if stats else None
    dtype = out_ref.dtype
    lag = 3 if kind == "stem7" else 1
    pad = 3 if kind == "stem7" else 1

    @pl.when(i == 0)
    def _init():
        _zeros(scr_in)
        if stats:
            scr_st[...] = jnp.zeros(scr_st.shape, scr_st.dtype)

    _shift(scr_in, 2 * lag)

    @pl.when(i < nb)
    def _place():
        if kind == "stem7":
            scr_in[2 * lag:2 * lag + th, pad:width + pad] = img_ref[...]
        elif kind == "mid1":
            scr_in[2 * lag:2 * lag + th, pad:width + pad] = _normed(
                x_ref[...], m_ref, v_ref)
        else:
            o1 = jax.nn.relu(
                _normed(a_ref[...], ma_ref, va_ref).astype(jnp.float32)
                + _normed(b2_ref[...], mb_ref, vb_ref)).astype(dtype)
            scr_in[2 * lag:2 * lag + th, pad:width + pad] = o1

    @pl.when(i >= nb)
    def _flush():
        _zeros(scr_in, slice(2 * lag, 2 * lag + th))

    if kind == "stem7":
        acc = _conv7_rows(scr_in, w_ref, th, width)
    else:
        acc = _conv_rows(scr_in, w_ref, th, width)
    out = acc + b_ref[...].astype(jnp.float32)
    new = out.astype(dtype)
    _aligned_out(out_ref, scr_prev, new, lag, th)

    if stats:
        # Running sums over VALID out rows (conv-of-zero + bias at the
        # top/flush rows would poison the next pass's normalize).
        contrib = _row_mask(i, -lag, th, hh, out)
        scr_st[0] += jnp.sum(contrib, axis=(0, 1))
        scr_st[1] += jnp.sum(jnp.square(contrib), axis=(0, 1))
        st_ref[...] = scr_st[...]


def _stats_to_mv(stats, n: int, eps: float = 1e-5):
    mean = stats[0] / n
    var = jnp.maximum(stats[1] / n - jnp.square(mean), 0.0)
    return mean.reshape(1, -1), jax.lax.rsqrt(var + eps).reshape(1, -1)


def _run_pass(kind, inputs, w, bias, hh, width, cout, dtype, stats: bool):
    """One streamed pass. inputs: list of (raw(H,W,C), mean, inv) triples
    ((img4, None, None) for stem7). Returns (raw_out(H,W,cout), stats?)."""
    th = _enc_th(hh, width)
    nb = hh // th
    lag = 0 if kind == "point3" else (3 if kind == "stem7" else 1)
    grid = nb + 1 if lag else nb

    def idx_in(i):
        return (jnp.minimum(i, nb - 1), 0, 0)

    in_specs, args = [], []
    for raw, m, v in inputs:
        in_specs.append(pl.BlockSpec((th, width, raw.shape[-1]), idx_in,
                                     memory_space=pltpu.VMEM))
        args.append(raw)
        if m is not None:
            for t in (m, v):
                in_specs.append(pl.BlockSpec(t.shape, lambda i: (0, 0),
                                             memory_space=pltpu.VMEM))
                args.append(t)
    if kind != "point3":
        for t in (w, bias):
            in_specs.append(pl.BlockSpec(t.shape,
                                         lambda i, nd=t.ndim: (0,) * nd,
                                         memory_space=pltpu.VMEM))
            args.append(t)

    kernel = functools.partial(_pass_kernel, kind=kind, th=th, nb=nb,
                               width=width, hh=hh, stats=stats)
    common = dict(
        grid=(grid,), in_specs=in_specs,
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=_ENC_VMEM),
        interpret=_interpret())
    if kind == "point3":
        return pl.pallas_call(
            kernel,
            out_specs=pl.BlockSpec((th, width, cout), lambda i: (i, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((hh, width, cout), dtype),
            **common)(*args)

    out_specs = [pl.BlockSpec((th, width, cout),
                              lambda i: (jnp.maximum(i - 1, 0), 0, 0),
                              memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((hh, width, cout), dtype)]
    if stats:
        out_specs.append(pl.BlockSpec((2, cout), lambda i: (0, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((2, cout), jnp.float32))
    scratch = [pltpu.VMEM((th + 2 * lag, width + 2 * pad_of(kind),
                           inputs[0][0].shape[-1]), dtype),
               pltpu.VMEM((th, width, cout), dtype)]
    if stats:
        scratch.append(pltpu.VMEM((2, cout), jnp.float32))
    outs = pl.pallas_call(
        kernel, out_specs=tuple(out_specs) if stats else out_specs[0],
        out_shape=tuple(out_shape) if stats else out_shape[0],
        scratch_shapes=scratch, **common)(*args)
    return outs if stats else (outs, None)


def pad_of(kind: str) -> int:
    return 3 if kind == "stem7" else 1


def _stem7_weights(w, dtype):
    """(7,7,3,Cout) -> per-dy merged-N (7, 4, 7*Cout): channel-pad K to 4,
    stack the dx taps along N."""
    cout = w.shape[-1]
    w4 = jnp.pad(w.astype(jnp.float32), ((0, 0), (0, 0), (0, 1), (0, 0)))
    return w4.transpose(0, 2, 1, 3).reshape(7, 4, 7 * cout).astype(dtype)


def _ident_mv(c):
    return jnp.zeros((1, c), jnp.float32), jnp.ones((1, c), jnp.float32)


def _fold_bn(conv: dict, bn: dict, dtype, eps: float = 1e-5):
    """Fold frozen-BN stats into the preceding conv (fp32 fold, one cast)."""
    k = (bn["scale"] * jax.lax.rsqrt(bn["var"] + eps)).astype(jnp.float32)
    w = conv["w"].astype(jnp.float32) * k
    b = (conv.get("b", 0.0) - bn["mean"]) * k + bn["bias"]
    return w.astype(dtype), jnp.asarray(b, jnp.float32).reshape(1, -1)


def _trunk_passes(x4, convs, hh, width, dtype, instance: bool):
    """Shared stem+layer1 chain. convs: [(w_stem7, b), (w3x3, b) x4] — BN
    pre-folded for the frozen-BN (cnet) trunk, raw for instance norm."""
    n = hh * width

    def mv(st, c):
        return _stats_to_mv(st, n) if instance else _ident_mv(c)

    (ws, bs), (w1, b1), (w2, b2), (w3, b3), (w4, b4) = convs
    stem, st = _run_pass("stem7", [(x4, None, None)], ws, bs,
                         hh, width, 64, dtype, instance)
    m1, v1 = mv(st, 64)
    y1, st = _run_pass("mid1", [(stem, m1, v1)], w1, b1,
                       hh, width, 64, dtype, instance)
    my, vy = mv(st, 64)
    y2, st = _run_pass("mid1", [(y1, my, vy)], w2, b2,
                       hh, width, 64, dtype, instance)
    m2, v2 = mv(st, 64)
    y3, st = _run_pass("mid2", [(stem, m1, v1), (y2, m2, v2)], w3, b3,
                       hh, width, 64, dtype, instance)
    m3, v3 = mv(st, 64)
    y4, st = _run_pass("mid1", [(y3, m3, v3)], w4, b4,
                       hh, width, 64, dtype, instance)
    m4, v4 = mv(st, 64)
    o2 = _run_pass("point3", [(stem, m1, v1), (y2, m2, v2), (y4, m4, v4)],
                   None, None, hh, width, 64, dtype, False)
    return o2[None]


def fused_stem_layer1_impl(p: dict, x: jax.Array):
    """Frozen-BN (cnet) stem + layer1; BN folded into the conv weights."""
    b, hh, width, _ = x.shape
    assert b == 1
    dtype = x.dtype
    blk1, blk2 = p["layer1"]
    ws, bs = _fold_bn(p["conv1"], p["norm1"], jnp.float32)
    convs = [(_stem7_weights(ws, dtype), bs)]
    for blk in (blk1, blk2):
        convs.append(_fold_bn(blk["conv1"], blk["norm1"], dtype))
        convs.append(_fold_bn(blk["conv2"], blk["norm2"], dtype))
    x4 = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, 1)))[0]
    return _trunk_passes(x4, convs, hh, width, dtype, instance=False)


def fused_in_stem_layer1_impl(p: dict, x: jax.Array):
    """Instance-norm (fnet) stem + layer1 for one (1, H, W, 3) image."""
    b, hh, width, _ = x.shape
    assert b == 1
    dtype = x.dtype
    blk1, blk2 = p["layer1"]

    def cb(conv):
        return conv["w"].astype(dtype), conv["b"].reshape(1, -1)

    convs = [(_stem7_weights(p["conv1"]["w"], dtype),
              p["conv1"]["b"].reshape(1, -1)),
             cb(blk1["conv1"]), cb(blk1["conv2"]),
             cb(blk2["conv1"]), cb(blk2["conv2"])]
    x4 = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, 1)))[0]
    return _trunk_passes(x4, convs, hh, width, dtype, instance=True)


def _fusable(p: dict, x, stride: int) -> bool:
    from raft_stereo_tpu.ops.pallas_stream import _dtype_ok
    if not ENABLE:
        return False
    if not (_dtype_ok(x) and x.shape[0] == 1 and stride == 1
            and x.shape[1] >= 24 and _enc_th(x.shape[1], x.shape[2]) > 0):
        return False
    blk1, blk2 = p["layer1"]
    # Identity shortcuts only (stride-1 equal-channel layer1 blocks).
    return "downsample" not in blk1 and "downsample" not in blk2


def stem_layer1_is_fusable(p: dict, x, norm_fn: str, stride: int) -> bool:
    return norm_fn == "batch" and _fusable(p, x, stride)


def in_stem_layer1_is_fusable(p: dict, x, norm_fn: str, stride: int) -> bool:
    return norm_fn == "instance" and _fusable(p, x, stride)


def _oracle(p: dict, x):
    from raft_stereo_tpu.models.layers import apply_conv, apply_residual_block
    from raft_stereo_tpu.ops.basic import frozen_batch_norm
    h = apply_conv(p["conv1"], x, stride=1, padding=3)
    h = jax.nn.relu(frozen_batch_norm(h, p["norm1"]))
    for blk in p["layer1"]:
        h = apply_residual_block(blk, h, "batch", stride=1)
    return h


def _in_oracle(p: dict, x):
    from raft_stereo_tpu.models.layers import apply_conv, apply_residual_block
    from raft_stereo_tpu.ops.basic import instance_norm
    h = apply_conv(p["conv1"], x, stride=1, padding=3)
    h = jax.nn.relu(instance_norm(h))
    for blk in p["layer1"]:
        h = apply_residual_block(blk, h, "instance", stride=1)
    return h


@jax.custom_vjp
def fused_stem_layer1(p: dict, x):
    """cnet stem + layer1 via streamed passes; backward via the XLA oracle."""
    return fused_stem_layer1_impl(p, x)


def _fwd(p, x):
    return fused_stem_layer1(p, x), (p, x)


def _bwd(res, g):
    p, x = res
    out, vjp = jax.vjp(_oracle, p, x)
    return vjp(g.astype(out.dtype))


fused_stem_layer1.defvjp(_fwd, _bwd)


@jax.custom_vjp
def fused_in_stem_layer1(p: dict, x):
    """fnet stem + layer1 via streamed norm-conv passes; backward via the
    XLA oracle."""
    return fused_in_stem_layer1_impl(p, x)


def _in_fwd(p, x):
    return fused_in_stem_layer1(p, x), (p, x)


def _in_bwd(res, g):
    p, x = res
    out, vjp = jax.vjp(_in_oracle, p, x)
    return vjp(g.astype(out.dtype))


fused_in_stem_layer1.defvjp(_in_fwd, _in_bwd)

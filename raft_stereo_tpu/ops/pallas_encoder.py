"""Streaming Pallas passes for the encoders' full-resolution trunks.

The cnet/fnet stem + layer1 run at FULL image resolution (stride-1 stem
for ``n_downsample=2``, reference ``core/extractor.py:122-146,199-225``):
five convs whose activations are ~770 MB each at Middlebury-F. Under XLA
every conv/norm/relu materializes in HBM — the profiled frame spends
~150 ms in pure normalize/relu/copy passes and runs the small-channel
convs far off roofline (~340 ms total for both encoders against a
~50 ms bound).

Design: ONE streamed pass per conv (ring-window row streaming like
``ops/pallas_stream.py``). Pass k reads conv k-1's RAW output, applies
the input transform inline — for fnet: relu((x - mean) * inv) with the
instance-norm stats pass k-1 accumulated in scratch; for cnet the frozen
BatchNorm is folded into the conv weights (the reference never updates
BN — ``freeze_bn``, ``train_stereo.py:151``), so the same kernels run
with mean=0, inv=1 — convolves, and writes conv k's raw output while
accumulating its stats. The global-stats barrier between instance-norm
convs thus costs one HBM round trip per conv, the minimum possible.

Three structural choices that make this compile AND run fast on v5e:

- **Pixel-pair packed layout.** Every chain tensor lives as
  ``(H, W/2, 128)`` with channel ``c + 64*(w % 2)`` — two adjacent
  pixels' 64 channels fill one 128-lane vreg. A 64-channel tensor in
  the native ``T(8,128)`` tiling wastes HALF of every vector register,
  HBM tile, and MXU pass; packing halves HBM traffic and fills the
  MXU's N dimension. A 3x3 conv on the packed layout is the SAME
  9-dot ring structure (``_conv_rows``) with block-assembled
  ``(128, 128)`` weights over packed-column offsets (``_pack_w3``).
- **Width strips.** Mosaic code size — and with it compile time on the
  remote TPU compiler — scales with the vregs each vector op touches:
  the structure that compiles in tens of seconds at the GRU kernels'
  W≈744 takes >10 minutes at full Middlebury-F width (2976), measured.
  Every pass computes one strip per grid step — grid
  ``(row_blocks+1, n_strips+1)`` with strips minor. Step (i, s) lands
  input strip s of row block i into a full-width VMEM ring window
  (strip-local placement bounds live vregs — a full-width normalize at
  th=24 spilled ~80 MB), then convolves strip s-1, whose right-halo
  column was just landed.
- **The 7x7 stem is a pointwise batched dot.** A stride-1 7x7 conv
  over 3 channels is pathological everywhere: XLA runs it at ~3% MXU
  (~20 ms/image); in-kernel tap loops leave the MXU >90% idle; and any
  narrow-channel patches tensor in channel-minor layout pads 128/x in
  HBM (the ``conv_general_dilated_patches`` route measured 63 ms/image
  + OOM-scale padding). Instead XLA builds a TAP-MAJOR packed patches
  tensor ``(H, 294, W/2)`` (294 = 7*7*3 taps x 2 pixel parities) from
  cheap W-minor strided slices, and the stem kernel contracts the tap
  dimension on the MXU in one batched dot per row block, emitting the
  packed chain layout directly.

Residual structure (reference ResidualBlock, core/extractor.py:6-60):
x = act(stem); y1 = act(conv1(x)); y2 = conv2(y1);
o1 = relu(x + act0(y2)); y3 = act(conv3(o1)); y4 = conv4(y3);
out = relu(o1 + act0(y4)) — where act = relu(norm(.)) and act0 likewise;
identity shortcuts (stride-1, equal channels) only.

Gradients via custom_vjp through the XLA oracles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_stereo_tpu.ops.jax_compat import compiler_params

from raft_stereo_tpu.ops.pallas_stream import (
    _conv_rows, _interpret, _row_mask, _zeros, _shift)

_ENC_VMEM = 110 * 2**20  # v5e has 128M physical

import os as _os

def ENABLE() -> bool:
    """``RAFT_FUSED_ENCODERS`` kill switch, read at TRACE time (was an
    import-time constant; the serving circuit breaker flips the env var at
    runtime and rebuilds, which only works if every trace re-reads it —
    same pattern as ``_tail_enabled``)."""
    return _os.environ.get("RAFT_FUSED_ENCODERS", "1").lower() not in (
        "0", "false", "no", "")


def _strip_wb(width: int) -> int:
    """Width-strip size in TRUE columns (0 = unsupported).

    ≤768 computed columns per grid step keeps Mosaic code size in the
    regime where kernels compile in tens of seconds; wb % 16 == 0 keeps
    the packed (wb/2-sized) strip slices sublane-aligned (single-strip
    widths are exempt — their one dynamic slice lands at offset 8)."""
    for nwb in range(1, 9):
        wb = width // nwb
        if width % nwb == 0 and wb <= 768 and (wb % 16 == 0 or nwb == 1):
            return wb
    return 0


def _enc_th(hh: int, wp: int) -> int:
    """Row-block over packed-width ``wp`` strips: tall blocks amortize
    the ~5-10 us/step fixed cost the remote v5e shows; the cap bounds
    the full-width VMEM ring window."""
    for th in (48, 32, 24, 16, 12, 8, 6, 4, 2):
        if hh % th == 0 and th * wp <= 12 * 1024:
            return th
    return 0


# ---------------------------------------------------------------------------
# Packed layout helpers: X (H, W, 64) <-> P (H, W/2, 128),
# P[h, u, c + 64p] = X[h, 2u + p, c].
# ---------------------------------------------------------------------------


def _pack_mv(m, v):
    """(1, 64) mean/inv -> (1, 128) duplicated across the pixel parity."""
    return (jnp.concatenate([m, m], axis=-1),
            jnp.concatenate([v, v], axis=-1))


def _pack_bias(b):
    b = b.reshape(1, -1)
    return jnp.concatenate([b, b], axis=-1)


def _unpack_stats(st):
    """(2, 128) packed sums -> (2, 64): the two parities' partial sums
    add per channel."""
    return st[:, :64] + st[:, 64:]


def _pack_w3(w, dtype):
    """(3, 3, 64, 64) [dy, dx, cin, cout] conv weight -> packed
    (3, 3, 128, 128) [dy, packed-dx, cin*parity, cout*parity].

    Out parity 0 (true col 2u) taps true cols 2u-1 (hi of packed u-1),
    2u (lo of u), 2u+1 (hi of u); parity 1 taps 2u (lo u), 2u+1 (hi u),
    2u+2 (lo u+1). Laid out so the packed conv is the same
    three-packed-column ring walk as the unpacked one."""
    z = jnp.zeros_like(w[0, 0])
    packed = []
    for dy in range(3):
        wm1, w0, wp1 = w[dy, 0], w[dy, 1], w[dy, 2]
        pm1 = jnp.block([[z, z], [wm1, z]])      # packed col u-1
        p0 = jnp.block([[w0, wm1], [wp1, w0]])   # packed col u
        pp1 = jnp.block([[z, wp1], [z, z]])      # packed col u+1
        packed.append(jnp.stack([pm1, p0, pp1]))
    return jnp.stack(packed).astype(dtype)


def _normed(raw, m, v):
    """relu((raw - mean) * inv) in fp32 -> raw.dtype."""
    x = raw.astype(jnp.float32) - m.astype(jnp.float32)
    return jax.nn.relu(x * v.astype(jnp.float32)).astype(raw.dtype)


def _stats_update(scr_st, st_ref, contrib):
    """Accumulate per-channel sum / sum-of-squares over valid rows."""
    scr_st[0] += jnp.sum(contrib, axis=(0, 1))
    scr_st[1] += jnp.sum(jnp.square(contrib), axis=(0, 1))
    st_ref[...] = scr_st[...]


# ---------------------------------------------------------------------------
# Stem: tap-major packed patches (XLA) + one batched dot (kernel).
# ---------------------------------------------------------------------------


def stem_halves(x: jax.Array):
    """(1, H, W, 3) image -> even/odd column halves (3, H+8, W/2+4).

    The stem kernel assembles its tap-major patches IN VMEM from these
    two small resident arrays (one strided split here is the only
    strided read — strided DMA runs ~10x off bandwidth — and no
    patches tensor ever reaches HBM: the materialized (294, H, W/2)
    route measured ~11 ms/image of build fusion plus ~4 ms of HBM
    round trip). Padded col pc = true + 3; tap (dy, dx, parity p) for
    out col 2u+p reads pc = 2u + (p+dx): half (p+dx)%2, col
    u + (p+dx)//2."""
    b, hh, width, cin = x.shape
    assert b == 1
    img = x[0].transpose(2, 0, 1)  # (3, H, W)
    # Rows pad to H+8 (not the conv's H+6): the kernel reads aligned
    # (th+8)-row windows whose last one ends at H+8.
    xp = jnp.pad(img, ((0, 0), (3, 5), (3, 5)))  # (3, H+8, W+8)
    xr = xp.reshape(cin, hh + 8, (width + 8) // 2, 2)
    return xr[..., 0], xr[..., 1]


def _stem_weights(w: jax.Array, dtype) -> jax.Array:
    """(7, 7, 3, 64) -> packed (294, 128): tap-major rows in
    ``stem_patches_packed`` order, parity-block-diagonal columns."""
    cout = w.shape[-1]
    flat = w.astype(jnp.float32).transpose(2, 0, 1, 3).reshape(-1, cout)
    z = jnp.zeros_like(flat)
    return jnp.block([[flat, z], [z, flat]]).astype(dtype)


def _stem_kernel(even_ref, odd_ref, w_ref, b_ref, out_ref, st_ref, scr_st,
                 scr_xk, *, th: int, wp: int, cin: int, stats: bool):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        if stats:
            scr_st[...] = jnp.zeros(scr_st.shape, scr_st.dtype)

    # Assemble the (294, th, W/2) tap-major patches block in VMEM from
    # the resident even/odd halves: each tap is one contiguous (th, W/2)
    # copy. Then per image row, one transposed-lhs 2D dot contracts the
    # tap dim (the MXU feeds the transpose; Mosaic has no shape cast for
    # a 3D outer-dim contraction).
    base = pl.multiple_of(i * th, 8)
    we = even_ref[:, pl.ds(base, th + 8)]  # (3, th+8, W/2+4)
    wo = odd_ref[:, pl.ds(base, th + 8)]
    t = 0
    for p_ in range(2):
        for ci in range(cin):
            for dy in range(7):
                for dx in range(7):
                    src = we if (p_ + dx) % 2 == 0 else wo
                    k2 = (p_ + dx) // 2
                    scr_xk[t] = src[ci, dy:dy + th, k2:k2 + wp]
                    t += 1

    bias = b_ref[...].astype(jnp.float32)
    rows = []
    for r in range(th):
        out_r = jax.lax.dot_general(
            scr_xk[:, r], w_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) + bias
        out_ref[r] = out_r.astype(out_ref.dtype)
        rows.append(out_r)
    if stats:
        out = jnp.stack(rows)
        _stats_update(scr_st, st_ref, out)


def _stem_th(hh: int, wp_total: int, taps: int) -> int:
    """Stem row block: bound the in-VMEM tap scratch to ~8 MB. th sits
    on sublane dims, so it must be a multiple of 8."""
    for th in (16, 8):
        if hh % th == 0 and th * taps * wp_total * 2 <= 8 * 2**20:
            return th
    return 0


def _run_stem(halves, w, bias, hh, wp_total, dtype, stats: bool):
    """halves: even/odd (3, H+8, W/2+4). Returns packed raw
    (H, W/2, 128) + stats."""
    even, odd = halves
    cin = even.shape[0]
    taps = 2 * cin * 49
    th = _stem_th(hh, wp_total, taps)
    nb = hh // th
    kernel = functools.partial(_stem_kernel, th=th, wp=wp_total, cin=cin,
                               stats=stats)
    outs = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[pl.BlockSpec(even.shape, lambda i: (0, 0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec(odd.shape, lambda i: (0, 0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec(w.shape, lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec(bias.shape, lambda i: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((th, wp_total, 128), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((2, 128), lambda i: (0, 0),
                                memory_space=pltpu.VMEM)),
        out_shape=(jax.ShapeDtypeStruct((hh, wp_total, 128), dtype),
                   jax.ShapeDtypeStruct((2, 128), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((2, 128), jnp.float32),
                        pltpu.VMEM((taps, th, wp_total), dtype)],
        compiler_params=compiler_params(vmem_limit_bytes=_ENC_VMEM),
        interpret=_interpret(),
    )(even, odd, w, bias)
    return outs if stats else (outs[0], None)


# ---------------------------------------------------------------------------
# 3x3 conv passes ('mid1': one normed input; 'mid2': relu(normed a +
# normed b)) and the final combine+unpack ('point3').
# ---------------------------------------------------------------------------


def _pass_kernel(*refs, kind: str, th: int, nb: int, nwb: int, wp: int,
                 hh: int, stats: bool, quant: bool = False):
    """Grid (nb+1, nwb+1), strips minor; all widths in packed columns.
    Step (i, s) lands input strip s of row block i into the full-width
    ring window, then convolves strip s-1 (whose right-halo column was
    just landed; the extra s=nwb step convolves the last strip, whose
    right halo is image-edge zero pad).

    ``quant`` (RAFT_LANE_PACK8 quantize-on-exit, r24): the grid grows a
    LEADING phase dim — (2, nb+1, nwb+1). Phase 0 runs the full pass but
    only accumulates the row-masked fp32 amax of the bf16-ROUNDED
    outputs; phase 1 re-runs it and emits width-group int8 containers
    quantized with that global per-tensor scale, plus the (1, 1) scale
    itself. Quantizing the ROUNDED values with the exact
    ``max(amax, 1e-30)/127`` fp32 arithmetic of ``feature_scale8`` makes
    the container bitwise identical to a host-side
    ``quantize_pack_feature8`` of the streamed bf16 output — so the
    geometry fallback in models/raft_stereo.py never changes a byte.
    Requires nwb == 1 (the in-register pack needs the whole row in one
    block) and wp % 4 == 0; stats never combines with quant."""
    if quant:
        assert not stats and nwb == 1 and wp % 4 == 0
        ph = pl.program_id(0)
        i, s = pl.program_id(1), pl.program_id(2)
    else:
        ph = None
        i, s = pl.program_id(0), pl.program_id(1)
    k = 0

    def take(n):
        nonlocal k
        r = refs[k:k + n]
        k += n
        return r

    if kind == "raw1":
        (x_ref,), (w_ref, b_ref) = take(1), take(2)
    elif kind == "mid1":
        (x_ref, m_ref, v_ref), (w_ref, b_ref) = take(3), take(2)
    else:  # mid2
        (a_ref, ma_ref, va_ref, b2_ref, mb_ref, vb_ref) = take(6)
        (w_ref, b_ref) = take(2)
    out_ref = take(1)[0]
    sc_ref = take(1)[0] if quant else None
    st_ref = take(1)[0] if stats else None
    scr_in, scr_prev = take(2)
    scr_q = take(1)[0] if quant else None
    scr_st = take(1)[0] if stats else None
    # The streamed-chain storage dtype. The quant pass's out_ref holds
    # fp32 bit containers, so it reads the dtype off the ring scratch.
    dtype = scr_prev.dtype if quant else out_ref.dtype

    @pl.when((i == 0) & (s == 0))
    def _init():
        _zeros(scr_in)
        _zeros(scr_prev)
        if stats:
            scr_st[...] = jnp.zeros(scr_st.shape, scr_st.dtype)
        if quant:
            @pl.when(ph == 0)
            def _zq():
                scr_q[...] = jnp.zeros(scr_q.shape, scr_q.dtype)

    @pl.when(s == 0)
    def _roll():
        _shift(scr_in, 2)

    # The ring window carries an 8-packed-column x-pad on each side:
    # Mosaic requires dynamic sublane slice starts to be provable
    # 8-multiples, so placement writes at 8 + s*wp and the conv reads an
    # aligned (wp+16)-wide window, slicing its interior statically.
    @pl.when((s < nwb) & (i < nb))
    def _place():
        # stats == instance norm; without it the m/v are identity by
        # construction (frozen BN folded into the conv weights), so the
        # transform collapses to a relu in the storage dtype.
        if kind == "raw1":
            # Input is already an activation (a block input / exact
            # tensor): no transform.
            v = x_ref[...]
        elif kind == "mid1":
            v = (_normed(x_ref[...], m_ref[...], v_ref[...]) if stats
                 else jax.nn.relu(x_ref[...]))
        elif stats:
            v = jax.nn.relu(
                _normed(a_ref[...], ma_ref[...], va_ref[...])
                .astype(jnp.float32)
                + _normed(b2_ref[...], mb_ref[...], vb_ref[...])
            ).astype(dtype)
        else:
            v = jax.nn.relu(jax.nn.relu(a_ref[...])
                            + jax.nn.relu(b2_ref[...]))
        scr_in[2:2 + th, pl.ds(pl.multiple_of(8 + s * wp, 8), wp)] = v

    @pl.when((s < nwb) & (i >= nb))
    def _flush():
        _zeros(scr_in,
               (slice(2, 2 + th), pl.ds(pl.multiple_of(8 + s * wp, 8), wp)))

    @pl.when(s > 0)
    def _conv():
        # Strip s-1, output rows [i*TH-1, (i+1)*TH-1): the aligned
        # (wp+16)-wide window starting at (s-1)*wp has the conv support
        # [strip start - 1, strip end + 1) at cols [7, wp+9).
        win8 = scr_in[:, pl.ds(pl.multiple_of((s - 1) * wp, 8), wp + 16)]
        win = win8[:, 7:wp + 9]
        acc = _conv_rows(win, w_ref, th, wp)
        out = acc + b_ref[...].astype(jnp.float32)
        new = out.astype(dtype)
        if quant:
            @pl.when(ph == 0)
            def _amax():
                # amax of the ROUNDED values, masked to real rows — the
                # exact reduction feature_scale8 runs on the host.
                m = jnp.max(jnp.abs(
                    _row_mask(i, -1, th, hh, new.astype(jnp.float32))))
                scr_q[0, 0] = jnp.maximum(scr_q[0, 0], m)

            @pl.when(ph == 1)
            def _emit():
                # Assemble the SAME lagged block the plain pass emits,
                # then quantize + width-group pack it in-register.
                blk = jnp.concatenate(
                    [scr_prev[s - 1, 1:th], new[0:1]], axis=0
                ).astype(jnp.float32)
                scale = jnp.maximum(scr_q[0, 0], 1e-30) / 127.0
                out_ref[...] = _quant_pack_rows(blk, scale, wp)
                sc_ref[0, 0] = scale
            scr_prev[s - 1] = new
        else:
            # Block-aligned emission: block i-1 = previous step's tail +
            # this step's head (the conv lags one row); i=0 parks in the
            # trash block.
            out_ref[0:th - 1] = scr_prev[s - 1, 1:th]
            out_ref[th - 1:th] = new[0:1]
            scr_prev[s - 1] = new
        if stats:
            # Rows outside [0, H) occur only at the first (row -1) and
            # flush (rows >= H) steps; interior steps skip the mask pass.
            @pl.when((i > 0) & (i < nb))
            def _st_interior():
                _stats_update(scr_st, st_ref, out)

            @pl.when((i == 0) | (i >= nb))
            def _st_edge():
                _stats_update(scr_st, st_ref, _row_mask(i, -1, th, hh, out))


def _pass_q8_kernel(*refs, **kw):
    """Named entry point for the quantize-on-exit conv pass — thin wrapper
    so the r24 containers' engagement is greppable in lowered jaxprs by
    kernel NAME (the scratch/check_engagement.py contract), exactly like
    the lane8 GRU wrappers in ops/pallas_stream.py."""
    _pass_kernel(*refs, quant=True, **kw)


def _point3_kernel(s_ref, ms_ref, vs_ref, y2_ref, m2_ref, v2_ref,
                   y4_ref, m4_ref, v4_ref, out_ref, *, norm: bool):
    # ``norm`` is the trunk's norm mode (instance => apply the computed
    # mean/inv), NOT the stats-accumulation flag the conv passes take —
    # point3 never emits stats, so conflating the two silently skips
    # normalization on the instance trunk.
    if norm:
        o1 = jax.nn.relu(
            _normed(s_ref[...], ms_ref[...], vs_ref[...]).astype(jnp.float32)
            + _normed(y2_ref[...], m2_ref[...], v2_ref[...]))
        o2 = jax.nn.relu(
            o1 + _normed(y4_ref[...], m4_ref[...], v4_ref[...])
        ).astype(out_ref.dtype)
    else:  # identity norms: pure relu chain in the storage dtype
        o1 = jax.nn.relu(jax.nn.relu(s_ref[...]) + jax.nn.relu(y2_ref[...]))
        o2 = jax.nn.relu(o1 + jax.nn.relu(y4_ref[...]))
    out_ref[...] = o2  # packed; the caller unpacks via one XLA reshape


def _point2_kernel(x_ref, y_ref, m_ref, v_ref, out_ref, *, norm: bool):
    """Residual-block exit: out = relu(x + relu(norm(y2))) — ``x`` is the
    block input (already an activation, identity transform), ``y2`` the
    raw conv2 output. Same norm-vs-stats contract as point3."""
    if norm:
        out = jax.nn.relu(x_ref[...].astype(jnp.float32)
                          + _normed(y_ref[...], m_ref[...], v_ref[...]))
    else:
        out = jax.nn.relu(x_ref[...].astype(jnp.float32)
                          + jax.nn.relu(y_ref[...].astype(jnp.float32)))
    out_ref[...] = out.astype(out_ref.dtype)


def _quant_pack_rows(blk: jax.Array, scale, wp: int):
    """fp32 rows (th, wp, C) -> width-group int8 container (th, wp/4, C):
    the in-register mirror of corr/pallas_reg.py's ``_qfeat8_impl`` —
    identical clip/round/shift arithmetic, so kernel and host packs of
    the same values are byte-equal."""
    q = jnp.clip(jnp.round(blk / scale), -127.0, 127.0).astype(jnp.int32)
    wq = wp // 4
    packed = ((jax.lax.slice_in_dim(q, 0, wq, axis=1) & 0xFF)
              | ((jax.lax.slice_in_dim(q, wq, 2 * wq, axis=1) & 0xFF) << 8)
              | ((jax.lax.slice_in_dim(q, 2 * wq, 3 * wq, axis=1)
                  & 0xFF) << 16)
              | ((jax.lax.slice_in_dim(q, 3 * wq, 4 * wq, axis=1)
                  & 0xFF) << 24))
    return jax.lax.bitcast_convert_type(packed, jnp.float32)


def _point2_q8_kernel(x_ref, y_ref, m_ref, v_ref, out_ref, sc_ref, scr_q, *,
                      norm: bool, wp: int):
    """point2 with the r24 quantize-on-exit epilogue: grid (2, nb, 1),
    phase 0 accumulates the fp32 amax of the bf16-rounded exit, phase 1
    re-runs the combine and emits the width-group container + scale.
    point2 output is exact (no lag block), so no row masking is needed —
    every computed row is real."""
    ph, i, s = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    if norm:
        out = jax.nn.relu(x_ref[...].astype(jnp.float32)
                          + _normed(y_ref[...], m_ref[...], v_ref[...]))
    else:
        out = jax.nn.relu(x_ref[...].astype(jnp.float32)
                          + jax.nn.relu(y_ref[...].astype(jnp.float32)))
    new = out.astype(x_ref.dtype)

    @pl.when((ph == 0) & (i == 0) & (s == 0))
    def _zq():
        scr_q[...] = jnp.zeros(scr_q.shape, scr_q.dtype)

    @pl.when(ph == 0)
    def _amax():
        scr_q[0, 0] = jnp.maximum(
            scr_q[0, 0], jnp.max(jnp.abs(new.astype(jnp.float32))))

    @pl.when(ph == 1)
    def _emit():
        scale = jnp.maximum(scr_q[0, 0], 1e-30) / 127.0
        out_ref[...] = _quant_pack_rows(
            new.astype(jnp.float32), scale, wp)
        sc_ref[0, 0] = scale


def _run_pass(kind, inputs, w, bias, hh, wp_total, wp, dtype,
              stats: bool, *, norm: bool = False, quant: bool = False):
    """One streamed pass over (H?, wp_total, C) chain tensors — the
    parity-packed trunk layout (wp_total = W/2, C = 128) or the plain
    unpacked layout of the deeper stages (wp_total = W, C = 96/128).

    inputs: list of (raw, mean, inv) triples whose raw arrays may
    carry trailing trash rows (the upstream pass's lag block) — index
    maps only ever touch the first ``hh`` rows; mid outputs carry one
    trash row-block themselves (only the point kinds exit exact). m/v
    are None for identity inputs (the raw1 conv and the point2 x side).
    ``wp`` is the strip width in STORED columns.

    ``stats`` = accumulate/emit per-channel stats (conv kinds only);
    ``norm`` = apply the computed instance norms in the point combines.
    They are SEPARATE flags on purpose: conflating them silently skipped
    normalization on the instance trunk (the r4 point3 regression).

    ``quant`` (r24): emit a width-group int8 container + (1, 1) scale
    instead of the bf16 tensor — supported for the raw1 conv pass and
    the point2 combine, single-strip (nwb == 1) wp % 4 == 0 geometry
    only (see _pass_kernel). Returns ``(container, scale)``."""
    th = _enc_th(hh, wp)
    nb, nwb = hh // th, wp_total // wp
    point = kind in ("point2", "point3")
    ch_out = inputs[0][0].shape[-1] if point else w.shape[-1]
    # quant adds a leading phase dim to the grid; index maps written in
    # (i, s) get lifted to ignore it.
    lift = ((lambda f: (lambda p, i, s: f(i, s))) if quant
            else (lambda f: f))

    if point:
        in_specs, args = [], []
        for raw, m, v in inputs:
            in_specs.append(pl.BlockSpec((th, wp, raw.shape[-1]),
                                         lift(lambda i, s: (i, s, 0)),
                                         memory_space=pltpu.VMEM))
            args.append(raw)
            for t in (m, v):
                if t is None:
                    continue
                in_specs.append(pl.BlockSpec(t.shape,
                                             lift(lambda i, s: (0, 0)),
                                             memory_space=pltpu.VMEM))
                args.append(t)
        if quant:
            assert kind == "point2" and nwb == 1 and wp % 4 == 0
            return pl.pallas_call(
                functools.partial(_point2_q8_kernel, norm=norm, wp=wp),
                grid=(2, nb, nwb),
                in_specs=in_specs,
                out_specs=(
                    pl.BlockSpec((th, wp // 4, ch_out),
                                 lambda p, i, s: (i, s, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, 1), lambda p, i, s: (0, 0),
                                 memory_space=pltpu.VMEM)),
                out_shape=(
                    jax.ShapeDtypeStruct((hh, wp_total // 4, ch_out),
                                         jnp.float32),
                    jax.ShapeDtypeStruct((1, 1), jnp.float32)),
                scratch_shapes=[pltpu.VMEM((1, 1), jnp.float32)],
                compiler_params=compiler_params(vmem_limit_bytes=_ENC_VMEM),
                interpret=_interpret(),
            )(*args)
        pk = _point3_kernel if kind == "point3" else _point2_kernel
        return pl.pallas_call(
            functools.partial(pk, norm=norm),
            grid=(nb, nwb),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((th, wp, ch_out), lambda i, s: (i, s, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((hh, wp_total, ch_out), dtype),
            compiler_params=compiler_params(vmem_limit_bytes=_ENC_VMEM),
            interpret=_interpret(),
        )(*args)

    def idx_in(i, s):
        return (jnp.minimum(i, nb - 1), jnp.minimum(s, nwb - 1), 0)

    ch_in = inputs[0][0].shape[-1]
    in_specs, args = [], []
    for raw, m, v in inputs:
        in_specs.append(pl.BlockSpec((th, wp, raw.shape[-1]), lift(idx_in),
                                     memory_space=pltpu.VMEM))
        args.append(raw)
        for t in (m, v):
            if t is None:
                continue
            in_specs.append(pl.BlockSpec(t.shape, lift(lambda i, s: (0, 0)),
                                         memory_space=pltpu.VMEM))
            args.append(t)

    for t in (w, bias):
        in_specs.append(pl.BlockSpec(t.shape,
                                     lift(lambda i, s, nd=t.ndim: (0,) * nd),
                                     memory_space=pltpu.VMEM))
        args.append(t)

    if quant:
        assert kind == "raw1" and not stats and nwb == 1 and wp % 4 == 0
        kernel = functools.partial(_pass_q8_kernel, kind=kind, th=th, nb=nb,
                                   nwb=nwb, wp=wp, hh=hh, stats=stats)
        outs = pl.pallas_call(
            kernel,
            grid=(2, nb + 1, nwb + 1),
            in_specs=in_specs,
            out_specs=(
                # Phase-0 visits (amax only) park in the trash row-block
                # alongside the usual i=0 / s=0 lag visits.
                pl.BlockSpec(
                    (th, wp // 4, ch_out),
                    lambda p, i, s: (
                        jnp.where((p == 0) | (i == 0) | (s == 0), nb, i - 1),
                        jnp.where(s == 0, 0, s - 1), 0),
                    memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1), lambda p, i, s: (0, 0),
                             memory_space=pltpu.VMEM)),
            out_shape=(
                jax.ShapeDtypeStruct(((nb + 1) * th, wp_total // 4, ch_out),
                                     jnp.float32),
                jax.ShapeDtypeStruct((1, 1), jnp.float32)),
            scratch_shapes=[
                pltpu.VMEM((th + 2, wp_total + 16, ch_in), dtype),
                pltpu.VMEM((nwb, th, wp, ch_out), dtype),
                pltpu.VMEM((1, 1), jnp.float32)],
            compiler_params=compiler_params(vmem_limit_bytes=_ENC_VMEM),
            interpret=_interpret(),
        )(*args)
        return outs

    kernel = functools.partial(_pass_kernel, kind=kind, th=th, nb=nb,
                               nwb=nwb, wp=wp, hh=hh, stats=stats)
    # Conv of strip s-1 emits block (i-1, s-1); the i=0 and s=0 visits
    # park in the trash row-block nb, so no real block is revisited.
    out_specs = [pl.BlockSpec(
        (th, wp, ch_out),
        lambda i, s: (jnp.where((i == 0) | (s == 0), nb, i - 1),
                      jnp.where(s == 0, 0, s - 1), 0),
        memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct(((nb + 1) * th, wp_total, ch_out),
                                      dtype)]
    if stats:
        out_specs.append(pl.BlockSpec((2, ch_out), lambda i, s: (0, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((2, ch_out), jnp.float32))
    scratch = [pltpu.VMEM((th + 2, wp_total + 16, ch_in), dtype),
               pltpu.VMEM((nwb, th, wp, ch_out), dtype)]
    if stats:
        scratch.append(pltpu.VMEM((2, ch_out), jnp.float32))
    outs = pl.pallas_call(
        kernel,
        grid=(nb + 1, nwb + 1),
        in_specs=in_specs,
        out_specs=tuple(out_specs) if stats else out_specs[0],
        out_shape=tuple(out_shape) if stats else out_shape[0],
        scratch_shapes=scratch,
        compiler_params=compiler_params(vmem_limit_bytes=_ENC_VMEM),
        interpret=_interpret(),
    )(*args)
    if not stats:
        return outs, None
    return outs[0], outs[1]


def _stats_to_mv(stats, n: int, eps: float = 1e-5):
    mean = stats[0] / n
    var = jnp.maximum(stats[1] / n - jnp.square(mean), 0.0)
    return mean.reshape(1, -1), jax.lax.rsqrt(var + eps).reshape(1, -1)


def _ident_mv(c):
    return jnp.zeros((1, c), jnp.float32), jnp.ones((1, c), jnp.float32)


def _fold_bn(conv: dict, bn: dict, eps: float = 1e-5):
    """Fold frozen-BN stats into the preceding conv (fp32 fold)."""
    k = (bn["scale"] * jax.lax.rsqrt(bn["var"] + eps)).astype(jnp.float32)
    w = conv["w"].astype(jnp.float32) * k
    b = (conv.get("b", 0.0) - bn["mean"]) * k + bn["bias"]
    return w, jnp.asarray(b, jnp.float32)


def _trunk_passes(halves, convs, hh, width, dtype, instance: bool):
    """Shared stem+layer1 chain over packed tensors. convs:
    [(w_stem(7,7,3,64), b), (w3x3(3,3,64,64), b) x4] — BN pre-folded for
    the frozen-BN (cnet) trunk, raw for instance norm."""
    n = hh * width
    wb = _strip_wb(width)
    wp_total = width // 2

    def mv(st):
        m, v = (_stats_to_mv(_unpack_stats(st), n) if instance
                else _ident_mv(64))
        return _pack_mv(m, v)

    (ws, bs), (w1, b1), (w2, b2), (w3, b3), (w4, b4) = convs
    wpk = [(_pack_w3(w.astype(jnp.float32), dtype), _pack_bias(b))
           for w, b in ((w1, b1), (w2, b2), (w3, b3), (w4, b4))]
    stem, st = _run_stem(halves, _stem_weights(ws, dtype), _pack_bias(bs),
                         hh, wp_total, dtype, instance)
    m1, v1 = mv(st)
    y1, st = _run_pass("mid1", [(stem, m1, v1)], *wpk[0],
                       hh, wp_total, wb // 2, dtype, instance)
    my, vy = mv(st)
    y2, st = _run_pass("mid1", [(y1, my, vy)], *wpk[1],
                       hh, wp_total, wb // 2, dtype, instance)
    m2, v2 = mv(st)
    y3, st = _run_pass("mid2", [(stem, m1, v1), (y2, m2, v2)], *wpk[2],
                       hh, wp_total, wb // 2, dtype, instance)
    m3, v3 = mv(st)
    y4, st = _run_pass("mid1", [(y3, m3, v3)], *wpk[3],
                       hh, wp_total, wb // 2, dtype, instance)
    m4, v4 = mv(st)
    o2 = _run_pass("point3", [(stem, m1, v1), (y2, m2, v2), (y4, m4, v4)],
                   None, None, hh, wp_total, wb // 2, dtype, False,
                   norm=instance)
    return o2  # packed (H, W/2, 128); _unpack_exit restores (1, H, W, 64)


def _unpack_exit(o2: jax.Array) -> jax.Array:
    """Packed (H, W/2, 128) -> (1, H, W, 64). The chain's one exit from the
    packed layout (Mosaic has no shape cast for the lane->sublane unpack;
    XLA does it in one fused copy — but the interleaving copy measured
    ~50 ms/frame across the three trunk exits at Middlebury-F, which is why
    the stride-2 layer2 entry consumes the packed form directly instead)."""
    hh, wp_total, _ = o2.shape
    return o2.reshape(hh, wp_total, 2, 64).reshape(hh, wp_total * 2, 64)[None]


def _stem_layer1_packed(p: dict, x: jax.Array):
    """Frozen-BN (cnet) stem + layer1, packed exit; BN folded into convs."""
    b, hh, width, _ = x.shape
    assert b == 1
    dtype = x.dtype
    blk1, blk2 = p["layer1"]
    convs = [_fold_bn(p["conv1"], p["norm1"])]
    for blk in (blk1, blk2):
        convs.append(_fold_bn(blk["conv1"], blk["norm1"]))
        convs.append(_fold_bn(blk["conv2"], blk["norm2"]))
    return _trunk_passes(stem_halves(x), convs, hh, width, dtype,
                         instance=False)


def _in_stem_layer1_packed(p: dict, x: jax.Array):
    """Instance-norm (fnet) stem + layer1, packed exit."""
    b, hh, width, _ = x.shape
    assert b == 1
    dtype = x.dtype
    blk1, blk2 = p["layer1"]

    def cb(conv):
        return conv["w"], conv["b"]

    convs = [cb(p["conv1"]), cb(blk1["conv1"]), cb(blk1["conv2"]),
             cb(blk2["conv1"]), cb(blk2["conv2"])]
    return _trunk_passes(stem_halves(x), convs, hh, width, dtype,
                         instance=True)


def fused_stem_layer1_impl(p: dict, x: jax.Array):
    """Frozen-BN (cnet) stem + layer1; BN folded into the conv weights."""
    return _unpack_exit(_stem_layer1_packed(p, x))


def fused_in_stem_layer1_impl(p: dict, x: jax.Array):
    """Instance-norm (fnet) stem + layer1 for one (1, H, W, 3) image."""
    return _unpack_exit(_in_stem_layer1_packed(p, x))


# ---------------------------------------------------------------------------
# Packed layer2 entry: stride 2 over true columns is stride 1 over packed
# columns, so layer2's entry convs can read the (H, W/2, 128) trunk exit in
# place — no interleaving unpack copy ever materializes.
# ---------------------------------------------------------------------------


def packed_entry_w3(w: jax.Array) -> jax.Array:
    """(3, 3, 64, C) stride-2 conv weight -> (3, 2, 128, C) over the packed
    layout. Output col j reads true cols 2j-1, 2j, 2j+1 = the odd half of
    packed col j-1 plus both halves of packed col j."""
    z = jnp.zeros_like(w[:, :1])
    k0 = jnp.concatenate([z, w[:, 0:1]], axis=2)          # [0 ; w(dx=-1)]
    k1 = jnp.concatenate([w[:, 1:2], w[:, 2:3]], axis=2)  # [w(0) ; w(+1)]
    return jnp.concatenate([k0, k1], axis=1)


def packed_entry_w1(w: jax.Array) -> jax.Array:
    """(1, 1, 64, C) stride-2 downsample weight -> (1, 1, 128, C): true col
    2j is the even half of packed col j; the odd half never contributes."""
    return jnp.concatenate([w, jnp.zeros_like(w)], axis=2)


def packed_entry_conv(xp: jax.Array, w: jax.Array, b, *, window_w: int):
    """Stride-(2,1) conv over the packed (H, W/2, 128) trunk exit, emitting
    the normal (1, H/2, W/2, C) layout. ``w`` comes from ``packed_entry_w3``
    (window_w=2) or ``packed_entry_w1`` (window_w=1)."""
    pads = ((1, 1), (1, 0)) if window_w == 2 else ((0, 0), (0, 0))
    out = jax.lax.conv_general_dilated(
        xp[None], w.astype(xp.dtype), window_strides=(2, 1), padding=pads,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if b is not None:
        out = out + b.astype(out.dtype)
    return out


def _fusable(p: dict, x, stride: int) -> bool:
    from raft_stereo_tpu.ops.pallas_stream import _dtype_ok
    if not ENABLE():
        return False
    if x.ndim != 4 or x.shape[2] % 2:
        return False
    wb = _strip_wb(x.shape[2])
    if not (_dtype_ok(x) and x.shape[0] == 1 and stride == 1
            and x.shape[1] >= 16 and wb > 0 and wb % 2 == 0
            and _enc_th(x.shape[1], wb // 2) > 0
            and _stem_th(x.shape[1], x.shape[2] // 2, 294) > 0):
        return False
    blk1, blk2 = p["layer1"]
    # Identity shortcuts only (stride-1 equal-channel layer1 blocks).
    return "downsample" not in blk1 and "downsample" not in blk2


def stem_layer1_is_fusable(p: dict, x, norm_fn: str, stride: int) -> bool:
    return norm_fn == "batch" and _fusable(p, x, stride)


def in_stem_layer1_is_fusable(p: dict, x, norm_fn: str, stride: int) -> bool:
    return norm_fn == "instance" and _fusable(p, x, stride)


def _oracle(p: dict, x):
    from raft_stereo_tpu.models.layers import apply_conv, apply_residual_block
    from raft_stereo_tpu.ops.basic import frozen_batch_norm
    h = apply_conv(p["conv1"], x, stride=1, padding=3)
    h = jax.nn.relu(frozen_batch_norm(h, p["norm1"]))
    for blk in p["layer1"]:
        h = apply_residual_block(blk, h, "batch", stride=1)
    return h


def _in_oracle(p: dict, x):
    from raft_stereo_tpu.models.layers import apply_conv, apply_residual_block
    from raft_stereo_tpu.ops.basic import instance_norm
    h = apply_conv(p["conv1"], x, stride=1, padding=3)
    h = jax.nn.relu(instance_norm(h))
    for blk in p["layer1"]:
        h = apply_residual_block(blk, h, "instance", stride=1)
    return h


@jax.custom_vjp
def fused_stem_layer1(p: dict, x):
    """cnet stem + layer1 via streamed passes; backward via the XLA oracle."""
    return fused_stem_layer1_impl(p, x)


def _fwd(p, x):
    return fused_stem_layer1(p, x), (p, x)


def _bwd(res, g):
    p, x = res
    out, vjp = jax.vjp(_oracle, p, x)
    return vjp(g.astype(out.dtype))


fused_stem_layer1.defvjp(_fwd, _bwd)


@jax.custom_vjp
def fused_in_stem_layer1(p: dict, x):
    """fnet stem + layer1 via streamed norm-conv passes; backward via the
    XLA oracle."""
    return fused_in_stem_layer1_impl(p, x)


def _in_fwd(p, x):
    return fused_in_stem_layer1(p, x), (p, x)


def _in_bwd(res, g):
    p, x = res
    out, vjp = jax.vjp(_in_oracle, p, x)
    return vjp(g.astype(out.dtype))


fused_in_stem_layer1.defvjp(_in_fwd, _in_bwd)


# ---------------------------------------------------------------------------
# Streamed tail: the stride-1 residual blocks of layer2/layer3 and the
# finest output heads, in the PLAIN (H', W', C) layout (C = 96/128 — at
# these channel counts the unpacked layout already fills vregs; packing
# buys nothing). One streamed pass per conv (raw1 -> mid1 -> point2), so
# the XLA tail's separate norm/relu/add materializations — ~2 extra HBM
# round trips per tensor per block at Middlebury-F's 1/2-res 288 MB
# activations — never happen. Stride-2 entry blocks stay XLA: their
# stride-2 reads don't fit the ring geometry, and at half the output
# resolution XLA runs them acceptably (the packed layer2 entry already
# consumes the trunk exit in place).
# ---------------------------------------------------------------------------


def _strip_cols(width: int) -> int:
    """Width-strip size in STORED columns for unpacked tail passes
    (0 = unsupported): <=384 columns per grid step keeps Mosaic code
    size in the packed trunk's compile-time regime (its 768 true columns
    = 384 stored); %8 keeps strip placement sublane-aligned."""
    for nwb in range(1, 13):
        wp = width // nwb
        if width % nwb == 0 and wp <= 384 and (wp % 8 == 0 or nwb == 1):
            return wp
    return 0


def _tail_enabled() -> bool:
    return _os.environ.get("RAFT_STREAM_TAIL", "1").strip().lower() not in (
        "0", "false", "no", "off")


def _bias_row(b, ch: int):
    return (jnp.zeros((1, ch), jnp.float32) if b is None
            else jnp.asarray(b, jnp.float32).reshape(1, -1))


def resblock_streamable(p: dict, x, norm_fn: str) -> bool:
    """Stride-1 identity-shortcut block over a (1, H, W, C) activation."""
    from raft_stereo_tpu.ops.pallas_stream import _dtype_ok
    if not (ENABLE() and _tail_enabled() and norm_fn in ("batch", "instance")):
        return False
    if "downsample" in p or x.ndim != 4 or x.shape[0] != 1 or x.shape[1] < 8:
        return False
    ch = x.shape[-1]
    wp = _strip_cols(x.shape[2])
    return (_dtype_ok(x) and wp > 0 and _enc_th(x.shape[1], wp) > 0
            and p["conv1"]["w"].shape[2:] == (ch, ch))


def head_conv_streamable(pc: dict, x) -> bool:
    """3x3 pad-1 head conv over a (1, H, W, C) activation."""
    from raft_stereo_tpu.ops.pallas_stream import _dtype_ok
    if not (ENABLE() and _tail_enabled()):
        return False
    if x.ndim != 4 or x.shape[0] != 1 or x.shape[1] < 8:
        return False
    wp = _strip_cols(x.shape[2])
    return (_dtype_ok(x) and wp > 0 and _enc_th(x.shape[1], wp) > 0
            and pc["w"].shape[:2] == (3, 3) and pc["w"].shape[2] == x.shape[-1])


def _lane8_enabled() -> bool:
    """``RAFT_LANE_PACK8`` read LOCALLY at trace time — this module
    declares the ``_pass_q8_kernel``/``_point2_q8_kernel`` rung entry
    points, so it must consult the kill switch itself (the breaker can
    flip the env var and rebuild; same contract as ``_tail_enabled``)."""
    return _os.environ.get("RAFT_LANE_PACK8", "0").strip().lower() in (
        "1", "true", "yes", "on")


def head_conv_q8_streamable(pc: dict, x) -> bool:
    """Narrow-exit (r24) variant of :func:`head_conv_streamable`: the
    in-register width-group pack needs the WHOLE row in one grid block
    (single strip) and a quad-divisible width. Off unless
    RAFT_LANE_PACK8 arms the lane — the epilogue changes the output
    layout, not just the schedule, so it must never engage by default."""
    return (_lane8_enabled() and head_conv_streamable(pc, x)
            and _strip_cols(x.shape[2]) == x.shape[2]
            and x.shape[2] % 4 == 0)


def _stream_resblock_impl(p: dict, x: jax.Array, norm_fn: str) -> jax.Array:
    _, hh, width, ch = x.shape
    dtype = x.dtype
    instance = norm_fn == "instance"
    if instance:
        w1, b1 = p["conv1"]["w"], p["conv1"].get("b")
        w2, b2 = p["conv2"]["w"], p["conv2"].get("b")
    else:
        w1, b1 = _fold_bn(p["conv1"], p["norm1"])
        w2, b2 = _fold_bn(p["conv2"], p["norm2"])
    wp = _strip_cols(width)
    n = hh * width
    x3 = x[0]

    def mv(st):
        return _stats_to_mv(st, n) if instance else _ident_mv(ch)

    y1, st = _run_pass("raw1", [(x3, None, None)], w1.astype(dtype),
                       _bias_row(b1, ch), hh, width, wp, dtype, instance)
    m1, v1 = mv(st)
    y2, st = _run_pass("mid1", [(y1, m1, v1)], w2.astype(dtype),
                       _bias_row(b2, ch), hh, width, wp, dtype, instance)
    m2, v2 = mv(st)
    out = _run_pass("point2", [(x3, None, None), (y2, m2, v2)],
                    None, None, hh, width, wp, dtype, False, norm=instance)
    return out[None]


def _stream_head_conv_impl(pc: dict, x: jax.Array) -> jax.Array:
    _, hh, width, ch = x.shape
    wp = _strip_cols(width)
    y, _ = _run_pass("raw1", [(x[0], None, None)], pc["w"].astype(x.dtype),
                     _bias_row(pc.get("b"), pc["w"].shape[-1]),
                     hh, width, wp, x.dtype, False)
    return y[:hh][None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def stream_resblock(norm_fn: str, p: dict, x):
    """Streamed stride-1 residual block (identity shortcut); backward via
    the XLA oracle (``apply_residual_block``)."""
    return _stream_resblock_impl(p, x, norm_fn)


def _rb_fwd(norm_fn, p, x):
    return stream_resblock(norm_fn, p, x), (p, x)


def _rb_bwd(norm_fn, res, g):
    from raft_stereo_tpu.models.layers import apply_residual_block
    p, x = res
    out, vjp = jax.vjp(
        lambda p_, x_: apply_residual_block(p_, x_, norm_fn, stride=1), p, x)
    return vjp(g.astype(out.dtype))


stream_resblock.defvjp(_rb_fwd, _rb_bwd)


@jax.custom_vjp
def stream_head_conv(pc: dict, x):
    """Streamed 3x3 pad-1 output-head conv; backward via the XLA oracle."""
    return _stream_head_conv_impl(pc, x)


def _hc_fwd(pc, x):
    return stream_head_conv(pc, x), (pc, x)


def _hc_bwd(res, g):
    from raft_stereo_tpu.models.layers import apply_conv
    pc, x = res
    out, vjp = jax.vjp(lambda p_, x_: apply_conv(p_, x_, padding=1), pc, x)
    return vjp(g.astype(out.dtype))


stream_head_conv.defvjp(_hc_fwd, _hc_bwd)


def _stream_head_conv_q8_impl(pc: dict, x: jax.Array):
    _, hh, width, ch = x.shape
    wp = _strip_cols(width)
    pk, scale = _run_pass("raw1", [(x[0], None, None)],
                          pc["w"].astype(x.dtype),
                          _bias_row(pc.get("b"), pc["w"].shape[-1]),
                          hh, width, wp, x.dtype, False, quant=True)
    return pk[:hh][None], scale.reshape(1, 1, 1, 1)


@jax.custom_vjp
def stream_head_conv_q8(pc: dict, x):
    """Streamed 3x3 head conv with the r24 quantize-on-exit epilogue:
    returns ``(container, scale)`` — a (1, H, W/4, C) fp32 width-group
    int8 container plus its (1, 1, 1, 1) per-sample scale — and the bf16
    head output never round-trips HBM. Bitwise identical to host-packing
    the streamed bf16 output (quantize_pack_feature8 of stream_head_conv;
    pinned in tests/test_lane_pack8.py). The container is an opaque bit
    transport with zero cotangent, like every pack8 seam — and the packed
    context path is inference-only, so the backward never actually runs."""
    return _stream_head_conv_q8_impl(pc, x)


def _hcq_fwd(pc, x):
    return stream_head_conv_q8(pc, x), (pc, x)


def _hcq_bwd(res, g):
    pc, x = res
    del g
    return (jax.tree_util.tree_map(jnp.zeros_like, pc), jnp.zeros_like(x))


stream_head_conv_q8.defvjp(_hcq_fwd, _hcq_bwd)


def _stream_resblock_q8_impl(p: dict, x: jax.Array, norm_fn: str):
    """:func:`_stream_resblock_impl` with the point2 exit emitting the
    width-group container + scale directly (same single-strip gate as the
    head conv; callers check :func:`resblock_q8_streamable`)."""
    _, hh, width, ch = x.shape
    dtype = x.dtype
    instance = norm_fn == "instance"
    if instance:
        w1, b1 = p["conv1"]["w"], p["conv1"].get("b")
        w2, b2 = p["conv2"]["w"], p["conv2"].get("b")
    else:
        w1, b1 = _fold_bn(p["conv1"], p["norm1"])
        w2, b2 = _fold_bn(p["conv2"], p["norm2"])
    wp = _strip_cols(width)
    n = hh * width
    x3 = x[0]

    def mv(st):
        return _stats_to_mv(st, n) if instance else _ident_mv(ch)

    y1, st = _run_pass("raw1", [(x3, None, None)], w1.astype(dtype),
                       _bias_row(b1, ch), hh, width, wp, dtype, instance)
    m1, v1 = mv(st)
    y2, st = _run_pass("mid1", [(y1, m1, v1)], w2.astype(dtype),
                       _bias_row(b2, ch), hh, width, wp, dtype, instance)
    m2, v2 = mv(st)
    pk, scale = _run_pass("point2", [(x3, None, None), (y2, m2, v2)],
                          None, None, hh, width, wp, dtype, False,
                          norm=instance, quant=True)
    return pk[None], scale.reshape(1, 1, 1, 1)


def resblock_q8_streamable(p: dict, x, norm_fn: str) -> bool:
    """Narrow-exit gate for :func:`stream_resblock_q8` — the resblock
    gate plus the single-strip / quad-width geometry the in-register
    pack needs, armed only under RAFT_LANE_PACK8."""
    return (_lane8_enabled() and resblock_streamable(p, x, norm_fn)
            and _strip_cols(x.shape[2]) == x.shape[2]
            and x.shape[2] % 4 == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def stream_resblock_q8(norm_fn: str, p: dict, x):
    """Streamed stride-1 residual block whose exit writes the r24
    width-group container + per-sample scale instead of the bf16 tensor
    (``_point2_q8_kernel``). Zero cotangent — bit-transport semantics."""
    return _stream_resblock_q8_impl(p, x, norm_fn)


def _rbq_fwd(norm_fn, p, x):
    return stream_resblock_q8(norm_fn, p, x), (p, x)


def _rbq_bwd(norm_fn, res, g):
    p, x = res
    del g
    return (jax.tree_util.tree_map(jnp.zeros_like, p), jnp.zeros_like(x))


stream_resblock_q8.defvjp(_rbq_fwd, _rbq_bwd)


def _packed_cotangent(g: jax.Array) -> jax.Array:
    """Packed (H, W/2, 128) cotangent -> unpacked (1, H, W, 64) for the
    XLA-oracle backward (the unpack is a reshape, so its transpose is the
    same reshape on the cotangent)."""
    return _unpack_exit(g)


@jax.custom_vjp
def fused_stem_layer1_packed(p: dict, x):
    """cnet stem + layer1 with the packed (H, W/2, 128) exit (for the
    stride-2 layer2 entry); backward via the XLA oracle."""
    return _stem_layer1_packed(p, x)


def _pk_fwd(p, x):
    return fused_stem_layer1_packed(p, x), (p, x)


def _pk_bwd(res, g):
    p, x = res
    out, vjp = jax.vjp(_oracle, p, x)
    return vjp(_packed_cotangent(g).astype(out.dtype))


fused_stem_layer1_packed.defvjp(_pk_fwd, _pk_bwd)


@jax.custom_vjp
def fused_in_stem_layer1_packed(p: dict, x):
    """fnet stem + layer1 with the packed exit; backward via the oracle."""
    return _in_stem_layer1_packed(p, x)


def _in_pk_fwd(p, x):
    return fused_in_stem_layer1_packed(p, x), (p, x)


def _in_pk_bwd(res, g):
    p, x = res
    out, vjp = jax.vjp(_in_oracle, p, x)
    return vjp(_packed_cotangent(g).astype(out.dtype))


fused_in_stem_layer1_packed.defvjp(_in_pk_fwd, _in_pk_bwd)

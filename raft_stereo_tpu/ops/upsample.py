"""Learned convex-combination upsampling.

Reference ``core/raft_stereo.py:55-67``: softmax over the 9-neighborhood mask,
applied to 3x3 patches of ``factor * flow``. The reference uses ``F.unfold``;
here the 9 shifted views are built by padding + static slicing (XLA fuses these
into the downstream einsum — no materialized im2col) and combined with one
einsum that maps straight onto the MXU.

Channel-order contract (needed for weight transplant): the mask conv emits
``factor**2 * 9`` channels viewed as ``(9, factor, factor)`` with the
9-neighborhood index outermost (torch ``mask.view(N, 1, 9, factor, factor, H, W)``),
and the neighborhood is enumerated row-major (dy, dx) like ``F.unfold``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _patches3x3(x: jax.Array) -> jax.Array:
    """3x3 zero-padded patches of (B, H, W, C) -> (B, H, W, 9, C), row-major taps."""
    b, h, w, c = x.shape
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    views = [xp[:, dy:dy + h, dx:dx + w, :] for dy in range(3) for dx in range(3)]
    return jnp.stack(views, axis=3)


def convex_upsample(flow: jax.Array, mask: jax.Array, factor: int) -> jax.Array:
    """Upsample (B, H, W, D) flow to (B, factor*H, factor*W, D).

    mask: (B, H, W, factor**2 * 9) raw logits from the mask head.
    """
    b, h, w, d = flow.shape
    mask = mask.astype(jnp.float32).reshape(b, h, w, 9, factor, factor)
    mask = jax.nn.softmax(mask, axis=3)
    patches = _patches3x3(flow.astype(jnp.float32) * factor)  # (B,H,W,9,D)
    # Emit the einsum already in interleaved (h, fy, w, fx) order: the
    # standalone transpose this replaces ran ~50x off bandwidth (tiny
    # minor dims -> pathological narrow-lane layout, 6.5 ms/frame at
    # Middlebury-F) while the dot can write the permuted layout directly.
    up = jnp.einsum("bhwkyx,bhwkd->bhywxd", mask, patches)  # (B,H,fy,W,fx,D)
    up = up.reshape(b, h * factor, w * factor, d)
    return up.astype(flow.dtype)

"""Convolution and normalization primitives (NHWC / HWIO).

Semantics match the reference's torch modules exactly:
- ``conv2d``: symmetric explicit padding like ``nn.Conv2d(padding=p)``;
- ``frozen_batch_norm``: ``nn.BatchNorm2d`` in eval mode — the reference always
  freezes BN (``train_stereo.py:151,193``; ``core/raft_stereo.py:41-44``), so BN
  is a pure affine transform of stored running statistics;
- ``instance_norm``: ``nn.InstanceNorm2d`` defaults — no affine, no running
  stats, biased variance, eps 1e-5 (``core/extractor.py:29-32,135``);
- ``group_norm``: ``nn.GroupNorm`` (``core/extractor.py:17-20,129``).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

Padding = Union[int, Tuple[int, int]]

_DIMNUMS = ("NHWC", "HWIO", "NHWC")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _conv_acc32(x: jax.Array, w: jax.Array, stride, padding) -> jax.Array:
    """Conv emitting the fp32 accumulator from reduced-precision operands.

    ``preferred_element_type=f32`` with bf16 operands is fine forward, but
    its autodiff transpose builds a conv of the fp32 cotangent against the
    bf16 operand — mixed dtypes, a trace-time error. This custom_vjp runs
    the backward in the compute dtype (cotangent rounded once), the
    standard mixed-precision training semantics.
    """
    return lax.conv_general_dilated(
        x, w, window_strides=stride, padding=padding,
        dimension_numbers=_DIMNUMS, preferred_element_type=jnp.float32)


def _conv_acc32_fwd(x, w, stride, padding):
    return _conv_acc32(x, w, stride, padding), (x, w)


def _conv_acc32_bwd(stride, padding, residuals, g):
    x, w = residuals
    _, vjp = jax.vjp(
        lambda a, b: lax.conv_general_dilated(
            a, b, window_strides=stride, padding=padding,
            dimension_numbers=_DIMNUMS),
        x, w)
    return vjp(g.astype(x.dtype))


_conv_acc32.defvjp(_conv_acc32_fwd, _conv_acc32_bwd)


def _pad_pair(padding: Padding) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    if isinstance(padding, int):
        return ((padding, padding), (padding, padding))
    ph, pw = padding
    return ((ph, ph), (pw, pw))


def conv2d(x: jax.Array, w: jax.Array, b: jax.Array | None = None, *,
           stride: Union[int, Tuple[int, int]] = 1,
           padding: Padding = 0, out_dtype=None) -> jax.Array:
    """2D convolution, NHWC input, HWIO kernel, torch-style symmetric padding.

    The conv runs in the dtype of ``x`` (bf16 under the mixed-precision
    policy) and emits that dtype: the MXU accumulates fp32 within a pass
    regardless, and requesting an fp32 *output type* forces XLA to
    materialize full-precision activation buffers — measured 3-6 GB
    space-to-depth stem intermediates at Middlebury-F that pushed the
    program out of HBM. Callers that sum several partial convs (the split
    gate convs) pass ``out_dtype=jnp.float32`` to keep the explicit fp32
    accumulator across convs and downcast once.
    """
    if isinstance(stride, int):
        stride = (stride, stride)
    w = w.astype(x.dtype)
    if out_dtype == jnp.float32 and x.dtype != jnp.float32:
        out = _conv_acc32(x, w, stride, _pad_pair(padding))
    else:
        out = lax.conv_general_dilated(
            x, w, window_strides=stride, padding=_pad_pair(padding),
            dimension_numbers=_DIMNUMS)
    if b is not None:
        out = out + b.astype(out.dtype)
    return out if out_dtype is None else out.astype(out_dtype)


def frozen_batch_norm(x: jax.Array, params: dict, *, eps: float = 1e-5) -> jax.Array:
    """BatchNorm2d in (permanently) eval mode: affine over stored running stats.

    params: {"scale", "bias", "mean", "var"} each shaped (C,).
    """
    # Fold stats into a single scale/shift (fp32), then apply in compute dtype.
    inv = params["scale"] * lax.rsqrt(params["var"] + eps)
    shift = params["bias"] - params["mean"] * inv
    return (x * inv.astype(x.dtype) + shift.astype(x.dtype)).astype(x.dtype)


def instance_norm(x: jax.Array, *, eps: float = 1e-5) -> jax.Array:
    """InstanceNorm2d with torch defaults: per-(sample, channel) over H, W,
    biased variance, no affine parameters.

    Statistics accumulate in fp32 but the map stays in the compute dtype:
    an ``x.astype(f32)`` of the whole activation would materialize a
    full-resolution fp32 copy (3 GB at Middlebury-F in the fnet stem) plus
    layout copies either side; the fp32 converts here fuse into the
    reductions instead. Identical arithmetic when x is fp32.

    Under bf16 compute the variance uses the one-pass ``E[x^2]-E[x]^2``
    form: both sums come out of a single multi-output reduction fusion, so
    the activation is read twice (stats + normalize) instead of three
    times — at full-res encoder shapes the extra pass costs more than the
    catastrophic-cancellation risk, which fp32 accumulation over bf16
    inputs keeps benign (values are O(1) post-norm-pre-norm). The fp32
    path keeps the exact two-pass form for reference parity.

    NOTE the benign-cancellation argument is activation-scale-dependent:
    it holds because every bf16 call site in this model feeds O(1)-scale
    conv activations. For mean/std ratios around 1e3 the one-pass VARIANCE
    loses most of its bits while the two-pass form does not
    (``tests/test_ops.py::test_instance_norm_one_pass_cancellation_bound``
    pins both against an fp64 oracle); do not reuse this path for
    large-dynamic-range inputs.
    """
    if x.dtype == jnp.bfloat16:
        mean = jnp.mean(x, axis=(1, 2), keepdims=True, dtype=jnp.float32)
        sq = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=(1, 2),
                      keepdims=True)
        var = jnp.maximum(sq - jnp.square(mean), 0.0)
    else:
        mean = jnp.mean(x, axis=(1, 2), keepdims=True, dtype=jnp.float32)
        var = jnp.mean(jnp.square(x.astype(jnp.float32) - mean), axis=(1, 2),
                       keepdims=True)
    inv = lax.rsqrt(var + eps)
    return ((x - mean.astype(x.dtype)) * inv.astype(x.dtype)).astype(x.dtype)


def group_norm(x: jax.Array, params: dict, num_groups: int, *,
               eps: float = 1e-5) -> jax.Array:
    """GroupNorm over (H, W, C//G) per group, affine. params: {"scale","bias"}."""
    b, h, w, c = x.shape
    xg = x.astype(jnp.float32).reshape(b, h, w, num_groups, c // num_groups)
    mean = jnp.mean(xg, axis=(1, 2, 4), keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * lax.rsqrt(var + eps)
    out = xg.reshape(b, h, w, c) * params["scale"] + params["bias"]
    return out.astype(x.dtype)

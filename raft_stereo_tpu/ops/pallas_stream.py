"""Streaming fused conv-chain Pallas kernels for the GRU scan body.

Why these exist: the per-iteration update at Middlebury-F (1/4-res ≈
504x744x128) is HBM-bandwidth-bound under XLA — profiling shows every gate
conv materializing fp32 partials, the zr tensor, r*h, and the state update
as separate full-tensor HBM round trips (~9 ms/iter for gru08 against a
~5 ms MXU roofline; `core/raft_stereo.py:108-136` is the reference hot
loop, its CUDA analog keeps this chain in torch ops). These kernels keep
every intermediate in VMEM, and chain kernel-to-kernel in row-major
layout (the corr lookup kernel's native output layout) so the scan body
never pays an XLA conv-layout round trip.

Streaming design (the TPU-native replacement for GPU-style halo tiles):
a 1D grid walks row-blocks of TH rows top-to-bottom. Convolution halos
are carried across grid steps in VMEM scratch ring-windows — each
intermediate row is computed EXACTLY once (no overlapped-tile recompute)
and consumed as soon as its dependents' rows arrive. A chain of k 3x3
convs delays the output by k rows, so kernels write out rows
``[i*TH - lag, (i+1)*TH - lag)`` as block i of a lag-shifted output
array; the caller slices ``out[lag:lag+H]``. Extra flush steps at the
end drain the pipeline (input index maps clamp with ``jnp.minimum``;
flushed input blocks are replaced with zeros so bottom conv padding is
exact). Top/bottom zero conv padding falls out of zero-initialized rings
and the zeroed flush blocks.

All arithmetic accumulates in fp32 (dots with preferred_element_type)
and downcasts once at each nonlinearity — numerically tighter than the
XLA path it replaces. Weights ride whole-array blocks with constant
index maps, so the pipeline fetches them once.

Kernels:
- ``fused_conv_gru``: the ConvGRU step (reference ``core/update.py:16-32``)
  — optionally chaining the FlowHead (``core/update.py:6-14``) onto the
  new hidden state at +2 rows of lag, emitting the x-delta map directly
  (the y-delta is zeroed by the epipolar projection, ``raft_stereo.py:120``,
  so only channel 0 is computed).
- ``fused_motion``: BasicMotionEncoder (``core/update.py:64-85``),
  consuming the lookup kernel's output and an XLA-built 7x7 patches
  tensor of the flow, emitting the 128-ch motion feature
  (126 fused + 2 raw flow).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_stereo_tpu.ops.jax_compat import compiler_params

_VMEM_LIMIT = 100 * 2**20  # v5e has 128M physical; default scoped cap is 16M


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu",)


# Test hook: lets CPU tests route fp32 through the fused kernels (interpret
# mode has no VMEM ceiling), giving a ~1e-3-tight end-to-end comparison
# against the XLA path instead of a bf16 rounding-envelope bound.
FORCE_FUSABLE_DTYPE = False


def _dtype_ok(t) -> bool:
    return t.dtype == jnp.bfloat16 or FORCE_FUSABLE_DTYPE


def pick_th(hh: int, width: int = 744) -> int:
    """Largest supported row-block evenly dividing H (0 = not supported).

    Bigger blocks amortize per-step DMA/loop overhead; the cap keeps the
    VMEM block buffers near what an 8x744 (Middlebury-F 1/4-res) block
    uses, which measures fastest on v5e."""
    for th in (24, 18, 16, 12, 8, 6, 4, 2):
        if hh % th == 0 and th * width <= 8192:
            return th
    return 0


def _dot(x, w):
    """(R, W, Cin) x (Cin, Cout) -> (R, W, Cout), fp32+ accumulation."""
    acc = jnp.promote_types(x.dtype, jnp.float32)
    return jax.lax.dot_general(
        x, w, (((2,), (0,)), ((), ())), preferred_element_type=acc)


def _conv_rows(scr, w, rows, width, acc=None):
    """3x3 conv over a scratch window: out row j reads scr rows j+dy.

    scr: (>= rows+2, width+2, C) window whose row 0 holds the first output
    row's top tap; w: (3, 3, Cin, Cout). Returns fp32 (rows, width, Cout).
    """
    for dy in range(3):
        x = scr[dy:dy + rows]
        for dx in range(3):
            y = _dot(x[:, dx:dx + width], w[dy, dx])
            acc = y if acc is None else acc + y
    return acc


def _zeros(ref, sl=slice(None)):
    ref[sl] = jnp.zeros(ref[sl].shape, ref.dtype)


def _row_mask(i, offset: int, th: int, hh: int, x):
    """Zero rows whose global index i*TH+offset+j falls outside [0, H).

    Needed for chained intermediates of the form relu(conv+bias): at
    out-of-range rows they are NOT zero (the bias passes the relu), but
    the downstream conv's zero padding requires them to be."""
    g = i * th + offset + jax.lax.broadcasted_iota(jnp.int32, (th, 1, 1), 0)
    return jnp.where((g >= 0) & (g < hh), x, jnp.zeros_like(x))


def _shift(ref, keep):
    """Move the window's last ``keep`` rows to the top (value-copy, safe
    for overlapping ranges)."""
    th = ref.shape[0] - keep
    tail = ref[th:th + keep]
    ref[0:keep] = tail


# ---------------------------------------------------------------------------
# Fused ConvGRU (+ optional FlowHead)
# ---------------------------------------------------------------------------


def _lane8_rows(pk_ref, scale_ref, width: int):
    """Dequantize one (1, TH, Wq, C) width-group int8 container block
    (corr/pallas_reg.py ``quantize_pack_feature8`` layout: byte b of lane
    column j holds width position b*Wq + j) to (TH, width, C) fp32 rows
    in-register: four sign-extending byte extracts concatenated on the
    width (sublane) axis — no minor-dim reshape, Mosaic-friendly — then
    one multiply by the per-sample scale riding a (1, 1) block."""
    gi = jax.lax.bitcast_convert_type(pk_ref[0], jnp.int32)
    parts = [(gi << 24) >> 24, (gi << 16) >> 24, (gi << 8) >> 24, gi >> 24]
    q = jnp.concatenate(parts, axis=1)[:, :width]
    return q.astype(jnp.float32) * scale_ref[0, 0]


def _gru_kernel(h_ref, czrq_ref, *rest, np_: int, th: int, nb: int,
                width: int, ch: int, head: bool, hh: int, coffs,
                lane8: bool = False):
    k = 0
    if lane8:
        czrq_scale_ref = rest[0]
        k = 1
    part_refs = rest[k:k + np_]
    k += np_
    whzr_ref, whq_ref, wx_ref = rest[k:k + 3]
    k += 3
    if head:
        w1_ref, b1_ref, w2_ref, out_ref, dx_ref = rest[k:k + 5]
        k += 5
    else:
        out_ref = rest[k]
        k += 1
    scr_h, scr_rh, scr_z, scr_aqx, scr_x = rest[k:k + 5]
    k += 5
    if head:
        scr_hn, scr_f1 = rest[k:k + 2]

    i = pl.program_id(1)  # row step; program_id(0) is the batch sample
    dtype = h_ref.dtype

    @pl.when(i == 0)
    def _init():
        scrs = [scr_h, scr_rh, scr_z, scr_aqx, scr_x]
        if head:
            scrs += [scr_hn, scr_f1]
        for s in scrs:
            _zeros(s)

    # Land the new input block (zeros on flush steps: exact bottom pad).
    # The x parts land in channel slices of ONE scratch so the gate x-conv
    # runs as K=sum(parts) dots (better MXU K-utilization than per-part
    # K=128 passes).
    _shift(scr_h, 3)
    _shift(scr_x, 2)

    @pl.when(i < nb)
    def _place():
        scr_h[3:3 + th, 1:width + 1] = h_ref[0]
        for p, c0, c1 in zip(part_refs, coffs[:-1], coffs[1:]):
            scr_x[2:2 + th, 1:width + 1, c0:c1] = p[0]

    @pl.when(i >= nb)
    def _flush():
        _zeros(scr_h, slice(3, 3 + th))
        _zeros(scr_x, slice(2, 2 + th))

    # ---- preact rows [i*TH-1, (i+1)*TH-1): all-gate x-side conv, z/r
    # h-side conv, nonlinearities (czrq arrives pre-shifted to these rows).
    acc_x = _conv_rows(scr_x, wx_ref, th, width)
    if lane8:
        acc_x = acc_x + _lane8_rows(czrq_ref, czrq_scale_ref, width)
    else:
        acc_x = acc_x + czrq_ref[0].astype(jnp.float32)
    acc_h = _conv_rows(scr_h[1:], whzr_ref, th, width)

    z_new = jax.nn.sigmoid(acc_h[..., :ch] + acc_x[..., :ch]).astype(dtype)
    r_new = jax.nn.sigmoid(acc_h[..., ch:] + acc_x[..., ch:2 * ch]).astype(dtype)
    rh_new = r_new * scr_h[2:2 + th, 1:width + 1]

    _shift(scr_rh, 3)
    scr_rh[3:3 + th, 1:width + 1] = rh_new
    _shift(scr_z, 2)
    scr_z[2:2 + th] = z_new
    _shift(scr_aqx, 2)
    scr_aqx[2:2 + th] = acc_x[..., 2 * ch:]

    # ---- h' rows [i*TH-3, (i+1)*TH-3): q gate + state update.
    acc_q = _conv_rows(scr_rh, whq_ref, th, width, None) + scr_aqx[0:th]
    q = jnp.tanh(acc_q).astype(dtype)
    z = scr_z[0:th]
    h_new = (1 - z) * scr_h[0:th, 1:width + 1] + z * q
    out_ref[0] = h_new

    if head:
        # ---- FlowHead chained on h': conv1+relu rows [i*TH-4, ...),
        # delta-x rows [i*TH-5, (i+1)*TH-5). h' and f1 rows outside [0, H)
        # are masked to zero — they stand in for conv zero padding.
        _shift(scr_hn, 2)
        scr_hn[2:2 + th, 1:width + 1] = _row_mask(i, -3, th, hh, h_new)
        f1 = jax.nn.relu(_conv_rows(scr_hn, w1_ref, th, width)
                         + b1_ref[...].astype(jnp.float32))
        _shift(scr_f1, 2)
        scr_f1[2:2 + th, 1:width + 1] = _row_mask(i, -4, th, hh,
                                                  f1.astype(dtype))
        dx = _conv_rows(scr_f1, w2_ref, th, width)
        dx_ref[0] = dx[..., 0].astype(dx_ref.dtype)


def _gru_lane8_kernel(*refs, **kw):
    """Named alias of ``_gru_kernel`` with the packed-czrq dequant engaged
    — a distinct top-level name so jaxpr text proves RAFT_LANE_PACK8
    engagement (scratch/check_engagement.py greps kernel names)."""
    _gru_kernel(*refs, lane8=True, **kw)


def _gru_pallas(h, parts, czrq, whzr, whq, wx_full, th: int, head):
    """Batch rides as the OUTER grid dimension: the row stream restarts
    (ring scratch re-zeroed at row step 0) for every sample, so training
    batches get the same fused scan body as B=1 eval (r3 fenced them to
    the XLA chain; reference analog: the CUDA sampler serving training
    at batch 8, ``README.md:106``).

    ``czrq`` is either the bf16 rows from ``prepare_gru_context`` or an
    ``(container, scale)`` pair from ``prepare_gru_context_any`` under
    RAFT_LANE_PACK8 — the container streams at half the bytes and the
    kernel dequantizes in-register."""
    b, hh, width, ch = h.shape
    nb = hh // th
    lag = 5 if head else 3
    grid = pl.cdiv(hh + lag, th)
    np_ = len(parts)
    lane8 = isinstance(czrq, tuple)
    if lane8:
        czrq_pk, czrq_scale = czrq
        czrq_scale = czrq_scale.reshape(b, 1).astype(jnp.float32)
    else:
        czrq_pk, czrq_scale = czrq, None
    # czrq arrives pre-shifted/pre-padded from prepare_gru_context (hoisted
    # out of the scan — padding it here would re-run a 300 MB pass per
    # iteration).
    assert czrq_pk.shape[1] >= grid * th, (czrq_pk.shape, grid, th)
    wq = czrq_pk.shape[2]

    def idx_in(bi, i):
        return (bi, jnp.minimum(i, nb - 1), 0, 0)

    coffs = [0]
    for p in parts:
        coffs.append(coffs[-1] + p.shape[-1])
    kernel = functools.partial(
        _gru_lane8_kernel if lane8 else _gru_kernel, np_=np_, th=th, nb=nb,
        width=width, ch=ch, head=head is not None, hh=hh, coffs=tuple(coffs))
    in_specs = (
        [pl.BlockSpec((1, th, width, ch), idx_in, memory_space=pltpu.VMEM),
         pl.BlockSpec((1, th, wq, 3 * ch) if lane8 else
                      (1, th, width, 3 * ch), lambda bi, i: (bi, i, 0, 0),
                      memory_space=pltpu.VMEM)] +
        ([pl.BlockSpec((1, 1), lambda bi, i: (bi, 0),
                       memory_space=pltpu.VMEM)] if lane8 else []) +
        [pl.BlockSpec((1, th, width, p.shape[-1]), idx_in,
                      memory_space=pltpu.VMEM) for p in parts] +
        [pl.BlockSpec(w.shape, lambda bi, i, nd=w.ndim: (0,) * nd,
                      memory_space=pltpu.VMEM)
         for w in [whzr, whq, wx_full]])
    out_specs = [pl.BlockSpec((1, th, width, ch),
                              lambda bi, i: (bi, i, 0, 0),
                              memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((b, grid * th, width, ch), h.dtype)]
    scratch = [pltpu.VMEM((th + 3, width + 2, ch), h.dtype),     # h window
               pltpu.VMEM((th + 3, width + 2, ch), h.dtype),     # r*h window
               pltpu.VMEM((th + 2, width, ch), h.dtype),         # z ring
               pltpu.VMEM((th + 2, width, ch), jnp.float32),     # aq_x ring
               pltpu.VMEM((th + 2, width + 2, coffs[-1]), h.dtype)]  # x parts
    inputs = [h, czrq_pk] + ([czrq_scale] if lane8 else []) \
        + [*parts, whzr, whq, wx_full]
    if head is not None:
        w1, b1, w2 = head
        in_specs += [pl.BlockSpec(w1.shape, lambda bi, i: (0,) * 4,
                                  memory_space=pltpu.VMEM),
                     pl.BlockSpec(b1.shape, lambda bi, i: (0, 0),
                                  memory_space=pltpu.VMEM),
                     pl.BlockSpec(w2.shape, lambda bi, i: (0,) * 4,
                                  memory_space=pltpu.VMEM)]
        out_specs.append(pl.BlockSpec((1, th, width),
                                      lambda bi, i: (bi, i, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(
            jax.ShapeDtypeStruct((b, grid * th, width), jnp.float32))
        scratch += [pltpu.VMEM((th + 2, width + 2, ch), h.dtype),  # h' window
                    pltpu.VMEM((th + 2, width + 2, w1.shape[-1]), h.dtype)]
        inputs += [w1, b1, w2]

    def call(*arrs):
        return pl.pallas_call(
            kernel,
            grid=(arrs[0].shape[0], grid),
            in_specs=in_specs,
            out_specs=tuple(out_specs) if head is not None else out_specs[0],
            out_shape=(
                tuple(jax.ShapeDtypeStruct((arrs[0].shape[0],) + o.shape[1:],
                                           o.dtype) for o in out_shape)
                if head is not None else
                jax.ShapeDtypeStruct((arrs[0].shape[0],) + out_shape[0]
                                     .shape[1:], out_shape[0].dtype)),
            scratch_shapes=scratch,
            compiler_params=compiler_params(
                vmem_limit_bytes=_VMEM_LIMIT),
            interpret=_interpret(),
        )(*arrs)

    # Batch is the kernel's outer grid dim, so a data-sharded batch runs
    # per-shard — the partitioning rule that lets fused training ride a
    # multi-chip data mesh (weights replicate).
    from raft_stereo_tpu.corr.pallas_reg import make_batch_partitioned
    lead = [0, 0, 0] if lane8 else [0, 0]
    axes_in = lead + [0] * np_ + [None] * (len(inputs) - len(lead) - np_)
    call_p = make_batch_partitioned(
        call, axes_in, [a.ndim for a in inputs],
        [0] * len(out_shape), [o.ndim for o in out_shape])
    outs = call_p(*inputs)
    if head is None:
        return outs[:, 3:3 + hh], None
    # h' streams at lag 3; the chained FlowHead delta trails 2 convs behind.
    h_out, dx_out = outs
    return h_out[:, 3:3 + hh], dx_out[:, 5:5 + hh][..., None]


def gru_weights(p: dict, ch: int):
    """Pack reference per-gate convs into kernel layout: h-side (z,r) and
    one x-side weight with all three gates' output channels concatenated
    (input channels ordered like the callers' x parts)."""
    wz, wr, wq = p["convz"]["w"], p["convr"]["w"], p["convq"]["w"]
    whzr = jnp.concatenate([wz[:, :, :ch], wr[:, :, :ch]], axis=-1)
    whq = wq[:, :, :ch]
    wx_full = jnp.concatenate([wz[:, :, ch:], wr[:, :, ch:], wq[:, :, ch:]],
                              axis=-1)
    return whzr, whq, wx_full


def prepare_gru_context(p: dict, context, dtype):
    """Fold the gate conv biases into the (loop-invariant) context tensor,
    shift it down one row (so kernel block i covers the preact rows
    [i*TH-1, (i+1)*TH-1) with an identity index map) and zero-pad through
    the flush steps. One pass per frame instead of per iteration — hoist
    outside the scan."""
    bias = jnp.concatenate([p["convz"]["b"], p["convr"]["b"], p["convq"]["b"]])
    czrq = jnp.concatenate(list(context), axis=-1).astype(jnp.float32)
    czrq = (czrq + bias).astype(dtype)
    hh, width = czrq.shape[1:3]
    th = pick_th(hh, width)
    if th == 0:
        return czrq
    rows = pl.cdiv(hh + 5, th) * th  # widest lag (head variant) = 5
    return jnp.pad(czrq, ((0, 0), (1, rows - hh - 1), (0, 0), (0, 0)))


def lane_pack8_on() -> bool:
    """Local RAFT_LANE_PACK8 consult for this module's packed-czrq kernel
    variants (the breaker/lint contract: a module declaring a rung's entry
    points reads that rung's switch itself — GL006). Same parse as
    corr/pallas_reg.py's ``lane_pack8``."""
    import os
    return os.environ.get("RAFT_LANE_PACK8", "0").strip().lower() in (
        "1", "true", "yes", "on")


def prepare_gru_context_any(p: dict, context, dtype):
    """``prepare_gru_context`` plus the r24 narrow-lane option: under
    RAFT_LANE_PACK8 the loop-invariant czrq rows are quantized ONCE per
    frame into a width-group int8 container (corr/pallas_reg.py seam) and
    returned as an ``(container, scale)`` pair the fused kernels stream at
    half the per-iteration HBM bytes, dequantizing in-register. The row
    zero-padding above survives packing bit-exactly (symmetric grid: pad
    rows quantize to zero bytes), and the scale is per-SAMPLE so batched
    rows stay independent."""
    czrq = prepare_gru_context(p, context, dtype)
    if not lane_pack8_on():
        return czrq
    from raft_stereo_tpu.corr.pallas_reg import (feature_scale8,
                                                 quantize_pack_feature8)
    scale = feature_scale8(czrq)
    return quantize_pack_feature8(czrq, scale), scale


def plan_lane_dma_bytes(h: int, w: int, *, n_levels: int = 3, ch: int = 128,
                        factor: int = 4, pack8: bool) -> float:
    """Per-ITERATION HBM bytes the GRU scan body's czrq context streams
    declare via their BlockSpecs, summed over the ``n_levels`` pyramid
    scales (level i runs at 1/(factor * 2**i) resolution with 3*ch gate
    channels). The analytic half of the r24 lane ledger: grid revisit /
    flush factors are identical between the bf16 and container paths
    (same TH, same index maps), so they cancel in the ratio and exact
    per-row arithmetic suffices — computable at any geometry without a
    compile. pack8 rows stream ``ceil(w/4)`` fp32 container lanes plus
    one (1, 1) fp32 scale block instead of ``w`` bf16 lanes."""
    total = 0.0
    for i in range(n_levels):
        f = factor << i
        hh_i, w_i = -(-h // f), -(-w // f)
        if pack8:
            total += hh_i * float(-(-w_i // 4)) * 3 * ch * 4 + 4.0
        else:
            total += hh_i * float(w_i) * 3 * ch * 2
    return total


def fused_conv_gru_fwd_impl(p: dict, h, czrq, *x_list, head_p=None):
    """Kernel forward. czrq from ``prepare_gru_context``; x parts separate.
    head_p: optional FlowHead params {conv1, conv2} chained onto h'."""
    ch = h.shape[-1]
    whzr, whq, wx_full = gru_weights(p, ch)
    dtype = h.dtype
    whzr, whq = whzr.astype(dtype), whq.astype(dtype)
    wx_full = wx_full.astype(dtype)
    head = None
    if head_p is not None:
        # conv2's bias and y-channel drop out: only delta-x is emitted and
        # conv2.b[0] is added by the caller (scalar, fused into the coords
        # update).
        head = (head_p["conv1"]["w"].astype(dtype),
                head_p["conv1"]["b"].reshape(1, -1),
                head_p["conv2"]["w"][..., :1].astype(dtype))
    th = pick_th(h.shape[1], h.shape[2])
    return _gru_pallas(h, x_list, czrq, whzr, whq, wx_full, th, head)


@jax.custom_vjp
def fused_conv_gru(p: dict, h, czrq, context, *x_list):
    """ConvGRU step via the streaming Pallas kernel.

    Gradients run through the XLA formulation (``apply_conv_gru``) — the
    same arithmetic modulo bf16 rounding points; the reference's own
    mixed-precision autocast tolerates larger fwd/bwd dtype asymmetry.
    ``context`` rides along unused in the forward so the VJP can rebuild
    the XLA computation (czrq is derived from it, so its cotangent is zero
    — no double counting).
    """
    out, _ = fused_conv_gru_fwd_impl(p, h, czrq, *x_list)
    return out


def _gru_oracle(p: dict, h, context, *x_list):
    from raft_stereo_tpu.models.update import apply_conv_gru
    return apply_conv_gru(p, h, context, *x_list)


def _fused_gru_fwd(p, h, czrq, context, *x_list):
    return (fused_conv_gru(p, h, czrq, context, *x_list),
            (p, h, czrq, context, x_list))


def _fused_gru_bwd(res, g):
    p, h, czrq, context, x_list = res
    out, vjp = jax.vjp(lambda *a: _gru_oracle(a[0], a[1], a[2], *a[3:]),
                       p, h, context, *x_list)
    dp, dh, dctx, *dxs = vjp(g.astype(out.dtype))
    # tree_map: czrq may be the bare bf16 rows or the r24 (container,
    # scale) pair — both zero-cotangent (STE through ``context``).
    return (dp, dh, jax.tree_util.tree_map(jnp.zeros_like, czrq),
            dctx, *dxs)


fused_conv_gru.defvjp(_fused_gru_fwd, _fused_gru_bwd)


@jax.custom_vjp
def fused_gru_head(p: dict, head_p: dict, h, czrq, context, *x_list):
    """ConvGRU + FlowHead in one streaming kernel (test-mode scan body).

    Returns ``(h', delta_x)`` with delta_x fp32 (1, H, W, 1) EXCLUDING the
    final conv bias — the caller adds the scalar ``conv2.b[0]`` (so its
    gradient flows through that add, matching the oracle below which also
    omits it)."""
    return fused_conv_gru_fwd_impl(p, h, czrq, *x_list, head_p=head_p)


def _gru_head_oracle(p, head_p, h, context, *x_list):
    from raft_stereo_tpu.models.update import apply_conv_gru
    from raft_stereo_tpu.models.layers import apply_conv
    from raft_stereo_tpu.ops.basic import conv2d
    h2 = apply_conv_gru(p, h, context, *x_list)
    f1 = jax.nn.relu(apply_conv(head_p["conv1"], h2, padding=1))
    dx = conv2d(f1, head_p["conv2"]["w"][..., :1], None, padding=1,
                out_dtype=jnp.float32)
    return h2, dx


def _fused_gru_head_fwd(p, head_p, h, czrq, context, *x_list):
    return (fused_gru_head(p, head_p, h, czrq, context, *x_list),
            (p, head_p, h, czrq, context, x_list))


def _fused_gru_head_bwd(res, g):
    p, head_p, h, czrq, context, x_list = res
    (h2, _), vjp = jax.vjp(
        lambda *a: _gru_head_oracle(a[0], a[1], a[2], a[3], *a[4:]),
        p, head_p, h, context, *x_list)
    gh, gdx = g
    dp, dhead, dh, dctx, *dxs = vjp((gh.astype(h2.dtype),
                                     gdx.astype(jnp.float32)))
    return (dp, dhead, dh, jax.tree_util.tree_map(jnp.zeros_like, czrq),
            dctx, *dxs)


fused_gru_head.defvjp(_fused_gru_head_fwd, _fused_gru_head_bwd)


def stream_batch_on() -> bool:
    """``RAFT_STREAM_BATCH`` — the r19 kill switch for B>1 engagement of
    the streamed scan-body kernels (default ON). Off restores the pre-r19
    serve behavior: batched device calls run the XLA twins, B=1 keeps its
    kernels. Read at trace time and registered in ENV_KNOBS so batched
    serving programs key on it (the stale-program discipline)."""
    import os
    return os.environ.get("RAFT_STREAM_BATCH", "1").strip().lower() not in (
        "0", "false", "no", "off")


# Crossover model constants (r19) — derived from the repo's own measured
# records rather than the old one-point 200k heuristic:
# - _STREAM_FIXED_S: per-SAMPLE fixed cost of a batched engagement — each
#   of the ~3 streamed kernels in the scan body pays its pipeline ramp +
#   lag-flush drain per sample (batch rides the outer grid dim, so the
#   ramp re-runs per sample; ~2 extra grid steps/kernel at the r4-measured
#   5-10 us/step fixed cost => ~36 us/sample).
# - _INTERSTITIAL_BYTES_PER_PX: HBM bytes/pixel the fusion saves per
#   iteration — the r5 profile's interstitial round-trips (gate preacts,
#   zr, r*h, state update, motion features: ~3 full-tensor write+read
#   pairs at 128 bf16 channels).
# Fusing a B>1 sample wins when saved-DMA time exceeds the fixed cost:
#   pixels * bytes_per_px / hbm_bw > fixed_s
# On v5e (819 GB/s) the crossover lands at ~19k px/sample — engaging the
# serve buckets (384x1248 -> 30k px at 1/4 res) the 200k heuristic fenced
# out, while still protecting the r4 regression case (batch-16 realtime
# 48x156 = 7.5k px: 129 -> 83 fps when force-fused).
_STREAM_FIXED_S = 36e-6
_INTERSTITIAL_BYTES_PER_PX = 1536.0


def stream_batch_crossover() -> int:
    """Pixels/sample above which B>1 engages the streamed kernels.

    ``RAFT_BATCH_FUSE_PIXELS`` (explicit override, 0 = always fuse) wins;
    otherwise the roofline crossover above, evaluated against the chip's
    ledger HBM bandwidth (obs/ledger.py PEAK_HBM_BW — the same table the
    MFU attribution uses; off-table hosts fall back to the v5e number,
    which only matters for CPU tests)."""
    import os
    spec = os.environ.get("RAFT_BATCH_FUSE_PIXELS", "").strip()
    if spec:
        return int(spec)
    bw = 819e9  # v5e default
    try:
        from raft_stereo_tpu.obs.ledger import chip_peaks
        peaks = chip_peaks(jax.devices()[0].device_kind)
        if peaks:
            bw = peaks[1]
    except Exception:  # noqa: BLE001 — policy heuristic, never fatal
        pass
    return int(_STREAM_FIXED_S * bw / _INTERSTITIAL_BYTES_PER_PX)


def _batch_worthwhile(t) -> bool:
    """B>1 engagement policy for the streamed kernels (EVAL heuristic;
    training's ``any_batch`` bypasses it). B=1 always engages. For B>1:
    the ``RAFT_STREAM_BATCH`` kill switch gates the path entirely, and
    the per-sample frame must clear :func:`stream_batch_crossover` —
    the r19 ledger-derived replacement for the old fixed 200k-pixel
    fence, sized so the scheduler's batch-4/8 serve buckets engage
    Pallas instead of the XLA twins (sweep table in BASELINE.md)."""
    if t.shape[0] == 1:
        return True
    return (stream_batch_on()
            and t.shape[1] * t.shape[2] >= stream_batch_crossover())


def gru_is_fusable(h, *x_list, any_batch: bool = False) -> bool:
    """Shapes/dtype the streaming kernel supports; callers fall back to
    the XLA path otherwise (fp32 runs exceed the VMEM budget at full
    res). Batch rides as the outer grid dimension since r4; B>1 engages
    only for big frames (``_batch_worthwhile``, an EVAL heuristic) unless
    ``any_batch`` — fused TRAINING (cfg.fused_train) measured 0.742 vs
    0.637 steps/s at the reference batch-6 320x720 crop config (r5, with
    the save-kernel-outputs remat policy), so it fuses at any batch."""
    return (_dtype_ok(h) and (any_batch or _batch_worthwhile(h))
            and pick_th(h.shape[1], h.shape[2]) > 0 and h.shape[1] >= 8)


# ---------------------------------------------------------------------------
# Fused gru16+gru32: the two coarse-scale ConvGRUs co-scheduled in ONE
# streaming kernel. Their small spatial extents (1/8- and 1/16-res) leave
# the chip latency-bound when the scan body dispatches them serially
# (r5 profile: 126 ms/frame vs a ~50 ms MXU bound): each kernel pays its
# own pipeline ramp, and the cross-scale upsample between them is a
# separate XLA dispatch whose output round-trips HBM every iteration.
# Here one grid step advances the gru32 stream by TH/2 rows, appends its
# fresh hidden rows to a VMEM window, and runs the gru16 stream ONE ROW
# BLOCK behind, building its aligned-corners upsampled x-input from the
# window in-register: H-interp as a 3-slot row lerp (each output row
# reads window rows c-1, c, c+1 with per-row weights riding as
# constants — the drift of floor(j*(H32-1)/(H16-1)) around j/2 never
# exceeds one row), W-interp as per-row banded-matrix MXU dots (the same
# matrices ops/resize.py builds, so the arithmetic — exact bf16
# products, fp32 accumulation, bf16 round between the H and W passes —
# is BIT-IDENTICAL to the serial kernels + XLA interp it replaces).
# The upsampled tensor never touches HBM, and both GRUs' DMA and MXU
# work share one pipeline.
# ---------------------------------------------------------------------------


def gru1632_th(h16: int, w16: int) -> int:
    """Row block for the fused gru16+gru32 stream (0 = unsupported):
    gru16's block must be even (gru32 advances TH/2 rows per step) and
    at least 8 (the availability bound needs TH/2 >= 4)."""
    th = pick_th(h16, w16)
    return th if th >= 8 and th % 2 == 0 and h16 % th == 0 else 0


def _upsample_weights(h32: int, h16: int, th16: int, dtype=jnp.bfloat16):
    """Per-block 3-slot H-interp weights (nb16, 6, th32, 1, 1) in the
    compute dtype.

    Output row j of the aligned-corners upsample lerps source rows
    lo(j) = floor(j*(H32-1)/(H16-1)) and min(lo+1, H32-1). Relative to
    the window slot center c = j//2 both taps live in {c-1, c, c+1};
    weight slots are (even rows: 0..2, odd rows: 3..5) x (c-1, c, c+1).
    Built in fp32 and rounded to bf16 exactly like ops/resize.py's
    banded matrix (slot sums in fp32, ONE bf16 round per entry), so the
    kernel's lerp reproduces the XLA einsum bit-for-bit. Returns None
    when any tap falls outside {c-1, c, c+1} (never for H16 == 2*H32)."""
    import numpy as np
    th32 = th16 // 2
    nb16 = h16 // th16
    scale = (h32 - 1) / (h16 - 1) if h16 > 1 else 0.0
    wh = np.zeros((nb16, 6, th32, 1, 1), np.float32)
    for blk in range(nb16):
        for r in range(th16):
            j = blk * th16 + r
            src = j * scale
            lo = min(int(np.floor(src)), h32 - 1)
            hi = min(lo + 1, h32 - 1)
            wt = np.float32(src - lo)
            c = j // 2
            base = 3 * (r % 2)
            k = r // 2
            for tap, twt in ((lo, np.float32(1.0) - wt), (hi, wt)):
                slot = tap - (c - 1)
                if not 0 <= slot <= 2:
                    return None
                wh[blk, base + slot, k, 0, 0] += twt
    # One round per entry from the fp32 slot sum — exactly how
    # ops/resize.py builds its banded matrix (fp32 accumulate, then
    # astype), so the kernel lerp matches the XLA einsum bit-for-bit.
    return jnp.asarray(wh).astype(dtype)


def _gru1632_kernel(h16_ref, h32_ref, czrq16_ref, czrq32_ref, *rest,
                    th16: int, nb16: int, w16: int, w32: int,
                    c16: int, c32: int, cx0: int, lane8: bool = False):
    k = 0
    if lane8:
        czrq16_s_ref, czrq32_s_ref = rest[:2]
        k = 2
    (x0_ref, x1_ref,
     whzr16_ref, whq16_ref, wx16_ref,
     whzr32_ref, whq32_ref, wx32_ref,
     mw_ref, wh_ref, out16_ref, out32_ref,
     s32_h, s32_rh, s32_z, s32_aqx, s32_x, s_up,
     s16_h, s16_rh, s16_z, s16_aqx, s16_x) = rest[k:]
    th32 = th16 // 2
    win = s_up.shape[0]
    i = pl.program_id(1)  # row step; program_id(0) is the batch sample
    dtype = h16_ref.dtype

    @pl.when(i == 0)
    def _init():
        for s in (s32_h, s32_rh, s32_z, s32_aqx, s32_x, s_up,
                  s16_h, s16_rh, s16_z, s16_aqx, s16_x):
            _zeros(s)

    # ---- gru32 stream: block i (same structure as _gru_kernel at TH/2).
    _shift(s32_h, 3)
    _shift(s32_x, 2)

    @pl.when(i < nb16)
    def _place32():
        s32_h[3:3 + th32, 1:w32 + 1] = h32_ref[0]
        s32_x[2:2 + th32, 1:w32 + 1] = x1_ref[0]

    @pl.when(i >= nb16)
    def _flush32():
        _zeros(s32_h, slice(3, 3 + th32))
        _zeros(s32_x, slice(2, 2 + th32))

    acc_x = _conv_rows(s32_x, wx32_ref, th32, w32)
    if lane8:
        acc_x = acc_x + _lane8_rows(czrq32_ref, czrq32_s_ref, w32)
    else:
        acc_x = acc_x + czrq32_ref[0].astype(jnp.float32)
    acc_h = _conv_rows(s32_h[1:], whzr32_ref, th32, w32)
    z_new = jax.nn.sigmoid(acc_h[..., :c32] + acc_x[..., :c32]).astype(dtype)
    r_new = jax.nn.sigmoid(acc_h[..., c32:]
                           + acc_x[..., c32:2 * c32]).astype(dtype)
    rh_new = r_new * s32_h[2:2 + th32, 1:w32 + 1]
    _shift(s32_rh, 3)
    s32_rh[3:3 + th32, 1:w32 + 1] = rh_new
    _shift(s32_z, 2)
    s32_z[2:2 + th32] = z_new
    _shift(s32_aqx, 2)
    s32_aqx[2:2 + th32] = acc_x[..., 2 * c32:]
    acc_q = _conv_rows(s32_rh, whq32_ref, th32, w32, None) + s32_aqx[0:th32]
    q32 = jnp.tanh(acc_q).astype(dtype)
    z32 = s32_z[0:th32]
    h32_new = (1 - z32) * s32_h[0:th32, 1:w32 + 1] + z32 * q32
    out32_ref[0] = h32_new
    # Append the fresh h32' rows to the upsample window: after this the
    # window holds global rows [(i+1)*TH/2 - 3 - win, (i+1)*TH/2 - 3).
    _shift(s_up, win - th32)
    s_up[win - th32:win] = h32_new

    # ---- gru16 stream: block i-1 (one block behind, so every upsample
    # source row is already in the window). Fully gated on i >= 1 — its
    # ring writes at i == 0 would inject czrq-biased junk the real
    # stream would then consume.
    @pl.when(i >= 1)
    def _gru16_phase():
        i16 = i - 1
        _shift(s16_h, 3)
        _shift(s16_x, 2)

        @pl.when(i16 < nb16)
        def _place16():
            s16_h[3:3 + th16, 1:w16 + 1] = h16_ref[0]
            s16_x[2:2 + th16, 1:w16 + 1, 0:cx0] = x0_ref[0]
            # Upsampled x part, computed in-register from the window.
            # Window index of slot center c = j//2 for j in block i16:
            # c - (b - win) with b = (i16+2)*TH/2 - 3 -> r//2 + win + 3
            # - TH, independent of the step. H-lerp (3 static slices x
            # per-row weights, fp32, ONE bf16 round — the XLA H-einsum's
            # bf16 intermediate), then the banded W matrix per row.
            o = win + 3 - th16
            sm = s_up[o - 1:o - 1 + th32].astype(jnp.float32)
            s0 = s_up[o:o + th32].astype(jnp.float32)
            sp = s_up[o + 1:o + 1 + th32].astype(jnp.float32)
            whw = wh_ref[0].astype(jnp.float32)  # (6, th32, 1, 1)
            even = whw[0] * sm + whw[1] * s0 + whw[2] * sp
            odd = whw[3] * sm + whw[4] * s0 + whw[5] * sp
            xh = jnp.stack([even, odd], axis=1).reshape(
                th16, w32, c32).astype(dtype)
            rows = [jax.lax.dot_general(
                mw_ref[...], xh[r], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) for r in range(th16)]
            up = jnp.stack(rows).astype(dtype)  # (th16, w16, c32)
            s16_x[2:2 + th16, 1:w16 + 1, cx0:cx0 + c32] = up

        @pl.when(i16 >= nb16)
        def _flush16():
            _zeros(s16_h, slice(3, 3 + th16))
            _zeros(s16_x, slice(2, 2 + th16))

        acc_x16 = _conv_rows(s16_x, wx16_ref, th16, w16)
        if lane8:
            acc_x16 = acc_x16 + _lane8_rows(czrq16_ref, czrq16_s_ref, w16)
        else:
            acc_x16 = acc_x16 + czrq16_ref[0].astype(jnp.float32)
        acc_h16 = _conv_rows(s16_h[1:], whzr16_ref, th16, w16)
        z16n = jax.nn.sigmoid(acc_h16[..., :c16]
                              + acc_x16[..., :c16]).astype(dtype)
        r16n = jax.nn.sigmoid(acc_h16[..., c16:]
                              + acc_x16[..., c16:2 * c16]).astype(dtype)
        rh16n = r16n * s16_h[2:2 + th16, 1:w16 + 1]
        _shift(s16_rh, 3)
        s16_rh[3:3 + th16, 1:w16 + 1] = rh16n
        _shift(s16_z, 2)
        s16_z[2:2 + th16] = z16n
        _shift(s16_aqx, 2)
        s16_aqx[2:2 + th16] = acc_x16[..., 2 * c16:]
        acc_q16 = (_conv_rows(s16_rh, whq16_ref, th16, w16, None)
                   + s16_aqx[0:th16])
        q16 = jnp.tanh(acc_q16).astype(dtype)
        z16 = s16_z[0:th16]
        out16_ref[0] = ((1 - z16) * s16_h[0:th16, 1:w16 + 1] + z16 * q16)


def _gru1632_lane8_kernel(*refs, **kw):
    """Named alias of ``_gru1632_kernel`` with packed-czrq dequant engaged
    (jaxpr-greppable engagement proof, like ``_gru_lane8_kernel``)."""
    _gru1632_kernel(*refs, lane8=True, **kw)


def gru1632_is_fusable(h16, h32, *, any_batch: bool = False) -> bool:
    """Fused co-schedule engages when both coarse GRUs are individually
    fusable, the scales nest exactly 2x (the padder's /32 rule guarantees
    it for real inputs), and a supported even row block exists. The x
    inputs need no separate guard: pool2x of the checked net states has
    their exact geometry by construction.
    ``RAFT_FUSE_GRU1632=0`` forces the serial two-kernel path (A/B)."""
    import os
    if os.environ.get("RAFT_FUSE_GRU1632", "1").strip().lower() in (
            "0", "false", "no", "off"):
        return False
    b16, hh16, ww16, c16 = h16.shape
    b32, hh32, ww32, c32 = h32.shape
    # Equal hidden dims required: the kernel sizes gru32's x input
    # (pool2x of the gru16 state) and scratch at c32 — unequal per-level
    # hidden_dims fall back to the serial kernels, which handle them.
    return (_dtype_ok(h16) and _dtype_ok(h32) and b16 == b32 and c16 == c32
            and (any_batch or _batch_worthwhile(h16))
            and hh16 == 2 * hh32 and ww16 == 2 * ww32
            and hh32 >= 8 and gru1632_th(hh16, ww16) > 0
            and _upsample_weights(hh32, hh16, gru1632_th(hh16, ww16))
            is not None)


def fused_gru1632_fwd_impl(p16: dict, p32: dict, h16, h32, czrq16, czrq32,
                           x0p, x1p):
    """Kernel forward: (h16', h32') with x inputs pool2x(net0) / pool2x(
    net1) supplied by the caller (cheap XLA pools; keeping them outside
    preserves bit-identity with the serial path) and the cross-scale
    upsample computed in-kernel."""
    from raft_stereo_tpu.ops.resize import _lerp_matrix
    b, hh16, w16, c16 = h16.shape
    _, hh32, w32, c32 = h32.shape
    cx0 = x0p.shape[-1]
    dtype = h16.dtype
    th16 = gru1632_th(hh16, w16)
    th32 = th16 // 2
    nb16 = hh16 // th16
    grid = nb16 + 2
    win = th16 + 4

    whzr16, whq16, wx16 = (w.astype(dtype) for w in gru_weights(p16, c16))
    whzr32, whq32, wx32 = (w.astype(dtype) for w in gru_weights(p32, c32))
    mw = _lerp_matrix(w32, w16, dtype)  # (w16, w32), the XLA W matrix
    wh = _upsample_weights(hh32, hh16, th16, dtype)

    lane8 = isinstance(czrq16, tuple)
    if lane8:
        czrq16, s16 = czrq16
        czrq32, s32 = czrq32
        s16 = s16.reshape(b, 1).astype(jnp.float32)
        s32 = s32.reshape(b, 1).astype(jnp.float32)
    wq16, wq32 = czrq16.shape[2], czrq32.shape[2]

    # czrq rows must cover every block index the schedule touches
    # (prepare_gru_context padded for the SERIAL kernels' geometry, whose
    # row block may differ); re-pad here is loop-invariant — XLA hoists
    # it out of the scan. Exact for containers too: pad rows are zero
    # bytes on the symmetric int8 grid.
    def pad_rows(czrq, rows):
        return (jnp.pad(czrq, ((0, 0), (0, rows - czrq.shape[1]),
                               (0, 0), (0, 0)))
                if czrq.shape[1] < rows else czrq)

    czrq16 = pad_rows(czrq16, (nb16 + 1) * th16)
    czrq32 = pad_rows(czrq32, grid * th32)

    def i16c(i):
        return jnp.clip(i - 1, 0, nb16 - 1)

    in_specs = [
        pl.BlockSpec((1, th16, w16, c16),
                     lambda bi, i: (bi, i16c(i), 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, th32, w32, c32),
                     lambda bi, i: (bi, jnp.minimum(i, nb16 - 1), 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, th16, wq16 if lane8 else w16, 3 * c16),
                     lambda bi, i: (bi, jnp.clip(i - 1, 0, nb16), 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, th32, wq32 if lane8 else w32, 3 * c32),
                     lambda bi, i: (bi, jnp.minimum(i, grid - 1), 0, 0),
                     memory_space=pltpu.VMEM),
    ] + ([pl.BlockSpec((1, 1), lambda bi, i: (bi, 0),
                       memory_space=pltpu.VMEM)] * 2 if lane8 else []) + [
        pl.BlockSpec((1, th16, w16, cx0),
                     lambda bi, i: (bi, i16c(i), 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, th32, w32, c32),
                     lambda bi, i: (bi, jnp.minimum(i, nb16 - 1), 0, 0),
                     memory_space=pltpu.VMEM),
    ] + [pl.BlockSpec(w.shape, lambda bi, i, nd=w.ndim: (0,) * nd,
                      memory_space=pltpu.VMEM)
         for w in (whzr16, whq16, wx16, whzr32, whq32, wx32, mw)] + [
        pl.BlockSpec((1,) + wh.shape[1:],
                     lambda bi, i: (i16c(i), 0, 0, 0, 0),
                     memory_space=pltpu.VMEM)]
    out_specs = (
        pl.BlockSpec((1, th16, w16, c16),
                     lambda bi, i: (bi, jnp.where(i == 0, nb16 + 1, i - 1),
                                    0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, th32, w32, c32),
                     lambda bi, i: (bi, jnp.minimum(i, nb16 + 1), 0, 0),
                     memory_space=pltpu.VMEM))
    out_shape = (
        jax.ShapeDtypeStruct((b, (nb16 + 2) * th16, w16, c16), dtype),
        jax.ShapeDtypeStruct((b, (nb16 + 2) * th32, w32, c32), dtype))
    scratch = [
        pltpu.VMEM((th32 + 3, w32 + 2, c32), dtype),      # gru32 h window
        pltpu.VMEM((th32 + 3, w32 + 2, c32), dtype),      # gru32 r*h
        pltpu.VMEM((th32 + 2, w32, c32), dtype),          # gru32 z ring
        pltpu.VMEM((th32 + 2, w32, c32), jnp.float32),    # gru32 aq_x
        pltpu.VMEM((th32 + 2, w32 + 2, c32), dtype),      # gru32 x
        pltpu.VMEM((win, w32, c32), dtype),               # h32' up window
        pltpu.VMEM((th16 + 3, w16 + 2, c16), dtype),      # gru16 h window
        pltpu.VMEM((th16 + 3, w16 + 2, c16), dtype),      # gru16 r*h
        pltpu.VMEM((th16 + 2, w16, c16), dtype),          # gru16 z ring
        pltpu.VMEM((th16 + 2, w16, c16), jnp.float32),    # gru16 aq_x
        pltpu.VMEM((th16 + 2, w16 + 2, cx0 + c32), dtype)]  # gru16 x
    kernel = functools.partial(
        _gru1632_lane8_kernel if lane8 else _gru1632_kernel,
        th16=th16, nb16=nb16, w16=w16, w32=w32,
        c16=c16, c32=c32, cx0=cx0)
    inputs = [h16, h32, czrq16, czrq32] + ([s16, s32] if lane8 else []) \
        + [x0p, x1p, whzr16, whq16, wx16, whzr32, whq32, wx32, mw, wh]

    def call(*arrs):
        return pl.pallas_call(
            kernel,
            grid=(arrs[0].shape[0], grid),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=tuple(
                jax.ShapeDtypeStruct((arrs[0].shape[0],) + o.shape[1:],
                                     o.dtype) for o in out_shape),
            scratch_shapes=scratch,
            compiler_params=compiler_params(vmem_limit_bytes=_VMEM_LIMIT),
            interpret=_interpret(),
        )(*arrs)

    from raft_stereo_tpu.corr.pallas_reg import make_batch_partitioned
    call_p = make_batch_partitioned(
        call, [0] * (8 if lane8 else 6) + [None] * 8,
        [a.ndim for a in inputs], [0, 0], [4, 4])
    o16, o32 = call_p(*inputs)
    return o16[:, 3:3 + hh16], o32[:, 3:3 + hh32]


def _gru1632_oracle(p16, p32, h16, h32, ctx16, ctx32, x0p, x1p):
    from raft_stereo_tpu.models.update import apply_conv_gru
    from raft_stereo_tpu.ops.resize import interp_align_corners
    h32n = apply_conv_gru(p32, h32, ctx32, x1p)
    up = interp_align_corners(h32n, h16.shape[1:3])
    h16n = apply_conv_gru(p16, h16, ctx16, x0p, up)
    return h16n, h32n


@jax.custom_vjp
def fused_gru1632(p16: dict, p32: dict, h16, h32, czrq16, czrq32,
                  ctx16, ctx32, x0p, x1p):
    """gru32 step + aligned-corners upsample + gru16 step in ONE streaming
    kernel. ``ctx16``/``ctx32`` ride along unused in the forward so the
    VJP can rebuild the XLA composition (czrq is derived from them, zero
    cotangent — same contract as ``fused_conv_gru``)."""
    return fused_gru1632_fwd_impl(p16, p32, h16, h32, czrq16, czrq32,
                                  x0p, x1p)


def _fused_gru1632_fwd(p16, p32, h16, h32, czrq16, czrq32, ctx16, ctx32,
                       x0p, x1p):
    return (fused_gru1632(p16, p32, h16, h32, czrq16, czrq32, ctx16, ctx32,
                          x0p, x1p),
            (p16, p32, h16, h32, czrq16, czrq32, ctx16, ctx32, x0p, x1p))


def _fused_gru1632_bwd(res, g):
    p16, p32, h16, h32, czrq16, czrq32, ctx16, ctx32, x0p, x1p = res
    (h16n, h32n), vjp = jax.vjp(_gru1632_oracle, p16, p32, h16, h32,
                                ctx16, ctx32, x0p, x1p)
    g16, g32 = g
    dp16, dp32, dh16, dh32, dctx16, dctx32, dx0, dx1 = vjp(
        (g16.astype(h16n.dtype), g32.astype(h32n.dtype)))
    return (dp16, dp32, dh16, dh32,
            jax.tree_util.tree_map(jnp.zeros_like, czrq16),
            jax.tree_util.tree_map(jnp.zeros_like, czrq32),
            dctx16, dctx32, dx0, dx1)


fused_gru1632.defvjp(_fused_gru1632_fwd, _fused_gru1632_bwd)


# ---------------------------------------------------------------------------
# Height-sharded (``space`` mesh axis) execution: the row streams cannot
# cross a shard cut, so each shard runs the SAME kernels over its rows
# plus an 8-row halo fetched from its neighbors (ppermute fills
# non-participating edges with zeros — exactly the kernels' top/bottom
# zero conv padding), and the halo rows of the output are discarded.
# This is what lets ``fused_update`` survive ``--spatial_shard`` (r3
# silently swapped the whole scan body to XLA under space>1). 8 rows
# cover the deepest chain (GRU+FlowHead reads 4 rows each side).
# ---------------------------------------------------------------------------

_HALO = 8


def _sharded_rows(hh: int, ns: int):
    """(local rows, extended rows padded to /8) for an ns-way H shard."""
    hl = hh // ns
    ext = hl + 2 * _HALO
    return hl, ext + (-ext % 8)


def spatial_gru_is_fusable(h, ns: int) -> bool:
    if not (_dtype_ok(h) and h.shape[1] % ns == 0):
        return False
    hl, ext = _sharded_rows(h.shape[1], ns)
    return hl >= _HALO and pick_th(ext, h.shape[2]) > 0


def _exchange_halo(x, pad_rows: int):
    """(B, H_loc, W, C) -> (B, H_ext(+pad), W, C): neighbours' edge rows
    on both sides over the ``space`` axis (zeros at the image edges),
    plus bottom zero-pad rows to reach a row-block multiple (they sit
    beyond the halo, so no in-range output depends on them)."""
    ns = jax.lax.axis_size("space")
    up = jax.lax.ppermute(x[:, -_HALO:], "space",
                          [(i, i + 1) for i in range(ns - 1)])
    dn = jax.lax.ppermute(x[:, :_HALO], "space",
                          [(i + 1, i) for i in range(ns - 1)])
    out = jnp.concatenate([up, x, dn], axis=1)
    if pad_rows:
        out = jnp.pad(out, ((0, 0), (0, pad_rows), (0, 0), (0, 0)))
    return out


@functools.lru_cache(maxsize=None)
def _spatial_prepare(mesh):
    """shard_map'd ``prepare_gru_context`` twin: halo-exchange the raw
    per-level context ONCE PER FRAME and emit each shard's pre-shifted,
    pre-padded czrq block — hoisted outside the scan exactly like the
    unsharded path (refolding per iteration would re-run the exchange +
    bias-fold ~100x per frame)."""
    from jax.sharding import PartitionSpec as P
    row = P("data", "space")
    ns = mesh.shape["space"]

    def per_shard(p, context):
        hl = context[0].shape[1]
        _, ext = _sharded_rows(hl * ns, ns)
        pad = ext - (hl + 2 * _HALO)
        ctx_e = tuple(_exchange_halo(c, pad) for c in context)
        return prepare_gru_context(p, ctx_e, context[0].dtype)

    return jax.shard_map(per_shard, mesh=mesh,
                         in_specs=(P(), (row,) * 3), out_specs=row,
                         check_vma=False)


def spatial_prepare_gru_context(mesh, p: dict, context):
    """Per-shard czrq (global rows = ns * per-shard padded rows)."""
    return _spatial_prepare(mesh)(p, tuple(context))


@functools.lru_cache(maxsize=None)
def _spatial_gru(mesh, head: bool, n_x: int):
    from jax.sharding import PartitionSpec as P
    row = P("data", "space")
    ns = mesh.shape["space"]

    def per_shard(p, head_p, h, czrq, *x_list):
        hl = h.shape[1]
        _, ext = _sharded_rows(hl * ns, ns)
        pad = ext - (hl + 2 * _HALO)
        h_e = _exchange_halo(h, pad)
        xs_e = [_exchange_halo(x, pad) for x in x_list]
        out, dx = fused_conv_gru_fwd_impl(
            p, h_e, czrq, *xs_e, head_p=head_p if head else None)
        out = out[:, _HALO:_HALO + hl]
        if not head:
            return out
        return out, dx[:, _HALO:_HALO + hl]

    return jax.shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(), P(), row, row) + (row,) * n_x,
        out_specs=(row, row) if head else row, check_vma=False)


def fused_conv_gru_spatial(mesh, p: dict, h, czrq, context, *x_list):
    """ConvGRU step with H sharded over the mesh ``space`` axis: halo
    exchange + the streaming kernel per shard. ``czrq`` from
    ``spatial_prepare_gru_context`` (hoisted per frame); ``context``
    rides along for the XLA-oracle backward (GSPMD partitions it
    natively)."""
    return _spatial_call(mesh, False, p, None, h, czrq, context, *x_list)


def fused_gru_head_spatial(mesh, p: dict, head_p: dict, h, czrq, context,
                           *x_list):
    """ConvGRU + FlowHead under a ``space`` shard (test-mode scan body);
    delta-x excludes conv2.b[0], like ``fused_gru_head``."""
    return _spatial_call(mesh, True, p, head_p, h, czrq, context, *x_list)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _spatial_call(mesh, head: bool, p, head_p, h, czrq, context, *x_list):
    fn = _spatial_gru(mesh, head, len(x_list))
    return fn(p, head_p, h, czrq, *x_list)


def _spatial_fwd(mesh, head, p, head_p, h, czrq, context, *x_list):
    return (_spatial_call(mesh, head, p, head_p, h, czrq, context,
                          *x_list),
            (p, head_p, h, czrq, context, x_list))


def _spatial_bwd(mesh, head, res, g):
    # czrq is derived from context, so its cotangent is zero — no double
    # counting, exactly like the unsharded kernels.
    p, head_p, h, czrq, context, x_list = res
    if head:
        (h2, _), vjp = jax.vjp(
            lambda *a: _gru_head_oracle(a[0], a[1], a[2], a[3], *a[4:]),
            p, head_p, h, context, *x_list)
        gh, gdx = g
        dp, dhead, dh, dctx, *dxs = vjp((gh.astype(h2.dtype),
                                         gdx.astype(jnp.float32)))
        return (dp, dhead, dh, jnp.zeros_like(czrq), dctx, *dxs)
    out, vjp = jax.vjp(lambda *a: _gru_oracle(a[0], a[1], a[2], *a[3:]),
                       p, h, context, *x_list)
    dp, dh, dctx, *dxs = vjp(g.astype(out.dtype))
    return (dp, None, dh, jnp.zeros_like(czrq), dctx, *dxs)


_spatial_call.defvjp(_spatial_fwd, _spatial_bwd)


def spatial_motion_is_fusable(corr, ns: int) -> bool:
    if not (_dtype_ok(corr) and corr.shape[1] % ns == 0):
        return False
    hl, ext = _sharded_rows(corr.shape[1], ns)
    return hl >= _HALO and pick_th(ext, corr.shape[2]) > 0


@functools.lru_cache(maxsize=None)
def _spatial_motion_map(mesh):
    from jax.sharding import PartitionSpec as P
    row = P("data", "space")
    ns = mesh.shape["space"]

    def per_shard(p, flow, corr):
        hl = corr.shape[1]
        _, ext = _sharded_rows(hl * ns, ns)
        pad = ext - (hl + 2 * _HALO)
        out = fused_motion_fwd_impl(p, _exchange_halo(flow, pad),
                                    _exchange_halo(corr, pad))
        return out[:, _HALO:_HALO + hl]

    return jax.shard_map(per_shard, mesh=mesh,
                         in_specs=(P(), row, row), out_specs=row,
                         check_vma=False)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def fused_motion_spatial(mesh, p: dict, flow, corr):
    """BasicMotionEncoder under a ``space`` shard: halo exchange + the
    streaming kernel per shard; backward via the XLA oracle."""
    return _spatial_motion_map(mesh)(p, flow, corr)


def _spatial_motion_fwd(mesh, p, flow, corr):
    return fused_motion_spatial(mesh, p, flow, corr), (p, flow, corr)


def _spatial_motion_bwd(mesh, res, g):
    p, flow, corr = res
    from raft_stereo_tpu.models.update import apply_motion_encoder
    out, vjp = jax.vjp(apply_motion_encoder, p, flow, corr)
    return vjp(g.astype(out.dtype))


fused_motion_spatial.defvjp(_spatial_motion_fwd, _spatial_motion_bwd)


# ---------------------------------------------------------------------------
# Fused motion encoder (reference BasicMotionEncoder, core/update.py:64-85).
# The 7x7 flow conv is re-expressed as an XLA-built patches tensor (49 taps
# x 2 channels) consumed by a POINTWISE dot in the kernel — a 7x7 conv over
# 2 channels is pathological for both XLA conv layouts and in-kernel
# lane-packing, but its im2col is one cheap shifted-copy fusion. Both
# branches then stream with the same lag structure: stage-1 pointwise
# (c1 from corr, f1 from patches), stage-2 3x3 (c2, f2), fusion conv over
# [c2 ; f2] at lag 2, with the raw 2-ch flow (the patch center taps)
# riding along as output channels 126:128 (update.py:85).
# ---------------------------------------------------------------------------


def _motion_kernel(corr_ref, pat_ref, flow_ref, wc1_ref, wf1_ref, b1_ref,
                   w2_ref, b2_ref, wf_ref, bf_ref, out_ref, scr_s1, scr_s2,
                   scr_fl, *, th: int, nb: int, width: int, cfused: int,
                   hh: int):
    i = pl.program_id(1)  # row step; program_id(0) is the batch sample
    dtype = corr_ref.dtype

    @pl.when(i == 0)
    def _init():
        for s in (scr_s1, scr_s2, scr_fl):
            _zeros(s)

    for s in (scr_s1, scr_s2):
        _shift(s, 2)
    _shift(scr_fl, 2)

    # Stage 1 (pointwise, rows [i*TH, (i+1)*TH)): c1 from the corr taps,
    # f1 from the TAP-MAJOR flow patches — per image row one
    # transposed-lhs dot contracts the 49-tap dim (the patches arrive as
    # (49, rows, W) so no channel-minor tensor ever exists; the XLA
    # patches op measured ~2.4 ms/iter of pathological-layout conv plus
    # a relayout copy). Both land in one [c1 | f1] buffer.
    acc_c = _dot(corr_ref[0], wc1_ref[...])
    n1 = wc1_ref.shape[-1]
    f1_rows = [jax.lax.dot_general(
        pat_ref[:, 0, r], wf1_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) for r in range(th)]
    acc_f = jnp.stack(f1_rows)
    bias1 = b1_ref[...].astype(jnp.float32)
    s1v = jnp.concatenate(
        [jax.nn.relu(acc_c + bias1[:, :n1]),
         jax.nn.relu(acc_f + bias1[:, n1:])], axis=-1).astype(dtype)

    @pl.when(i < nb)
    def _place():
        scr_s1[2:2 + th, 1:width + 1] = s1v
        scr_fl[2:2 + th] = flow_ref[0]

    @pl.when(i >= nb)
    def _flush():
        _zeros(scr_s1, slice(2, 2 + th))
        _zeros(scr_fl, slice(2, 2 + th))

    # Stage 2 (3x3, rows [i*TH-1, (i+1)*TH-1)): one block-diagonal conv
    # computes [c2 | f2]; out-of-range rows masked to zero (they stand in
    # for the fusion conv's padding; relu(bias) is not zero there).
    s2 = jax.nn.relu(_conv_rows(scr_s1, w2_ref, th, width)
                     + b2_ref[...].astype(jnp.float32)).astype(dtype)
    scr_s2[2:2 + th, 1:width + 1] = _row_mask(i, -1, th, hh, s2)

    # Fusion rows [i*TH-2, (i+1)*TH-2): the reference's fusion conv reads
    # [c2 ; f2] exactly in this channel order (update.py:85), so its
    # weight is used verbatim; the raw 2-ch flow rides along as output
    # channels 126:128.
    acc = _conv_rows(scr_s2, wf_ref, th, width)
    fused = jax.nn.relu(acc + bf_ref[...].astype(jnp.float32)).astype(dtype)
    out_ref[0, :, :, :cfused] = fused
    out_ref[0, :, :, cfused:] = scr_fl[0:th]


def flow_patches(flow_x, dtype):
    """(B, H, W) flow-x -> (49, B, H, W) tap-major 7x7 zero-padded
    patches, row dy*7 + dx.

    Taps OUTER-most from contiguous slices of the padded map: W stays
    the minor dim everywhere, so the build is one cheap stack fusion
    (``conv_general_dilated_patches`` lowers to a T(2,128)-layout conv —
    measured ~2.4 ms/iteration at Middlebury-F plus a relayout copy —
    and a channel-minor 49-wide tensor pads 128/49 in HBM)."""
    b, hh, ww = flow_x.shape
    fp = jnp.pad(flow_x.astype(dtype), ((0, 0), (3, 3), (3, 3)))
    return jnp.stack([fp[:, dy:dy + hh, dx:dx + ww]
                      for dy in range(7) for dx in range(7)], axis=0)


def _blockdiag3x3(wa, wb):
    """(3,3,Ka,Na), (3,3,Kb,Nb) -> (3,3,Ka+Kb,Na+Nb) block-diagonal."""
    ka, na = wa.shape[2:]
    kb, nb_ = wb.shape[2:]
    top = jnp.concatenate([wa, jnp.zeros((3, 3, ka, nb_), wa.dtype)], axis=3)
    bot = jnp.concatenate([jnp.zeros((3, 3, kb, na), wb.dtype), wb], axis=3)
    return jnp.concatenate([top, bot], axis=2)


def fused_motion_fwd_impl(p: dict, flow, corr):
    b, hh, width, ccorr = corr.shape
    dtype = corr.dtype
    th = pick_th(hh, width)
    nb = hh // th
    lag = 2
    grid = pl.cdiv(hh + lag, th)
    n1 = p["convc1"]["w"].shape[-1]
    # Stage-1 weights: convc1 (1x1) on the corr taps; convf1's x-channel
    # rows on the tap-major flow patches. The patches cover ONLY flow-x:
    # the model's flow y-component is identically zero (the epipolar
    # projection zeroes every y-delta, raft_stereo.py:120, and
    # warm-start inits come from prior disparity runs with equal
    # y-coords), so convf1's y-channel weights multiply zeros and are
    # dropped. Stage-2: block-diagonal (convc2, convf2); the raw 2-ch
    # flow rides along as output channels 126:128.
    wc1 = p["convc1"]["w"].reshape(p["convc1"]["w"].shape[2:]).astype(dtype)
    wf1 = p["convf1"]["w"][:, :, 0].reshape(-1, n1).astype(dtype)  # dy*7+dx
    b1 = jnp.concatenate([p["convc1"]["b"], p["convf1"]["b"]]).reshape(1, -1)
    w2 = _blockdiag3x3(p["convc2"]["w"], p["convf2"]["w"]).astype(dtype)
    b2 = jnp.concatenate([p["convc2"]["b"], p["convf2"]["b"]]).reshape(1, -1)
    wf = p["conv"]["w"].astype(dtype)  # verbatim: input order [c2 ; f2]
    bf = p["conv"]["b"].reshape(1, -1)
    cfused = wf.shape[-1]
    pat = flow_patches(flow[..., 0], dtype)  # (49, B, H, W)
    ns1 = 2 * n1

    def idx_in(bi, i):
        return (bi, jnp.minimum(i, nb - 1), 0, 0)

    kernel = functools.partial(_motion_kernel, th=th, nb=nb, width=width,
                               cfused=cfused, hh=hh)
    weights = (wc1, wf1, b1, w2, b2, wf, bf)

    def call(*arrs):
        return pl.pallas_call(
            kernel,
            grid=(arrs[0].shape[0], grid),
            in_specs=[pl.BlockSpec((1, th, width, ccorr), idx_in,
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((49, 1, th, width),
                                   lambda bi, i: (0, bi,
                                                  jnp.minimum(i, nb - 1), 0),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((1, th, width, flow.shape[-1]), idx_in,
                                   memory_space=pltpu.VMEM)] +
                     [pl.BlockSpec(w.shape,
                                   lambda bi, i, nd=w.ndim: (0,) * nd,
                                   memory_space=pltpu.VMEM)
                      for w in weights],
            out_specs=pl.BlockSpec((1, th, width, cfused + 2),
                                   lambda bi, i: (bi, i, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct(
                (arrs[0].shape[0], grid * th, width, cfused + 2), dtype),
            scratch_shapes=[
                pltpu.VMEM((th + 2, width + 2, ns1), dtype),
                pltpu.VMEM((th + 2, width + 2, ns1), dtype),
                pltpu.VMEM((th + 2, width, flow.shape[-1]), dtype)],
            compiler_params=compiler_params(
                vmem_limit_bytes=_VMEM_LIMIT),
            interpret=_interpret(),
        )(*arrs)

    # Same batch-axis partitioning rule as the GRU kernel (grid dim 0 is
    # the sample; the tap-major patches carry batch on axis 1).
    from raft_stereo_tpu.corr.pallas_reg import make_batch_partitioned
    args = [corr, pat, flow.astype(dtype), *weights]
    call_p = make_batch_partitioned(
        call, [0, 1, 0] + [None] * len(weights),
        [a.ndim for a in args], [0], [4])
    out = call_p(*args)
    return out[:, lag:lag + hh]


def motion_is_fusable(corr, any_batch: bool = False) -> bool:
    return (_dtype_ok(corr) and (any_batch or _batch_worthwhile(corr))
            and pick_th(corr.shape[1], corr.shape[2]) > 0 and corr.shape[1] >= 8)


@jax.custom_vjp
def fused_motion(p: dict, flow, corr):
    """BasicMotionEncoder with both branches streamed in Pallas; backward
    via the XLA oracle (``apply_motion_encoder``)."""
    return fused_motion_fwd_impl(p, flow, corr)


def _fused_motion_fwd(p, flow, corr):
    return fused_motion(p, flow, corr), (p, flow, corr)


def _fused_motion_bwd(res, g):
    p, flow, corr = res
    from raft_stereo_tpu.models.update import apply_motion_encoder
    out, vjp = jax.vjp(apply_motion_encoder, p, flow, corr)
    return vjp(g.astype(out.dtype))


fused_motion.defvjp(_fused_motion_fwd, _fused_motion_bwd)

"""The resident iteration (r19): corr lookup + motion encoder + finest
ConvGRU + FlowHead co-scheduled in ONE streaming Pallas kernel.

Why: after r6 the per-iteration update chain still round-trips HBM between
its streamed kernels every one of the 32 refinement iterations — the corr
taps (36 ch) and the motion features (128 ch) are written by one kernel
and re-read by the next at the full 1/4-res plane, and each kernel pays
its own pipeline ramp (the r5 profile attributes the remaining ~126 ms of
the frame to exactly these interstitials). This module extends the
``fused_gru1632`` co-scheduling pattern (ops/pallas_stream.py) to the
FINE scale, where the bytes live: one grid step gathers the correlation
taps for a row block straight from the packed pyramid containers, runs
the motion encoder's stages at their streaming lags, and advances the
gru08+FlowHead stream ONE ROW BLOCK behind, consuming the motion rows
from a VMEM window — the corr and motion tensors never touch HBM.

Bit-identity: every stage is the SAME arithmetic as the serial fused
composition it replaces — the standalone lookup's gather/lerp on the same
containers (corr/pallas_reg.py), ``_motion_kernel``'s two stages + fusion
conv, ``_gru_kernel``'s gate convs + head — at the same fp32 accumulation
and bf16 rounding points, so the resident advance is BITWISE equal to the
serial kernels (test-pinned in tests/test_fused_stream.py, the
fused_gru1632 precedent). ``RAFT_FUSE_ITER=0`` kills the path (breaker
rung ``fuse_iter``, serve/guard.py); it is inference-only by construction
(engaged in the ``compute_mask=False`` test-mode scan body — the serving
advance/segment programs and the test-mode forward).

Residency budget at Middlebury-F (th=8, 1/4-res 504x744, bf16): pyramid
block 18.3 MB/buffer -> ~36.6 MB double-buffered (9.2/18.3 under
RAFT_CORR_PACK8), motion + gru08 + head rings/windows ~25 MB,
czrq/h/up16/coords/patches blocks ~15 MB, weights ~4 MB => ~80 MB
against the 100 MB scoped cap (~62 with pack8). A VMEM overflow on an
untested geometry trips the ``fuse_iter`` rung and serving falls back to
the serial kernels — the r7 breaker contract.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_stereo_tpu.corr.pallas_reg import (
    corr_coords_operand, gather_level_taps, make_batch_partitioned)
from raft_stereo_tpu.ops.jax_compat import compiler_params
from raft_stereo_tpu.ops.pallas_stream import (
    _VMEM_LIMIT, _conv_rows, _dot, _dtype_ok, _interpret, _lane8_rows,
    _row_mask, _shift, _zeros, flow_patches, gru_weights)


def fuse_iter_on() -> bool:
    """``RAFT_FUSE_ITER`` kill switch (default ON). Read at trace time and
    registered in ENV_KNOBS so serving programs key on it."""
    return os.environ.get("RAFT_FUSE_ITER", "1").strip().lower() not in (
        "0", "false", "no", "off")


def lane_pack8_on() -> bool:
    """``RAFT_LANE_PACK8`` kill switch (default OFF). The resident stream
    never packs on its own — prepare_gru_context_any decides — but the
    switch is consulted here so a packed czrq arriving with the lane
    disarmed fails loudly instead of silently serving stale quantization."""
    return os.environ.get("RAFT_LANE_PACK8", "0").strip().lower() in (
        "1", "true", "yes", "on")


def resident_th(hh: int) -> int:
    """Row block of the resident stream (0 = unsupported). Fixed at 8:
    every /32-padded image's 1/4-res height divides it, and it bounds the
    VMEM window set (the budget table in the module docstring); larger
    blocks would double the pyramid block DMA buffer for marginal step
    amortization. Must stay > the head chain's 5-row lag (the nb+2-step
    grid covers the drain only for th >= 6)."""
    return 8 if hh % 8 == 0 and hh >= 8 else 0


def _corr_rows(corr_ops, coords_blk, vol_refs, th: int, width: int, dtype):
    """The standalone lookup's per-level gather, on a row block's pixels.

    coords_blk: (th*W, cw) fp32 (column 0 = position, packed8 scales
    behind); vol_refs: the kernel refs of ``corr_ops['kernel_ops']`` (or
    ``flat`` when nothing packs). Returns (th, W, nlev*(2r+1)) taps cast
    to the compute dtype — exactly the bytes the standalone kernel would
    have written to HBM (same gathers, same fp32 lerp, one cast)."""
    radius = corr_ops["radius"]
    widths = corr_ops["widths"]
    spec = corr_ops["spec"]
    k = 2 * radius + 1
    c = coords_blk[:, :1]
    pack8_views = {}
    taps = []
    for lvl, (op, mode, base) in enumerate(spec):
        cl = c * (1.0 / (1 << lvl))
        if mode == "packed8":
            if op not in pack8_views:  # bitcast the container view once
                pack8_views[op] = jax.lax.bitcast_convert_type(
                    vol_refs[op][0], jnp.int32)
            vol = pack8_views[op]
            scale = coords_blk[:, 1 + lvl:2 + lvl]
        else:
            vol = vol_refs[op][0]
            scale = None  # no scale columns exist on non-pack8 coords
        # gather_level_taps is THE dispatcher the standalone lookup
        # kernel runs — shared code, not a parallel copy, is what keeps
        # the resident-vs-serial bitwise pin structurally safe.
        taps.append(gather_level_taps(vol, cl, radius, widths[lvl], mode,
                                      base, scale))
    out = jnp.concatenate(taps, axis=-1).astype(dtype)
    return out.reshape(th, width, len(spec) * k)


def _resident_kernel(coords_ref, flow_ref, pat_ref, h_ref, czrq_ref,
                     *rest, nops: int, nx2: int, th: int, nb: int,
                     width: int, ch: int, hh: int, c1: int,
                     corr_static: dict, coffs, lane8: bool = False):
    """One grid step = corr+motion for row block ``i`` plus gru08+head for
    block ``i-1`` (the fused_gru1632 one-block-behind schedule)."""
    k = 0
    if lane8:
        czrq_scale_ref = rest[0]
        k = 1
    vol_refs = rest[k:k + nops]
    k += nops
    x2_refs = rest[k:k + nx2]
    k += nx2
    (wc1_ref, wf1_ref, b1_ref, w2_ref, b2_ref, wf_ref, bf_ref,
     whzr_ref, whq_ref, wx_ref, w1h_ref, b1h_ref, w2h_ref) = rest[k:k + 13]
    k += 13
    out_ref, dx_ref = rest[k:k + 2]
    k += 2
    (scr_s1, scr_s2, scr_fl, w_mot,
     scr_h, scr_rh, scr_z, scr_aqx, scr_x, scr_hn, scr_f1) = rest[k:]

    i = pl.program_id(1)  # row step; program_id(0) is the batch sample
    dtype = h_ref.dtype

    @pl.when(i == 0)
    def _init():
        for s in (scr_s1, scr_s2, scr_fl, w_mot, scr_h, scr_rh, scr_z,
                  scr_aqx, scr_x, scr_hn, scr_f1):
            _zeros(s)

    # ---- corr + motion stage 1 for block i (rows [i*TH, (i+1)*TH)):
    # the gather feeds convc1 directly from registers; the flow branch's
    # tap-major patches dot is _motion_kernel's verbatim. Shifts always
    # run (the stream structure); placement is gated like _place/_flush.
    for s in (scr_s1, scr_s2):
        _shift(s, 2)
    _shift(scr_fl, 2)

    @pl.when(i < nb)
    def _place_motion():
        corr = _corr_rows(corr_static, coords_ref[0], vol_refs, th, width,
                          dtype)
        acc_c = _dot(corr, wc1_ref[...])
        f1_rows = [jax.lax.dot_general(
            pat_ref[:, 0, r], wf1_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) for r in range(th)]
        acc_f = jnp.stack(f1_rows)
        bias1 = b1_ref[...].astype(jnp.float32)
        s1v = jnp.concatenate(
            [jax.nn.relu(acc_c + bias1[:, :c1]),
             jax.nn.relu(acc_f + bias1[:, c1:])], axis=-1).astype(dtype)
        scr_s1[2:2 + th, 1:width + 1] = s1v
        scr_fl[2:2 + th] = flow_ref[0]

    @pl.when(i >= nb)
    def _flush_motion():
        _zeros(scr_s1, slice(2, 2 + th))
        _zeros(scr_fl, slice(2, 2 + th))

    # Stage 2 rows [i*TH-1, ...): block-diagonal conv, out-of-range rows
    # masked (relu(bias) stands in for zero padding otherwise).
    s2 = jax.nn.relu(_conv_rows(scr_s1, w2_ref, th, width)
                     + b2_ref[...].astype(jnp.float32)).astype(dtype)
    scr_s2[2:2 + th, 1:width + 1] = _row_mask(i, -1, th, hh, s2)

    # Fusion rows [i*TH-2, ...): [fused 126 | raw 2-ch flow] appended to
    # the motion window (2*TH+2 rows) the gru08 stream consumes from.
    acc = _conv_rows(scr_s2, wf_ref, th, width)
    fused = jax.nn.relu(acc + bf_ref[...].astype(jnp.float32)).astype(dtype)
    mrows = jnp.concatenate([fused, scr_fl[0:th]], axis=-1)
    _shift(w_mot, th + 2)
    w_mot[th + 2:2 * th + 2] = mrows

    # ---- gru08 + FlowHead stream: block j = i-1, one block behind (its
    # x window rows [j*TH-2, (j+1)*TH) are all in w_mot by now). The body
    # is _gru_kernel's with the motion x part placed from the window
    # instead of an HBM operand.
    @pl.when(i >= 1)
    def _gru_phase():
        j = i - 1
        _shift(scr_h, 3)
        _shift(scr_x, 2)

        @pl.when(j < nb)
        def _place():
            scr_h[3:3 + th, 1:width + 1] = h_ref[0]
            # Motion rows [j*TH, (j+1)*TH): window offset 4 (the window
            # holds [(j)*TH-4, (j+2)*TH-2) after this step's append).
            scr_x[2:2 + th, 1:width + 1, 0:coffs[1]] = w_mot[4:4 + th]
            for p, c0, cend in zip(x2_refs, coffs[1:-1], coffs[2:]):
                scr_x[2:2 + th, 1:width + 1, c0:cend] = p[0]

        @pl.when(j >= nb)
        def _flush():
            _zeros(scr_h, slice(3, 3 + th))
            _zeros(scr_x, slice(2, 2 + th))

        acc_x = _conv_rows(scr_x, wx_ref, th, width)
        if lane8:
            acc_x = acc_x + _lane8_rows(czrq_ref, czrq_scale_ref, width)
        else:
            acc_x = acc_x + czrq_ref[0].astype(jnp.float32)
        acc_h = _conv_rows(scr_h[1:], whzr_ref, th, width)
        z_new = jax.nn.sigmoid(acc_h[..., :ch]
                               + acc_x[..., :ch]).astype(dtype)
        r_new = jax.nn.sigmoid(acc_h[..., ch:]
                               + acc_x[..., ch:2 * ch]).astype(dtype)
        rh_new = r_new * scr_h[2:2 + th, 1:width + 1]
        _shift(scr_rh, 3)
        scr_rh[3:3 + th, 1:width + 1] = rh_new
        _shift(scr_z, 2)
        scr_z[2:2 + th] = z_new
        _shift(scr_aqx, 2)
        scr_aqx[2:2 + th] = acc_x[..., 2 * ch:]
        acc_q = _conv_rows(scr_rh, whq_ref, th, width, None) \
            + scr_aqx[0:th]
        q = jnp.tanh(acc_q).astype(dtype)
        z = scr_z[0:th]
        h_new = (1 - z) * scr_h[0:th, 1:width + 1] + z * q
        out_ref[0] = h_new

        # FlowHead chained on h' (rows [j*TH-4, ...) and [j*TH-5, ...)).
        _shift(scr_hn, 2)
        scr_hn[2:2 + th, 1:width + 1] = _row_mask(j, -3, th, hh, h_new)
        f1 = jax.nn.relu(_conv_rows(scr_hn, w1h_ref, th, width)
                         + b1h_ref[...].astype(jnp.float32))
        _shift(scr_f1, 2)
        scr_f1[2:2 + th, 1:width + 1] = _row_mask(j, -4, th, hh,
                                                  f1.astype(dtype))
        dx = _conv_rows(scr_f1, w2h_ref, th, width)
        dx_ref[0] = dx[..., 0].astype(dx_ref.dtype)


def _resident_lane8_kernel(*refs, **kw):
    """Named alias of ``_resident_kernel`` with packed-czrq dequant
    engaged (jaxpr-greppable engagement proof — the check_engagement
    contract shared with ``_gru_lane8_kernel``)."""
    _resident_kernel(*refs, lane8=True, **kw)


def fused_iter_fwd_impl(p_enc: dict, p_gru: dict, head_p: dict,
                        corr_ops: dict, h, czrq, coords_x, flow, *x2_list):
    """Resident advance of the finest scale for ONE iteration.

    corr_ops: the :func:`~raft_stereo_tpu.corr.pallas_reg.
    build_corr_operands` struct (volume containers built once per frame,
    outside the scan). ``h``: gru08 hidden (B, H, W, ch); ``czrq``: the
    pre-folded context from ``prepare_gru_context``; ``coords_x``: fp32
    (B, H, W) matching x-coordinates; ``flow``: (B, H, W, 2) compute-dtype
    flow (the motion encoder's raw input); ``x2_list``: the gru08 x parts
    AFTER motion (the upsampled mid state, when n_gru_layers > 1).
    Returns ``(h', delta_x)`` with delta_x fp32 (B, H, W, 1) EXCLUDING
    ``conv2.b[0]`` — the fused_gru_head contract."""
    b, hh, width, ch = h.shape
    dtype = h.dtype
    th = resident_th(hh)
    nb = hh // th
    grid = nb + 2
    c1 = p_enc["convc1"]["w"].shape[-1]
    cfused = p_enc["conv"]["w"].shape[-1]
    cm = cfused + 2

    # Motion weights — _motion_kernel's exact packing (pallas_stream).
    from raft_stereo_tpu.ops.pallas_stream import _blockdiag3x3
    wc1 = p_enc["convc1"]["w"].reshape(
        p_enc["convc1"]["w"].shape[2:]).astype(dtype)
    wf1 = p_enc["convf1"]["w"][:, :, 0].reshape(-1, c1).astype(dtype)
    b1 = jnp.concatenate([p_enc["convc1"]["b"],
                          p_enc["convf1"]["b"]]).reshape(1, -1)
    w2 = _blockdiag3x3(p_enc["convc2"]["w"],
                       p_enc["convf2"]["w"]).astype(dtype)
    b2 = jnp.concatenate([p_enc["convc2"]["b"],
                          p_enc["convf2"]["b"]]).reshape(1, -1)
    wf = p_enc["conv"]["w"].astype(dtype)
    bf = p_enc["conv"]["b"].reshape(1, -1)
    pat = flow_patches(flow[..., 0], dtype)  # (49, B, H, W)

    # gru08 + head weights — fused_conv_gru_fwd_impl's exact packing.
    whzr, whq, wx_full = (w.astype(dtype) for w in gru_weights(p_gru, ch))
    w1h = head_p["conv1"]["w"].astype(dtype)
    b1h = head_p["conv1"]["b"].reshape(1, -1)
    w2h = head_p["conv2"]["w"][..., :1].astype(dtype)

    coffs = [0, cm]
    for p in x2_list:
        coffs.append(coffs[-1] + p.shape[-1])
    cx = coffs[-1]

    # czrq is the bf16 rows or an (container, scale) pair under
    # RAFT_LANE_PACK8 (prepare_gru_context_any) — the resident stream
    # dequantizes in-register like the serial gru kernels.
    lane8 = isinstance(czrq, tuple)
    if lane8 and not lane_pack8_on():
        raise RuntimeError(
            "fused_iter_fwd_impl: packed czrq container with "
            "RAFT_LANE_PACK8 disarmed — the kill switch must stay armed "
            "for the lifetime of a packed state (flip it only between "
            "prepare calls)")
    if lane8:
        czrq, czrq_s = czrq
        czrq_s = czrq_s.reshape(b, 1).astype(jnp.float32)
    wq = czrq.shape[2]

    # czrq rows must cover gru blocks j in [0, nb] (prepare_gru_context's
    # lag-5 pad gives exactly (nb+1)*TH rows for TH > 5). Exact for the
    # container too: pad rows are zero bytes on the symmetric grid.
    need = (nb + 1) * th
    if czrq.shape[1] < need:
        czrq = jnp.pad(czrq, ((0, 0), (0, need - czrq.shape[1]),
                              (0, 0), (0, 0)))

    coords_aug = corr_coords_operand(corr_ops, coords_x)  # (B, N, cw)
    cw = coords_aug.shape[-1]
    vol_ops = corr_ops["kernel_ops"] or corr_ops["flat"]
    nops = len(vol_ops)
    pxb = th * width  # pixels per row block

    def blk(bi, i):
        return (bi, jnp.minimum(i, nb - 1), 0)

    def blk4(bi, i):
        return (bi, jnp.minimum(i, nb - 1), 0, 0)

    def jblk4(bi, i):
        return (bi, jnp.clip(i - 1, 0, nb - 1), 0, 0)

    in_specs = [
        pl.BlockSpec((1, pxb, cw), blk, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, th, width, 2), blk4, memory_space=pltpu.VMEM),
        pl.BlockSpec((49, 1, th, width),
                     lambda bi, i: (0, bi, jnp.minimum(i, nb - 1), 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, th, width, ch), jblk4, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, th, wq if lane8 else width, 3 * ch),
                     lambda bi, i: (bi, jnp.clip(i - 1, 0, nb), 0, 0),
                     memory_space=pltpu.VMEM),
    ] + ([pl.BlockSpec((1, 1), lambda bi, i: (bi, 0),
                       memory_space=pltpu.VMEM)] if lane8 else []) \
      + [pl.BlockSpec((1, pxb, v.shape[-1]), blk, memory_space=pltpu.VMEM)
         for v in vol_ops] \
      + [pl.BlockSpec((1, th, width, p.shape[-1]), jblk4,
                      memory_space=pltpu.VMEM) for p in x2_list] \
      + [pl.BlockSpec(w.shape, lambda bi, i, nd=w.ndim: (0,) * nd,
                      memory_space=pltpu.VMEM)
         for w in (wc1, wf1, b1, w2, b2, wf, bf, whzr, whq, wx_full,
                   w1h, b1h, w2h)]
    out_specs = (
        pl.BlockSpec((1, th, width, ch),
                     lambda bi, i: (bi, jnp.where(i == 0, nb + 1, i - 1),
                                    0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, th, width),
                     lambda bi, i: (bi, jnp.where(i == 0, nb + 1, i - 1),
                                    0),
                     memory_space=pltpu.VMEM))
    out_shape = (
        jax.ShapeDtypeStruct((b, (nb + 2) * th, width, ch), dtype),
        jax.ShapeDtypeStruct((b, (nb + 2) * th, width), jnp.float32))
    scratch = [
        pltpu.VMEM((th + 2, width + 2, 2 * c1), dtype),   # motion s1
        pltpu.VMEM((th + 2, width + 2, 2 * c1), dtype),   # motion s2
        pltpu.VMEM((th + 2, width, 2), dtype),            # raw flow ring
        pltpu.VMEM((2 * th + 2, width, cm), dtype),       # motion window
        pltpu.VMEM((th + 3, width + 2, ch), dtype),       # gru h window
        pltpu.VMEM((th + 3, width + 2, ch), dtype),       # gru r*h
        pltpu.VMEM((th + 2, width, ch), dtype),           # gru z ring
        pltpu.VMEM((th + 2, width, ch), jnp.float32),     # gru aq_x
        pltpu.VMEM((th + 2, width + 2, cx), dtype),       # gru x parts
        pltpu.VMEM((th + 2, width + 2, ch), dtype),       # h' window
        pltpu.VMEM((th + 2, width + 2, w1h.shape[-1]), dtype)]  # head f1

    corr_static = {"radius": corr_ops["radius"],
                   "widths": tuple(corr_ops["widths"]),
                   "spec": tuple(corr_ops["spec"])}
    kernel = functools.partial(
        _resident_lane8_kernel if lane8 else _resident_kernel,
        nops=nops, nx2=len(x2_list), th=th, nb=nb,
        width=width, ch=ch, hh=hh, c1=c1,
        corr_static=corr_static, coffs=tuple(coffs))
    inputs = [coords_aug, flow.astype(dtype), pat, h, czrq] \
        + ([czrq_s] if lane8 else []) \
        + [*vol_ops, *x2_list, wc1, wf1, b1, w2, b2, wf, bf, whzr, whq,
           wx_full, w1h, b1h, w2h]

    def call(*arrs):
        return pl.pallas_call(
            kernel,
            grid=(arrs[3].shape[0], grid),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=tuple(
                jax.ShapeDtypeStruct((arrs[3].shape[0],) + o.shape[1:],
                                     o.dtype) for o in out_shape),
            scratch_shapes=scratch,
            compiler_params=compiler_params(vmem_limit_bytes=_VMEM_LIMIT),
            interpret=_interpret(),
        )(*arrs)

    # Batch rides the outer grid dim; the tap-major patches carry batch
    # on axis 1 (the fused_motion partitioning rule).
    axes_in = [0, 0, 1, 0, 0] + ([0] if lane8 else []) + [0] * nops \
        + [0] * len(x2_list) + [None] * 13
    call_p = make_batch_partitioned(
        call, axes_in, [a.ndim for a in inputs], [0, 0],
        [o.ndim for o in out_shape])
    h_out, dx_out = call_p(*inputs)
    return h_out[:, 3:3 + hh], dx_out[:, 5:5 + hh][..., None]


def iter_is_fusable(h, corr_ops, *x2_list, any_batch: bool = False) -> bool:
    """Resident-iteration engagement: the kill switch, the gru08 stream's
    own fusability (dtype, row block, batch policy — the r19 crossover),
    and a reg_tpu operand struct for the in-kernel gather."""
    from raft_stereo_tpu.ops.pallas_stream import gru_is_fusable
    if not fuse_iter_on() or corr_ops is None:
        return False
    return (gru_is_fusable(h, *x2_list, any_batch=any_batch)
            and resident_th(h.shape[1]) > 0
            and _dtype_ok(h))

"""Bilinear sampling with grid_sample semantics (align_corners=True, zero pad).

The reference samples the correlation volume through
``bilinear_sampler`` (``core/utils/utils.py:59-73``), a pixel-coordinate wrapper
over ``F.grid_sample(align_corners=True)`` that asserts the problem is 1D
(H == 1). Out-of-range taps contribute zero (grid_sample ``padding_mode='zeros'``):
a sample at x gets ``(1-frac)*v[floor(x)] + frac*v[floor(x)+1]`` with each tap
zeroed when its index falls outside ``[0, W-1]``.

Because every lookup in this problem is along a single row (epipolar line),
both samplers here are 1D gather-lerps — no 2D grid_sample is ever needed
(the reference's ``alt`` path calls 2D grid_sample with integer y, which
reduces to the same row gather; ``core/corr.py:82``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _taps(x: jax.Array, width: int):
    """Common tap/weight computation for zero-padded linear interpolation."""
    x0 = jnp.floor(x)
    frac = x - x0
    i0 = x0.astype(jnp.int32)
    i1 = i0 + 1
    in0 = (i0 >= 0) & (i0 <= width - 1)
    in1 = (i1 >= 0) & (i1 <= width - 1)
    i0c = jnp.clip(i0, 0, width - 1)
    i1c = jnp.clip(i1, 0, width - 1)
    w0 = jnp.where(in0, 1.0 - frac, 0.0)
    w1 = jnp.where(in1, frac, 0.0)
    return i0c, i1c, w0, w1


def sample_1d_zeros(values: jax.Array, x: jax.Array) -> jax.Array:
    """Sample rows of scalars at fractional positions.

    values: (..., W) — per-row 1D signals (e.g. a correlation-volume row).
    x:      (..., K) — fractional sample positions, batch dims matching values.
    Returns (..., K).
    """
    width = values.shape[-1]
    i0, i1, w0, w1 = _taps(x, width)
    v0 = jnp.take_along_axis(values, i0, axis=-1)
    v1 = jnp.take_along_axis(values, i1, axis=-1)
    return v0 * w0 + v1 * w1


def sample_rows_zeros(fmap: jax.Array, x: jax.Array) -> jax.Array:
    """Sample feature rows at fractional x positions (vector-valued signal).

    fmap: (..., W, D) — per-row features (e.g. fmap2 rows).
    x:    (..., K)    — fractional sample positions.
    Returns (..., K, D).
    """
    width = fmap.shape[-2]
    i0, i1, w0, w1 = _taps(x, width)
    v0 = jnp.take_along_axis(fmap, i0[..., None], axis=-2)
    v1 = jnp.take_along_axis(fmap, i1[..., None], axis=-2)
    return v0 * w0[..., None] + v1 * w1[..., None]

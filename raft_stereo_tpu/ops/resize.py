"""Aligned-corners bilinear resize.

The reference resizes GRU hidden states across pyramid scales with
``F.interpolate(mode='bilinear', align_corners=True)`` (``core/update.py:93-95``)
and upsamples fallback flow the same way (``core/utils/utils.py:82-84``).
``jax.image.resize`` uses half-pixel-center semantics, which differ, so the
aligned-corners variant is built here as two banded-matrix MXU contractions:
each output row/col is a 2-tap lerp, i.e. a (out, in) matrix with two
nonzeros per row. The earlier gather-lerp form (jnp.take per axis) made XLA
materialize transposed intermediates for the W-axis gather — ~1.1 ms per
GRU iteration at Middlebury-F, ~36 ms/frame; the dense dot wastes MXU FLOPs
on zeros but runs in their shadow, accumulates fp32, and needs no relayout.
The matrices derive from iota, so under a scan they are loop-invariant
constants.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def _lerp_indices(in_size: int, out_size: int, dtype) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Source taps (lo, hi) and fractional weight for aligned-corners sampling."""
    if out_size == 1:
        src = jnp.zeros((1,), dtype)
    else:
        scale = (in_size - 1) / (out_size - 1)
        src = jnp.arange(out_size, dtype=dtype) * scale
    lo = jnp.clip(jnp.floor(src), 0, in_size - 1).astype(jnp.int32)
    hi = jnp.clip(lo + 1, 0, in_size - 1)
    w = (src - lo.astype(src.dtype))
    return lo, hi, w


def _lerp_matrix(in_size: int, out_size: int, dtype) -> jax.Array:
    """(out, in) aligned-corners lerp matrix: two nonzeros per row."""
    lo, hi, wt = _lerp_indices(in_size, out_size, jnp.float32)
    m = (jax.nn.one_hot(lo, in_size, dtype=jnp.float32) * (1 - wt)[:, None]
         + jax.nn.one_hot(hi, in_size, dtype=jnp.float32) * wt[:, None])
    return m.astype(dtype)


def interp_align_corners(x: jax.Array, size: Tuple[int, int]) -> jax.Array:
    """Bilinear resize of (B, H, W, C) to (B, size[0], size[1], C), align_corners=True."""
    b, h, w, c = x.shape
    oh, ow = size
    if (oh, ow) == (h, w):
        return x
    # Contractions run in the input dtype (bf16 under mixed precision —
    # the reference's F.interpolate runs inside autocast too) with fp32
    # accumulation. Precision.HIGHEST keeps fp32 inputs EXACT (the TPU
    # default would demote fp32 operands to bf16 MXU multiplies — a
    # silent regression vs the elementwise lerp this replaced) and is
    # free for bf16 inputs. bf16 nuance: (1-wt) and wt round
    # independently here, so a row may sum to 1 +/- 1 ulp and constant
    # regions can drift ~1 bf16 ulp where the old a+(b-a)*wt form
    # preserved them bit-exactly — same order as that form's own
    # rounding, covered by the parity batteries.
    out = x
    hp = jax.lax.Precision.HIGHEST
    if oh != h:
        out = jnp.einsum("Oh,bhwc->bOwc", _lerp_matrix(h, oh, x.dtype), out,
                         precision=hp)
    if ow != w:
        out = jnp.einsum("Pw,bOwc->bOPc", _lerp_matrix(w, ow, x.dtype), out,
                         precision=hp)
    return out.astype(x.dtype)

"""Aligned-corners bilinear resize.

The reference resizes GRU hidden states across pyramid scales with
``F.interpolate(mode='bilinear', align_corners=True)`` (``core/update.py:93-95``)
and upsamples fallback flow the same way (``core/utils/utils.py:82-84``).
``jax.image.resize`` uses half-pixel-center semantics, which differ, so the
aligned-corners variant is built here from two 1D gather-lerps (each lowers to
a pair of gathers + fused FMA — cheap on TPU, no conv needed).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def _lerp_indices(in_size: int, out_size: int, dtype) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Source taps (lo, hi) and fractional weight for aligned-corners sampling."""
    if out_size == 1:
        src = jnp.zeros((1,), dtype)
    else:
        scale = (in_size - 1) / (out_size - 1)
        src = jnp.arange(out_size, dtype=dtype) * scale
    lo = jnp.clip(jnp.floor(src), 0, in_size - 1).astype(jnp.int32)
    hi = jnp.clip(lo + 1, 0, in_size - 1)
    w = (src - lo.astype(src.dtype))
    return lo, hi, w


def interp_align_corners(x: jax.Array, size: Tuple[int, int]) -> jax.Array:
    """Bilinear resize of (B, H, W, C) to (B, size[0], size[1], C), align_corners=True."""
    b, h, w, c = x.shape
    oh, ow = size
    if (oh, ow) == (h, w):
        return x
    # Lerp in the input dtype: under mixed precision the reference's
    # F.interpolate runs inside autocast (fp16) too, and the fp32
    # round-trip doubled this op's HBM traffic (~0.7 ms/GRU-iteration at
    # Middlebury-F). The fractional weights stay fp32 until the multiply.
    compute = x
    if oh != h:
        lo, hi, wt = _lerp_indices(h, oh, jnp.float32)
        a = jnp.take(compute, lo, axis=1)
        bb = jnp.take(compute, hi, axis=1)
        compute = a + (bb - a) * wt[None, :, None, None].astype(x.dtype)
    if ow != w:
        lo, hi, wt = _lerp_indices(w, ow, jnp.float32)
        a = jnp.take(compute, lo, axis=2)
        bb = jnp.take(compute, hi, axis=2)
        compute = a + (bb - a) * wt[None, None, :, None].astype(x.dtype)
    return compute.astype(x.dtype)

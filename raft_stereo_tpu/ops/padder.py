"""Input padding to compilation-friendly sizes.

Reference ``core/utils/utils.py:7-26``: pad H, W up to a multiple of
``divis_by`` with replicate padding ('sintel' mode centers, default mode pads
bottom/right-of-center on W only). The reference re-pads every image to its own
size; on TPU every distinct padded shape is a fresh XLA compilation, so this
padder adds an optional *bucketing* mode: round H, W up to the next multiple of
``bucket`` (>= divis_by), collapsing the eval sets onto a handful of compiled
shapes. ``unpad`` restores the original extent either way, so metrics are
computed only over real pixels.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class InputPadder:
    """Pads (B, H, W, C) arrays so H, W are divisible by ``divis_by``."""

    def __init__(self, dims: Sequence[int], mode: str = "sintel",
                 divis_by: int = 8, bucket: int | None = None):
        # dims: an NHWC shape, an (H, W, C) shape, or a bare (H, W) pair.
        if len(dims) >= 3:
            self.ht, self.wd = int(dims[-3]), int(dims[-2])
        else:
            self.ht, self.wd = int(dims[0]), int(dims[1])
        if bucket is not None:
            if bucket % divis_by:
                raise ValueError("bucket size must be a multiple of divis_by")
            pad_ht = (-self.ht) % bucket
            pad_wd = (-self.wd) % bucket
        else:
            # Reference formula (utils.py:11-12): pads to the *next* multiple,
            # the trailing % keeps already-divisible sizes unpadded.
            pad_ht = (((self.ht // divis_by) + 1) * divis_by - self.ht) % divis_by
            pad_wd = (((self.wd // divis_by) + 1) * divis_by - self.wd) % divis_by
        if mode == "sintel":
            self._pad = (pad_wd // 2, pad_wd - pad_wd // 2,
                         pad_ht // 2, pad_ht - pad_ht // 2)
        else:
            self._pad = (pad_wd // 2, pad_wd - pad_wd // 2, 0, pad_ht)

    @property
    def padded_shape(self) -> Tuple[int, int]:
        l, r, t, b = self._pad
        return self.ht + t + b, self.wd + l + r

    def pad(self, *inputs: jax.Array) -> list:
        l, r, t, b = self._pad
        return [jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)), mode="edge")
                for x in inputs]

    def pad_np(self, *inputs: np.ndarray) -> list:
        l, r, t, b = self._pad
        return [np.pad(x, ((0, 0), (t, b), (l, r), (0, 0)), mode="edge")
                for x in inputs]

    def unpad(self, x: jax.Array) -> jax.Array:
        l, r, t, b = self._pad
        ht, wd = x.shape[1], x.shape[2]
        return x[:, t:ht - b, l:wd - r, :]

    def unpad_np(self, x: np.ndarray) -> np.ndarray:
        """``unpad`` for host arrays — basic slicing works identically on
        numpy, returning a view, so callers that already fetched the
        result don't round-trip it through a device array."""
        return self.unpad(x)


def bucket_shape(dims: Sequence[int], bucket: int,
                 divis_by: int = 8) -> Tuple[int, int]:
    """Padded (H, W) that ``InputPadder(dims, bucket=bucket)`` would produce
    — the serving layer's way to enumerate its compiled-shape buckets
    without building padders."""
    return InputPadder(dims, divis_by=divis_by, bucket=bucket).padded_shape

"""``python -m raft_stereo_tpu.analysis`` — the graftlint CLI.

Exit codes: 0 clean (suppressed findings allowed), 1 unsuppressed
findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from raft_stereo_tpu.analysis.core import git_changed_files, run_analysis

_REPO_MARKERS = ("pyproject.toml", ".git")


def _repo_root(start: str) -> str:
    cur = os.path.abspath(start)
    while True:
        if any(os.path.exists(os.path.join(cur, m)) for m in _REPO_MARKERS):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return os.path.abspath(start)
        cur = nxt


def _default_roots() -> List[str]:
    """The package directory itself — works from any CWD."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m raft_stereo_tpu.analysis",
        description="graftlint: static analysis for this repo's recurring "
                    "bug classes (GL001-GL006). Zero unsuppressed findings "
                    "is a tier-1/release-gate invariant.")
    p.add_argument("paths", nargs="*",
                   help="files/directories to analyze (default: the "
                        "raft_stereo_tpu package)")
    p.add_argument("--changed-only", action="store_true",
                   help="report findings only for git-changed files (the "
                        "full tree is still analyzed for cross-file "
                        "context)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable JSON report on stdout")
    p.add_argument("--select", metavar="CODES",
                   help="comma-separated finding codes to report "
                        "(e.g. GL001,GL004); GL000 always reports")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed findings (with reasons)")
    p.add_argument("--list-checkers", action="store_true",
                   help="print the checker table and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_checkers:
        from raft_stereo_tpu.analysis.checkers import ALL_CHECKERS
        for cls in ALL_CHECKERS:
            print(f"{cls.code}  {cls.name:<24} {cls.description}")
        return 0
    roots = args.paths or _default_roots()
    for r in roots:
        if not os.path.exists(r):
            print(f"graftlint: no such path: {r}", file=sys.stderr)
            return 2
    base = _repo_root(roots[0])
    only_paths = None
    if args.changed_only:
        try:
            only_paths = git_changed_files(base)
        except Exception as e:
            print(f"graftlint: --changed-only needs a git checkout: {e}",
                  file=sys.stderr)
            return 2
    select = None
    if args.select:
        select = tuple(c.strip() for c in args.select.split(",") if c.strip())
    try:
        report = run_analysis(roots, base=base, select=select,
                              only_paths=only_paths)
    except Exception as e:  # an internal error must not read as "clean"
        print(f"graftlint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    print(report.render_json() if args.as_json
          else report.render_text(show_suppressed=args.show_suppressed))
    return 0 if report.ok else 1

"""``python -m raft_stereo_tpu.analysis`` — the graftlint/graftverify/
graftlock CLI.

Default: the AST suite (GL001-GL006, milliseconds, no jax). With
``--trace``, ALSO runs graftverify (GV101-GV105): traces the repo's real
entry points on CPU via jax.eval_shape/make_jaxpr/.lower() — no TPU, no
execution — and walks the jaxprs/StableHLO. With ``--concurrency``,
ALSO runs graftlock (GC201-GC206, stdlib-only like the AST stage): the
whole-repo lock model, the ``LOCK_ORDER.md`` manifest ceremony
(``--write-manifest`` regenerates it), Future-lifecycle and
sink/blocking-under-lock contracts.  All requested stages merge into
one verdict/JSON artifact.

Exit codes: 0 clean (suppressed findings allowed), 1 unsuppressed
findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from raft_stereo_tpu.analysis.core import git_changed_files, run_analysis

_REPO_MARKERS = ("pyproject.toml", ".git")


def _repo_root(start: str) -> str:
    cur = os.path.abspath(start)
    while True:
        if any(os.path.exists(os.path.join(cur, m)) for m in _REPO_MARKERS):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return os.path.abspath(start)
        cur = nxt


def _default_roots() -> List[str]:
    """The package directory itself — works from any CWD."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m raft_stereo_tpu.analysis",
        description="graftlint: static analysis for this repo's recurring "
                    "bug classes (GL001-GL006). Zero unsuppressed findings "
                    "is a tier-1/release-gate invariant.")
    p.add_argument("paths", nargs="*",
                   help="files/directories to analyze (default: the "
                        "raft_stereo_tpu package)")
    p.add_argument("--changed-only", action="store_true",
                   help="report findings only for git-changed files (the "
                        "full tree is still analyzed for cross-file "
                        "context)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable JSON report on stdout")
    p.add_argument("--select", metavar="CODES",
                   help="comma-separated finding codes to report "
                        "(e.g. GL001,GL004); GL000 always reports")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed findings (with reasons)")
    p.add_argument("--list-checkers", action="store_true",
                   help="print the checker table and exit")
    p.add_argument("--trace", action="store_true",
                   help="also run graftverify (GV101-GV105): trace the "
                        "real entry points at pinned shapes on CPU and "
                        "verify jaxpr/HLO-level invariants (needs jax; "
                        "~1 min at headline geometry)")
    p.add_argument("--trace-geometry", choices=("headline", "small"),
                   default=None,
                   help="trace shapes: 'headline' (bench north-star, "
                        "ladder+knob proofs included) or 'small' (fast "
                        "dev loop; ladder/knob probes are headline-only "
                        "because kernel heuristics don't engage at small "
                        "shapes)")
    p.add_argument("--trace-registry", metavar="FILE",
                   help="load the trace registry from a python file "
                        "defining build_registry() instead of the "
                        "default — tests point this at poisoned fixture "
                        "registries to prove each GV checker fires")
    p.add_argument("--concurrency", action="store_true",
                   help="also run graftlock (GC201-GC206): lock-order "
                        "graph vs the committed LOCK_ORDER.md, Future "
                        "lifecycle, blocking/sink-under-lock, _*_locked "
                        "and Thread lifecycle contracts (stdlib-only, "
                        "fast)")
    p.add_argument("--write-manifest", action="store_true",
                   help="with --concurrency: regenerate LOCK_ORDER.md "
                        "from the tree before checking (the reviewed-"
                        "diff ceremony — commit the result)")
    return p


def _load_registry_file(path: str):
    import importlib.util
    spec = importlib.util.spec_from_file_location("_graftverify_fixture",
                                                  path)
    if spec is None or spec.loader is None:
        raise ValueError(f"cannot load trace registry from {path!r}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.build_registry()


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if not args.trace and (args.trace_registry or args.trace_geometry):
        # A trace option without --trace would silently skip the trace
        # stage — the analyzer quietly not running must never read as
        # "clean" (the GV000 principle, applied to the CLI itself).
        print("graftlint: --trace-registry/--trace-geometry require "
              "--trace", file=sys.stderr)
        return 2
    if args.write_manifest and not args.concurrency:
        # Same principle: a manifest silently not regenerated must never
        # read as "regenerated".
        print("graftlint: --write-manifest requires --concurrency",
              file=sys.stderr)
        return 2
    if args.list_checkers:
        from raft_stereo_tpu.analysis.checkers import ALL_CHECKERS
        for cls in ALL_CHECKERS:
            print(f"{cls.code}  {cls.name:<24} {cls.description}")
        # The GV table imports without jax (checker modules defer their
        # jax-touching work to check()), so always list it too.
        from raft_stereo_tpu.analysis.trace.checkers import \
            ALL_TRACE_CHECKERS
        for cls in ALL_TRACE_CHECKERS:
            print(f"{cls.code}  {cls.name:<24} {cls.description}")
        from raft_stereo_tpu.analysis.concurrency.checkers import \
            ALL_CONCURRENCY_CHECKERS
        for cls in ALL_CONCURRENCY_CHECKERS:
            print(f"{cls.code}  {cls.name:<24} {cls.description}")
        return 0
    roots = args.paths or _default_roots()
    for r in roots:
        if not os.path.exists(r):
            print(f"graftlint: no such path: {r}", file=sys.stderr)
            return 2
    base = _repo_root(roots[0])
    only_paths = None
    if args.changed_only:
        try:
            only_paths = git_changed_files(base)
        except Exception as e:
            print(f"graftlint: --changed-only needs a git checkout: {e}",
                  file=sys.stderr)
            return 2
    select = None
    if args.select:
        select = tuple(c.strip() for c in args.select.split(",") if c.strip())
    try:
        report = run_analysis(roots, base=base, select=select,
                              only_paths=only_paths)
    except Exception as e:  # an internal error must not read as "clean"
        print(f"graftlint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if args.trace:
        # The trace stage analyzes whole programs, not files —
        # --changed-only's path filter applies to the AST report only.
        try:
            if args.trace_registry:
                registry = _load_registry_file(args.trace_registry)
            else:
                from raft_stereo_tpu.analysis.trace import default_registry
                registry = default_registry(args.trace_geometry
                                            or "headline")
            from raft_stereo_tpu.analysis.trace import run_trace_analysis
            report = report.merged(
                run_trace_analysis(registry, select=select))
        except Exception as e:
            print(f"graftverify: internal error: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
    if args.concurrency:
        try:
            from raft_stereo_tpu.analysis.concurrency import (
                run_concurrency_analysis, write_lock_order_manifest)
            if args.write_manifest:
                path = write_lock_order_manifest(roots, base=base)
                print(f"graftlock: wrote {path}", file=sys.stderr)
            gc_report = run_concurrency_analysis(
                roots, base=base, select=select, only_paths=only_paths,
                # The AST stage above already reported parse errors and
                # reasonless suppressions for this same file set.
                emit_file_meta=False)
            gc_report.files_analyzed = 0
            report = report.merged(gc_report)
        except Exception as e:
            print(f"graftlock: internal error: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
    print(report.render_json() if args.as_json
          else report.render_text(show_suppressed=args.show_suppressed))
    return 0 if report.ok else 1

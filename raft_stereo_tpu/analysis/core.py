"""graftlint framework: source model, suppressions, runner, report.

Checkers (``analysis/checkers/``) operate on a :class:`Project` — every
analyzed file pre-parsed to an AST with parent pointers, import-alias
maps and a per-line suppression table.  The project is always built from
the FULL file set so cross-file checkers (GL002/GL003/GL006 read the knob
registry, the config dataclass and the guard ladder) see their context
even when only a subset of findings is reported (``--changed-only``).

Stdlib only — the linter runs in any environment, without jax.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import subprocess
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: Meta-code: suppression syntax errors and unparsable files.  GL000
#: findings are never themselves suppressible (a broken suppression must
#: not be able to hide itself).
META_CODE = "GL000"

#: Meta-code of the graftlock (concurrency) stage — same non-suppressible,
#: non-filterable contract as GL000, emitted by the GC stage for stale
#: GC-code suppressions and (when the stage runs standalone) parse errors.
CONCURRENCY_META_CODE = "GC200"

#: Codes that are never suppressible and always pass ``--select``.
META_CODES = (META_CODE, CONCURRENCY_META_CODE)

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)"
    r"\s*(\([^)]*\))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding; ``path`` is relative to the analysis root."""

    code: str
    message: str
    path: str
    line: int
    col: int = 0
    suppressed: bool = False
    suppress_reason: str = ""

    def render(self) -> str:
        tag = " (suppressed: %s)" % self.suppress_reason if self.suppressed \
            else ""
        return f"{self.path}:{self.line}:{self.col}: {self.code} " \
               f"{self.message}{tag}"


@dataclasses.dataclass(frozen=True)
class _Suppression:
    codes: Tuple[str, ...]
    reason: str  # empty string == malformed (missing reason)


class SourceFile:
    """One parsed source file plus the lookup tables checkers need."""

    def __init__(self, abspath: str, relpath: str, text: str):
        self.abspath = abspath
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        #: line number -> suppression found on that line
        self.suppressions: Dict[int, _Suppression] = {}
        #: local alias -> canonical dotted module ("_os" -> "os")
        self.import_aliases: Dict[str, str] = {}
        #: local name -> canonical dotted origin ("environ" -> "os.environ")
        self.from_imports: Dict[str, str] = {}
        self.module_names: Set[str] = set()  # names bound at module scope
        try:
            self.tree = ast.parse(text, filename=relpath)
        except SyntaxError as e:  # reported as a GL000 finding by the runner
            self.parse_error = e
            return
        _attach_parents(self.tree)
        self._scan_suppressions()
        self._scan_imports()
        self._scan_module_names()

    # -- construction helpers ---------------------------------------------

    def _scan_suppressions(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                codes = tuple(c.strip() for c in m.group(1).split(","))
                reason = (m.group(2) or "").strip("() \t")
                self.suppressions[i] = _Suppression(codes, reason)

    def _scan_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.import_aliases[a.asname] = a.name
                    else:
                        # `import os.path` binds the ROOT name `os` — the
                        # alias must map os -> os, not os -> os.path
                        # (which would hide every os.environ read).
                        root = a.name.split(".")[0]
                        self.import_aliases[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def _scan_module_names(self) -> None:
        for node in self.tree.body:
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        self.module_names.add(n.id)

    # -- queries -----------------------------------------------------------

    def canonical(self, node: ast.expr) -> str:
        """Dotted name of an expression with import aliases resolved:
        ``_os.environ.get`` -> ``os.environ.get``; a bare ``environ``
        imported via ``from os import environ`` -> ``os.environ``."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            head = self.from_imports.get(
                cur.id, self.import_aliases.get(cur.id, cur.id))
            parts.append(head)
        elif isinstance(cur, ast.Call):
            # e.g. ``importlib.import_module("os").environ`` — give up on
            # the head but keep the attribute tail for suffix matches.
            parts.append("()")
        else:
            return ""
        return ".".join(reversed(parts))

    def suppression_for(self, line: int) -> Optional[_Suppression]:
        """The suppression governing ``line``: a trailing comment on the
        line itself, or a comment-only line directly above it."""
        sup = self.suppressions.get(line)
        if sup is not None:
            return sup
        prev = self.suppressions.get(line - 1)
        if prev is not None and 1 <= line - 1 <= len(self.lines) and \
                self.lines[line - 2].lstrip().startswith("#"):
            return prev
        return None


def _attach_parents(tree: ast.AST) -> None:
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            child._gl_parent = parent  # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_gl_parent", None)


def ancestors(node: ast.AST) -> Iterable[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda, or None when
    the node executes at import time (module or class scope)."""
    for a in ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return a
    return None


@dataclasses.dataclass(frozen=True)
class EnvRead:
    """One environment-variable read site."""

    key: Optional[str]  # None when the key expression isn't a literal
    node: ast.AST       # the Call / Subscript expression


def env_reads(sf: SourceFile) -> List[EnvRead]:
    """Every ``os.environ.get`` / ``os.environ[...]`` / ``os.getenv``
    site in the file, alias-resolved."""
    out: List[EnvRead] = []
    if sf.tree is None:
        return out
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            name = sf.canonical(node.func)
            if name in ("os.environ.get", "os.getenv", "os.environ.__getitem__"):
                key = None
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    key = node.args[0].value
                out.append(EnvRead(key, node))
        elif isinstance(node, ast.Subscript):
            # Load context only: os.environ["K"] = "1" is a WRITE, not a
            # read — flagging it as a stale-read would be a false positive.
            if sf.canonical(node.value) == "os.environ" and \
                    isinstance(node.ctx, ast.Load):
                key = None
                sl = node.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    key = sl.value
                out.append(EnvRead(key, node))
    return out


@dataclasses.dataclass(frozen=True)
class LadderRung:
    """One guard-ladder rung, as extracted from the AST of the module
    defining ``DEFAULT_LADDER`` (no import of serve/ needed)."""

    name: str
    env_var: Optional[str]
    cfg_field: Optional[str]


class Project:
    """The full analyzed file set plus the injected registries.

    ``knobs`` / ``kernel_entries`` default to the real registry
    (:mod:`raft_stereo_tpu.analysis.knobs`); tests inject fixture
    registries to exercise drift findings without touching the tree.
    """

    def __init__(self, files: Sequence[SourceFile], *,
                 knobs: Optional[Sequence[str]] = None,
                 serve_knobs: Optional[Sequence[str]] = None,
                 kernel_entries: Optional[Dict] = None):
        from raft_stereo_tpu.analysis import knobs as knobs_mod
        self.files = list(files)
        self.knobs: Tuple[str, ...] = tuple(
            knobs if knobs is not None else knobs_mod.ENV_KNOBS)
        #: Host/serving-side registries (SERVE_ENV_KNOBS + HOST_ENV_KNOBS):
        #: GL002's widened scan over serve/ and native/ accepts a RAFT_*
        #: read that appears in ANY registry — the registries differ in
        #: what they imply (cache-key membership vs documented host knob),
        #: not in lint visibility.
        self.serve_knobs: Tuple[str, ...] = tuple(
            serve_knobs if serve_knobs is not None
            else knobs_mod.SERVE_ENV_KNOBS + knobs_mod.HOST_ENV_KNOBS)
        self.kernel_entries = (dict(kernel_entries) if kernel_entries
                               is not None else
                               dict(knobs_mod.KERNEL_ENTRY_POINTS))

    # -- cross-file lookups -----------------------------------------------

    def ladder(self) -> Optional[List[LadderRung]]:
        """Rungs of the first ``DEFAULT_LADDER = (FastPath(...), ...)``
        assignment found in the file set; None when absent (the
        corresponding GL006 cross-checks are then skipped)."""
        for sf in self.files:
            if sf.tree is None:
                continue
            for node in sf.tree.body:
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target]
                           if isinstance(node, ast.AnnAssign) else [])
                if not any(isinstance(t, ast.Name) and
                           t.id == "DEFAULT_LADDER" for t in targets):
                    continue
                if node.value is None:
                    continue
                if not isinstance(node.value, (ast.Tuple, ast.List)):
                    continue
                rungs = []
                for el in node.value.elts:
                    if not (isinstance(el, ast.Call) and
                            sf.canonical(el.func).endswith("FastPath")):
                        continue
                    kw = {k.arg: k.value for k in el.keywords}

                    def const(key):
                        v = kw.get(key)
                        return v.value if isinstance(v, ast.Constant) \
                            else None
                    if const("name"):
                        rungs.append(LadderRung(const("name"),
                                                const("env_var"),
                                                const("cfg_field")))
                if rungs:
                    return rungs
        return None

    def config_fields(self, class_name: str = "RAFTStereoConfig"
                      ) -> Optional[List[str]]:
        """Field names of the named dataclass, from its AST (annotated
        class-body assignments); None when the class isn't in the set."""
        for sf in self.files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef) and \
                        node.name == class_name:
                    return [st.target.id for st in node.body
                            if isinstance(st, ast.AnnAssign) and
                            isinstance(st.target, ast.Name)]
        return None

    def find(self, suffix: str) -> Optional[SourceFile]:
        """Path-segment-bounded suffix lookup ('corr/pallas_reg.py' does
        not match 'xcorr/pallas_reg.py')."""
        for sf in self.files:
            if sf.relpath == suffix or sf.relpath.endswith("/" + suffix):
                return sf
        return None


@dataclasses.dataclass
class Report:
    findings: List[Finding]          # unsuppressed — these fail the build
    suppressed: List[Finding]
    files_analyzed: int
    #: Programs traced by graftverify (``--trace``); 0 for AST-only runs.
    entries_traced: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def merged(self, other: "Report") -> "Report":
        """Fold another report in (the ``--trace`` stage merges the GV
        report into the AST one — a single artifact, a single verdict)."""
        return Report(self.findings + other.findings,
                      self.suppressed + other.suppressed,
                      self.files_analyzed + other.files_analyzed,
                      self.entries_traced + other.entries_traced)

    def render_text(self, show_suppressed: bool = False) -> str:
        out = [f.render() for f in sorted(
            self.findings, key=lambda f: (f.path, f.line, f.code))]
        if show_suppressed:
            out += [f.render() for f in sorted(
                self.suppressed, key=lambda f: (f.path, f.line, f.code))]
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.code] = counts.get(f.code, 0) + 1
        summary = ", ".join(f"{c}: {n}" for c, n in sorted(counts.items()))
        out.append(
            f"graftlint: {len(self.findings)} finding(s)"
            + (f" [{summary}]" if summary else "")
            + f", {len(self.suppressed)} suppressed, "
            f"{self.files_analyzed} file(s) analyzed"
            + (f", {self.entries_traced} program(s) traced"
               if self.entries_traced else ""))
        return "\n".join(out)

    def render_json(self) -> str:
        return json.dumps({
            "findings": [dataclasses.asdict(f) for f in self.findings],
            "suppressed": [dataclasses.asdict(f) for f in self.suppressed],
            "files_analyzed": self.files_analyzed,
            "entries_traced": self.entries_traced,
            "ok": self.ok,
        }, indent=2, sort_keys=True)


# -- file collection -------------------------------------------------------

#: Directory basenames never descended into.
_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist"}


def collect_files(roots: Sequence[str], base: Optional[str] = None
                  ) -> List[SourceFile]:
    """All ``.py`` files under ``roots`` (files accepted verbatim), with
    relpaths relative to ``base`` (default: the common parent)."""
    paths: List[str] = []
    for root in roots:
        root = os.path.abspath(root)
        if os.path.isfile(root):
            paths.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            paths.extend(os.path.join(dirpath, f)
                         for f in sorted(filenames) if f.endswith(".py"))
    base = os.path.abspath(base) if base else (
        os.path.commonpath([os.path.dirname(p) if os.path.isfile(p) else p
                            for p in map(os.path.abspath, roots)])
        if roots else os.getcwd())
    out = []
    for p in paths:
        with open(p, "r", encoding="utf-8") as fh:
            text = fh.read()
        rel = os.path.relpath(p, base)
        out.append(SourceFile(p, rel.replace(os.sep, "/"), text))
    return out


def git_changed_files(repo_root: str) -> Set[str]:
    """Absolute paths of files changed vs HEAD (staged, unstaged and
    untracked) — the ``--changed-only`` report filter."""
    # -z: NUL-separated, unquoted paths — the line-oriented form C-quotes
    # names with spaces/non-ASCII, which would never match an abspath.
    res = subprocess.run(
        ["git", "status", "--porcelain=v1", "-z", "-uall", "--no-renames"],
        cwd=repo_root, capture_output=True, text=True, check=True)
    out: Set[str] = set()
    for entry in res.stdout.split("\0"):
        if len(entry) > 3:
            out.add(os.path.abspath(os.path.join(repo_root, entry[3:])))
    return out


# -- runner ----------------------------------------------------------------

def run_checkers(project: Project, checkers: Optional[Sequence] = None, *,
                 meta_code: str = META_CODE,
                 emit_file_meta: bool = True,
                 stale_prefix: Optional[str] = "GL") -> Report:
    """Run ``checkers`` (default: the full AST registry) over ``project``
    and fold suppressions into the verdict.

    meta_code: code for this stage's meta findings (GL000 for the AST
        stage, GC200 for the concurrency stage).
    emit_file_meta: emit parse errors and reasonless-suppression findings.
        True for whichever stage runs first over a project; the
        concurrency stage passes False when merging into an AST report so
        the same broken suppression is not reported twice.
    stale_prefix: suppressions whose codes ALL carry this prefix and that
        suppressed nothing in this run are reported as stale meta
        findings (the GL000/GC200 rot guard); None disables the check
        (used when running a checker subset, where "unused" is
        meaningless).
    """
    if checkers is None:
        from raft_stereo_tpu.analysis.checkers import ALL_CHECKERS
        checkers = [c() for c in ALL_CHECKERS]
    raw: List[Finding] = []
    by_rel = {sf.relpath: sf for sf in project.files}
    if emit_file_meta:
        for sf in project.files:
            if sf.parse_error is not None:
                raw.append(Finding(
                    meta_code, f"file does not parse: {sf.parse_error.msg}",
                    sf.relpath, sf.parse_error.lineno or 1))
    for checker in checkers:
        raw.extend(checker.check_project(project))
    # Malformed suppressions are findings in their own right.
    if emit_file_meta:
        for sf in project.files:
            for line, sup in sorted(sf.suppressions.items()):
                if not sup.reason:
                    raw.append(Finding(
                        meta_code, "suppression without a reason — use "
                        "# graftlint: disable=XXnnn (why this is "
                        "intentional)", sf.relpath, line))
    active, suppressed = [], []
    used: Set[int] = set()  # id() of _Suppression objects that suppressed
    for f in raw:
        sf = by_rel.get(f.path)
        sup = sf.suppression_for(f.line) if sf is not None else None
        if (f.code not in META_CODES and sup is not None and sup.reason
                and f.code in sup.codes):
            used.add(id(sup))
            suppressed.append(dataclasses.replace(
                f, suppressed=True, suppress_reason=sup.reason))
        else:
            active.append(f)
    # Stale suppressions: a disable comment that no longer suppresses
    # anything must not rot silently — it reads as "this line has a
    # waived finding" when nothing is waived (satellite of ISSUE 19).
    if stale_prefix is not None:
        for sf in project.files:
            for line, sup in sorted(sf.suppressions.items()):
                if (sup.reason and id(sup) not in used and sup.codes and
                        all(c.startswith(stale_prefix) and
                            c not in META_CODES for c in sup.codes)):
                    active.append(Finding(
                        meta_code,
                        "stale suppression: "
                        f"{','.join(sup.codes)} no longer fires here — "
                        "delete the comment (or re-point it at the code "
                        "that actually fires)", sf.relpath, line))
    return Report(active, suppressed, len(project.files))


def run_analysis(roots: Sequence[str], *, base: Optional[str] = None,
                 knobs: Optional[Sequence[str]] = None,
                 serve_knobs: Optional[Sequence[str]] = None,
                 kernel_entries: Optional[Dict] = None,
                 checkers: Optional[Sequence] = None,
                 select: Optional[Sequence[str]] = None,
                 only_paths: Optional[Set[str]] = None) -> Report:
    """Analyze ``roots`` end to end.

    select: restrict to these finding codes (post-filter; GL000 always
        passes through — a broken suppression is never filterable away).
    only_paths: absolute paths whose findings are reported (the
        ``--changed-only`` filter); the full tree is still analyzed so
        cross-file context stays complete.
    """
    files = collect_files(roots, base=base)
    project = Project(files, knobs=knobs, serve_knobs=serve_knobs,
                      kernel_entries=kernel_entries)
    report = run_checkers(project, checkers=checkers)
    by_rel = {sf.relpath: sf.abspath for sf in files}

    def keep(f: Finding) -> bool:
        if select is not None and f.code not in META_CODES and \
                f.code not in select:
            return False
        if only_paths is not None and by_rel.get(f.path) not in only_paths:
            return False
        return True
    return Report([f for f in report.findings if keep(f)],
                  [f for f in report.suppressed if keep(f)],
                  report.files_analyzed)

"""The ONE registry of program-shaping env knobs and kernel entry points.

Before this module existed the same information lived in three hand-synced
places: ``serve/session.py``'s ``_ENV_KNOBS`` tuple (cache-key coverage),
``serve/guard.py``'s ladder declarations (fallback coverage), and the
reviewers' heads (which module reads which switch).  PR 1–3 review rounds
caught drift between them by hand; now ``serve/session.py``,
``serve/guard.py`` and the graftlint checkers (GL002/GL006) all import
THIS module, and the linter cross-checks the registry against the tree.

Import-light on purpose (stdlib only): ``serve/`` pulls it at import time
and the linter must run without jax present.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

# Env switches whose trace-time values shape the compiled program — part of
# every serving cache key so a flipped switch (breaker trip or operator
# export) can never be served a stale program (the compile-cache-staleness
# bug class).  Every ``RAFT_*`` env read in a forward-relevant module
# (models/, ops/, corr/) must appear here or carry an explicit graftlint
# suppression — enforced by GL002.
ENV_KNOBS: Tuple[str, ...] = (
    "RAFT_STREAM_TAIL",        # streamed encoder tail (ops/pallas_encoder.py)
    "RAFT_FUSE_GRU1632",       # co-scheduled gru16+32 (ops/pallas_stream.py)
    "RAFT_FUSED_ENCODERS",     # one-pass-per-conv stems (ops/pallas_encoder.py)
    "RAFT_PACKED_L2",          # packed layer2 bit-layout (models/extractor.py)
    "RAFT_CORR_TILE",          # corr gather tile size (corr/pallas_reg.py)
    "RAFT_BATCH_FUSE_PIXELS",  # batch-fusion threshold (ops/pallas_stream.py)
    # r19 (graftresident) switches — all three shape traced programs:
    "RAFT_FUSE_ITER",          # resident per-iteration mega-kernel
                               # (ops/pallas_resident.py, default on)
    "RAFT_CORR_PACK8",         # int8 quad-packed correlation containers
                               # (corr/pallas_reg.py, default OFF —
                               # canary-banded, not bit-identical)
    "RAFT_STREAM_BATCH",       # B>1 engagement of the streamed scan-body
                               # kernels (ops/pallas_stream.py, default on;
                               # crossover from stream_batch_crossover)
    "RAFT_LANE_PACK8",         # r24 narrow-lane context streams: int8
                               # width-group containers for the
                               # iteration-invariant context/fmap state +
                               # the in-kernel czrq lane (corr/pallas_reg,
                               # ops/pallas_{stream,resident,encoder},
                               # models/raft_stereo.py; default OFF —
                               # canary-banded like RAFT_CORR_PACK8)
)

# Serving-behavior env knobs (continuous batching, DESIGN.md r9). These are
# deliberately NOT in ENV_KNOBS — neither changes what any ONE compiled
# program computes, so folding them into the config fingerprint would be
# dishonest cache-key bloat:
#
# - RAFT_BATCH_BUCKETS only selects WHICH batch sizes get compiled; the
#   batch size itself is an explicit cache-key component (``b`` in
#   ``InferenceSession.cache_key``), so two sessions with different bucket
#   ladders can safely share every program they both compile;
# - RAFT_SCHED_TICK_MS is pure host-side scheduling (the idle-poll
#   interval of the scheduler thread) and never reaches a trace.
#
# Registered here so the flag matrix has one home and a future reviewer
# asking "does this knob need to be in the fingerprint?" finds the answer
# where the fingerprint is defined.
#
# The graftguard supervision knobs (DESIGN.md r13) follow the same rule:
# each steers HOST-side supervision policy — when a watchdog fires, how
# many times a request may re-admit, how long a drain waits — read once
# at service construction (serve/supervise.py resolve_* helpers), and no
# compiled program's bytes depend on any of them.  Folding them into the
# fingerprint would recompile the whole cache because an operator tuned
# a timeout.
SERVE_ENV_KNOBS: Tuple[str, ...] = (
    "RAFT_BATCH_BUCKETS",   # batch-bucket ladder, e.g. "1,2,4,8"
                            # (serve/session.py, resolved at construction)
    "RAFT_SCHED_TICK_MS",   # scheduler idle poll, ms (serve/service.py,
                            # read at service start)
    "RAFT_WATCHDOG_MS",     # hang-watchdog deadline floor, ms; 0 = off
                            # (serve/supervise.py, read at service
                            # construction)
    "RAFT_RETRY_BUDGET",    # bounded per-request re-admissions for
                            # transient failures (serve/supervise.py)
    "RAFT_DRAIN_GRACE_MS",  # graceful-drain hard deadline, ms
                            # (serve/supervise.py)
    # graftwire HTTP ingress knobs (DESIGN.md r14) — same rule again:
    # each steers the WIRE side of serving (where the listener binds,
    # how many body bytes one request may declare, how long a socket
    # read may stall, how fast one tenant may submit), resolved once at
    # frontend construction (serve/http.py resolve_* helpers with
    # named-ValueError parsing), and no compiled program's bytes depend
    # on any of them — fingerprinting them would recompile the cache
    # because an operator moved a port.
    "RAFT_HTTP_PORT",          # listen port (serve/http.py, frontend
                               # construction; 0 = ephemeral)
    "RAFT_HTTP_BODY_MAX",      # hard content-length cap, bytes —
                               # oversize declarations are 413 BEFORE any
                               # body byte buffers (serve/http.py)
    "RAFT_HTTP_READ_TIMEOUT_MS",  # per-read socket timeout, ms; the
                               # whole body must land within
                               # BODY_DEADLINE_FACTOR of these
                               # (serve/http.py)
    "RAFT_TENANT_RATE",        # per-tenant token-bucket admission quota,
                               # "rate[:burst]" requests/s; unset =
                               # unlimited (serve/http.py)
)

# Host-pipeline env knobs: they steer HOST code (the data loader's native
# photometric kernels, the graftscope telemetry sinks) and can never reach
# a trace, so they belong in neither ENV_KNOBS (no compiled program
# depends on them) nor SERVE_ENV_KNOBS (they are not serving behavior).
# Registered so GL002's widened scan (native/, serve/, obs/) has an answer
# for every RAFT_* read and a NEW host knob must be deliberately placed
# here rather than silently invisible to lint.
#
# The obs/ knobs stay OUT of the program fingerprint for the same reason
# RAFT_SCHED_TICK_MS does: each selects where host-side telemetry is
# WRITTEN (a JSONL sink path, a profiler dump dir, the trajectory
# artifact), read once at object construction, and no compiled program's
# bytes depend on any of them — fingerprinting them would recompile every
# cached program just because an operator turned tracing on.
HOST_ENV_KNOBS: Tuple[str, ...] = (
    "RAFT_NATIVE",          # force the numpy photometric path
                            # (native/__init__.py:lib, read at first use)
    "RAFT_TRACE",           # request-trace JSONL sink path
                            # (obs/tracing.py Tracer, read at construction)
    "RAFT_PROFILE_DIR",     # on-demand jax.profiler window output dir
                            # (obs/profiler.py, read at construction)
    "RAFT_TRAJECTORY",      # perf-trajectory artifact the benches emit
                            # into (obs/trajectory.py emit(), read per call)
    "RAFT_FLIGHT_DIR",      # SLO flight-record output dir (obs/flight.py
                            # FlightRecorder, read at construction)
    "RAFT_LEDGER",          # device-ledger dump target the serve bench
                            # writes for the gate's report step
                            # (obs/ledger.py dump_path(), read per call)
    "RAFT_CHAOS_SPEC",      # chaos-soak overrides (JSON: n/seed/fault
                            # mix) for scratch/chaos_serve.py — drives a
                            # test harness, never a compiled program
    "RAFT_DECODE_MAX_PIXELS",  # decompression-bomb guard: cap on an
                            # image's HEADER-DECLARED pixel count,
                            # checked before any full decode
                            # (data/frame_utils.py read_image_rgb + the
                            # serve/wire.py ingress decode). Host decode
                            # policy only — admitted arrays are already
                            # bounded by AdmissionConfig.max_pixels, so
                            # no compiled program's shape depends on it
    # graftdeck operator-plane knobs (DESIGN.md r15) — telemetry sizing/
    # windowing only, read once at session construction, exactly like
    # RAFT_TRACE's ring: no compiled program's bytes depend on either.
    "RAFT_DECK_TICKS",      # tick flight-deck ring depth (obs/deck.py
                            # resolve_deck_ticks, default 1024)
    "RAFT_CAPACITY_WINDOW_MS",  # saturation sliding window for the
                            # capacity model (obs/capacity.py
                            # resolve_capacity_window_s, default 60 s)
    # graftstream knobs (DESIGN.md r17, serve/stream.py) — all three
    # stay OUT of the program fingerprint:
    # - RAFT_STREAM_SESSIONS / RAFT_STREAM_TTL_MS size the HOST-side
    #   session table (how many warm-start seeds are held, for how
    #   long); no compiled program's bytes depend on either — they are
    #   RAFT_DECK_TICKS-class table sizing;
    # - RAFT_CONVERGE_TOL is compared on the HOST against the per-row
    #   delta-flow norm the advance program ALREADY returns for every
    #   caller — the tolerance never reaches a trace, so it does not
    #   belong in the program key.  (Had the monitor been compiled
    #   against the tolerance, it would change the advance program and
    #   would have to ride the key — the design deliberately avoids
    #   that: one advance program serves every tolerance.)
    "RAFT_STREAM_SESSIONS",  # stream session-table global cap
                            # (serve/stream.py resolve_stream_sessions,
                            # default 128)
    "RAFT_STREAM_TTL_MS",   # idle stream-session expiry, ms
                            # (serve/stream.py resolve_stream_ttl_ms,
                            # default 60 s)
    "RAFT_CONVERGE_TOL",    # convergence early-exit tolerance, px/iter
                            # at 1/8 res (serve/stream.py
                            # resolve_converge_tol, default 0.01)
    # graftrecall knobs (DESIGN.md r18, serve/cache.py) — all four stay
    # OUT of the program fingerprint for the stream-knob reason: they
    # size/steer a HOST-side response store and never reach a trace.
    # Staleness is handled the other way around — the cache folds the
    # LIVE program fingerprint into every entry key, so a knob that DID
    # change compiled programs (ENV_KNOBS, config) automatically
    # invalidates every cached response without ever being part of
    # these knobs' semantics:
    # - RAFT_CACHE_BYTES / RAFT_CACHE_TTL_MS bound the host-RAM LRU
    #   (RAFT_STREAM_SESSIONS-class table sizing; 0 bytes = disabled,
    #   the library default — serve_stereo.py defaults it ON at 256 MiB,
    #   the watchdog precedent);
    # - RAFT_CACHE_NEAR_TOL is a HOST-side signature comparison whose
    #   only effect is handing the existing prepare_warm program an
    #   x-only seed operand — the RAFT_CONVERGE_TOL argument verbatim;
    # - RAFT_CACHE_DIR is a telemetry-sink-class output path (spilled
    #   entries), read once at cache construction.
    "RAFT_CACHE_BYTES",     # response-cache host-RAM budget, bytes
                            # (serve/cache.py resolve_cache_bytes,
                            # 0 = disabled; CLI default 256 MiB)
    "RAFT_CACHE_TTL_MS",    # response-cache entry TTL, ms
                            # (serve/cache.py resolve_cache_ttl_ms,
                            # default 10 min)
    "RAFT_CACHE_NEAR_TOL",  # near-tier block-mean signature threshold,
                            # gray levels; 0 = near tier off
                            # (serve/cache.py resolve_cache_near_tol)
    "RAFT_CACHE_DIR",       # optional disk-spill directory for evicted
                            # exact-tier entries (serve/cache.py
                            # resolve_cache_dir, read at construction)
    # graftfleet knobs (DESIGN.md r20, serve/fleet.py) — pure fleet
    # topology read by the SUPERVISOR process: they size and pace a tree
    # of subprocesses and never exist inside an instance, let alone a
    # trace.  Instance-side behavior keeps riding its own knobs
    # (RAFT_DRAIN_GRACE_MS, RAFT_CACHE_DIR ... forwarded verbatim).
    "RAFT_FLEET_INSTANCES",  # fleet width (serve/fleet.py
                            # resolve_fleet_instances, default 2)
    "RAFT_FLEET_RESTART_BUDGET",  # per-slot launch retries +
                            # death replacements per deploy generation
                            # before the slot degrades (serve/fleet.py
                            # resolve_fleet_restart_budget, default 3)
    "RAFT_FLEET_PROBE_MS",  # health-probe period, ms; <= 0 disables the
                            # background prober (serve/fleet.py
                            # resolve_fleet_probe_ms, default 500)
    "RAFT_FLEET_WARMUP_TIMEOUT_MS",  # readiness-handshake deadline per
                            # launch attempt (serve/fleet.py
                            # resolve_fleet_warmup_timeout_ms,
                            # default 600 s)
    # graftpod knobs (DESIGN.md r21, serve/session.py) — the explicit
    # fingerprint-vs-key call, made here on purpose: the mesh extent DOES
    # change the compiled program (sharded lowering — the PR 3
    # stale-program class), so it MUST re-key cached programs.  But it
    # re-keys the way the batch bucket ``b`` does — as an explicit
    # trailing cache-key component (("mesh", n_data, epoch), appended in
    # InferenceSession.cache_key), NOT via the config fingerprint.
    # ``fingerprint_id()`` stays mesh-independent by design: the PR 14
    # response cache keys on the fingerprint and must remain ONE
    # host-side cache above all N chips (DESIGN r18) — folding the mesh
    # into the fingerprint would shard the response cache per mesh shape
    # for no correctness gain.  Hence HOST_ENV_KNOBS, not ENV_KNOBS.
    "RAFT_SERVE_MESH_DATA",  # data-mesh extent (chips one session
                            # drives; serve/session.py
                            # resolve_serve_mesh_data, default 1 =
                            # single-device, byte-identical keys)
    "RAFT_SERVE_MESH_FALLBACK",  # pod kill switch: force n_data=1
                            # regardless of config/env (serve/session.py
                            # resolve_mesh_fallback) — the operator
                            # escape every kill switch here honors
    # graftheal knobs (DESIGN.md r22, serve/heal.py) — recovery-plane
    # PACING only: when a half-open probe may run, how many chip flaps
    # are tolerated, how fast a fleet restart budget refills.  None of
    # them shapes a compiled program — a re-engaged rung/chip is keyed
    # exactly the way tripping keyed it (the trip set is already in the
    # config fingerprint projection; the mesh extent/epoch is already a
    # trailing cache-key component), so healing re-USES keys that
    # tripping minted and these knobs never belong in any fingerprint.
    "RAFT_HEAL",            # recovery-plane master switch (serve/heal.py
                            # resolve_heal_enabled, default ON; 0
                            # restores the one-way PR 3..17 semantics)
    "RAFT_HEAL_BACKOFF_MS",  # initial probation backoff per rung/chip,
                            # ms (serve/heal.py resolve_heal_backoff_ms,
                            # default 30 s; doubles per failed probe)
    "RAFT_HEAL_BACKOFF_MAX_MS",  # probation backoff doubling cap, ms
                            # (serve/heal.py resolve_heal_backoff_max_ms,
                            # default 480 s)
    "RAFT_HEAL_FLAP_CAP",   # chip re-admissions per window before
                            # permanent quarantine (serve/heal.py
                            # resolve_heal_flap_cap, default 2)
    "RAFT_HEAL_WINDOW_MS",  # the flap-counting window, ms
                            # (serve/heal.py resolve_heal_window_ms,
                            # default 600 s)
    "RAFT_HEAL_REFILL_MS",  # fleet restart-budget decay: one charge
                            # refunded per interval, ms (serve/heal.py
                            # resolve_heal_refill_ms, default 60 s)
)


@dataclasses.dataclass(frozen=True)
class KernelEntry:
    """Declared coverage for one module that issues ``pl.pallas_call``.

    rungs: guard-ladder rung names (``serve/guard.py`` ``DEFAULT_LADDER``)
        whose kill switches cover this module's kernels — tripping them
        must route every kernel here onto its XLA fallback.
    exempt: reason string for a module deliberately outside the ladder
        (none today; an exemption must say why its failure mode is
        acceptable).
    """

    rungs: Tuple[str, ...] = ()
    exempt: Optional[str] = None


# Every module containing a ``pl.pallas_call`` must appear here (keyed by
# path suffix) with the ladder rungs that kill-switch it — enforced by
# GL006, which also cross-checks that the rungs exist in DEFAULT_LADDER
# and that each rung's env switch is actually consulted by the module.
KERNEL_ENTRY_POINTS = {
    "ops/pallas_encoder.py": KernelEntry(
        rungs=("fused_encoders", "stream_tail", "lane_pack8")),
    "ops/pallas_stream.py": KernelEntry(
        rungs=("fuse_gru1632", "fused_update", "stream_batch",
               "lane_pack8")),
    "ops/pallas_resident.py": KernelEntry(rungs=("fuse_iter", "lane_pack8")),
    "corr/pallas_reg.py": KernelEntry(rungs=("corr_kernel", "corr_pack8")),
    "corr/pallas_alt.py": KernelEntry(rungs=("corr_kernel",)),
}

"""The ONE registry of program-shaping env knobs and kernel entry points.

Before this module existed the same information lived in three hand-synced
places: ``serve/session.py``'s ``_ENV_KNOBS`` tuple (cache-key coverage),
``serve/guard.py``'s ladder declarations (fallback coverage), and the
reviewers' heads (which module reads which switch).  PR 1–3 review rounds
caught drift between them by hand; now ``serve/session.py``,
``serve/guard.py`` and the graftlint checkers (GL002/GL006) all import
THIS module, and the linter cross-checks the registry against the tree.

Import-light on purpose (stdlib only): ``serve/`` pulls it at import time
and the linter must run without jax present.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

# Env switches whose trace-time values shape the compiled program — part of
# every serving cache key so a flipped switch (breaker trip or operator
# export) can never be served a stale program (the compile-cache-staleness
# bug class).  Every ``RAFT_*`` env read in a forward-relevant module
# (models/, ops/, corr/) must appear here or carry an explicit graftlint
# suppression — enforced by GL002.
ENV_KNOBS: Tuple[str, ...] = (
    "RAFT_STREAM_TAIL",        # streamed encoder tail (ops/pallas_encoder.py)
    "RAFT_FUSE_GRU1632",       # co-scheduled gru16+32 (ops/pallas_stream.py)
    "RAFT_FUSED_ENCODERS",     # one-pass-per-conv stems (ops/pallas_encoder.py)
    "RAFT_PACKED_L2",          # packed layer2 bit-layout (models/extractor.py)
    "RAFT_CORR_TILE",          # corr gather tile size (corr/pallas_reg.py)
    "RAFT_BATCH_FUSE_PIXELS",  # batch-fusion threshold (ops/pallas_stream.py)
)


@dataclasses.dataclass(frozen=True)
class KernelEntry:
    """Declared coverage for one module that issues ``pl.pallas_call``.

    rungs: guard-ladder rung names (``serve/guard.py`` ``DEFAULT_LADDER``)
        whose kill switches cover this module's kernels — tripping them
        must route every kernel here onto its XLA fallback.
    exempt: reason string for a module deliberately outside the ladder
        (none today; an exemption must say why its failure mode is
        acceptable).
    """

    rungs: Tuple[str, ...] = ()
    exempt: Optional[str] = None


# Every module containing a ``pl.pallas_call`` must appear here (keyed by
# path suffix) with the ladder rungs that kill-switch it — enforced by
# GL006, which also cross-checks that the rungs exist in DEFAULT_LADDER
# and that each rung's env switch is actually consulted by the module.
KERNEL_ENTRY_POINTS = {
    "ops/pallas_encoder.py": KernelEntry(
        rungs=("fused_encoders", "stream_tail")),
    "ops/pallas_stream.py": KernelEntry(
        rungs=("fuse_gru1632", "fused_update")),
    "corr/pallas_reg.py": KernelEntry(rungs=("corr_kernel",)),
    "corr/pallas_alt.py": KernelEntry(rungs=("corr_kernel",)),
}

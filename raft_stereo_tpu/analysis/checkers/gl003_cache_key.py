"""GL003 — program-cache fingerprint completeness.

The serving fingerprint (``serve/session.py`` ``config_fingerprint``)
must cover EVERY model-config field: a field left out lets two different
configs alias one compiled program (the cache-key drift class PR 3's
review rounds caught by hand, e.g. corr_implementation-only-differs).

Mechanized as an AST cross-check: the function named
``config_fingerprint`` either iterates ``dataclasses.fields(...)``
(conservative-by-default — a new config field is covered automatically,
the shipped pattern) or must literally mention every field of the
``RAFTStereoConfig`` dataclass (string constants, ``cfg.<field>``
attribute reads, or ``getattr(cfg, "<field>")``).  Adding a config field
while hand-enumerating the fingerprint fails the lint until the
fingerprint names it.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from raft_stereo_tpu.analysis.checkers.base import Checker
from raft_stereo_tpu.analysis.core import Finding, Project

FINGERPRINT_FUNC = "config_fingerprint"
CONFIG_CLASS = "RAFTStereoConfig"


def _mentioned_fields(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "getattr" and len(node.args) >= 2 and \
                isinstance(node.args[1], ast.Constant):
            out.add(node.args[1].value)
    return out


def _uses_dataclasses_fields(sf, fn: ast.FunctionDef) -> bool:
    # canonical() resolves both `import dataclasses [as dc]` and
    # `from dataclasses import fields [as f]` to "dataclasses.fields";
    # an arbitrary helper merely NAMED fields must not disable the check.
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                sf.canonical(node.func) == "dataclasses.fields":
            return True
    return False


class CacheKeyCompletenessChecker(Checker):
    code = "GL003"
    name = "cache-key-completeness"
    description = ("program fingerprint does not cover every model-config "
                   "field (two configs could alias one compiled program)")

    def check_project(self, project: Project) -> Iterator[Finding]:
        fields = project.config_fields(CONFIG_CLASS)
        if fields is None:
            return  # config class outside the analyzed set — cannot check
        for sf in project.files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.FunctionDef) and
                        node.name == FINGERPRINT_FUNC):
                    continue
                if _uses_dataclasses_fields(sf, node):
                    continue  # generic iteration covers every field
                missing = [f for f in fields
                           if f not in _mentioned_fields(node)]
                for f in missing:
                    yield self.finding(
                        sf, node,
                        f"{CONFIG_CLASS} field {f!r} is not covered by "
                        f"{FINGERPRINT_FUNC} — two configs differing only "
                        "in it would share one compiled program; add it "
                        "to the fingerprint (or iterate "
                        "dataclasses.fields so new fields are "
                        "conservative-by-default)")

"""GL005 — trace purity of jit / scan-body / pallas-kernel functions.

A function handed to ``jax.jit``, ``jax.checkpoint``, ``lax.scan`` or
``pl.pallas_call`` executes ONCE, at trace time; the compiled program
replays its recorded ops.  An impure host call inside it —
``time.time()``, ``np.random``, an ``os.environ`` read, mutation of a
module-level object — silently becomes a constant baked into the program
(or a side effect that fires once per compile instead of once per call).
This is the trace-staleness family of the PR 3 kill-switch bug, one
level down: not wrong at trace time, wrong on every later call.

The checker inspects the DIRECT body (plus nested defs/lambdas) of
functions literally passed to / decorated with the tracing entry points;
it does not chase the transitive call graph, so trace-time-by-design
helpers like ``corr_tile()`` (read when the corr fn is BUILT, keyed into
the program cache) stay out of scope.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from raft_stereo_tpu.analysis.checkers.base import (Checker,
                                                    call_name_candidates,
                                                    funcdefs_by_name)
from raft_stereo_tpu.analysis.checkers.gl004_lock_discipline import MUTATORS
from raft_stereo_tpu.analysis.core import (Finding, Project, SourceFile,
                                           ancestors, enclosing_function)

#: call-target last components that trace their function argument /
#: decorated function.
_TRACER_TAILS = {"jit", "scan", "pallas_call", "checkpoint", "remat"}

_IMPURE_EXACT = {
    "time.time", "time.time_ns", "time.perf_counter",
    "time.perf_counter_ns", "time.monotonic", "time.sleep",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today", "uuid.uuid4", "os.urandom", "os.getenv",
    "os.environ.get",
}
_IMPURE_PREFIX = ("numpy.random.", "random.")


def _tracer_tail(sf: SourceFile, func: ast.expr) -> Optional[str]:
    for cand in call_name_candidates(sf, func):
        tail = cand.split(".")[-1]
        if tail in _TRACER_TAILS:
            return tail
    return None


def _unwrap_partial(sf: SourceFile, expr: ast.expr) -> ast.expr:
    if isinstance(expr, ast.Call) and \
            sf.canonical(expr.func).split(".")[-1] == "partial" and expr.args:
        return expr.args[0]
    return expr


def _resolve_visible(defs, call_node: ast.AST, name: str) -> List[ast.AST]:
    """The defs a Name argument can actually refer to AT the call site,
    Python scoping order: the call's innermost enclosing function first,
    then outward, then module scope — so a host-side namesake of a traced
    closure (e.g. two functions both called ``step``) is never flagged."""
    cands = defs.get(name, [])
    if len(cands) <= 1:
        return cands
    scopes = [a for a in ancestors(call_node)
              if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda))] + [None]
    for scope in scopes:
        matches = [d for d in cands if enclosing_function(d) is scope]
        if matches:
            return matches
    return []


def _traced_functions(sf: SourceFile) -> List[Tuple[ast.AST, str]]:
    """(function node, how-it-is-traced) pairs for this file."""
    defs = funcdefs_by_name(sf.tree)
    out: List[Tuple[ast.AST, str]] = []
    seen: Set[int] = set()

    def add(node: Optional[ast.AST], how: str) -> None:
        if node is not None and id(node) not in seen:
            seen.add(id(node))
            out.append((node, how))

    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = _unwrap_partial(sf, dec)
                target = target.func if isinstance(target, ast.Call) \
                    else target
                tail = _tracer_tail(sf, target)
                if tail:
                    add(node, f"@{tail}")
        elif isinstance(node, ast.Call):
            tail = _tracer_tail(sf, node.func)
            if not tail or not node.args:
                continue
            arg = _unwrap_partial(sf, node.args[0])
            if isinstance(arg, ast.Lambda):
                add(arg, tail)
            elif isinstance(arg, ast.Name):
                for fd in _resolve_visible(defs, node, arg.id):
                    add(fd, tail)
    return out


def _local_names(fn: ast.AST) -> Set[str]:
    names: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            names.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(node.name)
    return names


def _base_name(expr: ast.expr) -> Optional[str]:
    while isinstance(expr, (ast.Subscript, ast.Attribute)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


class TracePurityChecker(Checker):
    code = "GL005"
    name = "trace-purity"
    description = ("impure host call / global mutation inside a function "
                   "traced by jit, lax.scan or pallas_call (executes once "
                   "at trace time, not per call)")

    def check_file(self, project: Project, sf: SourceFile
                   ) -> Iterator[Finding]:
        for fn, how in _traced_functions(sf):
            fname = getattr(fn, "name", "<lambda>")
            locals_ = _local_names(fn)
            for node in ast.walk(fn):
                yield from self._check_node(sf, node, fname, how, locals_)

    def _check_node(self, sf, node, fname, how, locals_):
        if isinstance(node, ast.Call):
            name = sf.canonical(node.func)
            if name in _IMPURE_EXACT or \
                    name.startswith(_IMPURE_PREFIX):
                yield self.finding(
                    sf, node,
                    f"impure call {name}() inside {fname!r} (traced via "
                    f"{how}) — it runs once at trace time and its result "
                    "is baked into the compiled program; hoist it to the "
                    "caller and pass the value in")
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in MUTATORS:
                base = _base_name(node.func.value)
                if base and base in sf.module_names and \
                        base not in locals_:
                    yield self.finding(
                        sf, node,
                        f"mutation of module-level {base!r} inside "
                        f"{fname!r} (traced via {how}) — the side effect "
                        "fires once per trace, not once per call")
        elif isinstance(node, ast.Subscript):
            if sf.canonical(node.value) == "os.environ" and \
                    isinstance(node.ctx, ast.Load):
                yield self.finding(
                    sf, node,
                    f"os.environ read inside {fname!r} (traced via {how}) "
                    "— the value is baked in at trace time; resolve it in "
                    "the caller and key the program cache on it")
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    base = _base_name(t)
                    if base and base in sf.module_names and \
                            base not in locals_:
                        yield self.finding(
                            sf, t,
                            f"mutation of module-level {base!r} inside "
                            f"{fname!r} (traced via {how}) — the side "
                            "effect fires once per trace, not once per "
                            "call")
        elif isinstance(node, ast.Global):
            yield self.finding(
                sf, node,
                f"`global {', '.join(node.names)}` inside {fname!r} "
                f"(traced via {how}) — rebinding module state from traced "
                "code fires once per trace, not once per call")

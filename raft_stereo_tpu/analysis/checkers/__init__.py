"""graftlint checker registry — one module per mechanized bug class."""

from raft_stereo_tpu.analysis.checkers.base import Checker  # noqa: F401
from raft_stereo_tpu.analysis.checkers.gl001_import_time_switch import \
    ImportTimeSwitchChecker
from raft_stereo_tpu.analysis.checkers.gl002_knob_registry import \
    KnobRegistryChecker
from raft_stereo_tpu.analysis.checkers.gl003_cache_key import \
    CacheKeyCompletenessChecker
from raft_stereo_tpu.analysis.checkers.gl004_lock_discipline import \
    LockDisciplineChecker
from raft_stereo_tpu.analysis.checkers.gl005_trace_purity import \
    TracePurityChecker
from raft_stereo_tpu.analysis.checkers.gl006_kill_switch import \
    KillSwitchCoverageChecker

ALL_CHECKERS = (
    ImportTimeSwitchChecker,
    KnobRegistryChecker,
    CacheKeyCompletenessChecker,
    LockDisciplineChecker,
    TracePurityChecker,
    KillSwitchCoverageChecker,
)

"""GL001 — kill switch read at import scope or cached into a constant.

The bug class PR 3 shipped: ``ops/pallas_encoder.py`` read
``RAFT_FUSED_ENCODERS`` into a module constant ``ENABLE`` at import time,
so the serving circuit breaker's runtime env flip silently never took
effect — the stale program kept running the kernel the operator had just
killed.  Program-shaping switches must be read at trace/build time, i.e.
inside a function that every trace calls.

Flagged, for any ``RAFT_*`` env key (or a key in the knob registry):

- a read at module or class scope (executes once, at import);
- a read inside a function decorated ``functools.lru_cache`` / ``cache``
  (same staleness with one extra step of indirection).
"""

from __future__ import annotations

import ast
from typing import Iterator

from raft_stereo_tpu.analysis.checkers.base import Checker
from raft_stereo_tpu.analysis.core import (Finding, Project, SourceFile,
                                           enclosing_function, env_reads)

_CACHE_DECORATORS = ("functools.lru_cache", "lru_cache", "functools.cache",
                     "cache")


def _is_cached(sf: SourceFile, fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if sf.canonical(target) in _CACHE_DECORATORS:
            return True
    return False


class ImportTimeSwitchChecker(Checker):
    code = "GL001"
    name = "import-time-switch"
    description = ("program-shaping env switch read at module import "
                   "scope or cached into a constant (must be read at "
                   "trace/build time)")

    def check_file(self, project: Project, sf: SourceFile
                   ) -> Iterator[Finding]:
        for read in env_reads(sf):
            if read.key is None:
                continue
            if not (read.key.startswith("RAFT_")
                    or read.key in project.knobs):
                continue
            fn = enclosing_function(read.node)
            if fn is None:
                yield self.finding(
                    sf, read.node,
                    f"env switch {read.key!r} read at import scope — a "
                    "runtime flip (circuit-breaker trip, operator export) "
                    "will never take effect; read it inside the function "
                    "that traces/builds the program")
            elif _is_cached(sf, fn):
                yield self.finding(
                    sf, read.node,
                    f"env switch {read.key!r} read inside the cached "
                    f"function {getattr(fn, 'name', '<lambda>')!r} — the "
                    "first call pins the value for the process lifetime; "
                    "drop the cache decorator or hoist the read to the "
                    "caller")

"""GL002 — knob-registry drift.

Every ``RAFT_*`` env read in a forward-relevant module (``models/``,
``ops/``, ``corr/``) shapes the traced program, so it must be part of the
serving cache key — i.e. listed in the one knob registry
(``analysis/knobs.py`` ``ENV_KNOBS``) that ``serve/session.py``
fingerprints and ``serve/guard.py`` validates its ladder against.  A read
missing from the registry is the stale-program class the session can only
runtime-check for ladder rungs: two requests under different switch
values would silently share one compiled program.

The scan also covers ``serve/`` and ``native/`` (widened in r10),
``obs/`` (r11) and ``data/`` (r14): a ``RAFT_*`` read there is
host/serving behavior rather than program shape, so it may live in ANY
registry (``ENV_KNOBS``, ``SERVE_ENV_KNOBS`` or ``HOST_ENV_KNOBS``) —
but it must live somewhere.
Before the widening, a new env read in serve/ (e.g. ``RAFT_NATIVE``-style
pipeline switches) was simply invisible to lint and the flag matrix
drifted; the r11 telemetry knobs (``RAFT_TRACE``/``RAFT_PROFILE_DIR``/
``RAFT_TRAJECTORY``) are covered from birth, and so is the r14
decode-bomb cap (``RAFT_DECODE_MAX_PIXELS``, read in
``data/frame_utils.py``).
"""

from __future__ import annotations

from typing import Iterator

from raft_stereo_tpu.analysis.checkers.base import Checker
from raft_stereo_tpu.analysis.core import (Finding, Project, SourceFile,
                                           env_reads)

#: Path segments marking a module whose env reads shape the forward
#: program (the serving cache key must cover them).
FORWARD_DIRS = ("models", "ops", "corr")

#: Path segments whose RAFT_* reads are host/serving behavior: they must
#: appear in SOME registry (ENV_KNOBS counts too — a forward knob read
#: from serve/ is legal) so the flag matrix has one home. ``data`` joined
#: in r14 (the ingress decode-bomb cap lives in data/frame_utils.py).
HOST_DIRS = ("serve", "native", "obs", "data")


def is_forward_module(relpath: str) -> bool:
    return any(seg in FORWARD_DIRS for seg in relpath.split("/")[:-1])


def is_host_module(relpath: str) -> bool:
    return any(seg in HOST_DIRS for seg in relpath.split("/")[:-1])


class KnobRegistryChecker(Checker):
    code = "GL002"
    name = "knob-registry"
    description = ("RAFT_* env read missing from the knob registries — "
                   "ENV_KNOBS for forward modules (models/ops/corr), any "
                   "registry for host modules (serve/native/obs/data)")

    def check_file(self, project: Project, sf: SourceFile
                   ) -> Iterator[Finding]:
        forward = is_forward_module(sf.relpath)
        host = is_host_module(sf.relpath)
        if not (forward or host):
            return
        for read in env_reads(sf):
            if read.key is None or not read.key.startswith("RAFT_"):
                continue
            if forward:
                if read.key not in project.knobs:
                    yield self.finding(
                        sf, read.node,
                        f"env knob {read.key!r} is read in a "
                        "forward-relevant module but missing from ENV_KNOBS "
                        "(raft_stereo_tpu/analysis/knobs.py) — programs "
                        "traced under different values would share one "
                        "cache entry; register it (or suppress with a "
                        "reason if it provably cannot change the traced "
                        "program)")
            elif read.key not in project.knobs and \
                    read.key not in project.serve_knobs:
                yield self.finding(
                    sf, read.node,
                    f"env knob {read.key!r} is read in a host/serving "
                    "module but appears in no registry — add it to "
                    "SERVE_ENV_KNOBS or HOST_ENV_KNOBS "
                    "(raft_stereo_tpu/analysis/knobs.py) with a rationale "
                    "for staying out of the cache-key set, or to "
                    "ENV_KNOBS if it can shape a traced program")

"""GL002 — knob-registry drift.

Every ``RAFT_*`` env read in a forward-relevant module (``models/``,
``ops/``, ``corr/``) shapes the traced program, so it must be part of the
serving cache key — i.e. listed in the one knob registry
(``analysis/knobs.py`` ``ENV_KNOBS``) that ``serve/session.py``
fingerprints and ``serve/guard.py`` validates its ladder against.  A read
missing from the registry is the stale-program class the session can only
runtime-check for ladder rungs: two requests under different switch
values would silently share one compiled program.
"""

from __future__ import annotations

from typing import Iterator

from raft_stereo_tpu.analysis.checkers.base import Checker
from raft_stereo_tpu.analysis.core import (Finding, Project, SourceFile,
                                           env_reads)

#: Path segments marking a module whose env reads shape the forward
#: program (the serving cache key must cover them).
FORWARD_DIRS = ("models", "ops", "corr")


def is_forward_module(relpath: str) -> bool:
    return any(seg in FORWARD_DIRS for seg in relpath.split("/")[:-1])


class KnobRegistryChecker(Checker):
    code = "GL002"
    name = "knob-registry"
    description = ("RAFT_* env read in a forward-relevant module missing "
                   "from the program-cache knob registry (ENV_KNOBS)")

    def check_file(self, project: Project, sf: SourceFile
                   ) -> Iterator[Finding]:
        if not is_forward_module(sf.relpath):
            return
        for read in env_reads(sf):
            if read.key is None or not read.key.startswith("RAFT_"):
                continue
            if read.key not in project.knobs:
                yield self.finding(
                    sf, read.node,
                    f"env knob {read.key!r} is read in a forward-relevant "
                    "module but missing from ENV_KNOBS "
                    "(raft_stereo_tpu/analysis/knobs.py) — programs traced "
                    "under different values would share one cache entry; "
                    "register it (or suppress with a reason if it provably "
                    "cannot change the traced program)")

"""GL004 — lock discipline on instance attributes.

The quarantine-dict / metrics-counter race class: a class declares
``self._lock = threading.Lock()`` and guards an attribute's mutations in
one method, while another method mutates the same attribute bare (PR 1's
loader quarantine and PR 3's session metrics both shipped a variant that
review caught by hand).  A half-guarded attribute is worse than an
unguarded one — the lock documents an intent the code doesn't keep.

Flagged, per class that owns at least one ``threading.Lock``/``RLock``
attribute:

- an attribute mutated under a ``with self.<lock>`` block in one place
  and outside any such block in another (``__init__`` is exempt —
  construction is single-threaded by convention);
- an attribute whose guarded mutation sites share NO common lock (two
  methods agreeing to lock but not on which lock).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set

from raft_stereo_tpu.analysis.checkers.base import Checker
from raft_stereo_tpu.analysis.core import (Finding, Project, SourceFile,
                                           ancestors)

#: Method names whose receiver object is mutated by the call.
MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "add", "discard", "sort", "reverse",
    "move_to_end", "appendleft", "popleft",
})

#: Methods where unguarded mutation is conventional (single-threaded).
EXEMPT_METHODS = ("__init__", "__new__", "__del__")


def _self_attr(expr: ast.expr) -> Optional[str]:
    """The leftmost ``self.<attr>`` an lvalue/receiver chain hangs off:
    ``self.a``, ``self.a[k]``, ``self.a.b`` all resolve to ``a``."""
    while isinstance(expr, (ast.Subscript, ast.Attribute)):
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return expr.attr
        expr = expr.value
    return None


@dataclasses.dataclass
class _Site:
    node: ast.AST
    method: str
    locks: frozenset  # self-lock attrs held at this site


def _lock_attrs(cls: ast.ClassDef, sf: SourceFile) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = sf.canonical(node.value.func)
            if name.split(".")[-1] in ("Lock", "RLock"):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        out.add(attr)
    return out


def _held_locks(node: ast.AST, locks: Set[str], fn: ast.AST) -> frozenset:
    held = set()
    for a in ancestors(node):
        if a is fn:
            break
        if isinstance(a, ast.With):
            for item in a.items:
                attr = _self_attr(item.context_expr)
                if attr in locks:
                    held.add(attr)
    return frozenset(held)


def _mutation_sites(cls: ast.ClassDef, locks: Set[str]) -> Dict[str,
                                                                List[_Site]]:
    sites: Dict[str, List[_Site]] = {}

    def record(attr: Optional[str], node: ast.AST, method: str,
               fn: ast.AST) -> None:
        if attr is None or attr in locks:
            return
        sites.setdefault(attr, []).append(
            _Site(node, method, _held_locks(node, locks, fn)))

    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name in EXEMPT_METHODS:
            continue
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    record(_self_attr(t), node, fn.name, fn)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    record(_self_attr(t), node, fn.name, fn)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in MUTATORS:
                record(_self_attr(node.func.value), node, fn.name, fn)
    return sites


class LockDisciplineChecker(Checker):
    code = "GL004"
    name = "lock-discipline"
    description = ("instance attribute mutated both inside and outside "
                   "its lock (half-guarded state race)")

    def check_file(self, project: Project, sf: SourceFile
                   ) -> Iterator[Finding]:
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = _lock_attrs(cls, sf)
            if not locks:
                continue
            for attr, sites in sorted(_mutation_sites(cls, locks).items()):
                guarded = [s for s in sites if s.locks]
                bare = [s for s in sites if not s.locks]
                if guarded and bare:
                    lock_names = sorted({l for s in guarded for l in s.locks})
                    for s in bare:
                        yield self.finding(
                            sf, s.node,
                            f"{cls.name}.{attr} is mutated under "
                            f"{'/'.join(lock_names)} elsewhere but bare in "
                            f"{s.method}() — take the lock here or move "
                            "the attribute out of locked use")
                elif len(guarded) > 1:
                    common = frozenset.intersection(
                        *[s.locks for s in guarded])
                    if not common:
                        s = guarded[-1]
                        yield self.finding(
                            sf, s.node,
                            f"{cls.name}.{attr} mutation sites hold no "
                            "common lock (" + ", ".join(
                                f"{x.method}: {'/'.join(sorted(x.locks))}"
                                for x in guarded) +
                            ") — agree on one lock for this attribute")

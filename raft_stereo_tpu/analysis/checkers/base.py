"""Checker base class."""

from __future__ import annotations

import ast
from typing import Iterator, List

from raft_stereo_tpu.analysis.core import Finding, Project, SourceFile


class Checker:
    """One finding code.  Subclasses set the class attributes and
    implement either :meth:`check_file` (per-file checkers) or
    :meth:`check_project` (cross-file checkers)."""

    code: str = "GL???"
    name: str = ""
    description: str = ""

    def check_project(self, project: Project) -> Iterator[Finding]:
        for sf in project.files:
            if sf.tree is not None:
                yield from self.check_file(project, sf)

    def check_file(self, project: Project, sf: SourceFile
                   ) -> Iterator[Finding]:
        return iter(())

    def finding(self, sf: SourceFile, node: ast.AST, message: str) -> Finding:
        return Finding(self.code, message, sf.relpath,
                       getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0))


def funcdefs_by_name(tree: ast.AST) -> dict:
    """name -> [FunctionDef] for every def anywhere in the module (nested
    included — closures passed to jit/scan are usually nested)."""
    out: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def call_name_candidates(sf: SourceFile, func: ast.expr) -> List[str]:
    """Dotted-name forms a call target can be matched under: the
    canonical alias-resolved name plus its raw tail (``pl.pallas_call``
    resolves to ``jax.experimental.pallas.pallas_call`` AND matches
    ``pallas_call``)."""
    name = sf.canonical(func)
    if not name:
        return []
    parts = name.split(".")
    return [name] + [".".join(parts[i:]) for i in range(1, len(parts))]

"""GL006 — kill-switch / fallback-ladder coverage of pallas_call sites.

Every module that issues a ``pl.pallas_call`` is a production risk the
serving circuit breaker must be able to turn OFF: it needs a kill switch,
an XLA fallback, and a guard-ladder rung that flips the switch when the
kernel fails (DESIGN.md r7 — the ladder's terminal rung must be a
genuinely kernel-free forward).  Coverage is declared once, in
``analysis/knobs.py`` ``KERNEL_ENTRY_POINTS``, and this checker keeps the
declaration honest:

- a module containing ``pallas_call`` with no registry entry (and no
  explicit exemption) is flagged — a new kernel cannot ship without
  deciding its fallback story;
- declared rungs must exist in ``serve/guard.py`` ``DEFAULT_LADDER``
  (AST cross-check — renaming a rung can't silently orphan a kernel);
- an env-var rung's switch must actually be consulted somewhere in the
  module it covers (a declared-but-never-read switch kills nothing);
- a cfg-field rung's field must exist on the model config dataclass;
- a registry entry whose module no longer has any ``pallas_call`` is
  stale and flagged (the registry never overstates coverage).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from raft_stereo_tpu.analysis.checkers.base import (Checker,
                                                    call_name_candidates)
from raft_stereo_tpu.analysis.core import (Finding, Project, SourceFile,
                                           env_reads)

REGISTRY_HINT = "raft_stereo_tpu/analysis/knobs.py KERNEL_ENTRY_POINTS"


def _suffix_match(relpath: str, key: str) -> bool:
    """Path-segment-bounded suffix match: 'xcorr/pallas_reg.py' must NOT
    inherit the 'corr/pallas_reg.py' entry."""
    return relpath == key or relpath.endswith("/" + key)


def _pallas_calls(sf: SourceFile) -> List[ast.Call]:
    out = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and any(
                c.split(".")[-1] == "pallas_call"
                for c in call_name_candidates(sf, node.func)):
            out.append(node)
    return out


def _env_keys_read(sf: SourceFile) -> Set[str]:
    return {r.key for r in env_reads(sf) if r.key is not None}


class KillSwitchCoverageChecker(Checker):
    code = "GL006"
    name = "kill-switch-coverage"
    description = ("pallas_call entry point without a registered kill "
                   "switch + guard-ladder rung (or explicit exemption)")

    def check_project(self, project: Project) -> Iterator[Finding]:
        ladder = project.ladder()
        rung_by_name = {r.name: r for r in (ladder or [])}
        config_fields = project.config_fields()
        matched_entries: Set[str] = set()

        for sf in project.files:
            if sf.tree is None:
                continue
            calls = _pallas_calls(sf)
            if not calls:
                continue
            entry_key = next((k for k in project.kernel_entries
                              if _suffix_match(sf.relpath, k)), None)
            if entry_key is None:
                yield self.finding(
                    sf, calls[0],
                    f"module issues pallas_call but has no entry in "
                    f"{REGISTRY_HINT} — declare the ladder rungs whose "
                    "kill switches cover it (or an exemption saying why "
                    "its failure mode is acceptable)")
                continue
            matched_entries.add(entry_key)
            entry = project.kernel_entries[entry_key]
            if entry.exempt:
                continue
            if not entry.rungs:
                yield self.finding(
                    sf, calls[0],
                    f"registry entry for this module declares no ladder "
                    f"rungs and no exemption ({REGISTRY_HINT})")
                continue
            env_keys = _env_keys_read(sf)
            for rung_name in entry.rungs:
                if ladder is not None and rung_name not in rung_by_name:
                    yield self.finding(
                        sf, calls[0],
                        f"declared ladder rung {rung_name!r} does not "
                        "exist in DEFAULT_LADDER (serve/guard.py) — the "
                        "breaker cannot trip a rung that isn't there")
                    continue
                rung = rung_by_name.get(rung_name)
                if rung is None:
                    continue  # no ladder in the analyzed set
                if rung.env_var is not None and \
                        rung.env_var not in env_keys:
                    yield self.finding(
                        sf, calls[0],
                        f"rung {rung_name!r} kill switch {rung.env_var!r} "
                        "is never read in this module — flipping it "
                        "would kill nothing here; consult the switch on "
                        "the path that reaches pallas_call")
                if rung.cfg_field is not None and \
                        config_fields is not None and \
                        rung.cfg_field not in config_fields:
                    yield self.finding(
                        sf, calls[0],
                        f"rung {rung_name!r} config switch "
                        f"{rung.cfg_field!r} is not a field of the model "
                        "config — the breaker's cfg rewrite would be a "
                        "no-op")

        for key, entry in sorted(project.kernel_entries.items()):
            sf = project.find(key)
            if sf is None or sf.tree is None:
                continue  # module outside the analyzed set
            if key not in matched_entries and not _pallas_calls(sf):
                yield self.finding(
                    sf, sf.tree,
                    f"stale registry entry: {key} no longer issues any "
                    f"pallas_call — remove it from {REGISTRY_HINT} so the "
                    "registry never overstates coverage")

"""graftverify runner: trace entries once, run the GV checkers, fold
table suppressions into a :class:`~raft_stereo_tpu.analysis.core.Report`.

Mirrors ``analysis/core.run_checkers``' contract: GV000 (trace/internal
meta findings) is never suppressible and never filterable by ``--select``
— an entry that fails to trace, or a reasonless suppression, must not be
able to read as "clean".
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence

from raft_stereo_tpu.analysis.core import Finding, Report
from raft_stereo_tpu.analysis.trace.registry import TraceEntry, TraceRegistry

#: Meta-code for graftverify itself: trace failures, missing probes,
#: reasonless suppressions. Not suppressible, not selectable-away.
GV_META_CODE = "GV000"


class TraceFailure(Exception):
    """An entry failed to build/trace — surfaced as a GV000 finding."""


class TraceChecker:
    """One GV finding code. Subclasses set the class attrs and implement
    :meth:`check`. Use :meth:`finding` so contexts (the suppression keys)
    stay uniform: ``trace:<entry-or-probe-name>``."""

    code: str = "GV???"
    name: str = ""
    description: str = ""

    def check(self, ctx: "TraceContext") -> Iterator[Finding]:
        return iter(())

    def finding(self, context: str, message: str) -> Finding:
        return Finding(self.code, message, f"trace:{context}", 0)


class TraceContext:
    """Per-run cache of traced programs, shared by all checkers so the
    expensive artifacts (jaxpr, scrubbed text, lowered module) are built
    once per entry regardless of how many checkers read them."""

    def __init__(self, registry: TraceRegistry):
        self.registry = registry
        self._jaxprs: Dict[str, object] = {}   # name -> ClosedJaxpr | Exception
        self._texts: Dict[str, str] = {}
        self._lowered: Dict[str, object] = {}

    # Every accessor returns None on a failed entry — the failure itself
    # is reported exactly once, by the runner's pre-trace pass.

    def jaxpr(self, entry: TraceEntry):
        cached = self._jaxprs.get(entry.name)
        if cached is not None:
            return None if isinstance(cached, Exception) else cached
        try:
            import jax

            from raft_stereo_tpu.serve.session import _env_overrides
            with _env_overrides(dict(entry.env)):
                fn, args = entry.build()
                closed = jax.make_jaxpr(fn)(*args)
        except Exception as e:  # noqa: BLE001 — converted to GV000
            self._jaxprs[entry.name] = e
            return None
        self._jaxprs[entry.name] = closed
        return closed

    def text(self, entry: TraceEntry) -> Optional[str]:
        if entry.name not in self._texts:
            from raft_stereo_tpu.analysis.trace.jaxprs import scrubbed_text
            closed = self.jaxpr(entry)
            if closed is None:
                return None
            self._texts[entry.name] = scrubbed_text(closed)
        return self._texts[entry.name]

    def lowered(self, entry: TraceEntry):
        """``(stablehlo_text, donated_leaves)`` for a GV105 entry."""
        cached = self._lowered.get(entry.name)
        if cached is not None:
            return None if isinstance(cached, Exception) else cached
        if entry.build_lowered is None:
            return None
        try:
            from raft_stereo_tpu.serve.session import _env_overrides
            with _env_overrides(dict(entry.env)):
                result = entry.build_lowered()
        except Exception as e:  # noqa: BLE001 — converted to GV000
            self._lowered[entry.name] = e
            return None
        self._lowered[entry.name] = result
        return result

    def trace_errors(self) -> List[Finding]:
        out = []
        for name in sorted(self._jaxprs):
            e = self._jaxprs[name]
            if isinstance(e, Exception):
                out.append(Finding(
                    GV_META_CODE,
                    f"entry failed to trace: {type(e).__name__}: {e}",
                    f"trace:{name}", 0))
        for name in sorted(self._lowered):
            e = self._lowered[name]
            if isinstance(e, Exception):
                out.append(Finding(
                    GV_META_CODE,
                    f"entry failed to lower: {type(e).__name__}: {e}",
                    f"trace:{name}", 0))
        return out

    @property
    def entries_traced(self) -> int:
        return sum(1 for v in self._jaxprs.values()
                   if not isinstance(v, Exception))


def run_trace_analysis(registry: TraceRegistry, *,
                       select: Optional[Sequence[str]] = None,
                       checkers: Optional[Sequence[TraceChecker]] = None
                       ) -> Report:
    """Trace + check + suppress; the trace-side half of ``--trace``."""
    if checkers is None:
        from raft_stereo_tpu.analysis.trace.checkers import \
            ALL_TRACE_CHECKERS
        checkers = [c() for c in ALL_TRACE_CHECKERS]
    ctx = TraceContext(registry)
    raw: List[Finding] = []
    # Pre-trace every declared entry: a dead entry is a finding even if no
    # checker would have touched it (the analyzer must not silently shrink).
    for entry in registry.all_entries():
        ctx.jaxpr(entry)
    for checker in checkers:
        raw.extend(checker.check(ctx))
    raw.extend(ctx.trace_errors())

    sup = registry.suppressions
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for f in raw:
        context = f.path[len("trace:"):] if f.path.startswith("trace:") \
            else f.path
        reason = sup.get((f.code, context))
        if f.code != GV_META_CODE and reason is not None and reason.strip():
            suppressed.append(dataclasses.replace(
                f, suppressed=True, suppress_reason=reason.strip()))
        else:
            # Blank includes whitespace-only — a reasonless suppression
            # must not be able to hide anything, itself included.
            if f.code != GV_META_CODE and reason is not None:
                active.append(Finding(
                    GV_META_CODE,
                    f"suppression for ({f.code}, {context!r}) has no "
                    "reason — registry suppressions must say why",
                    f.path, 0))
            active.append(f)

    def keep(f: Finding) -> bool:
        return (select is None or f.code == GV_META_CODE
                or f.code in select)
    return Report([f for f in active if keep(f)],
                  [f for f in suppressed if keep(f)],
                  files_analyzed=0, entries_traced=ctx.entries_traced)

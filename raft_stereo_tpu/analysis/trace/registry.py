"""The traceable-entry-point registry: WHAT graftverify analyzes.

Entries are built from the repo's real builders — ``serve/session.py``'s
``build_program`` (the exact callables the serving cache jits),
``engine/steps.py``'s ``make_train_step`` (the exact jitted+donated train
step), and the eval forward — at pinned geometries, so the GV checkers
walk the programs production compiles rather than hand-written stand-ins.

Geometries:

- ``headline``: the bench north-star shape (bench.py: Middlebury-F padded,
  2016x2976, 32 iters, reg_tpu bf16). This is where the acceptance-grade
  claims live — every kernel path engages, so GV102 can prove each
  breaker rung and each ENV_KNOBS entry actually changes the program.
- ``small``: a fast shape for development loops. Kernel engagement
  heuristics (the ``stream_batch_crossover`` pixel threshold) do NOT
  clear at this size, so ladder/knob probes are headline-only — at small
  shapes several rungs are legitimately no-ops and GV102 would report
  false vacuity (``ladder_variants``/``knob_flips`` are empty here).

Everything is lazy: ``TraceEntry.build`` closures defer jax work to the
runner, which converts a failing entry into a GV000 finding instead of a
crash — the GL006 lesson (an extractor that silently resolves nothing
must not read as "clean") applies doubly to a tracer.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, List, Optional, Tuple

@dataclasses.dataclass(frozen=True)
class KnobProbe:
    """Where and how one env knob provably changes the traced program:
    ``flip`` is a value different from the default; ``kind``/``batch``
    pick the serving program the knob engages on (most knobs bite the
    B=1 full forward; RAFT_BATCH_FUSE_PIXELS by construction only bites
    batched programs — ``_batch_worthwhile`` short-circuits at B=1).
    ``env``: extra (key, value) pairs applied to BOTH the base and the
    flipped trace — for a knob that only shapes programs when another
    switch is in a given state (RAFT_CORR_TILE sizes the STANDALONE
    lookup's grid, which the r19 resident path replaces in-kernel, so
    its probe runs from a RAFT_FUSE_ITER=0 base; the knob still rides
    every cache key because resident-off programs depend on it)."""

    flip: str
    kind: str = "full"
    batch: int = 1
    env: Tuple[Tuple[str, str], ...] = ()


#: Declared flip probe per registered env knob: a value provably different
#: from the default that must change the traced program at headline
#: geometry, on the program kind where the knob engages. A knob added to
#: ENV_KNOBS without a probe here is itself a GV102 finding (the registry
#: must stay exhaustive, mechanically).
KNOB_FLIP_PROBES: Dict[str, KnobProbe] = {
    "RAFT_STREAM_TAIL": KnobProbe("0"),          # default on -> off
    "RAFT_FUSE_GRU1632": KnobProbe("0"),         # default on -> off
    "RAFT_FUSED_ENCODERS": KnobProbe("0"),       # default on -> off
    "RAFT_PACKED_L2": KnobProbe("0"),            # default on -> off
    "RAFT_CORR_TILE": KnobProbe("1024",          # 2048 -> half (new grid)
                                env=(("RAFT_FUSE_ITER", "0"),)),
    # The batch-fusion threshold is a no-op at B=1 (that is its spec:
    # _batch_worthwhile gives B=1 an unconditional pass) — probe it on the
    # continuous-batching advance program at b=2, where headline
    # per-sample frames clear the crossover default and a never-fuse flip
    # provably de-fuses the kernels.
    "RAFT_BATCH_FUSE_PIXELS": KnobProbe("1000000000", kind="advance",
                                        batch=2),
    # r19 switches: the resident mega-kernel and the int8 correlation
    # containers both bite on the B=1 full forward at headline; the B>1
    # stream engagement (like the crossover it replaces) is a no-op at
    # B=1 by spec, so it probes on the batched advance program.
    "RAFT_FUSE_ITER": KnobProbe("0"),            # default on -> off
    "RAFT_CORR_PACK8": KnobProbe("1"),           # default OFF -> on
    "RAFT_STREAM_BATCH": KnobProbe("0", kind="advance", batch=2),
    # r24: packed context lanes bite the B=1 full forward (the fake-quant
    # inp/fmap roundtrip plus the packed-czrq gru kernels).
    "RAFT_LANE_PACK8": KnobProbe("1"),           # default OFF -> on
}

GEOMETRIES: Dict[str, Dict[str, int]] = {
    # bench.py headline defaults (RAFT_BENCH_H/W), 32 refinement iters,
    # segment length = valid_iters // segments with the serving defaults.
    "headline": dict(h=2016, w=2976, iters=32, seg_iters=8),
    "small": dict(h=256, w=320, iters=4, seg_iters=2),
}

#: Train-step trace geometry (shared by both registry geometries): the
#: donation/callback/constant invariants are geometry-independent and the
#: CPU lowering of the full value_and_grad program is the single most
#: expensive trace — keep it at a tiny crop.
TRAIN_GEOMETRY = dict(h=64, w=96, batch=1, iters=2)


@dataclasses.dataclass
class TraceEntry:
    """One traceable program.

    build: ``() -> (fn, args)`` — called by the runner inside the entry's
        env override window, so trace-time env reads see exactly ``env``.
    env: FULLY RESOLVED kernel-switch mapping (``None`` = unset) exported
        around the trace; also what cache keys are computed from.
    mixed_precision: GV101 applies (the program computes in bf16).
    build_lowered: when set, GV105 applies — ``() -> (stablehlo_text,
        donated_leaves)`` where ``donated_leaves`` is ``[(path, aval)]``
        in flattened argument order for the donated argnums.
    """

    name: str
    build: Callable[[], Tuple[Callable, Tuple]]
    env: Dict[str, Optional[str]]
    hot_path: str = "serve"
    mixed_precision: bool = False
    build_lowered: Optional[Callable[[], Tuple[str, List[Tuple[str, object]]]]] = None


@dataclasses.dataclass
class KnobFlip:
    """One GV102 knob probe: flipping ``knob`` to ``flip_value`` must
    change the traced program text IFF it changes the program-cache key.
    ``flipped`` is None when no probe is declared for a registered knob —
    itself a finding."""

    knob: str
    flip_value: Optional[str]
    base: TraceEntry
    flipped: Optional[TraceEntry]
    base_key: object = None
    flipped_key: object = None


@dataclasses.dataclass
class TraceRegistry:
    """Everything one graftverify run analyzes, plus its thresholds and
    table-level suppressions (trace findings have no source line to hang
    a comment on, so suppressions are ``(code, context) -> reason``
    entries here; a reasonless suppression is a GV000 finding, exactly
    like graftlint's reasonless inline disables)."""

    geometry: str
    entries: List[TraceEntry]
    ladder_variants: List[Tuple[str, TraceEntry]]
    knob_flips: List[KnobFlip]
    suppressions: Dict[Tuple[str, str], str] = dataclasses.field(
        default_factory=dict)
    gv101_min_elements: int = 4096
    gv104_const_bytes: int = 2 * 1024 * 1024

    def all_entries(self) -> List[TraceEntry]:
        seen: Dict[str, TraceEntry] = {}
        for e in self.entries:
            seen.setdefault(e.name, e)
        for _, e in self.ladder_variants:
            seen.setdefault(e.name, e)
        for kf in self.knob_flips:
            seen.setdefault(kf.base.name, kf.base)
            if kf.flipped is not None:
                seen.setdefault(kf.flipped.name, kf.flipped)
        return list(seen.values())


def default_registry(geometry: str = "headline") -> TraceRegistry:
    """The real tree's registry: six serving program kinds (the
    graftstream ``prepare_warm`` included) + the train step + the eval
    forward, with ladder/knob probes at headline."""
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.analysis.knobs import ENV_KNOBS
    from raft_stereo_tpu.config import RAFTStereoConfig, with_eval_precision
    from raft_stereo_tpu.models.raft_stereo import (init_raft_stereo,
                                                    raft_stereo_forward)
    from raft_stereo_tpu.serve.session import (build_program,
                                               config_fingerprint,
                                               resolve_env)

    if geometry not in GEOMETRIES:
        raise ValueError(f"unknown trace geometry {geometry!r} "
                         f"(have {sorted(GEOMETRIES)})")
    g = GEOMETRIES[geometry]

    # The bench headline config: reg_tpu corr + the shared eval bf16
    # policy (config.eval_mixed_precision) — what serving/eval actually
    # runs on TPU, kernels engaged.
    cfg_serve = with_eval_precision(
        RAFTStereoConfig(corr_implementation="reg_tpu"))
    # The reference eval config: plain XLA, fp32 (reg corr).
    cfg_eval = RAFTStereoConfig()
    # The analyzer's canonical env: every registered switch UNSET, i.e.
    # defaults — results never depend on the operator's live environment.
    base_env: Dict[str, Optional[str]] = {k: None for k in ENV_KNOBS}

    img = jax.ShapeDtypeStruct((1, g["h"], g["w"], 3), jnp.float32)

    @functools.lru_cache(maxsize=None)
    def params_spec():
        return jax.eval_shape(
            functools.partial(init_raft_stereo, cfg=cfg_serve),
            jax.random.PRNGKey(0))

    @functools.lru_cache(maxsize=None)
    def _state_spec(batch: int, lane8: str):
        prep = build_program("prepare", cfg_serve, 0)
        bimg = jax.ShapeDtypeStruct((batch, g["h"], g["w"], 3),
                                    jnp.float32)
        (state,) = jax.eval_shape(prep, params_spec(), bimg, bimg)
        return state

    def state_spec(batch: int = 1):
        # The refinement carry's structure, from the same prepare program
        # serving compiles (shape-only — eval_shape executes nothing).
        # The structure depends on RAFT_LANE_PACK8 (r24: packed context
        # containers ride the carry pytree), and builds run inside each
        # entry's env-override window — re-key the cache on the live
        # switch so an armed ladder trace never reuses a baseline spec.
        import os
        return _state_spec(batch, os.environ.get("RAFT_LANE_PACK8", ""))

    def serve_entry(name: str, kind: str, iters: int, *,
                    carry_input: bool) -> TraceEntry:
        def build(kind=kind, iters=iters, carry_input=carry_input):
            fn = build_program(kind, cfg_serve, iters)
            args = ((params_spec(), state_spec()) if carry_input
                    else (params_spec(), img, img))
            return fn, args
        return TraceEntry(name=name, build=build, env=dict(base_env),
                          hot_path="serve", mixed_precision=True)

    entries = [
        serve_entry("serve/full", "full", g["iters"], carry_input=False),
        serve_entry("serve/prepare", "prepare", 0, carry_input=False),
        serve_entry("serve/segment", "segment", g["seg_iters"],
                    carry_input=True),
        serve_entry("serve/advance", "advance", g["seg_iters"],
                    carry_input=True),
        serve_entry("serve/epilogue", "epilogue", 0, carry_input=True),
    ]

    # graftstream warm start (DESIGN.md r17): prepare_warm is a separate
    # program kind (extra x-only flow operand), so the GV checkers walk
    # it like every other serving program.
    def build_prep_warm():
        fn = build_program("prepare_warm", cfg_serve, 0)
        f = cfg_serve.downsample_factor
        flow = jax.ShapeDtypeStruct((1, g["h"] // f, g["w"] // f, 1),
                                    jnp.float32)
        return fn, (params_spec(), img, img, flow)
    entries.append(TraceEntry(name="serve/prepare_warm",
                              build=build_prep_warm, env=dict(base_env),
                              hot_path="serve", mixed_precision=True))

    def build_eval():
        def fwd(p, i1, i2):
            return raft_stereo_forward(p, cfg_eval, i1, i2,
                                       iters=g["iters"], test_mode=True)
        return fwd, (params_spec(), img, img)
    entries.append(TraceEntry(name="eval/forward", build=build_eval,
                              env=dict(base_env), hot_path="eval",
                              mixed_precision=False))

    entries.append(_train_entry(base_env))

    ladder_variants: List[Tuple[str, TraceEntry]] = []
    knob_flips: List[KnobFlip] = []
    if geometry == "headline":
        from raft_stereo_tpu.serve.guard import KernelCircuitBreaker
        breaker = KernelCircuitBreaker()
        names = [p.name for p in breaker.ladder]
        # The ladder walk traces a COMBINED program — the B=1 full
        # forward AND the b=2 continuous-batching advance — because since
        # r19 the ladder carries rungs that only bite on batched device
        # calls (stream_batch: B=1 engagement is unconditional by spec)
        # alongside rungs that only bite where encoders run (stream_tail
        # etc.: the advance program has no encoder half). One combined
        # jaxpr gives every rung a program text it provably changes, and
        # GV102's pairwise comparison logic applies unchanged. The walk's
        # base env additionally ARMS the opt-in pack paths
        # (RAFT_CORR_PACK8=1, RAFT_LANE_PACK8=1): an opt-in rung can only
        # be non-vacuous from an armed base — which is exactly the
        # operational state the rung exists to degrade from.
        ladder_base = resolve_env({"RAFT_CORR_PACK8": "1",
                                   "RAFT_LANE_PACK8": "1"}, base_env)

        def ladder_build(run_cfg):
            def build(run_cfg=run_cfg):
                full_fn = build_program("full", run_cfg, g["iters"])
                adv_fn = build_program("advance", run_cfg, g["seg_iters"])

                def combined(p, i1, i2, state2):
                    return full_fn(p, i1, i2), adv_fn(p, state2)
                return combined, (params_spec(), img, img, state_spec(2))
            return build

        ladder_variants.append(("untripped", TraceEntry(
            name="serve/full+advance@ladder:0:armed",
            build=ladder_build(cfg_serve), env=dict(ladder_base),
            hot_path="serve")))
        for k in range(1, len(names) + 1):
            run_cfg, env_over = breaker.apply(
                cfg_serve, tripped=tuple(names[:k]))
            env = resolve_env(env_over, ladder_base)
            ladder_variants.append((names[k - 1], TraceEntry(
                name=f"serve/full+advance@ladder:{k}:{names[k - 1]}",
                build=ladder_build(run_cfg), env=env, hot_path="serve")))

        def probe_build(kind: str, batch: int):
            def build(kind=kind, batch=batch):
                iters = g["seg_iters"] if kind in ("segment", "advance") \
                    else g["iters"]
                fn = build_program(kind, cfg_serve, iters)
                if kind in ("segment", "advance", "epilogue"):
                    return fn, (params_spec(), state_spec(batch))
                bimg = jax.ShapeDtypeStruct((batch, g["h"], g["w"], 3),
                                            jnp.float32)
                return fn, (params_spec(), bimg, bimg)
            return build

        probe_bases: Dict[Tuple, TraceEntry] = {
            ("full", 1, ()): entries[0]}
        for knob in ENV_KNOBS:
            probe = KNOB_FLIP_PROBES.get(knob)
            if probe is None:
                knob_flips.append(KnobFlip(knob, None, entries[0], None))
                continue
            bk = (probe.kind, probe.batch, probe.env)
            base_probe_env = resolve_env(dict(probe.env), base_env)
            if bk not in probe_bases:
                suffix = "".join(f"@{k}={v}" for k, v in probe.env)
                probe_bases[bk] = TraceEntry(
                    name=f"serve/{probe.kind}@b{probe.batch}{suffix}",
                    build=probe_build(probe.kind, probe.batch),
                    env=dict(base_probe_env), hot_path="serve")
            env = resolve_env({**dict(probe.env), knob: probe.flip},
                              base_env)
            knob_flips.append(KnobFlip(
                knob, probe.flip, probe_bases[bk],
                TraceEntry(name=f"serve/{probe.kind}@b{probe.batch}"
                                f"@knob:{knob}",
                           build=probe_build(probe.kind, probe.batch),
                           env=env, hot_path="serve"),
                base_key=config_fingerprint(cfg_serve, base_probe_env),
                flipped_key=config_fingerprint(cfg_serve, env)))

    return TraceRegistry(geometry=geometry, entries=entries,
                         ladder_variants=ladder_variants,
                         knob_flips=knob_flips)


def _train_entry(base_env: Dict[str, Optional[str]]) -> TraceEntry:
    """The real jitted train step (optimizer stack + donation included)."""
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.engine.optimizer import make_optimizer
    from raft_stereo_tpu.engine.steps import (TRAIN_STEP_DONATE,
                                              make_train_step)
    from raft_stereo_tpu.models.raft_stereo import init_raft_stereo

    tg = TRAIN_GEOMETRY
    cfg_train = RAFTStereoConfig()

    @functools.lru_cache(maxsize=None)
    def pieces():
        tx, _ = make_optimizer(0.0002, 100, skip_nonfinite=5)
        step = make_train_step(cfg_train, tx, train_iters=tg["iters"])
        pspec = jax.eval_shape(
            functools.partial(init_raft_stereo, cfg=cfg_train),
            jax.random.PRNGKey(0))
        ospec = jax.eval_shape(tx.init, pspec)
        b, h, w = tg["batch"], tg["h"], tg["w"]
        batch = {
            "image1": jax.ShapeDtypeStruct((b, h, w, 3), jnp.float32),
            "image2": jax.ShapeDtypeStruct((b, h, w, 3), jnp.float32),
            "flow": jax.ShapeDtypeStruct((b, h, w, 1), jnp.float32),
            "valid": jax.ShapeDtypeStruct((b, h, w), jnp.float32),
        }
        return step, pspec, ospec, batch

    def build():
        step, pspec, ospec, batch = pieces()
        return step, (pspec, ospec, batch)

    def build_lowered():
        step, pspec, ospec, batch = pieces()
        donated_specs = (pspec, ospec)
        assert TRAIN_STEP_DONATE == tuple(range(len(donated_specs))), \
            "GV105's donated-leaf bookkeeping assumes donate_argnums " \
            "covers a leading prefix of the step arguments"
        leaves = jax.tree_util.tree_flatten_with_path(donated_specs)[0]
        return (step.lower(pspec, ospec, batch).as_text(),
                [(jax.tree_util.keystr(p), v) for p, v in leaves])

    return TraceEntry(name="train/step", build=build, env=dict(base_env),
                      hot_path="train", mixed_precision=False,
                      build_lowered=build_lowered)

"""graftverify — trace-level (jaxpr/StableHLO) program analysis.

graftlint (``analysis/checkers/``) proves source-level invariants; the
costliest regressions live one level down, in the traced program: a
silent bf16→fp32 upcast inside the refinement scan, a breaker rung whose
"fallback" compiles to the identical HLO, a closure-captured array baked
into the jaxpr as a multi-MB constant, a train step whose donation is
silently dropped by an aliasing change. This package traces the repo's
REAL entry points (the serving program kinds from ``serve/session.py``
``build_program``, the train step, the eval forward) at pinned shapes via
``jax.eval_shape`` / ``jax.make_jaxpr`` / ``.lower()`` on CPU — no TPU,
no execution — and walks the resulting jaxprs with the GV-series checker
suite (DESIGN.md "Trace-level analysis (r10)"):

GV101  bf16→fp32 upcast in a scan body outside the accumulator set
GV102  breaker-ladder rung vacuity + env-knob cache-key sufficiency
GV103  host callback / debug effect in a hot-path program
GV104  baked-in constant above the bloat threshold
GV105  train-step donation not honored by the lowered aliasing

Unlike the rest of ``analysis/`` this package imports jax — it is loaded
ONLY under ``python -m raft_stereo_tpu.analysis --trace`` (or direct
import); ``analysis/__init__`` stays import-light so the AST linter and
the knob registry keep working without jax.
"""

from raft_stereo_tpu.analysis.trace.registry import (  # noqa: F401
    KnobFlip, TraceEntry, TraceRegistry, default_registry)
from raft_stereo_tpu.analysis.trace.runner import (  # noqa: F401
    TraceContext, run_trace_analysis)

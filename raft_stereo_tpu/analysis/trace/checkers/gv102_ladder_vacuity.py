"""GV102 — breaker-ladder vacuity + env-knob cache-key sufficiency.

Two halves of one invariant: *every degree of freedom the serving layer
believes in must actually exist in the traced program, and every degree
of freedom in the traced program must exist in the cache key.*

Ladder half: each rung of ``serve/guard.py``'s ``DEFAULT_LADDER``, when
tripped on top of its predecessors, must produce a DIFFERENT program text
at the declared geometry. A vacuous rung means the breaker "falls back"
to the identical program — the retry after a trip re-runs the exact
failure, the ladder walks to exhaustion, and the session dies where it
was designed to degrade (PR 3's whole point, previously only
pattern-matched by GL006's env-consultation check).

Knob half: flipping each registered ``ENV_KNOBS`` entry (with its
declared probe value) must change the traced program text IFF it changes
the program-cache key:

- program changed, key unchanged -> THE stale-program class (two switch
  values silently share one compiled program);
- key changed, program unchanged -> the registry/probe is dishonest at
  the geometry where this knob claims to matter (either the knob is dead
  or the probe is wrong — both need a human);
- neither changed -> a dead registry entry (not keyed, not consulted).
"""

from __future__ import annotations

from typing import Iterator

from raft_stereo_tpu.analysis.core import Finding
from raft_stereo_tpu.analysis.trace.runner import TraceChecker, TraceContext


class LadderVacuityChecker(TraceChecker):
    code = "GV102"
    name = "ladder-vacuity"
    description = ("breaker rung producing an identical program to its "
                   "predecessor / env knob whose program and cache-key "
                   "effects disagree")

    def check(self, ctx: TraceContext) -> Iterator[Finding]:
        # PAIRWISE, not just adjacent: rung k's projection cancelling rung
        # k-1's (variant k == variant k-2 while both adjacent pairs
        # differ) would still mean two cumulative trip sets share one
        # program. All texts are cached in ctx, so the extra comparisons
        # are string equality only.
        variants = ctx.registry.ladder_variants
        for j, (label, cur) in enumerate(variants[1:], start=1):
            cur_text = ctx.text(cur)
            if cur_text is None:
                continue  # trace failure already reported as GV000
            for i in range(j):
                prev_label, prev = variants[i]
                prev_text = ctx.text(prev)
                if prev_text is None or prev_text != cur_text:
                    continue
                how = ("its predecessor" if i == j - 1
                       else f"the earlier trip set through {prev_label!r}")
                yield self.finding(
                    f"ladder:{label}",
                    f"tripping rung {label!r} produces a program "
                    f"IDENTICAL to {how} at {ctx.registry.geometry} "
                    "geometry — the fallback is vacuous: a breaker trip "
                    "would re-run a program that already failed")
                break  # one finding per rung is enough

        for kf in ctx.registry.knob_flips:
            if kf.flipped is None:
                yield self.finding(
                    f"knob:{kf.knob}",
                    f"env knob {kf.knob!r} is registered in ENV_KNOBS but "
                    "has no flip probe in KNOB_FLIP_PROBES "
                    "(analysis/trace/registry.py) — declare a value that "
                    "provably changes the program so GV102 can keep "
                    "proving the cache key covers it")
                continue
            base_text, flip_text = ctx.text(kf.base), ctx.text(kf.flipped)
            if base_text is None or flip_text is None:
                continue
            program_changed = base_text != flip_text
            key_changed = kf.base_key != kf.flipped_key
            if program_changed and not key_changed:
                yield self.finding(
                    f"knob:{kf.knob}",
                    f"flipping {kf.knob}={kf.flip_value!r} CHANGES the "
                    "traced program but NOT the program-cache key — the "
                    "stale-program class: requests under different switch "
                    "values would share one compiled program (fold the "
                    "knob into config_fingerprint / ENV_KNOBS)")
            elif key_changed and not program_changed:
                yield self.finding(
                    f"knob:{kf.knob}",
                    f"flipping {kf.knob}={kf.flip_value!r} changes the "
                    "cache key but NOT the traced program at "
                    f"{ctx.registry.geometry} geometry — dead cache-key "
                    "bloat or a wrong probe value; fix the probe "
                    "(KNOB_FLIP_PROBES) or justify the registry entry")
            elif not key_changed and not program_changed:
                yield self.finding(
                    f"knob:{kf.knob}",
                    f"flipping {kf.knob}={kf.flip_value!r} changes "
                    "neither the program nor the cache key — a dead "
                    "registry entry (or the knob is no longer consulted "
                    "anywhere the trace can see)")

"""GV105 — donation integrity: the lowered train step really aliases.

``engine/steps.py`` donates ``(params, opt_state)``
(``TRAIN_STEP_DONATE``) so the optimizer update runs HBM-flat — without
it, peak memory holds params+opt_state TWICE (~2x Adam state for an 11M
-param model is survivable; for the batch-6 full-res finetune configs it
is the difference between fitting and OOM). Donation is a *request*:
XLA honors it only when the aliasing survives lowering, and a refactor
that reorders outputs, changes a dtype, or routes a donated buffer into
a secondary output silently drops it. Nothing fails — training just
quietly needs more HBM.

The check reads the lowered StableHLO's ``tf.aliasing_output`` arg
attributes — the compiler-facing truth — and requires every non-scalar
donated leaf to carry one. Rank-0 leaves (schedule/skip counters) are
exempt: identical scalars legitimately share buffers and XLA picks one
winner per buffer.
"""

from __future__ import annotations

from typing import Iterator

from raft_stereo_tpu.analysis.core import Finding
from raft_stereo_tpu.analysis.trace.runner import TraceChecker, TraceContext


class DonationChecker(TraceChecker):
    code = "GV105"
    name = "donation-integrity"
    description = ("donated train-step input without input-output "
                   "aliasing in the lowered program")

    def check(self, ctx: TraceContext) -> Iterator[Finding]:
        # Deferred: jaxprs imports jax; --list-checkers must not.
        from raft_stereo_tpu.analysis.trace.jaxprs import \
            aliased_arg_indices
        for entry in ctx.registry.entries:
            if entry.build_lowered is None:
                continue
            lowered = ctx.lowered(entry)
            if lowered is None:
                continue  # failure already reported as GV000
            text, donated_leaves = lowered
            aliased = aliased_arg_indices(text)
            if aliased is None:
                yield self.finding(
                    entry.name,
                    "lowered module has no public @main function — "
                    "cannot verify donation aliasing")
                continue
            missing = [
                (i, path, aval)
                for i, (path, aval) in enumerate(donated_leaves)
                if i not in aliased and getattr(aval, "ndim", 0) > 0]
            if not missing:
                continue
            sample = ", ".join(
                f"{path} {tuple(aval.shape)}"
                for _, path, aval in missing[:4])
            yield self.finding(
                entry.name,
                f"{len(missing)} of {len(donated_leaves)} donated "
                "(params, opt_state) leaves have NO input-output aliasing "
                f"in the lowered program (first: {sample}) — donation is "
                "being dropped and peak HBM grows by the unaliased "
                "bytes; check donate_argnums (engine/steps.py "
                "TRAIN_STEP_DONATE) and that outputs still mirror inputs")

"""GV103 — no host callbacks / debug effects in hot-path programs.

``jax.debug.print``, ``pure_callback`` and friends are invaluable while
debugging and catastrophic when they ship: each one is a device->host
round trip per invocation (per ITERATION when it lands in the scan body),
serializes dispatch, and on TPU forces the program into a
host-synchronized mode. None of the serving/train/eval hot paths has any
business talking to the host mid-program — the serving layer's host
fetches happen between programs, by design (DESIGN.md r7).

A debug print left in a kernel is the classic escape: it survives every
numeric test (outputs are identical) and shows up only as a mysterious
2-10x slowdown in the next bench run.
"""

from __future__ import annotations

from typing import Iterator

from raft_stereo_tpu.analysis.core import Finding
from raft_stereo_tpu.analysis.trace.runner import TraceChecker, TraceContext


class HostCallbackChecker(TraceChecker):
    code = "GV103"
    name = "host-callbacks"
    description = ("host callback / debug-print primitive or effect in a "
                   "hot-path program")

    def check(self, ctx: TraceContext) -> Iterator[Finding]:
        # Deferred: jaxprs imports jax; --list-checkers must not.
        from raft_stereo_tpu.analysis.trace.jaxprs import (
            effect_names, host_callback_sites)
        # all_entries(): ladder-variant and knob-probe programs included —
        # the fallback program serving runs AFTER a breaker trip is a hot
        # path too (a debug print only in the plain-XLA branch must not
        # hide behind the untripped default).
        for entry in ctx.registry.all_entries():
            closed = ctx.jaxpr(entry)
            if closed is None:
                continue
            for prim, in_pallas in host_callback_sites(closed):
                where = "a pallas kernel body" if in_pallas \
                    else "the traced program"
                yield self.finding(
                    entry.name,
                    f"host-callback primitive {prim!r} in {where} — a "
                    "device->host round trip on the hot path (per "
                    "iteration if inside the scan); remove it or move the "
                    "host work between programs")
            for eff in effect_names(closed):
                yield self.finding(
                    entry.name,
                    f"jaxpr carries host-facing effect {eff} — same "
                    "class as a callback primitive (forces host "
                    "synchronization), even if no callback eqn is "
                    "visible at this level")

"""GV-series trace checkers. Registration order = code order."""

from raft_stereo_tpu.analysis.trace.checkers.gv101_dtype_discipline import \
    DtypeDisciplineChecker
from raft_stereo_tpu.analysis.trace.checkers.gv102_ladder_vacuity import \
    LadderVacuityChecker
from raft_stereo_tpu.analysis.trace.checkers.gv103_host_callbacks import \
    HostCallbackChecker
from raft_stereo_tpu.analysis.trace.checkers.gv104_constant_bloat import \
    ConstantBloatChecker
from raft_stereo_tpu.analysis.trace.checkers.gv105_donation import \
    DonationChecker

ALL_TRACE_CHECKERS = (
    DtypeDisciplineChecker,
    LadderVacuityChecker,
    HostCallbackChecker,
    ConstantBloatChecker,
    DonationChecker,
)

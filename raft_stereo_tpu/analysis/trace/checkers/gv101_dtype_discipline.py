"""GV101 — dtype discipline inside scan bodies.

Under the eval bf16 policy (``config.eval_mixed_precision``) the
refinement scan body must compute in bf16: a silent ``convert_element_type
-> f32`` on a big tensor inside the body doubles that tensor's HBM
traffic and flips its ops onto the fp32 MXU path — ``iters`` times per
frame. The r4/r5 perf work (BASELINE.md) exists precisely because these
casts are invisible to every numeric test (fp32 is MORE accurate) and to
AST lint (the ``.astype`` may be far from the scan).

Allowed upcasts — the accumulator set:

- a convert whose result reaches an **fp32 scan carry** through an
  fp32-only path (the epipolar delta-flow feeding the ``coords1``
  accumulator);
- a convert whose result feeds **reduction-class primitives** through at
  most a couple of elementwise glue ops (instance-norm moments, pooling
  sums — fp32 accumulation over bf16 maps is the sanctioned pattern,
  ops/basic.py:105);
- anything inside a ``pallas_call`` kernel body (in-kernel fp32
  accumulation with in-kernel downcast is the kernels' design).

Everything else is a finding.
"""

from __future__ import annotations

from typing import Iterator

from raft_stereo_tpu.analysis.core import Finding
from raft_stereo_tpu.analysis.trace.runner import TraceChecker, TraceContext


class DtypeDisciplineChecker(TraceChecker):
    code = "GV101"
    name = "dtype-discipline"
    description = ("bf16->f32 upcast inside a scan body outside the "
                   "allowlisted accumulator set (mixed-precision entries)")

    def check(self, ctx: TraceContext) -> Iterator[Finding]:
        # Deferred: jaxprs imports jax; --list-checkers must not.
        from raft_stereo_tpu.analysis.trace.jaxprs import (
            iter_scans, offending_upcasts)
        min_el = ctx.registry.gv101_min_elements
        for entry in ctx.registry.entries:
            if not entry.mixed_precision:
                continue
            closed = ctx.jaxpr(entry)
            if closed is None:
                continue
            for scan_eqn in iter_scans(closed.jaxpr):
                for shape, why in offending_upcasts(scan_eqn,
                                                    min_elements=min_el):
                    yield self.finding(
                        entry.name,
                        f"bf16->f32 upcast of a {shape} tensor inside a "
                        f"scan body: {why} — this is fp32 COMPUTE paid "
                        "every iteration, not fp32 accumulation; keep the "
                        "map in bf16 (accumulate via "
                        "preferred_element_type or a reduction) or add a "
                        "registry suppression with the measured "
                        "justification")

"""GV104 — constant bloat: no multi-MB arrays baked into a program.

A closure-captured concrete array becomes a jaxpr CONSTANT: it is
embedded in every compiled executable that traces it, uploaded per
program (not per session), multiplied across the serving cache's shape x
batch x fingerprint grid, and silently re-materialized on every breaker
rebuild. The correct form is an ARGUMENT (weights live in the params
pytree; grids/iota are generated on device). The classic source: a helper
that computes ``np.something(shape)`` at trace time instead of
``jnp``-on-tracer, or a debugging snapshot captured by a closure.

Threshold: ``TraceRegistry.gv104_const_bytes`` (default 2 MiB) — small
trace-time constants (lerp index vectors, per-block kernel tables) are
the idiom and stay invisible.
"""

from __future__ import annotations

from typing import Iterator

from raft_stereo_tpu.analysis.core import Finding
from raft_stereo_tpu.analysis.trace.runner import TraceChecker, TraceContext


class ConstantBloatChecker(TraceChecker):
    code = "GV104"
    name = "constant-bloat"
    description = "baked-in jaxpr constant above the byte threshold"

    def check(self, ctx: TraceContext) -> Iterator[Finding]:
        # Deferred: jaxprs imports jax; --list-checkers must not.
        from raft_stereo_tpu.analysis.trace.jaxprs import baked_consts
        limit = ctx.registry.gv104_const_bytes
        # all_entries(): tripped-ladder and knob-probe programs count too —
        # a constant baked only into a fallback program still ships.
        for entry in ctx.registry.all_entries():
            closed = ctx.jaxpr(entry)
            if closed is None:
                continue
            for shape, dtype, nbytes in baked_consts(closed):
                if nbytes <= limit:
                    continue
                yield self.finding(
                    entry.name,
                    f"program bakes in a {shape} {dtype} constant "
                    f"({nbytes / 2**20:.1f} MiB > "
                    f"{limit / 2**20:.1f} MiB limit) — embedded per "
                    "compiled executable across the whole program cache; "
                    "pass it as an argument or build it on device from "
                    "tracers")

"""Jaxpr/StableHLO walking utilities shared by the GV checkers.

Everything here operates on already-traced ``ClosedJaxpr`` objects (or
lowered module text) — tracing itself lives in the runner so a trace
failure is a GV000 finding, not a crash inside a checker.
"""

from __future__ import annotations

import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from jax._src import core as _jcore

ClosedJaxpr = _jcore.ClosedJaxpr
Jaxpr = _jcore.Jaxpr
Var = _jcore.Var

_ADDR_RE = re.compile(r"0x[0-9a-f]+")


def scrubbed_text(closed: ClosedJaxpr) -> str:
    """Deterministic program text: ``str(jaxpr)`` with memory addresses
    scrubbed. Two traces of the same program yield identical text (var
    naming is deterministic); the only nondeterminism is object reprs in
    eqn params (``<... at 0x7f..>``), which the scrub removes — verified
    by ``tests/test_trace_analysis.py::test_text_deterministic``."""
    return _ADDR_RE.sub("0xX", str(closed))


def sub_jaxprs(params: Dict) -> Iterator[Jaxpr]:
    """Raw sub-jaxprs held in one eqn's params (pjit/scan/cond/custom_*/
    pallas all stash theirs under different keys and container shapes)."""
    for v in params.values():
        if isinstance(v, ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                if isinstance(x, ClosedJaxpr):
                    yield x.jaxpr
                elif isinstance(x, Jaxpr):
                    yield x


def sub_closed_jaxprs(params: Dict) -> Iterator[ClosedJaxpr]:
    """Like :func:`sub_jaxprs` but only the CLOSED ones (the carriers of
    baked-in consts — GV104's quarry)."""
    for v in params.values():
        if isinstance(v, ClosedJaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                if isinstance(x, ClosedJaxpr):
                    yield x


def walk_eqns(jaxpr: Jaxpr, *, in_pallas: bool = False
              ) -> Iterator[Tuple[_jcore.JaxprEqn, bool]]:
    """Every eqn at every depth, tagged with whether it executes inside a
    ``pallas_call`` kernel body."""
    for eqn in jaxpr.eqns:
        yield eqn, in_pallas
        child_in_pallas = in_pallas or eqn.primitive.name == "pallas_call"
        for sub in sub_jaxprs(eqn.params):
            yield from walk_eqns(sub, in_pallas=child_in_pallas)


def iter_scans(jaxpr: Jaxpr) -> Iterator[_jcore.JaxprEqn]:
    """Every ``scan`` eqn at any depth OUTSIDE pallas kernels (lax.scan
    and lax.map both lower to it)."""
    for eqn, in_pallas in walk_eqns(jaxpr):
        if not in_pallas and eqn.primitive.name == "scan":
            yield eqn


# -- GV101: dtype discipline inside scan bodies -----------------------------

#: Elementwise/shape glue a legal fp32-statistics upcast may pass through
#: on its way to a reduction (instance norm: convert -> square -> mean).
_ELEMENTWISE_GLUE = frozenset({
    "mul", "add", "sub", "div", "neg", "integer_pow", "square", "abs",
    "max", "min", "reshape", "squeeze", "expand_dims", "broadcast_in_dim",
    "transpose", "convert_element_type",
})

#: Reduction-class primitives: an upcast whose value is consumed by one of
#: these is fp32 ACCUMULATION — the whole point of mixed-precision
#: discipline is that sums accumulate in fp32 while maps stay bf16.
_REDUCTIONS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_window_sum", "reduce_window_max", "reduce_window_min",
    "argmax", "argmin", "reduce_and", "reduce_or", "reduce_precision",
})


def _uses(jaxpr: Jaxpr) -> Dict[Var, List[_jcore.JaxprEqn]]:
    out: Dict[Var, List[_jcore.JaxprEqn]] = {}
    for eqn in jaxpr.eqns:
        for iv in eqn.invars:
            if isinstance(iv, Var):
                out.setdefault(iv, []).append(eqn)
    return out


def _f32_sink_vars(jaxpr: Jaxpr, allowed_outs: Sequence[Var]) -> Set[Var]:
    """Vars from which an allowed fp32 output is reachable through an
    ALL-fp32 path: walk backward from the allowed outputs, refusing to
    cross any ``convert_element_type`` (an upcast is the boundary where
    fp32 accumulation begins; a downcast ends it). A bf16→f32 convert is a
    legal accumulator feed iff its OUTPUT var lands in this set."""
    sinks: Set[Var] = {v for v in allowed_outs if isinstance(v, Var)}
    changed = True
    while changed:
        changed = False
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "convert_element_type":
                continue  # converts never extend an fp32-only path
            if any(ov in sinks for ov in eqn.outvars):
                for iv in eqn.invars:
                    if isinstance(iv, Var) and iv not in sinks and \
                            str(iv.aval.dtype) == "float32":
                        sinks.add(iv)
                        changed = True
    return sinks


def _feeds_reduction(start: Var, uses: Dict[Var, List[_jcore.JaxprEqn]],
                     depth: int = 3) -> bool:
    """True when EVERY consumer path from ``start`` reaches a
    reduction-class primitive within ``depth`` hops of elementwise glue —
    the fp32-statistics pattern (norm moments, pooling sums). A consumer
    that is neither glue nor a reduction (a conv, a gather, a downcast
    back to bf16) disqualifies immediately: that is fp32 COMPUTE, not
    fp32 accumulation."""
    consumers = uses.get(start, [])
    if not consumers:
        return False
    for eqn in consumers:
        nm = eqn.primitive.name
        if nm in _REDUCTIONS:
            continue
        if nm in _ELEMENTWISE_GLUE and nm != "convert_element_type":
            if depth <= 0:
                return False
            if not all(_feeds_reduction(ov, uses, depth - 1)
                       for ov in eqn.outvars if isinstance(ov, Var)):
                return False
            continue
        return False
    return True


def offending_upcasts(scan_eqn: _jcore.JaxprEqn, *, min_elements: int
                      ) -> List[Tuple[Tuple[int, ...], str]]:
    """bf16→f32 converts in a scan body that are NEITHER fp32-carry
    accumulator feeds NOR fp32-statistics reductions.

    Returns ``(operand_shape, why)`` per offender. Analysis covers the
    body's direct eqns plus nested non-pallas sub-jaxprs (each level
    analyzed against its own fp32 outputs); pallas kernel bodies are
    exempt by design — their in-kernel fp32 accumulation with in-kernel
    downcast IS the sanctioned pattern (DESIGN.md r5/r6).
    """
    body = scan_eqn.params["jaxpr"].jaxpr
    num_carry = scan_eqn.params["num_carry"]
    f32_carries = [v for v in body.outvars[:num_carry]
                   if str(v.aval.dtype) == "float32"]
    out: List[Tuple[Tuple[int, ...], str]] = []

    def check_level(jaxpr: Jaxpr, allowed_outs: Sequence[Var]) -> None:
        uses = _uses(jaxpr)
        sinks = _f32_sink_vars(jaxpr, allowed_outs)
        for eqn in jaxpr.eqns:
            nm = eqn.primitive.name
            if nm == "convert_element_type":
                op = eqn.invars[0]
                if not isinstance(op, Var):
                    continue
                if str(op.aval.dtype) != "bfloat16" or \
                        str(eqn.outvars[0].aval.dtype) != "float32":
                    continue
                if op.aval.size < min_elements:
                    continue
                ov = eqn.outvars[0]
                if ov in sinks:
                    continue  # fp32 accumulator feed (e.g. the epipolar
                    # delta-flow into the coords carry)
                if _feeds_reduction(ov, uses):
                    continue  # fp32 statistics (norm moments, pool sums)
                out.append((tuple(op.aval.shape),
                            "result neither reaches an fp32 carry on an "
                            "fp32-only path nor feeds a reduction"))
            elif nm != "pallas_call":
                for sub in sub_jaxprs(eqn.params):
                    # Nested levels: any fp32 output of the sub-jaxpr is
                    # an allowed sink (conservative — the outer level
                    # already constrains where those outputs may go).
                    check_level(sub, [v for v in sub.outvars
                                      if isinstance(v, Var) and
                                      str(v.aval.dtype) == "float32"])

    check_level(body, f32_carries)
    return out


# -- GV103: host callbacks --------------------------------------------------

_CALLBACK_PRIM_NAMES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "debug_print",
    "outside_call", "host_callback_call",
})


def host_callback_sites(closed: ClosedJaxpr) -> List[Tuple[str, bool]]:
    """``(primitive_name, in_pallas)`` for every host-callback/debug
    primitive anywhere in the program (pallas kernels included — a
    ``pl.debug_print`` in a hot-path kernel serializes the grid)."""
    out = []
    for eqn, in_pallas in walk_eqns(closed.jaxpr):
        nm = eqn.primitive.name
        if nm in _CALLBACK_PRIM_NAMES or nm.endswith("_callback"):
            out.append((nm, in_pallas))
    return out


def effect_names(closed: ClosedJaxpr) -> List[str]:
    """Names of jaxpr-level effects that imply host round trips."""
    out = []
    for eff in getattr(closed, "effects", ()) or ():
        nm = type(eff).__name__
        if any(t in nm for t in ("Callback", "Debug", "IO", "Print")):
            out.append(nm)
    return sorted(out)


# -- GV104: baked-in constants ----------------------------------------------

def baked_consts(closed: ClosedJaxpr) -> List[Tuple[Tuple[int, ...], str, int]]:
    """``(shape, dtype, nbytes)`` of every constant baked into the program
    (top-level consts plus every nested closed sub-jaxpr's), deduped by
    object identity."""
    seen: Set[int] = set()
    out: List[Tuple[Tuple[int, ...], str, int]] = []

    def visit(cj: ClosedJaxpr) -> None:
        for c in cj.consts:
            if id(c) in seen:
                continue
            seen.add(id(c))
            nbytes = getattr(c, "nbytes", None)
            if nbytes is None:
                continue
            out.append((tuple(getattr(c, "shape", ())),
                        str(getattr(c, "dtype", "?")), int(nbytes)))
        for eqn in cj.jaxpr.eqns:
            for sub in sub_closed_jaxprs(eqn.params):
                visit(sub)

    visit(closed)
    return out


# -- GV105: lowered input-output aliasing -----------------------------------

_MAIN_SIG_RE = re.compile(r"func\.func public @main\((.*?)\)\s*->", re.S)
_ARG_RE = re.compile(r"%arg(\d+): tensor<[^>]*>\s*(\{[^{}]*\})?")


def aliased_arg_indices(lowered_text: str) -> Optional[Set[int]]:
    """Indices of @main args carrying a ``tf.aliasing_output`` attribute
    in the lowered StableHLO module — the lowering-level truth of buffer
    donation. None when no public @main is found (caller reports GV000)."""
    m = _MAIN_SIG_RE.search(lowered_text)
    if m is None:
        return None
    out: Set[int] = set()
    for idx, attrs in _ARG_RE.findall(m.group(1)):
        if attrs and "tf.aliasing_output" in attrs:
            out.add(int(idx))
    return out

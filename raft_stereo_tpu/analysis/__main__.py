import sys

from raft_stereo_tpu.analysis.cli import main

sys.exit(main())

"""graftlock — the concurrency contract suite (GC201-GC206).

Third static-analysis stage beside graftlint (AST, GL) and graftverify
(trace, GV): builds one :class:`LockModel` over the full file set, runs
the six GC checkers through the shared :func:`run_checkers` runner
(same suppression/stale/meta semantics, meta code GC200), and owns the
``LOCK_ORDER.md`` manifest ceremony.  Stdlib-only, like the GL stage.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Set

from raft_stereo_tpu.analysis.concurrency.checkers import \
    ALL_CONCURRENCY_CHECKERS
from raft_stereo_tpu.analysis.concurrency.graph import (MANIFEST_NAME,
                                                        build_lock_graph,
                                                        render_manifest)
from raft_stereo_tpu.analysis.concurrency.model import LockModel
from raft_stereo_tpu.analysis.core import (CONCURRENCY_META_CODE,
                                           META_CODES, Project, Report,
                                           collect_files, run_checkers)


def build_concurrency_report(project: Project, *,
                             manifest_text: Optional[str] = None,
                             check_manifest: bool = True,
                             emit_file_meta: bool = True) -> Report:
    """Run GC201-GC206 over an already-built project."""
    model = LockModel(project)
    checkers = [cls(model, manifest_text=manifest_text,
                    check_manifest=check_manifest)
                for cls in ALL_CONCURRENCY_CHECKERS]
    return run_checkers(project, checkers,
                        meta_code=CONCURRENCY_META_CODE,
                        emit_file_meta=emit_file_meta,
                        stale_prefix="GC")


def run_concurrency_analysis(roots: Sequence[str], *,
                             base: Optional[str] = None,
                             manifest_path: Optional[str] = None,
                             check_manifest: bool = True,
                             emit_file_meta: bool = True,
                             select: Optional[Sequence[str]] = None,
                             only_paths: Optional[Set[str]] = None
                             ) -> Report:
    """Analyze ``roots`` with the GC suite end to end.

    manifest_path: the committed ``LOCK_ORDER.md`` to check against
        (default: ``<base>/LOCK_ORDER.md``); a missing file is a GC201
        finding unless ``check_manifest`` is off.
    emit_file_meta: False when this report merges into an AST-stage
        report that already carries parse-error/reasonless-suppression
        findings (they must not appear twice).
    """
    files = collect_files(roots, base=base)
    project = Project(files)
    manifest_text = _read_manifest(manifest_path, base, roots)
    report = build_concurrency_report(project,
                                      manifest_text=manifest_text,
                                      check_manifest=check_manifest,
                                      emit_file_meta=emit_file_meta)
    by_rel = {sf.relpath: sf.abspath for sf in files}

    def keep(f) -> bool:
        if select is not None and f.code not in META_CODES and \
                f.code not in select:
            return False
        if only_paths is not None and f.path != MANIFEST_NAME and \
                by_rel.get(f.path) not in only_paths:
            return False
        return True
    return Report([f for f in report.findings if keep(f)],
                  [f for f in report.suppressed if keep(f)],
                  report.files_analyzed)


def write_lock_order_manifest(roots: Sequence[str], *,
                              base: Optional[str] = None,
                              manifest_path: Optional[str] = None) -> str:
    """Regenerate ``LOCK_ORDER.md`` from the tree; returns the path."""
    files = collect_files(roots, base=base)
    model = LockModel(Project(files))
    text = render_manifest(build_lock_graph(model))
    path = manifest_path or os.path.join(
        _manifest_base(base, roots), MANIFEST_NAME)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(text)
    return path


def _manifest_base(base: Optional[str], roots: Sequence[str]) -> str:
    if base:
        return os.path.abspath(base)
    root = os.path.abspath(roots[0]) if roots else os.getcwd()
    return root if os.path.isdir(root) else os.path.dirname(root)


def _read_manifest(manifest_path: Optional[str], base: Optional[str],
                   roots: Sequence[str]) -> Optional[str]:
    path = manifest_path or os.path.join(_manifest_base(base, roots),
                                         MANIFEST_NAME)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return fh.read()
    except OSError:
        return None


__all__ = ["ALL_CONCURRENCY_CHECKERS", "LockModel", "MANIFEST_NAME",
           "build_concurrency_report", "run_concurrency_analysis",
           "write_lock_order_manifest"]

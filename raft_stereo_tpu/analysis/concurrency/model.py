"""graftlock shared model: lock declarations, held-lock stacks, and the
call-graph propagation every GC checker and the lock-order graph build
on.  Stdlib-only, AST-only — the concurrency stage must stay as fast and
environment-independent as the GL stage it rides beside.

The model is deliberately lexical-plus-one-calls-layer:

- a lock NODE is a declaration site — ``self._x = threading.Lock()``
  inside a class (node ``path::Class._x``) or a module-level
  ``_x = threading.Lock()`` (node ``path::_x``).  Locks minted
  dynamically (``setdefault(key, threading.Lock())`` per-key maps) have
  no stable identity and stay outside the model; the runtime witness
  skips them for the same reason.
- the HELD STACK at an AST node is the ordered chain of ``with <lock>``
  items between the node and its enclosing function def.  Nested
  function defs reset the stack: a closure handed to a Thread runs on a
  thread that holds nothing.
- a ``try: ... finally: <lock>.release()`` region counts as holding
  the released lock — the manual ``acquire(blocking=False)`` idiom the
  watchdog's one-sweep-at-a-time gate uses is a real held region even
  though no ``with`` appears.
- calls that resolve inside the repo (``self.m()``, ``self._attr.m()``
  through the attr→class map, module functions, cross-module functions)
  are edges in a call graph; :func:`propagate_entry_contexts` pushes
  held sets through it so a helper only ever called under a lock is
  analyzed as holding that lock (the GL004→GC205 upgrade: cross-file,
  not single-class).
- receivers this repo leaves unannotated (``self._clock``, a local
  ``reg``) resolve DUCK-TYPED: a method name defined by at most
  :data:`DUCK_MAX_CANDIDATES` repo classes resolves to ALL of them —
  the runtime witness proved these chains produce real lock edges, so
  over-approximating a small tie beats dropping the edge.  Names every
  container/stdlib object also carries (:data:`DUCK_DENYLIST`) never
  duck-resolve, and neither do calls whose receiver is an imported
  module.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from raft_stereo_tpu.analysis.core import Project, SourceFile, parent

#: threading factory tails that mint a lock-like object.  Condition is a
#: lock for ordering purposes (``with cond:`` acquires its inner lock).
LOCK_FACTORY_TAILS = ("Lock", "RLock", "Condition")

#: duck-typed call resolution: a method name defined by more classes
#: than this stays unresolved (a 2-3-way tie like FakeClock/RealClock
#: ``now`` is fine — lock-free candidates contribute nothing).
DUCK_MAX_CANDIDATES = 3

#: method names too generic to duck-resolve — every queue/dict/file/
#: Future/Thread carries them, so a small repo-class tie would hijack
#: stdlib calls and fabricate held-context propagation.
DUCK_DENYLIST = frozenset({
    "get", "put", "put_nowait", "pop", "append", "add", "remove",
    "items", "keys", "values", "update", "copy", "clear", "setdefault",
    "join", "start", "stop", "run", "close", "open", "read", "write",
    "send", "recv", "sleep", "acquire", "release", "wait", "notify",
    "notify_all", "result", "set", "set_result", "set_exception",
    "cancel", "submit", "flush", "next", "reset",
})


@dataclasses.dataclass(frozen=True)
class LockDecl:
    """One statically-declared lock."""

    key: str        # "pkg/serve/fleet.py::Fleet._lock" | "pkg/native/__init__.py::_lock"
    relpath: str
    owner: str      # class name, or "" for module-level locks
    attr: str
    kind: str       # "lock" | "rlock" | "condition"
    lineno: int     # first line of the creating assignment
    end_lineno: int  # last line (witness creation-site match is a range)


def lexical_nodes(fn: ast.AST):
    """Descendants of ``fn`` excluding nested function/lambda bodies — a
    closure's statements run on some other thread at some other time, so
    lexical analyses must not attribute them to the enclosing frame."""
    todo = list(ast.iter_child_nodes(fn))
    while todo:
        node = todo.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        todo.extend(ast.iter_child_nodes(node))


def _self_attr_chain(expr: ast.expr) -> Optional[List[str]]:
    """``self.a.b.c`` -> ["a", "b", "c"]; None when not rooted at self."""
    parts: List[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name) and cur.id == "self":
        return list(reversed(parts))
    return None


@dataclasses.dataclass(frozen=True)
class CallSite:
    node: ast.Call
    stack: Tuple[str, ...]            # lexically-held lock keys, outer→inner
    #: in-repo resolution candidates (relpath, class|"", func) — one
    #: entry for an exact resolution, several for a duck-typed tie,
    #: empty for out-of-repo calls
    targets: Tuple[Tuple[str, str, str], ...]


@dataclasses.dataclass(frozen=True)
class AcquireSite:
    key: str
    stack: Tuple[str, ...]            # locks held when this one is taken
    node: ast.AST


@dataclasses.dataclass
class FunctionSummary:
    sf: SourceFile
    cls_name: str                     # "" for module-level functions
    fn: ast.AST                       # FunctionDef / AsyncFunctionDef
    acquisitions: List[AcquireSite] = dataclasses.field(default_factory=list)
    calls: List[CallSite] = dataclasses.field(default_factory=list)

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.sf.relpath, self.cls_name, self.fn.name)

    @property
    def qualname(self) -> str:
        return (f"{self.cls_name}.{self.fn.name}" if self.cls_name
                else self.fn.name)


class LockModel:
    """Whole-project lock + call-graph model, built once per run and
    shared by every GC checker (the expensive part is one AST pass)."""

    def __init__(self, project: Project):
        self.project = project
        self.decls: Dict[str, LockDecl] = {}
        #: attr name -> decls carrying it (cross-object resolution)
        self.by_attr: Dict[str, List[LockDecl]] = {}
        #: (relpath, cls) -> lock attr names of that class
        self.class_locks: Dict[Tuple[str, str], Set[str]] = {}
        #: relpath -> module-level lock names
        self.module_locks: Dict[str, Set[str]] = {}
        #: class name -> [(relpath, ClassDef, sf)]
        self.classes: Dict[str, List[Tuple[str, ast.ClassDef, SourceFile]]] = {}
        #: (relpath, cls) -> {self attr -> (relpath, cls) of its value type}
        self.attr_types: Dict[Tuple[str, str], Dict[str, Tuple[str, str]]] = {}
        #: (relpath, cls, attr) bindings that callers may substitute
        #: (defaulted-dependency idiom with an un-annotated parameter)
        self.attr_open: Set[Tuple[str, str, str]] = set()
        #: method name -> [(relpath, cls)] across every repo class
        self.methods_by_name: Dict[str, List[Tuple[str, str]]] = {}
        #: (relpath, cls, fname) -> (relpath, cls) from `-> Class` returns
        self.fn_return_class: Dict[Tuple[str, str, str],
                                   Tuple[str, str]] = {}
        #: (relpath, cls|"", fname) -> FunctionSummary
        self.functions: Dict[Tuple[str, str, str], FunctionSummary] = {}
        #: dotted module path -> relpath ("a.b.c" for "a/b/c.py")
        self.modules: Dict[str, str] = {}
        self._index()
        self._summarize()
        self.entry_contexts = propagate_entry_contexts(self)

    # -- pass 1: declarations ---------------------------------------------

    def _index(self) -> None:
        for sf in self.project.files:
            if sf.tree is None:
                continue
            mod = sf.relpath[:-3].replace("/", ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            self.modules[mod] = sf.relpath
            for node in sf.tree.body:
                self._maybe_lock_assign(sf, "", node)
            for cls in ast.walk(sf.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                self.classes.setdefault(cls.name, []).append(
                    (sf.relpath, cls, sf))
                for sub in cls.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self.methods_by_name.setdefault(
                            sub.name, []).append((sf.relpath, cls.name))
                for sub in ast.walk(cls):
                    self._maybe_lock_assign(sf, cls.name, sub)
        # second sweep: every class is registered, so attr→type and
        # return-annotation edges can resolve forward references too
        for sf in self.project.files:
            if sf.tree is None:
                continue
            for cls in ast.walk(sf.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                for sub in cls.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        ret = self._annotation_class(sub.returns)
                        if ret is not None:
                            self.fn_return_class[
                                (sf.relpath, cls.name, sub.name)] = ret
                for sub in cls.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        params: Dict[str, Tuple[str, str]] = {}
                        for arg in (sub.args.args + sub.args.kwonlyargs):
                            t = self._annotation_class(arg.annotation)
                            if t is not None:
                                params[arg.arg] = t
                        for node in ast.walk(sub):
                            self._maybe_attr_type(sf, cls.name, node,
                                                  params)
                    else:
                        for node in ast.walk(sub):
                            self._maybe_attr_type(sf, cls.name, node, {})

    def _annotation_class(self, ann: Optional[ast.expr]
                          ) -> Optional[Tuple[str, str]]:
        """``-> Counter`` / ``-> "Counter"`` / ``-> mod.Counter`` resolved
        to a repo class (Optional[...]/quoted forms included)."""
        if ann is None:
            return None
        if isinstance(ann, ast.Subscript):  # Optional[X] / "X | None" etc
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.strip("'\" ")
        elif isinstance(ann, ast.Name):
            name = ann.id
        elif isinstance(ann, ast.Attribute):
            name = ann.attr
        else:
            return None
        return self._class_by_name(name)

    def _lock_kind(self, sf: SourceFile, value: ast.expr) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        tail = sf.canonical(value.func).split(".")[-1]
        if tail in LOCK_FACTORY_TAILS:
            return tail.lower().replace("rlock", "rlock")
        return None

    def _maybe_lock_assign(self, sf: SourceFile, cls_name: str,
                           node: ast.AST) -> None:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            return
        value = node.value
        if value is None:
            return
        kind = self._lock_kind(sf, value)
        if kind is None:
            return
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            attr: Optional[str] = None
            if cls_name:
                chain = _self_attr_chain(t)
                if chain is not None and len(chain) == 1:
                    attr = chain[0]
            elif isinstance(t, ast.Name):
                attr = t.id
            if attr is None:
                continue
            key = (f"{sf.relpath}::{cls_name}.{attr}" if cls_name
                   else f"{sf.relpath}::{attr}")
            decl = LockDecl(key, sf.relpath, cls_name, attr, kind,
                            node.lineno,
                            getattr(node, "end_lineno", node.lineno))
            self.decls[key] = decl
            self.by_attr.setdefault(attr, []).append(decl)
            if cls_name:
                self.class_locks.setdefault(
                    (sf.relpath, cls_name), set()).add(attr)
            else:
                self.module_locks.setdefault(sf.relpath, set()).add(attr)

    def _maybe_attr_type(self, sf: SourceFile, cls_name: str,
                         node: ast.AST,
                         params: Dict[str, Tuple[str, str]]) -> None:
        """``self.X = SomeRepoClass(...)``, ``self.X = reg.counter(...)``
        (return-annotated factory) or ``self.X = param`` (annotated
        parameter) -> attr→class edge — the seam that lets
        ``self._gauge.set()`` resolve into obs/metrics.py.

        A defaulted-dependency binding whose injected branch stays
        untyped (``clock if clock is not None else RealClock()`` with an
        un-annotated ``clock``) is recorded as **open** in
        :attr:`attr_open`: callers may substitute any duck-compatible
        class, so call resolution through an open attr unions the typed
        default with the duck candidates."""
        if not isinstance(node, ast.Assign):
            return
        value = node.value
        # `X()`, `arg if arg is not None else X()`, `arg or X()` — the
        # defaulted-dependency idiom types the attr by its default class
        branches: List[ast.expr] = [value]
        if isinstance(value, ast.IfExp):
            branches = [value.body, value.orelse]
        elif isinstance(value, ast.BoolOp):
            branches = list(value.values)
        target: Optional[Tuple[str, str]] = None
        open_binding = False
        for branch in branches:
            got: Optional[Tuple[str, str]] = None
            if isinstance(branch, ast.Call):
                got = self._value_class(sf, branch)
            elif isinstance(branch, ast.Name):
                got = params.get(branch.id)
                if got is None:
                    # an injected parameter without a resolvable
                    # annotation: the binding stays substitutable
                    open_binding = True
                    continue
            else:
                continue
            if got is None:
                continue
            if target is not None and got != target:
                return  # ambiguous branches: leave the attr untyped
            target = got
        if target is None:
            return
        for t in node.targets:
            chain = _self_attr_chain(t)
            if chain is not None and len(chain) == 1:
                self.attr_types.setdefault(
                    (sf.relpath, cls_name), {})[chain[0]] = target
                if open_binding:
                    self.attr_open.add((sf.relpath, cls_name, chain[0]))

    def _value_class(self, sf: SourceFile, call: ast.Call
                     ) -> Optional[Tuple[str, str]]:
        """Class a call expression evaluates to: a constructor, or a
        return-annotated factory method (reg.counter(...) -> Counter)."""
        name = sf.canonical(call.func)
        target = self._class_by_name(name.split(".")[-1], hint=name)
        if target is not None or not isinstance(call.func, ast.Attribute):
            return target
        for owner in self._duck_candidates(sf, call.func):
            ret = self.fn_return_class.get(
                (owner[0], owner[1], call.func.attr))
            if ret is None:
                continue
            if target is not None and ret != target:
                return None  # ambiguous tie: different return types
            target = ret
        return target

    def _duck_candidates(self, sf: SourceFile, func: ast.Attribute
                         ) -> List[Tuple[str, str]]:
        """Repo classes a ``<recv>.m(...)`` call may dispatch into when
        nothing types the receiver: every class defining ``m``, capped at
        :data:`DUCK_MAX_CANDIDATES` and gated on the denylist.  Calls
        whose receiver head is an imported module (``time.monotonic()``)
        never duck-resolve — those are stdlib, not repo dispatch."""
        if func.attr in DUCK_DENYLIST:
            return []
        head = func.value
        while isinstance(head, ast.Attribute):
            head = head.value
        if isinstance(head, ast.Name) and head.id in sf.import_aliases:
            return []
        if not isinstance(head, (ast.Name, ast.Attribute)):
            return []  # calls on literals/calls: no stable receiver
        cands = self.methods_by_name.get(func.attr, [])
        if 0 < len(cands) <= DUCK_MAX_CANDIDATES:
            return list(cands)
        return []

    def _class_by_name(self, name: str, hint: str = ""
                       ) -> Optional[Tuple[str, str]]:
        cands = self.classes.get(name, [])
        if len(cands) == 1:
            return (cands[0][0], name)
        if len(cands) > 1 and hint:
            # disambiguate by the canonical dotted prefix when present
            mod_hint = hint.rsplit(".", 1)[0]
            rel = self.modules.get(mod_hint)
            for relpath, _cls, _sf in cands:
                if rel == relpath:
                    return (relpath, name)
        return None

    # -- lock-expression resolution ---------------------------------------

    def resolve_lock(self, sf: SourceFile, cls_name: str,
                     expr: ast.expr) -> Optional[str]:
        """Lock key acquired by ``with <expr>:``, or None when the
        expression is not a statically-known lock."""
        chain = _self_attr_chain(expr)
        if chain is not None:
            if len(chain) == 1:
                if chain[0] in self.class_locks.get(
                        (sf.relpath, cls_name), ()):
                    return f"{sf.relpath}::{cls_name}.{chain[0]}"
                return None
            # self.a.b...attr: follow the attr→class map one hop, else
            # fall back to a unique attr-name match across the repo.
            owner = self.attr_types.get((sf.relpath, cls_name), {}) \
                .get(chain[0])
            if owner is not None and len(chain) == 2 and \
                    chain[1] in self.class_locks.get(owner, ()):
                return f"{owner[0]}::{owner[1]}.{chain[1]}"
            cands = [d for d in self.by_attr.get(chain[-1], ())
                     if d.owner]
            if len(cands) == 1:
                return cands[0].key
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks.get(sf.relpath, ()):
                return f"{sf.relpath}::{expr.id}"
            origin = sf.from_imports.get(expr.id)
            if origin is not None:
                mod, _, nm = origin.rpartition(".")
                rel = self.modules.get(mod)
                if rel is not None and nm in self.module_locks.get(rel, ()):
                    return f"{rel}::{nm}"
            return None
        if isinstance(expr, ast.Attribute):
            # non-self receiver (``prog.lock``, ``inst._lock``): a unique
            # instance-lock attr name across the repo is unambiguous
            cands = [d for d in self.by_attr.get(expr.attr, ())
                     if d.owner]
            if len(cands) == 1:
                return cands[0].key
        return None

    # -- pass 2: per-function summaries -----------------------------------

    def _summarize(self) -> None:
        # Register every function FIRST, walk bodies second — call
        # resolution must see the whole repo, not just the functions
        # defined above the caller (forward references are the norm:
        # check_now calls _check_locked defined right below it).
        for sf in self.project.files:
            if sf.tree is None:
                continue
            for fn in ast.walk(sf.tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                cls_name = self._enclosing_class(fn)
                summary = FunctionSummary(sf, cls_name, fn)
                # last-definition-wins on duplicate names, matching
                # Python's own rebinding semantics
                self.functions[summary.key] = summary
        for summary in list(self.functions.values()):
            self._walk_body(summary, summary.fn.body, ())

    @staticmethod
    def _enclosing_class(fn: ast.AST) -> str:
        """Nearest enclosing ClassDef — THROUGH intervening function
        defs: a closure nested in a method still closes over that
        method's ``self``, so its ``self.x`` chains type against the
        same class."""
        cur = parent(fn)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = parent(cur)
        return ""

    def _walk_body(self, summary: FunctionSummary,
                   body: Sequence[ast.stmt],
                   stack: Tuple[str, ...]) -> None:
        for stmt in body:
            self._walk_stmt(summary, stmt, stack)

    def _walk_stmt(self, summary: FunctionSummary, stmt: ast.AST,
                   stack: Tuple[str, ...]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # separate context; the closure's thread holds nothing
        if isinstance(stmt, ast.Try):
            # `try: ... finally: <lock>.release()` is a held region for
            # that lock — the manual acquire(blocking=False) gate idiom.
            inner = stack
            for fin in stmt.finalbody:
                for node in ast.walk(fin):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "release":
                        key = self.resolve_lock(
                            summary.sf, summary.cls_name, node.func.value)
                        if key is not None and key not in inner:
                            summary.acquisitions.append(
                                AcquireSite(key, inner, stmt))
                            inner = inner + (key,)
            self._walk_body(summary, stmt.body, inner)
            self._walk_body(summary, stmt.orelse, inner)
            for handler in stmt.handlers:
                self._walk_stmt(summary, handler, inner)
            self._walk_body(summary, stmt.finalbody, stack)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = stack
            for item in stmt.items:
                self._scan_calls(summary, item.context_expr, inner)
                key = self.resolve_lock(summary.sf, summary.cls_name,
                                        item.context_expr)
                if key is not None and key not in inner:
                    summary.acquisitions.append(
                        AcquireSite(key, inner, item.context_expr))
                    inner = inner + (key,)
            self._walk_body(summary, stmt.body, inner)
            return
        # every other statement-ish node (If/Try/For/ExceptHandler/...):
        # scan this level's expressions, recurse into nested statement
        # lists with the same stack
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.stmt, ast.excepthandler)) or \
                    type(child).__name__ == "match_case":
                self._walk_stmt(summary, child, stack)
            else:
                self._scan_calls(summary, child, stack)

    def _scan_calls(self, summary: FunctionSummary, expr: ast.AST,
                    stack: Tuple[str, ...]) -> None:
        todo: List[ast.AST] = [expr]
        while todo:
            node = todo.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # a closure body's calls must not inherit the outer stack
                continue
            if isinstance(node, ast.Call):
                summary.calls.append(CallSite(
                    node, stack, self._resolve_call(summary, node)))
            todo.extend(ast.iter_child_nodes(node))

    def _resolve_call(self, summary: FunctionSummary, call: ast.Call
                      ) -> Tuple[Tuple[str, str, str], ...]:
        sf = summary.sf
        func = call.func
        if isinstance(func, ast.Attribute):
            chain = _self_attr_chain(func)
            if chain is not None:
                if len(chain) == 1:
                    t = self._method(sf.relpath, summary.cls_name,
                                     chain[0])
                    if t is not None:
                        return (t,)
                    return self._duck_methods(sf, func)
                # self.a.b...m(): walk the attr→class map hop by hop
                # (self.session.breaker.status() needs two hops).  A
                # hop through an open binding unions the typed result
                # with the duck candidates — the injected substitute
                # (FakeClock for RealClock) must stay in the graph.
                owner: Optional[Tuple[str, str]] = \
                    (sf.relpath, summary.cls_name)
                open_walk = False
                for hop in chain[:-1]:
                    if (owner[0], owner[1], hop) in self.attr_open:
                        open_walk = True
                    owner = self.attr_types.get(owner, {}).get(hop)
                    if owner is None:
                        break
                if owner is not None:
                    t = self._method(owner[0], owner[1], chain[-1])
                    out = (t,) if t is not None else ()
                    if open_walk:
                        out = tuple(dict.fromkeys(
                            out + self._duck_methods(sf, func)))
                    return out
                return self._duck_methods(sf, func)
            # module-function calls: resolve the dotted head to a module
            name = sf.canonical(func)
            if name:
                mod, _, fn_name = name.rpartition(".")
                rel = self.modules.get(mod)
                if rel is not None:
                    t = self._method(rel, "", fn_name)
                    if t is not None:
                        return (t,)
                t = self._ctor(sf, func)
                if t is not None:
                    return (t,)
            return self._duck_methods(sf, func)
        if isinstance(func, ast.Name):
            origin = sf.from_imports.get(func.id)
            if origin is not None:
                mod, _, nm = origin.rpartition(".")
                rel = self.modules.get(mod)
                if rel is not None:
                    t = self._method(rel, "", nm)
                    if t is not None:
                        return (t,)
                t = self._ctor(sf, func)
                return (t,) if t is not None else ()
            # a def nested in a method registers under the class (it
            # closes over self), so try the class scope before module
            t = None
            if summary.cls_name:
                t = self._method(sf.relpath, summary.cls_name, func.id)
            if t is None:
                t = self._method(sf.relpath, "", func.id)
            if t is None:
                t = self._ctor(sf, func)
            return (t,) if t is not None else ()
        return ()

    def _ctor(self, sf: SourceFile, func: ast.expr
              ) -> Optional[Tuple[str, str, str]]:
        """``ClassName(...)`` resolves into the class's ``__init__`` —
        constructors run caller-side, so a lock acquired while
        instantiating (``with self._lock: self.hb = Heartbeat(...)``)
        is held across everything the initializer does."""
        name = sf.canonical(func)
        if not name:
            return None
        cls = self._class_by_name(name.split(".")[-1], hint=name)
        if cls is None:
            return None
        return self._method(cls[0], cls[1], "__init__")

    def _duck_methods(self, sf: SourceFile, func: ast.Attribute
                      ) -> Tuple[Tuple[str, str, str], ...]:
        out = []
        for relpath, cls in self._duck_candidates(sf, func):
            t = self._method(relpath, cls, func.attr)
            if t is not None:
                out.append(t)
        return tuple(out)

    def _method(self, relpath: str, cls: str, name: str
                ) -> Optional[Tuple[str, str, str]]:
        key = (relpath, cls, name)
        return key if key in self.functions else None

    # -- queries -----------------------------------------------------------

    def held_variants(self, key: Tuple[str, str, str]
                      ) -> List[Tuple[FrozenSet[str], str]]:
        """Entry-held contexts of a function: ``[(held_set, via)]`` where
        ``via`` names an example caller chain (empty for the default
        lock-free entry)."""
        out = [(frozenset(), "")]
        out.extend(self.entry_contexts.get(key, {}).items())
        seen: Dict[FrozenSet[str], str] = {}
        for held, via in out:
            seen.setdefault(held, via)
        return [(frozenset(k), v) for k, v in
                sorted(seen.items(), key=lambda kv: sorted(kv[0]))]

    def decl_at(self, relpath: str, lineno: int) -> Optional[LockDecl]:
        """Declaration covering (relpath, lineno) — the witness's
        creation-site → static-node join."""
        for decl in self.decls.values():
            if decl.relpath == relpath and \
                    decl.lineno <= lineno <= decl.end_lineno:
                return decl
        return None


def propagate_entry_contexts(model: LockModel
                             ) -> Dict[Tuple[str, str, str],
                                       Dict[FrozenSet[str], str]]:
    """Push held-lock sets through the call graph: if ``A.m`` calls
    ``B.n`` while holding {L}, then ``B.n`` has an entry context {L}.
    Bounded: the visited set is (function, frozen held set)."""
    contexts: Dict[Tuple[str, str, str], Dict[FrozenSet[str], str]] = {}
    work: List[Tuple[Tuple[str, str, str], FrozenSet[str], str]] = []
    seen: Set[Tuple[Tuple[str, str, str], FrozenSet[str]]] = set()

    def enqueue(target, held: FrozenSet[str], via: str) -> None:
        if not held or (target, held) in seen:
            return
        seen.add((target, held))
        contexts.setdefault(target, {}).setdefault(held, via)
        work.append((target, held, via))

    for summary in model.functions.values():
        for call in summary.calls:
            if call.stack:
                for target in call.targets:
                    enqueue(target, frozenset(call.stack),
                            summary.qualname)
    while work:
        target, held, via = work.pop()
        summary = model.functions.get(target)
        if summary is None:
            continue
        for call in summary.calls:
            total = held | frozenset(call.stack)
            for nxt in call.targets:
                enqueue(nxt, total, f"{via} -> {summary.qualname}")
    return contexts

"""GC205 — ``_*_locked`` helper discipline (the GL004 successor).

The repo's convention since PR 12: a method named ``_foo_locked``
documents "caller holds the owning lock".  GL004 can only see
half-guarded attributes inside one class; GC205 enforces the convention
itself, cross-file, via the shared call-graph model:

- a ``_*_locked`` helper may only be called with a lock lexically held,
  from another ``_*_locked`` helper (the contract chains), or from a
  construction-exempt method;
- an attribute that a ``_*_locked`` helper mutates is GUARDED — any
  other method of the class mutating it without a lexically-held lock
  breaks the contract the helper's name advertises.

GL004 stays registered as the fallback for lock patterns this model
cannot resolve (dynamically-minted locks, non-``self`` receivers).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from raft_stereo_tpu.analysis.checkers.gl004_lock_discipline import (
    EXEMPT_METHODS, MUTATORS, _self_attr)
from raft_stereo_tpu.analysis.concurrency.checkers.base import \
    ConcurrencyChecker
from raft_stereo_tpu.analysis.concurrency.contracts import LOCKED_HELPER_RE
from raft_stereo_tpu.analysis.concurrency.model import lexical_nodes
from raft_stereo_tpu.analysis.core import (Finding, Project, SourceFile,
                                           ancestors)


def _mutations(fn: ast.AST) -> List[Tuple[str, ast.AST]]:
    """Lexical ``self.<attr>`` mutation sites of a method."""
    out: List[Tuple[str, ast.AST]] = []
    for node in lexical_nodes(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    out.append((attr, node))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    out.append((attr, node))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATORS:
            attr = _self_attr(node.func.value)
            if attr is not None:
                out.append((attr, node))
    return out


class LockedHelperChecker(ConcurrencyChecker):
    code = "GC205"
    name = "locked-helper-discipline"
    description = ("_*_locked helper called without a held lock, or its "
                   "guarded attributes mutated lock-free elsewhere")

    def check_project(self, project: Project) -> Iterator[Finding]:
        yield from self._check_calls()
        yield from self._check_guarded_attrs()

    # -- rule 1: callers of _*_locked hold a lock ---------------------------

    def _check_calls(self) -> Iterator[Finding]:
        for key in sorted(self.model.functions):
            summary = self.model.functions[key]
            caller = summary.fn.name
            if LOCKED_HELPER_RE.match(caller) or caller in EXEMPT_METHODS:
                continue
            for call in summary.calls:
                func = call.node.func
                callee = func.attr if isinstance(func, ast.Attribute) \
                    else func.id if isinstance(func, ast.Name) else ""
                if not LOCKED_HELPER_RE.match(callee):
                    continue
                if call.stack:
                    continue
                yield Finding(
                    self.code,
                    f"'{callee}' called from {summary.qualname}() with "
                    "no lock lexically held — _*_locked helpers require "
                    "the owning lock at the call site (or a _*_locked "
                    "caller that chains the contract)",
                    summary.sf.relpath, call.node.lineno,
                    call.node.col_offset)

    # -- rule 2: guarded attributes stay behind a lock ----------------------

    def _check_guarded_attrs(self) -> Iterator[Finding]:
        for cls_name in sorted(self.model.classes):
            for relpath, cls, sf in self.model.classes[cls_name]:
                yield from self._check_class(sf, relpath, cls)

    def _check_class(self, sf: SourceFile, relpath: str,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        lock_attrs = self.model.class_locks.get((relpath, cls.name), set())
        methods = [m for m in cls.body
                   if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))]
        guarded: Dict[str, str] = {}   # attr -> guarding helper name
        for m in methods:
            if not LOCKED_HELPER_RE.match(m.name):
                continue
            for attr, _node in _mutations(m):
                if attr not in lock_attrs:
                    guarded.setdefault(attr, m.name)
        if not guarded:
            return
        for m in methods:
            if LOCKED_HELPER_RE.match(m.name) or m.name in EXEMPT_METHODS:
                continue
            for attr, node in _mutations(m):
                helper = guarded.get(attr)
                if helper is None:
                    continue
                if self._held_here(sf, cls.name, node, m):
                    continue
                yield Finding(
                    self.code,
                    f"'self.{attr}' is guarded by {cls.name}.{helper}() "
                    f"but mutated lock-free in {m.name}() — take the "
                    "owning lock or route the mutation through the "
                    "helper",
                    relpath, node.lineno,
                    getattr(node, "col_offset", 0))

    def _held_here(self, sf: SourceFile, cls_name: str, node: ast.AST,
                   fn: ast.AST) -> bool:
        for a in ancestors(node):
            if a is fn:
                return False
            if isinstance(a, (ast.With, ast.AsyncWith)):
                for item in a.items:
                    if self.model.resolve_lock(sf, cls_name,
                                               item.context_expr):
                        return True
        return False

"""GC201 — the whole-repo lock-order graph.

Two findings share the code:

- a CYCLE in the graph (lock A taken under B somewhere, B under A
  elsewhere — a deadlock waiting for its interleaving);
- DRIFT between the regenerated graph and the committed
  ``LOCK_ORDER.md`` manifest (the bench-checksum ceremony applied to
  acquisition order: a new edge must show up in a reviewed diff, not
  slide in silently).

Cycle findings land on the source line of the first edge in the cycle;
drift findings land on the manifest itself, which is not a python file,
so they are by construction unsuppressable — regenerate and review.
"""

from __future__ import annotations

from typing import Iterator, Optional

from raft_stereo_tpu.analysis.concurrency.checkers.base import \
    ConcurrencyChecker
from raft_stereo_tpu.analysis.concurrency.graph import (
    MANIFEST_NAME, build_lock_graph, find_cycles, manifest_drift)
from raft_stereo_tpu.analysis.concurrency.model import LockModel
from raft_stereo_tpu.analysis.core import Finding, Project


class LockOrderChecker(ConcurrencyChecker):
    code = "GC201"
    name = "lock-order-graph"
    description = ("lock-order cycle across the repo, or drift between "
                   "the tree and the committed LOCK_ORDER.md manifest")

    def __init__(self, model: LockModel, *,
                 manifest_text: Optional[str] = None,
                 check_manifest: bool = False, **_kw):
        super().__init__(model)
        self.manifest_text = manifest_text
        self.check_manifest = check_manifest

    def check_project(self, project: Project) -> Iterator[Finding]:
        edges = build_lock_graph(self.model)
        for cyc in find_cycles(edges):
            ring = cyc + [cyc[0]]
            first = edges[(ring[0], ring[1])]
            sites = "; ".join(
                edges[(a, b)].example for a, b in zip(ring, ring[1:])
                if (a, b) in edges)
            yield Finding(
                self.code,
                "lock-order cycle: " + " -> ".join(f"`{n}`" for n in ring)
                + f" (edge sites: {sites}) — pick one global order and "
                "restructure the out-of-order acquisition",
                first.relpath, first.line)
        if self.check_manifest:
            drift = manifest_drift(edges, self.manifest_text)
            if drift is not None:
                yield Finding(self.code, drift, MANIFEST_NAME, 1)

"""GC203 — blocking call while a lock is held.

A blocking call under a lock turns that lock into a convoy: every
thread that needs it queues behind a sleep, a queue.get, a subprocess,
or — worst — a ``Future.result()`` that the lock-holder itself is the
only one able to resolve (the caller-deadlock shape).  Judged per call
site against the reviewed registry in :mod:`contracts`; both lexically
held locks and call-graph-propagated entry contexts count (a helper
only ever invoked under the admission lock blocks the admission lock).

One deliberate carve-out: a blocking call ON a held Condition/lock
itself (``self._cv.wait()`` inside ``with self._cv:``) is the canonical
wait pattern — ``wait`` releases the lock while parked — so the
receiver lock is subtracted before judging; it is still flagged when
OTHER locks remain held across the wait.
"""

from __future__ import annotations

import ast
from typing import FrozenSet, Iterator, List, Tuple

from raft_stereo_tpu.analysis.concurrency.checkers.base import \
    ConcurrencyChecker
from raft_stereo_tpu.analysis.concurrency.contracts import is_blocking_call
from raft_stereo_tpu.analysis.concurrency.model import (CallSite,
                                                        FunctionSummary)
from raft_stereo_tpu.analysis.core import Finding, Project


def held_contexts(model, summary: FunctionSummary, call: CallSite
                  ) -> List[Tuple[FrozenSet[str], str]]:
    """Lock sets this call can run under: the lexical stack when there
    is one, else every nonempty call-graph entry context."""
    if call.stack:
        return [(frozenset(call.stack), "")]
    return [(held, via) for held, via in model.held_variants(summary.key)
            if held]


class BlockingUnderLockChecker(ConcurrencyChecker):
    code = "GC203"
    name = "blocking-under-lock"
    description = ("blocking call (queue.get/join/wait/sleep/subprocess/"
                   "socket/invoke/Future.result) while a lock is held")

    def check_project(self, project: Project) -> Iterator[Finding]:
        for key in sorted(self.model.functions):
            summary = self.model.functions[key]
            sf = summary.sf
            for call in summary.calls:
                canonical = sf.canonical(call.node.func)
                if not canonical:
                    continue
                args = call.node.args
                first_num = bool(args) and \
                    isinstance(args[0], ast.Constant) and \
                    isinstance(args[0].value, (int, float))
                if not is_blocking_call(canonical, len(args), first_num):
                    continue
                for held, via in held_contexts(self.model, summary, call):
                    effective = set(held)
                    if isinstance(call.node.func, ast.Attribute):
                        recv = self.model.resolve_lock(
                            sf, summary.cls_name, call.node.func.value)
                        if recv is not None:
                            # cv.wait() under `with cv:` — wait releases
                            # the cv; only OTHER held locks convoy.
                            effective.discard(recv)
                    if not effective:
                        continue
                    yield Finding(
                        self.code,
                        f"blocking call '{canonical}' in "
                        f"{summary.qualname}() while holding "
                        + ", ".join(f"`{k}`" for k in sorted(effective))
                        + (f" (reached via {via})" if via else "")
                        + " — move the blocking call outside the lock",
                        sf.relpath, call.node.lineno, call.node.col_offset)
                    break  # one finding per call site is enough

"""Concurrency checker base: a :class:`Checker` that shares the one
:class:`LockModel` built per run (the expensive AST pass happens once,
all six GC checkers query it)."""

from __future__ import annotations

from raft_stereo_tpu.analysis.checkers.base import Checker
from raft_stereo_tpu.analysis.concurrency.model import LockModel


class ConcurrencyChecker(Checker):
    def __init__(self, model: LockModel, **_kw):
        self.model = model

"""GC202 — Future lifecycle in serve/.

The PR 3 bug class, machine-checked: a ``Future()`` minted in serve/
parks a caller thread on ``.result()``; abandon it on any path and that
caller blocks forever.  Every minted Future must therefore either

- be handed to a REGISTERED drain (``contracts.FUTURE_DRAINS`` — sinks
  whose owner's ``stop()`` provably resolves parked Futures, the
  reviewed PR 3 contract), or
- be returned to the caller before anything can raise (a factory — the
  caller owns the obligation), or
- be resolved inline, in which case every call made BETWEEN the moment
  the Future escapes to a waiter and its resolution must sit under a
  ``try`` whose handler/finally resolves it (the exception path is the
  path PR 3 shipped broken).

Path-insensitive by design: linenos order events, a ``try`` ancestor
with a resolving handler is the protection proof.  Futures that never
escape before resolution carry no risk — an exception simply propagates
to the only thread that knows about them.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from raft_stereo_tpu.analysis.concurrency.checkers.base import \
    ConcurrencyChecker
from raft_stereo_tpu.analysis.concurrency.contracts import (
    FUTURE_DIRS, FUTURE_DRAINS, FUTURE_FACTORIES, in_dirs)
from raft_stereo_tpu.analysis.concurrency.model import lexical_nodes
from raft_stereo_tpu.analysis.core import (Finding, Project, SourceFile,
                                           ancestors)

#: Attribute calls on the Future that discharge the obligation.
RESOLVE_ATTRS = frozenset({"set_result", "set_exception", "cancel"})


def _is_resolve(node: ast.AST, var: str) -> bool:
    return (isinstance(node, ast.Call) and
            isinstance(node.func, ast.Attribute) and
            node.func.attr in RESOLVE_ATTRS and
            isinstance(node.func.value, ast.Name) and
            node.func.value.id == var)


def _call_tail(func: ast.expr) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return "<call>"


class _Escape:
    def __init__(self, kind: str, sink: str, node: ast.AST):
        self.kind = kind    # "drain" | "sink" | "return"
        self.sink = sink
        self.node = node
        self.line = getattr(node, "lineno", 0)


class FutureLifecycleChecker(ConcurrencyChecker):
    code = "GC202"
    name = "future-lifecycle"
    description = ("Future minted in serve/ abandoned on some path — not "
                   "resolved, handed to an unregistered sink, or "
                   "unprotected calls between escape and resolution")

    def check_file(self, project: Project, sf: SourceFile
                   ) -> Iterator[Finding]:
        if not in_dirs(sf.relpath, FUTURE_DIRS):
            return
        for fn in ast.walk(sf.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(sf, fn)

    def _check_function(self, sf: SourceFile, fn: ast.AST
                        ) -> Iterator[Finding]:
        for var, creation in self._minted(sf, fn):
            yield from self._check_future(sf, fn, var, creation)

    @staticmethod
    def _minted(sf: SourceFile, fn: ast.AST
                ) -> List[Tuple[str, ast.Assign]]:
        out = []
        for node in lexical_nodes(fn):
            if (isinstance(node, ast.Assign) and
                    len(node.targets) == 1 and
                    isinstance(node.targets[0], ast.Name) and
                    isinstance(node.value, ast.Call) and
                    sf.canonical(node.value.func) in FUTURE_FACTORIES):
                out.append((node.targets[0].id, node))
        return out

    def _check_future(self, sf: SourceFile, fn: ast.AST, var: str,
                      creation: ast.Assign) -> Iterator[Finding]:
        resolves: List[ast.Call] = []
        escapes: List[_Escape] = []
        for node in ast.walk(fn):   # resolves may live in callbacks
            if _is_resolve(node, var):
                resolves.append(node)
            elif (isinstance(node, ast.Name) and node.id == var and
                  isinstance(node.ctx, ast.Load)):
                esc = self._classify_escape(fn, var, node)
                if esc is not None:
                    escapes.append(esc)
        drain = min((e.line for e in escapes if e.kind == "drain"),
                    default=None)
        sinks = [e for e in escapes if e.kind == "sink"]
        returns = [e for e in escapes if e.kind == "return"]
        if not resolves and drain is None:
            if sinks:
                e = sinks[0]
                yield Finding(
                    self.code,
                    f"Future '{var}' handed to unregistered sink "
                    f"'{e.sink}' with no set_result/set_exception in "
                    f"{fn.name}() — register the drain in "
                    "contracts.FUTURE_DRAINS (with a stop()-drains "
                    "proof) or resolve on every path",
                    sf.relpath, e.line)
            elif not returns:
                yield Finding(
                    self.code,
                    f"Future '{var}' created but never resolved or "
                    f"handed off in {fn.name}() — its waiter blocks "
                    "forever",
                    sf.relpath, creation.lineno)
            return
        # Risky window: after the first escape to a waiter, before the
        # obligation is discharged (registered drain, or last resolve).
        start = min((e.line for e in sinks), default=None)
        if start is None:
            return
        end = drain if drain is not None else \
            max(r.lineno for r in resolves) if resolves else None
        if end is None:
            return  # the no-resolve/no-drain case was flagged above
        risky = self._first_risky(fn, var, start, end)
        if risky is not None:
            yield Finding(
                self.code,
                f"Future '{var}' escapes at line {start} but "
                f"'{_call_tail(risky.func)}' at line {risky.lineno} can "
                "raise before it is resolved — wrap in try/except "
                "set_exception, or hand the Future to a registered drain",
                sf.relpath, risky.lineno)

    def _classify_escape(self, fn: ast.AST, var: str, name: ast.Name
                         ) -> Optional[_Escape]:
        prev: ast.AST = name
        for a in ancestors(name):
            if a is fn:
                return None
            if isinstance(a, ast.Call):
                if prev is a.func or (isinstance(a.func, ast.Attribute)
                                      and a.func.value is name):
                    return None  # receiver: a resolve or a query
                tail = _call_tail(a.func)
                kind = "drain" if tail in FUTURE_DRAINS else "sink"
                return _Escape(kind, tail, a)
            if isinstance(a, (ast.Return, ast.Yield)):
                return _Escape("return", "", a)
            if isinstance(a, ast.Assign) and prev is not a.targets[0]:
                for t in a.targets:
                    if isinstance(t, ast.Name):
                        return None  # plain alias — not tracked
                tail = getattr(a.targets[0], "attr", "<store>")
                return _Escape("sink", tail, a)
            prev = a
        return None

    def _first_risky(self, fn: ast.AST, var: str, start: int, end: int
                     ) -> Optional[ast.Call]:
        cands = [n for n in lexical_nodes(fn)
                 if isinstance(n, ast.Call) and start < n.lineno < end]
        for call in sorted(cands, key=lambda c: (c.lineno, c.col_offset)):
            if _is_resolve(call, var):
                continue
            if (isinstance(call.func, ast.Attribute) and
                    isinstance(call.func.value, ast.Name) and
                    call.func.value.id == var):
                continue  # query on the Future itself
            if any(isinstance(a, ast.Call) and _is_resolve(a, var)
                   for a in ancestors(call)):
                continue  # argument of the resolve — part of resolution
            if self._protected(fn, var, call):
                continue
            return call
        return None

    @staticmethod
    def _protected(fn: ast.AST, var: str, call: ast.Call) -> bool:
        for a in ancestors(call):
            if a is fn:
                return False
            if isinstance(a, ast.Try):
                recovery = list(a.finalbody)
                for h in a.handlers:
                    recovery.extend(h.body)
                for stmt in recovery:
                    if any(_is_resolve(n, var) for n in ast.walk(stmt)):
                        return True
        return False

"""GC204 — callback/sink/IO invocation under a held lock.

The PR 7 trace-sink rule, generalized: user-registered callbacks and
file IO have unbounded latency and can re-enter the caller, so they
must not run under a state lock.  The carve-out that made the PR 7 fix
idiomatic is honored: a lock whose NAME declares it a dedicated IO
serializer (``_sink_lock``, ``_disk_lock`` — :data:`contracts.
IO_LOCK_NAME_RE`) is allowed to cover IO, because serializing the sink
is its entire job and it is never nested under state locks (GC201's
graph proves that part).
"""

from __future__ import annotations

from typing import Iterator

from raft_stereo_tpu.analysis.concurrency.checkers.base import \
    ConcurrencyChecker
from raft_stereo_tpu.analysis.concurrency.checkers.gc203_blocking_under_lock \
    import held_contexts
from raft_stereo_tpu.analysis.concurrency.contracts import (is_io_lock,
                                                            is_sink_call)
from raft_stereo_tpu.analysis.core import Finding, Project


class SinkUnderLockChecker(ConcurrencyChecker):
    code = "GC204"
    name = "sink-under-lock"
    description = ("registered callback/sink or file IO invoked while "
                   "holding a non-IO lock")

    def check_project(self, project: Project) -> Iterator[Finding]:
        for key in sorted(self.model.functions):
            summary = self.model.functions[key]
            sf = summary.sf
            for call in summary.calls:
                canonical = sf.canonical(call.node.func)
                if not canonical or not is_sink_call(canonical):
                    continue
                for held, via in held_contexts(self.model, summary, call):
                    state_locks = sorted(k for k in held
                                         if not is_io_lock(k))
                    if not state_locks:
                        continue
                    yield Finding(
                        self.code,
                        f"sink/IO call '{canonical}' in "
                        f"{summary.qualname}() under "
                        + ", ".join(f"`{k}`" for k in state_locks)
                        + (f" (reached via {via})" if via else "")
                        + " — snapshot under the lock, invoke the sink "
                        "outside it (or use a dedicated *_sink_lock)",
                        sf.relpath, call.node.lineno, call.node.col_offset)
                    break

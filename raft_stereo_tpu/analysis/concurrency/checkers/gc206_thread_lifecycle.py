"""GC206 — thread lifecycle in serve/ and obs/.

Every ``threading.Thread(...)`` started in the serving/observability
planes must have a reachable join/stop path: a fire-and-forget thread
outlives ``stop()``, keeps references alive across deploys (the fleet
rolling-deploy invariant), and turns shutdown into a race.  Accepted
proofs, per binding shape:

- ``self._t = Thread(...)`` — some method of the class joins
  ``self._t`` (or hands it to something: escape transfers ownership);
- ``t = Thread(...)`` — the same function joins ``t`` or lets it
  escape (returned, appended to a registry, passed to a reaper);
- ``Thread(...).start()`` — no binding at all: always a finding.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from raft_stereo_tpu.analysis.checkers.gl004_lock_discipline import \
    _self_attr
from raft_stereo_tpu.analysis.concurrency.checkers.base import \
    ConcurrencyChecker
from raft_stereo_tpu.analysis.concurrency.contracts import (THREADED_DIRS,
                                                            in_dirs)
from raft_stereo_tpu.analysis.concurrency.model import lexical_nodes
from raft_stereo_tpu.analysis.core import (Finding, Project, SourceFile,
                                           ancestors, enclosing_function,
                                           parent)


def _enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    for a in ancestors(node):
        if isinstance(a, ast.ClassDef):
            return a
    return None


class ThreadLifecycleChecker(ConcurrencyChecker):
    code = "GC206"
    name = "thread-lifecycle"
    description = ("Thread started in serve//obs/ without a reachable "
                   "join/stop path")

    def check_file(self, project: Project, sf: SourceFile
                   ) -> Iterator[Finding]:
        if not in_dirs(sf.relpath, THREADED_DIRS):
            return
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and \
                    sf.canonical(node.func) == "threading.Thread":
                yield from self._check_thread(sf, node)

    def _check_thread(self, sf: SourceFile, call: ast.Call
                      ) -> Iterator[Finding]:
        p = parent(call)
        if isinstance(p, ast.Attribute):
            if p.attr == "start":
                yield Finding(
                    self.code,
                    "fire-and-forget Thread(...).start() — bind the "
                    "thread and register a join/stop path in the "
                    "owner's stop()/drain",
                    sf.relpath, call.lineno, call.col_offset)
            return
        if isinstance(p, ast.Assign):
            target = p.targets[0]
            if isinstance(target, ast.Name):
                yield from self._check_local(sf, call, target.id)
                return
            attr = _self_attr(target)
            if attr is not None and isinstance(target, ast.Attribute):
                yield from self._check_attr(sf, call, attr)
            return
        # Every other parent shape (call argument, container element,
        # return value, keyword) hands the thread to other machinery —
        # ownership, and the join obligation, transfer with it.

    def _check_local(self, sf: SourceFile, call: ast.Call, name: str
                     ) -> Iterator[Finding]:
        fn = enclosing_function(call)
        scope = fn if fn is not None else sf.tree
        for node in lexical_nodes(scope):
            if not (isinstance(node, ast.Name) and node.id == name and
                    isinstance(node.ctx, ast.Load)):
                continue
            if self._use_discharges(node):
                return
        yield Finding(
            self.code,
            f"Thread '{name}' is started but never joined and never "
            "escapes this function — join it or hand it to a "
            "reaper/registry with a stop path",
            sf.relpath, call.lineno, call.col_offset)

    def _check_attr(self, sf: SourceFile, call: ast.Call, attr: str
                    ) -> Iterator[Finding]:
        cls = _enclosing_class(call)
        scope: ast.AST = cls if cls is not None else sf.tree
        if self._attr_discharged(scope, attr):
            return
        owner = f"{cls.name}." if cls is not None else ""
        yield Finding(
            self.code,
            f"Thread 'self.{attr}' has no join anywhere in "
            f"{owner.rstrip('.') or sf.relpath} — the owner's "
            "stop()/close() must join its worker threads",
            sf.relpath, call.lineno, call.col_offset)

    def _attr_discharged(self, scope: ast.AST, attr: str) -> bool:
        """True when some use of ``self.<attr>`` in ``scope`` joins the
        thread or hands it off — directly, or through a one-hop local
        alias (``t = self._thread; ...; t.join()``, the snapshot-then-
        join idiom stop() methods use against concurrent restarts)."""
        for node in ast.walk(scope):
            if not (isinstance(node, ast.Attribute) and node.attr == attr
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and isinstance(node.ctx, ast.Load)):
                continue
            p = parent(node)
            if isinstance(p, ast.Attribute) and p.attr == "join":
                return True
            if isinstance(p, ast.Assign) and node is p.value:
                for t in p.targets:
                    if isinstance(t, ast.Name) and \
                            self._alias_discharges(p, t.id):
                        return True
            prev: ast.AST = node
            for a in ancestors(node):
                if isinstance(a, ast.Call) and prev is not a.func:
                    return True  # escapes to other machinery
                if isinstance(a, ast.stmt):
                    break
                prev = a
        return False

    def _alias_discharges(self, assign: ast.Assign, alias: str) -> bool:
        fn = enclosing_function(assign)
        if fn is None:
            return False
        return any(isinstance(n, ast.Name) and n.id == alias and
                   isinstance(n.ctx, ast.Load) and self._use_discharges(n)
                   for n in lexical_nodes(fn))

    @staticmethod
    def _use_discharges(name: ast.Name) -> bool:
        """True when this use joins the thread or lets it escape."""
        prev: ast.AST = name
        for a in ancestors(name):
            if isinstance(a, ast.Attribute) and a.value is prev:
                return a.attr == "join"
            if isinstance(a, ast.Call):
                return prev is not a.func   # in args/keywords: escapes
            if isinstance(a, (ast.Return, ast.Yield, ast.Tuple, ast.List,
                              ast.Set, ast.Dict)):
                return True
            if isinstance(a, ast.Assign):
                return not all(isinstance(t, ast.Name) for t in a.targets)
            if isinstance(a, ast.stmt):
                return False
            prev = a
        return False

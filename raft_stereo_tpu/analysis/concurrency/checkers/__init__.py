"""graftlock checker registry — GC201-GC206, all constructed against
one shared :class:`LockModel` per run."""

from __future__ import annotations

from raft_stereo_tpu.analysis.concurrency.checkers.gc201_lock_order import \
    LockOrderChecker
from raft_stereo_tpu.analysis.concurrency.checkers.gc202_future_lifecycle \
    import FutureLifecycleChecker
from raft_stereo_tpu.analysis.concurrency.checkers \
    .gc203_blocking_under_lock import BlockingUnderLockChecker
from raft_stereo_tpu.analysis.concurrency.checkers.gc204_sink_under_lock \
    import SinkUnderLockChecker
from raft_stereo_tpu.analysis.concurrency.checkers.gc205_locked_helpers \
    import LockedHelperChecker
from raft_stereo_tpu.analysis.concurrency.checkers.gc206_thread_lifecycle \
    import ThreadLifecycleChecker

ALL_CONCURRENCY_CHECKERS = (
    LockOrderChecker,
    FutureLifecycleChecker,
    BlockingUnderLockChecker,
    SinkUnderLockChecker,
    LockedHelperChecker,
    ThreadLifecycleChecker,
)

__all__ = ["ALL_CONCURRENCY_CHECKERS", "LockOrderChecker",
           "FutureLifecycleChecker", "BlockingUnderLockChecker",
           "SinkUnderLockChecker", "LockedHelperChecker",
           "ThreadLifecycleChecker"]

"""graftlock runtime witness — proves LOCK_ORDER.md against executions.

The static graph (GC201) claims to contain every nested acquisition the
tree can perform.  The witness closes the loop from the other side: a
test-only instrumented wrapper around ``threading.Lock``/``RLock``
records the ACTUAL acquisition orders a running battery produces, and
:func:`unexplained_edges` asserts every observed edge maps into the
static graph.  An edge the model missed (a lock taken through a code
path the AST resolution can't see) fails the witness step instead of
hiding until the interleaving ships.

Mechanics:

- :class:`LockWitness` is a context manager that patches the
  ``threading`` factories.  Locks are identified by CREATION SITE — the
  first stack frame inside ``raft_stereo_tpu/`` at mint time — which is
  exactly the declaration site the static model keys on
  (``LockModel.decl_at`` joins the two by line-range).  Locks minted
  dynamically (per-key ``setdefault`` maps) or outside the package
  (stdlib queue/logging internals) don't map to a declaration and are
  skipped, mirroring the model's own scope.
- Per-thread held stacks; each successful acquire under a non-empty
  stack records one ``(outer_site, inner_site)`` edge.  RLock re-entry
  and Condition ``wait`` (via ``_release_save``/``_acquire_restore``)
  keep the stack honest.
- Locks created BEFORE the witness arms (module-level locks minted at
  import) are not wrapped; batteries construct their serving stack
  inside the witness for full coverage.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

from raft_stereo_tpu.analysis.concurrency.graph import build_lock_graph
from raft_stereo_tpu.analysis.concurrency.model import LockModel

Site = Tuple[str, int]  # (relpath under the repo root, lineno)

_PKG = "raft_stereo_tpu"


def _creation_site() -> Optional[Site]:
    """First stack frame inside the package (excluding this module) —
    the declaration site of the lock being minted."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename.replace(os.sep, "/")
        if f"/{_PKG}/" in fn and not fn.endswith("witness.py"):
            idx = fn.rfind(f"/{_PKG}/")
            return (fn[idx + 1:], f.f_lineno)
        f = f.f_back
    return None


class _WitnessLock:
    """Wraps one real lock; reports acquisition order to the witness."""

    def __init__(self, inner, witness: "LockWitness",
                 site: Optional[Site]):
        self._inner = inner
        self._w = witness
        self.site = site

    # -- the recorded surface ----------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._w._note_acquire(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._w._note_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition integration ---------------------------------------------
    # Condition probes for these; implementing them here (instead of
    # letting __getattr__ expose the inner lock's versions) keeps the
    # witness's held-stack consistent across cv.wait()'s full release
    # and re-acquire.

    def _release_save(self):
        fn = getattr(self._inner, "_release_save", None)
        self._w._note_release_all(self)
        if fn is not None:
            return fn()
        self._inner.release()
        return None

    def _acquire_restore(self, saved) -> None:
        fn = getattr(self._inner, "_acquire_restore", None)
        if fn is not None:
            fn(saved)
        else:
            self._inner.acquire()
        self._w._note_acquire(self)

    def _is_owned(self) -> bool:
        fn = getattr(self._inner, "_is_owned", None)
        if fn is not None:
            return fn()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __getattr__(self, name: str):
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<witnessed {self._inner!r} @ {self.site}>"


class LockWitness:
    """Patch ``threading.Lock``/``RLock`` and record acquisition edges.

    ``edges`` after (or during) the run: a set of
    ``((relpath, line), (relpath, line))`` pairs — inner acquired while
    outer was the top of the acquiring thread's held stack."""

    def __init__(self):
        self.edges: Set[Tuple[Site, Site]] = set()
        self._guard = threading.Lock()  # minted pre-patch: never wrapped
        self._tls = threading.local()
        self._orig: Dict[str, object] = {}

    # -- patching ----------------------------------------------------------

    def __enter__(self) -> "LockWitness":
        self._orig = {"Lock": threading.Lock, "RLock": threading.RLock}
        witness = self

        def make(factory):
            def mint(*a, **kw):
                return _WitnessLock(factory(*a, **kw), witness,
                                    _creation_site())
            return mint
        threading.Lock = make(self._orig["Lock"])
        threading.RLock = make(self._orig["RLock"])
        return self

    def __exit__(self, *exc) -> None:
        threading.Lock = self._orig["Lock"]
        threading.RLock = self._orig["RLock"]

    # -- recording ---------------------------------------------------------

    def _stack(self) -> List["_WitnessLock"]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _note_acquire(self, lock: _WitnessLock) -> None:
        st = self._stack()
        if st and lock.site is not None:
            top = st[-1]
            if top is not lock and top.site is not None and \
                    top.site != lock.site:
                with self._guard:
                    self.edges.add((top.site, lock.site))
        st.append(lock)

    def _note_release(self, lock: _WitnessLock) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lock:
                del st[i]
                return

    def _note_release_all(self, lock: _WitnessLock) -> None:
        st = self._stack()
        st[:] = [l for l in st if l is not lock]


def package_model() -> LockModel:
    """The LockModel of the installed package tree, keyed with repo-root
    relpaths (``raft_stereo_tpu/...``) — the same node names the witness
    sites resolve to."""
    from raft_stereo_tpu.analysis.core import Project, collect_files
    pkg_dir = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    files = collect_files([pkg_dir], base=os.path.dirname(pkg_dir))
    return LockModel(Project(files))


def unexplained_edges(witness: LockWitness,
                      model: Optional[LockModel] = None) -> List[str]:
    """Observed edges that the static graph does not contain — each one
    is a witness failure.  Edges with an endpoint that maps to no static
    declaration (dynamic/stdlib locks) are out of the model's scope and
    skipped."""
    if model is None:
        model = package_model()
    static = set(build_lock_graph(model))
    out: List[str] = []
    for src, dst in sorted(witness.edges):
        a = model.decl_at(*src)
        b = model.decl_at(*dst)
        if a is None or b is None or a.key == b.key:
            continue
        if (a.key, b.key) not in static:
            out.append(
                f"observed lock edge `{a.key}` -> `{b.key}` "
                f"(minted at {src[0]}:{src[1]} and {dst[0]}:{dst[1]}) "
                "is not in the static lock-order graph — extend the "
                "model or reorder the acquisition")
    return out

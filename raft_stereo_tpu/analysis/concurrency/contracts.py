"""graftlock contract registries — the explicit, reviewed lists the GC
checkers match against (the GL002/GL006 registry discipline applied to
concurrency: the checker never guesses, the registry is the contract and
drifting from it is the finding).

Stdlib-only; importable without jax like the rest of ``analysis/``.
"""

from __future__ import annotations

import re

# -- GC202: Future lifecycle ------------------------------------------------

#: Call-name tails / store-target attrs that are REGISTERED Future
#: drains: handing a fresh Future to one of these transfers the
#: resolve-on-every-path obligation to machinery whose stop() provably
#: drains queued Futures (the PR 3 contract, reviewed per entry).
#:
#: - "put_nowait": the scheduler admission queue (service.submit);
#:   stop() drains the queue and resolves every parked Future.
FUTURE_DRAINS = frozenset({"put_nowait"})

#: Constructor names that mint a one-shot Future.
FUTURE_FACTORIES = frozenset({"Future", "concurrent.futures.Future"})

# -- GC203: blocking calls under a held lock --------------------------------

#: Exact canonical names that always block.
BLOCKING_CANONICAL = frozenset({
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
    "socket.create_connection",
})

#: Attribute tails that block regardless of receiver.
BLOCKING_TAILS = frozenset({
    "sleep",        # time.sleep / clock.sleep — a FakeClock sleep still
                    # serializes every tick behind the held lock
    "wait",         # Event.wait / Condition.wait / Popen.wait
    "result",       # Future.result — the canonical caller-deadlock
    "recv", "accept", "connect", "sendall", "communicate",
    "invoke",       # session.invoke: a device program under a host lock
})

#: Attribute tails that block only in their no-positional-arg form —
#: ``q.get()`` / ``q.get(timeout=...)`` blocks, ``d.get(k, v)`` doesn't;
#: ``t.join()`` / ``t.join(5)`` blocks, ``sep.join(parts)`` doesn't.
BLOCKING_TAILS_NOARG = frozenset({"get", "join"})


def is_blocking_call(canonical: str, n_pos_args: int,
                     first_arg_is_number: bool) -> bool:
    """Judge one call site by its alias-resolved dotted name + arg shape."""
    if canonical in BLOCKING_CANONICAL:
        return True
    tail = canonical.split(".")[-1]
    if tail in BLOCKING_TAILS:
        return True
    if tail in BLOCKING_TAILS_NOARG:
        return n_pos_args == 0 or (n_pos_args == 1 and first_arg_is_number)
    return False


# -- GC204: sinks / IO under a held lock ------------------------------------

#: A lock whose NAME declares it a dedicated IO/sink serializer is
#: allowed to cover IO — that is its whole job (obs/tracing.py's
#: ``_sink_lock``, serve/cache.py's ``_disk_lock`` are the pattern the
#: PR 7 fix introduced: sink writes get their OWN lock so the admission
#: lock never waits on a disk).
IO_LOCK_NAME_RE = re.compile(r"(sink|disk|io|file|spill|write)", re.I)

#: Call-name tails that invoke a registered callback/sink.
SINK_TAILS = re.compile(r"(^|_)(sink|sinks|callback|callbacks|hook|hooks)"
                        r"$|^emit$|^on_[a-z_]+$")

#: IO call names (canonical) that must not run under a non-IO lock.
IO_CANONICAL = frozenset({
    "open", "os.write", "json.dump", "pickle.dump", "np.save",
    "numpy.save", "shutil.copyfile", "os.replace", "os.rename",
})


def is_sink_call(canonical: str) -> bool:
    if canonical in IO_CANONICAL:
        return True
    tail = canonical.split(".")[-1]
    return bool(SINK_TAILS.search(tail))


def is_io_lock(lock_key: str) -> bool:
    attr = lock_key.rsplit(".", 1)[-1].rsplit("::", 1)[-1]
    return bool(IO_LOCK_NAME_RE.search(attr))


# -- GC205: lock-held helper discipline -------------------------------------

LOCKED_HELPER_RE = re.compile(r"^_\w*_locked$")

# -- GC206: thread lifecycle ------------------------------------------------

#: Directories whose Thread() starts need a reachable join/stop path.
THREADED_DIRS = ("serve/", "obs/")

# -- scope ------------------------------------------------------------------

#: GC202 scope: Futures minted under these path segments.
FUTURE_DIRS = ("serve/",)


def in_dirs(relpath: str, dirs) -> bool:
    return any(f"/{d}" in f"/{relpath}" for d in dirs)

"""graftlint — static analysis for this repo's recurring bug classes.

The linter mechanizes invariants that Python will never enforce and that
human review has repeatedly had to catch by hand (DESIGN.md "Static
analysis (r8)"):

GL001  kill-switch read at import scope (the PR 3 ``ENABLE`` bug)
GL002  RAFT_* env read missing from the program-cache knob registry
GL003  program fingerprint not covering every model-config field
GL004  instance attribute mutated both inside and outside its lock
GL005  impure host call inside jit / scan-body / pallas-kernel code
GL006  pallas_call entry point without kill switch + ladder registration

Run ``python -m raft_stereo_tpu.analysis`` (full tree) or with
``--changed-only`` (git-changed files only).  Suppress a finding inline
with ``# graftlint: disable=GLxxx (reason)``.

``--trace`` additionally runs graftverify (``analysis/trace/``, GV101-
GV105): trace-level jaxpr/StableHLO analysis of the real entry points,
proving the invariants the AST layer can only grep for (DESIGN.md
"Trace-level analysis (r10)").

This package's TOP LEVEL is import-light by design: no jax, no numpy —
the linter must run (and the knob registry must be importable by
serve/) in any environment, instantly.  Only the ``trace`` subpackage
imports jax, and only when ``--trace`` asks for it.
"""

from raft_stereo_tpu.analysis.core import (Finding, Project,  # noqa: F401
                                           run_analysis)
from raft_stereo_tpu.analysis.knobs import (ENV_KNOBS,  # noqa: F401
                                            KERNEL_ENTRY_POINTS, KernelEntry)

"""Weight transplant: reference PyTorch checkpoints -> param pytree.

Loads the published ``raftstereo-{middlebury,eth3d,realtime}.pth`` checkpoints
(or any reference-architecture state_dict) into this framework's parameter
pytree. The mapping is mechanical:

- strip the ``module.`` prefix (the reference saves the DataParallel-wrapped
  module, ``train_stereo.py:134,184``);
- conv kernels OIHW -> HWIO;
- BatchNorm running statistics become the frozen-BN affine state (the reference
  never updates BN: ``freeze_bn``, ``core/raft_stereo.py:41-44``); the
  ``num_batches_tracked`` counters are dropped;
- InstanceNorm carries no parameters (torch ``affine=False`` default);
- the reference registers the batch-norm residual shortcut's norm twice
  (``norm3`` and ``downsample.1`` alias the same tensors,
  ``core/extractor.py:44-45``); the ``downsample.1`` spelling is read.

Only numpy/torch are needed; no reference code is imported.
"""

from __future__ import annotations

from typing import Dict, Mapping

import numpy as np

import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def _conv(sd: Mapping, name: str) -> Dict:
    p = {"w": jnp.asarray(_np(sd[f"{name}.weight"]).transpose(2, 3, 1, 0))}
    if f"{name}.bias" in sd:
        p["b"] = jnp.asarray(_np(sd[f"{name}.bias"]))
    return p


def _bn(sd: Mapping, name: str) -> Dict:
    return {"scale": jnp.asarray(_np(sd[f"{name}.weight"])),
            "bias": jnp.asarray(_np(sd[f"{name}.bias"])),
            "mean": jnp.asarray(_np(sd[f"{name}.running_mean"])),
            "var": jnp.asarray(_np(sd[f"{name}.running_var"]))}


def _norm(sd: Mapping, name: str, norm_fn: str) -> Dict:
    if norm_fn == "batch":
        return _bn(sd, name)
    if norm_fn == "group":
        return {"scale": jnp.asarray(_np(sd[f"{name}.weight"])),
                "bias": jnp.asarray(_np(sd[f"{name}.bias"]))}
    return {}  # instance / none: stateless


def _residual_block(sd: Mapping, name: str, norm_fn: str) -> Dict:
    p = {"conv1": _conv(sd, f"{name}.conv1"),
         "conv2": _conv(sd, f"{name}.conv2"),
         "norm1": _norm(sd, f"{name}.norm1", norm_fn),
         "norm2": _norm(sd, f"{name}.norm2", norm_fn)}
    if f"{name}.downsample.0.weight" in sd:
        p["downsample"] = {"conv": _conv(sd, f"{name}.downsample.0"),
                           "norm": _norm(sd, f"{name}.downsample.1", norm_fn)}
    return p


def _stage(sd: Mapping, name: str, norm_fn: str) -> list:
    return [_residual_block(sd, f"{name}.0", norm_fn),
            _residual_block(sd, f"{name}.1", norm_fn)]


def _basic_encoder(sd: Mapping, prefix: str, norm_fn: str) -> Dict:
    return {"conv1": _conv(sd, f"{prefix}.conv1"),
            "norm1": _norm(sd, f"{prefix}.norm1", norm_fn),
            "layer1": _stage(sd, f"{prefix}.layer1", norm_fn),
            "layer2": _stage(sd, f"{prefix}.layer2", norm_fn),
            "layer3": _stage(sd, f"{prefix}.layer3", norm_fn),
            "conv2": _conv(sd, f"{prefix}.conv2")}


def _multi_encoder(sd: Mapping, prefix: str, norm_fn: str, n_heads: int) -> Dict:
    p = {"conv1": _conv(sd, f"{prefix}.conv1"),
         "norm1": _norm(sd, f"{prefix}.norm1", norm_fn),
         "layer1": _stage(sd, f"{prefix}.layer1", norm_fn),
         "layer2": _stage(sd, f"{prefix}.layer2", norm_fn),
         "layer3": _stage(sd, f"{prefix}.layer3", norm_fn),
         "layer4": _stage(sd, f"{prefix}.layer4", norm_fn),
         "layer5": _stage(sd, f"{prefix}.layer5", norm_fn)}
    for scale in ("outputs08", "outputs16"):
        p[scale] = [{"res": _residual_block(sd, f"{prefix}.{scale}.{j}.0", norm_fn),
                     "conv": _conv(sd, f"{prefix}.{scale}.{j}.1")}
                    for j in range(n_heads)]
    p["outputs32"] = [{"conv": _conv(sd, f"{prefix}.outputs32.{j}")}
                      for j in range(n_heads)]
    return p


def _gru(sd: Mapping, name: str) -> Dict:
    return {g: _conv(sd, f"{name}.{g}") for g in ("convz", "convr", "convq")}


def _update_block(sd: Mapping, prefix: str) -> Dict:
    return {
        "encoder": {c: _conv(sd, f"{prefix}.encoder.{c}")
                    for c in ("convc1", "convc2", "convf1", "convf2", "conv")},
        "gru08": _gru(sd, f"{prefix}.gru08"),
        "gru16": _gru(sd, f"{prefix}.gru16"),
        "gru32": _gru(sd, f"{prefix}.gru32"),
        "flow_head": {"conv1": _conv(sd, f"{prefix}.flow_head.conv1"),
                      "conv2": _conv(sd, f"{prefix}.flow_head.conv2")},
        "mask": {"conv1": _conv(sd, f"{prefix}.mask.0"),
                 "conv2": _conv(sd, f"{prefix}.mask.2")},
    }


def transplant_state_dict(state_dict: Mapping, cfg: RAFTStereoConfig) -> Dict:
    """Convert a reference state_dict (torch tensors or numpy) to a param pytree."""
    sd = {k[len("module."):] if k.startswith("module.") else k: v
          for k, v in state_dict.items()}
    params = {
        "cnet": _multi_encoder(sd, "cnet", "batch", n_heads=2),
        "update_block": _update_block(sd, "update_block"),
        "context_zqr_convs": [_conv(sd, f"context_zqr_convs.{i}")
                              for i in range(cfg.n_gru_layers)],
    }
    if cfg.shared_backbone:
        params["conv2"] = {"res": _residual_block(sd, "conv2.0", "instance"),
                           "conv": _conv(sd, "conv2.1")}
    else:
        params["fnet"] = _basic_encoder(sd, "fnet", "instance")
    return params


def load_pth(path: str, cfg: RAFTStereoConfig) -> Dict:
    """Load a reference ``.pth`` checkpoint into a param pytree."""
    import torch  # local import: torch is only needed for transplant
    state_dict = torch.load(path, map_location="cpu", weights_only=True)
    return transplant_state_dict(state_dict, cfg)


# ---------------------------------------------------------------------------
# Reverse transplant: param pytree -> reference state_dict / .pth, so
# checkpoints trained here can feed the torch ecosystem the reference's
# consumers expect (``train_stereo.py:184`` saves, ``demo.py:24-27`` /
# ``evaluate_stereo.py:215-220`` load strict with the ``module.`` prefix).
# ---------------------------------------------------------------------------


def _put_conv(out: Dict, name: str, p: Mapping) -> None:
    out[f"{name}.weight"] = np.asarray(p["w"], np.float32).transpose(3, 2, 0, 1)
    if "b" in p:
        out[f"{name}.bias"] = np.asarray(p["b"], np.float32)


def _put_norm(out: Dict, name: str, p: Mapping, norm_fn: str) -> None:
    if norm_fn == "batch":
        out[f"{name}.weight"] = np.asarray(p["scale"], np.float32)
        out[f"{name}.bias"] = np.asarray(p["bias"], np.float32)
        out[f"{name}.running_mean"] = np.asarray(p["mean"], np.float32)
        out[f"{name}.running_var"] = np.asarray(p["var"], np.float32)
        # Strict loading requires the counter key; its value is unused in
        # eval mode (and the reference always freezes BN).
        out[f"{name}.num_batches_tracked"] = np.asarray(0, np.int64)
    elif norm_fn == "group":
        out[f"{name}.weight"] = np.asarray(p["scale"], np.float32)
        out[f"{name}.bias"] = np.asarray(p["bias"], np.float32)
    # instance / none: stateless


def _put_residual_block(out: Dict, name: str, p: Mapping, norm_fn: str) -> None:
    _put_conv(out, f"{name}.conv1", p["conv1"])
    _put_conv(out, f"{name}.conv2", p["conv2"])
    _put_norm(out, f"{name}.norm1", p["norm1"], norm_fn)
    _put_norm(out, f"{name}.norm2", p["norm2"], norm_fn)
    if "downsample" in p:
        _put_conv(out, f"{name}.downsample.0", p["downsample"]["conv"])
        # The reference registers the downsample norm twice (``norm3`` and
        # ``downsample.1`` alias one module, core/extractor.py:40-45);
        # strict loading needs both spellings.
        _put_norm(out, f"{name}.downsample.1", p["downsample"]["norm"], norm_fn)
        _put_norm(out, f"{name}.norm3", p["downsample"]["norm"], norm_fn)


def _put_stage(out: Dict, name: str, blocks, norm_fn: str) -> None:
    for j, blk in enumerate(blocks):
        _put_residual_block(out, f"{name}.{j}", blk, norm_fn)


def _put_basic_encoder(out: Dict, prefix: str, p: Mapping, norm_fn: str) -> None:
    _put_conv(out, f"{prefix}.conv1", p["conv1"])
    _put_norm(out, f"{prefix}.norm1", p["norm1"], norm_fn)
    for stage in ("layer1", "layer2", "layer3"):
        _put_stage(out, f"{prefix}.{stage}", p[stage], norm_fn)
    _put_conv(out, f"{prefix}.conv2", p["conv2"])


def _put_multi_encoder(out: Dict, prefix: str, p: Mapping, norm_fn: str) -> None:
    _put_conv(out, f"{prefix}.conv1", p["conv1"])
    _put_norm(out, f"{prefix}.norm1", p["norm1"], norm_fn)
    for stage in ("layer1", "layer2", "layer3", "layer4", "layer5"):
        _put_stage(out, f"{prefix}.{stage}", p[stage], norm_fn)
    for scale in ("outputs08", "outputs16"):
        for j, head in enumerate(p[scale]):
            _put_residual_block(out, f"{prefix}.{scale}.{j}.0", head["res"],
                                norm_fn)
            _put_conv(out, f"{prefix}.{scale}.{j}.1", head["conv"])
    for j, head in enumerate(p["outputs32"]):
        _put_conv(out, f"{prefix}.outputs32.{j}", head["conv"])


def export_state_dict(params: Mapping, cfg: RAFTStereoConfig, *,
                      module_prefix: bool = True) -> Dict[str, np.ndarray]:
    """Param pytree -> reference-layout state_dict (numpy values).

    ``module_prefix=True`` emits ``module.``-prefixed keys so the result
    loads strict into the reference's DataParallel-wrapped model exactly
    like its own checkpoints.
    """
    out: Dict[str, np.ndarray] = {}
    _put_multi_encoder(out, "cnet", params["cnet"], "batch")
    ub = params["update_block"]
    for c in ("convc1", "convc2", "convf1", "convf2", "conv"):
        _put_conv(out, f"update_block.encoder.{c}", ub["encoder"][c])
    for g in ("gru08", "gru16", "gru32"):
        for conv in ("convz", "convr", "convq"):
            _put_conv(out, f"update_block.{g}.{conv}", ub[g][conv])
    _put_conv(out, "update_block.flow_head.conv1", ub["flow_head"]["conv1"])
    _put_conv(out, "update_block.flow_head.conv2", ub["flow_head"]["conv2"])
    _put_conv(out, "update_block.mask.0", ub["mask"]["conv1"])
    _put_conv(out, "update_block.mask.2", ub["mask"]["conv2"])
    for i, conv in enumerate(params["context_zqr_convs"]):
        _put_conv(out, f"context_zqr_convs.{i}", conv)
    if cfg.shared_backbone:
        _put_residual_block(out, "conv2.0", params["conv2"]["res"], "instance")
        _put_conv(out, "conv2.1", params["conv2"]["conv"])
    else:
        _put_basic_encoder(out, "fnet", params["fnet"], "instance")
    if module_prefix:
        out = {f"module.{k}": v for k, v in out.items()}
    return out


def save_pth(params: Mapping, cfg: RAFTStereoConfig, path: str) -> None:
    """Save a param pytree as a reference-loadable ``.pth`` checkpoint."""
    import torch  # local import: torch is only needed for transplant
    sd = {k: torch.from_numpy(np.ascontiguousarray(v))
          for k, v in export_state_dict(params, cfg).items()}
    torch.save(sd, path)

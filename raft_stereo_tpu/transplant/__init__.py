from raft_stereo_tpu.transplant.torch_loader import (  # noqa: F401
    export_state_dict,
    load_pth,
    save_pth,
    transplant_state_dict,
)

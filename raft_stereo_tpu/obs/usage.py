"""Per-tenant usage accounting — who is spending the device?

The PR 10 ingress already keys admission quotas by a sanitized
``X-Raft-Tenant`` header; this module joins that same key through
admission into the scheduler rows and accumulates, per tenant:

- **requests by outcome** (the same outcome keys
  ``raft_requests_total`` uses, so the two series reconcile);
- **device seconds** — every steady (non-warming) device invocation's
  device time, partitioned EXACTLY among the rows riding the batch.
  Exactness is the load-bearing property (it is what makes per-tenant
  billing honest and the ROADMAP item 4 tier policy enforceable), so
  the ledger is kept in integer NANOSECONDS: one invocation's
  ``round(device_s * 1e9)`` is split with :func:`partition_ints`, whose
  shares sum to the total by construction — the chaos soak pins
  ``sum(per-tenant ns) == accounted-total ns`` as an integer equality,
  and the accounted total reconciles with
  ``raft_program_device_seconds_total`` at float tolerance;
- **ledger flops** (the program's per-invocation estimate, same exact
  integer partition) and **bytes in/out** on the wire (the ingress
  accounts request-body and response-body bytes).

Label discipline mirrors the PR 10 quota buckets exactly: the first
``max_tenants`` distinct names keep their own label, every later name
shares ``__other__`` — the metrics registry keeps every (name, labels)
instrument forever, so hostile tenant-name churn must be bounded HERE
(regression-pinned: churn past the bound cannot grow ``/metrics``).

Stdlib-only, no jax; the registry is injected.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

SCHEMA = 1

#: Mirrors serve/http.py TenantQuotas: bounded label cardinality.
DEFAULT_MAX_TENANTS = 1024
OVERFLOW_LABEL = "__other__"

#: Requests that arrive with no tenant at all (in-process callers, the
#: CLI batch driver) — the same fallback the quota key uses.
DEFAULT_TENANT = "default"


def sanitize_tenant(raw: Optional[str], max_len: int = 64) -> str:
    """A hostile header value becomes a bounded, label-safe tenant key:
    [A-Za-z0-9._-] kept, everything else mapped to ``_``, capped at
    ``max_len``; empty/absent is the ``default`` tenant.  Deterministic,
    so quota accounting, usage accounting and metric labels all agree on
    the key (this is the ONE implementation — serve/http.py imports it)."""
    if not raw:
        return DEFAULT_TENANT
    out = "".join(c if (c.isalnum() or c in "._-") else "_"
                  for c in raw[:max_len])
    return out or DEFAULT_TENANT


def partition_ints(total: int, n: int) -> List[int]:
    """Split ``total`` into ``n`` integer shares that sum to ``total``
    EXACTLY (the first ``total % n`` shares carry the remainder unit).
    This is what keeps per-tenant device time an exact partition of the
    program total — float division would leak ulps on every tick."""
    if n < 1:
        raise ValueError(f"cannot partition across {n} riders")
    base, rem = divmod(int(total), n)
    return [base + 1] * rem + [base] * (n - rem)


class _TenantRow:
    """Mutable per-tenant account; all fields integer or plain dict,
    mutated only under the accountant's lock."""

    __slots__ = ("device_ns", "flops", "bytes_in", "bytes_out", "outcomes",
                 "warm_joins", "converged", "cache_hits",
                 "cache_near_hits", "cache_misses")

    def __init__(self):
        self.device_ns = 0
        self.flops = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.outcomes: Dict[str, int] = {}
        # graftstream (serve/stream.py): frames that warm-started and
        # rows that exited through the convergence monitor — the
        # /debug/usage view of who is actually getting the streaming
        # speedup.
        self.warm_joins = 0
        self.converged = 0
        # graftrecall (serve/cache.py): exact hits, near-tier warm
        # seeds and misses — the /debug/usage view of who is actually
        # getting the zero-device-seconds win.
        self.cache_hits = 0
        self.cache_near_hits = 0
        self.cache_misses = 0


class UsageAccountant:
    """Bounded per-tenant usage ledger + its registry mirror.

    The integer ledger here is the exactness truth (/debug/usage reads
    it); the ``raft_tenant_*`` Prometheus series mirror it in float for
    scrapes.  One accountant per serving process, owned by the session
    (like the registry), shared by service, scheduler and ingress.
    """

    def __init__(self, registry, max_tenants: int = DEFAULT_MAX_TENANTS):
        self.registry = registry
        self.max_tenants = max_tenants
        self._lock = threading.Lock()
        self._labels: set = set()
        self._rows: Dict[str, _TenantRow] = {}
        self._device_ns_total = 0
        self._flops_total = 0

    # -- label discipline --------------------------------------------------

    def label(self, tenant: Optional[str]) -> str:
        """Metric-safe tenant label under the first-come bound: the
        (sanitized) name itself while the label set has room, the shared
        ``__other__`` after — same discipline as the quota buckets."""
        tenant = sanitize_tenant(tenant)
        with self._lock:
            if tenant in self._labels:
                return tenant
            if len(self._labels) < self.max_tenants:
                self._labels.add(tenant)
                return tenant
            return OVERFLOW_LABEL

    def _row(self, label: str) -> _TenantRow:
        # Caller holds self._lock; label has already passed label().
        row = self._rows.get(label)
        if row is None:
            row = self._rows[label] = _TenantRow()
        return row

    # -- accounting --------------------------------------------------------

    def count_request(self, label: str, outcome: str) -> None:
        """One resolved request outcome (the same key the service counts
        into ``raft_requests_total``), attributed to its tenant."""
        with self._lock:
            row = self._row(label)
            row.outcomes[outcome] = row.outcomes.get(outcome, 0) + 1
        self.registry.counter(
            "raft_tenant_requests_total",
            "request outcomes by tenant (first-come-bounded labels)",
            tenant=label, outcome=outcome).inc()

    def add_device(self, labels: Sequence[str], device_s: float,
                   flops: Optional[float] = None) -> None:
        """One steady device invocation, partitioned exactly among the
        rows that rode it.  ``labels`` may repeat (two rows of one
        tenant in a batch) — shares accumulate, the integer sum stays
        exact."""
        if not labels or device_s < 0:
            return
        total_ns = int(round(device_s * 1e9))
        shares = partition_ints(total_ns, len(labels))
        flop_shares = (partition_ints(int(round(flops)), len(labels))
                       if flops else None)
        with self._lock:
            self._device_ns_total += total_ns
            if flop_shares is not None:
                self._flops_total += int(round(flops))
            for i, label in enumerate(labels):
                row = self._row(label)
                row.device_ns += shares[i]
                if flop_shares is not None:
                    row.flops += flop_shares[i]
        for i, label in enumerate(labels):
            self.registry.counter(
                "raft_tenant_device_seconds_total",
                "steady device seconds attributed to tenants (exact "
                "integer-ns partition across batch rows)",
                tenant=label).inc(shares[i] / 1e9)
            if flop_shares is not None and flop_shares[i]:
                self.registry.counter(
                    "raft_tenant_flops_total",
                    "ledger-estimated flops attributed to tenants",
                    tenant=label).inc(flop_shares[i])

    def note_stream(self, label: str, warm_join: bool = False,
                    converged: bool = False) -> None:
        """graftstream accounting: one warm join and/or one converged
        exit for this tenant.  Counted where the event actually happens
        (the scheduler's warm prepare, the convergence exit decision) —
        the per-tenant twin of the global ``raft_stream_*`` counters, so
        /debug/usage can answer "who is getting the streaming win"."""
        if not (warm_join or converged):
            return
        with self._lock:
            row = self._row(label)
            if warm_join:
                row.warm_joins += 1
            if converged:
                row.converged += 1
        if warm_join:
            self.registry.counter(
                "raft_tenant_stream_warm_joins_total",
                "warm-started frames by tenant", tenant=label).inc()
        if converged:
            self.registry.counter(
                "raft_tenant_stream_converged_total",
                "convergence early exits by tenant", tenant=label).inc()

    def note_cache(self, label: str, exact: bool = False,
                   near: bool = False, miss: bool = False) -> None:
        """graftrecall accounting (serve/cache.py): one exact hit, one
        near-tier warm seed, or one miss for this tenant.  Counted where
        the cache decision actually lands (ResponseCache.admit) — the
        per-tenant twin of the global ``raft_cache_*`` counters, so
        /debug/usage can answer "who is getting the cache win"."""
        if not (exact or near or miss):
            return
        with self._lock:
            row = self._row(label)
            if exact:
                row.cache_hits += 1
            if near:
                row.cache_near_hits += 1
            if miss:
                row.cache_misses += 1
        if exact:
            self.registry.counter(
                "raft_tenant_cache_hits_total",
                "exact-tier response-cache hits by tenant",
                tenant=label).inc()
        if near:
            self.registry.counter(
                "raft_tenant_cache_near_hits_total",
                "near-tier warm-start seeds by tenant",
                tenant=label).inc()
        if miss:
            self.registry.counter(
                "raft_tenant_cache_misses_total",
                "response-cache misses by tenant", tenant=label).inc()

    def add_bytes(self, label: str, n_in: int = 0, n_out: int = 0) -> None:
        """Wire bytes for one request (the ingress accounts these; the
        in-process paths have no wire bytes and account nothing)."""
        with self._lock:
            row = self._row(label)
            row.bytes_in += int(n_in)
            row.bytes_out += int(n_out)
        if n_in:
            self.registry.counter(
                "raft_tenant_bytes_in_total",
                "request body bytes read off the wire by tenant",
                tenant=label).inc(int(n_in))
        if n_out:
            self.registry.counter(
                "raft_tenant_bytes_out_total",
                "response body bytes written to the wire by tenant",
                tenant=label).inc(int(n_out))

    # -- reporting ---------------------------------------------------------

    @property
    def device_ns_total(self) -> int:
        with self._lock:
            return self._device_ns_total

    def doc(self) -> Dict:
        """The /debug/usage rollup: bounded (max_tenants + overflow),
        sorted by device time descending, integer-exact."""
        with self._lock:
            rows = {label: {
                "device_ns": r.device_ns,
                "device_s": r.device_ns / 1e9,
                "flops": r.flops,
                "bytes_in": r.bytes_in,
                "bytes_out": r.bytes_out,
                "requests": dict(sorted(r.outcomes.items())),
                "stream": {"warm_joins": r.warm_joins,
                           "converged_exits": r.converged},
                "cache": {"hits": r.cache_hits,
                          "near_hits": r.cache_near_hits,
                          "misses": r.cache_misses},
            } for label, r in self._rows.items()}
            total_ns = self._device_ns_total
            flops_total = self._flops_total
            n_labels = len(self._labels)
        ordered = dict(sorted(rows.items(),
                              key=lambda kv: (-kv[1]["device_ns"], kv[0])))
        return {"schema": SCHEMA,
                "max_tenants": self.max_tenants,
                "tenants_tracked": n_labels,
                "overflow_active": OVERFLOW_LABEL in rows,
                "device_ns_total": total_ns,
                "device_seconds_total": total_ns / 1e9,
                "flops_total": flops_total,
                "by_tenant": ordered}

    def status(self) -> Dict:
        """The small /healthz summary (the full rollup is /debug/usage)."""
        with self._lock:
            return {"tenants_tracked": len(self._labels),
                    "max_tenants": self.max_tenants,
                    "device_seconds_total": self._device_ns_total / 1e9}

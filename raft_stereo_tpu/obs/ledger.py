"""Program ledger: compiler-derived cost/memory accounting per program.

graftscope (r11) can say *that* fps/chip or requests/s moved; this module
is the device-facing half that says *why*.  At compile time every program
the serving session (or the train loop) builds feeds its compiled
executable's ``cost_analysis()`` / ``memory_analysis()`` into ONE ledger,
keyed by the exact program-cache key, so the repo finally has a
machine-readable answer to three questions the ROADMAP keeps asking:

- **what does each compiled program cost** (flops, HBM bytes accessed,
  argument/output/temp bytes, peak HBM while running) — straight from
  the compiler, ``None`` where a backend doesn't report (the CPU backend
  reports cost but thin memory stats; the contract is graceful absence,
  never a fabricated number);
- **what MFU does each program KIND achieve** — joining the ledger's
  per-invocation flop estimates (accumulated into
  ``raft_program_flops_total{kind=}`` by the session) against graftscope's
  ``raft_program_device_seconds_total{kind=}`` and the chip's peak-flops
  table yields per-kind MFU and a roofline class (compute- vs HBM-bound
  against peak flops / peak HBM bandwidth). MFU is reported **absent**
  whenever any join input is missing or zero — never divided into a lie;
- **does the warm program set fit HBM** — the session sums the ledger's
  peak-HBM column over its LRU cache per shape bucket (``/healthz``
  ``cache_hbm``), the question ROADMAP item 1 must answer before
  multiplying the bucket ladder by N chips.

**The scan caveat (measured, not assumed).** XLA's cost analysis counts a
``while``-loop body ONCE regardless of trip count (verified at 2 vs 8
scan iterations: identical flops — the same undercount ``bench.py`` found
in r6 and worked around with unrolled-slope extrapolation).  Ledger rows
therefore carry the RAW compiler numbers in ``flops``/``bytes_accessed``
plus a declared ``scan_scale``: the multiplier that converts
body-counted-once numbers into per-invocation estimates
(``flops_est = flops * scan_scale``).  Program kinds whose entire body
rides the refinement scan declare ``scan_scale = iters`` (``segment``,
``advance``); scan-free kinds declare ``1`` (``prepare``, ``epilogue``);
kinds mixing scan and non-scan stages (``full``, the train step) declare
``None`` and get NO estimate unless explicitly annotated (``bench.py``
annotates its headline row from the unrolled-slope measurement) — an
honest absence beats a 32x-wrong MFU.

Import-light on purpose (stdlib only at module scope): the report CLI and
the linter run without jax; ``analyze_compiled()`` only pokes at an
already-compiled object with getattr.

CLI::

    python -m raft_stereo_tpu.obs.ledger report LEDGER.json [--json]

exits 0 when every cached program has a ledger row, 1 when the dump
reports missing rows (the release-gate completeness bar), 2 on a
malformed file (never silently clean).
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import logging
import os
import sys
import threading
from typing import Dict, Iterable, List, Optional, Tuple

logger = logging.getLogger(__name__)

SCHEMA = 1

# -- chip peak tables ---------------------------------------------------------

#: Peak dense bf16 TFLOP/s by device kind (the MFU denominator). Matched
#: by substring of ``jax.devices()[0].device_kind``; moved here from
#: bench.py so the bench and the serving ledger share one table.
PEAK_FLOPS: Dict[str, float] = {
    "TPU v5 lite": 197e12, "TPU v5e": 197e12,
    "TPU v4": 275e12, "TPU v5p": 459e12, "TPU v6e": 918e12,
}

#: Peak HBM bandwidth, bytes/s — the roofline's other axis.
PEAK_HBM_BW: Dict[str, float] = {
    "TPU v5 lite": 819e9, "TPU v5e": 819e9,
    "TPU v4": 1228e9, "TPU v5p": 2765e9, "TPU v6e": 1640e9,
}

#: HBM capacity, bytes — the cache-accounting ceiling ("will this bucket
#: ladder fit one chip").
HBM_BYTES: Dict[str, float] = {
    "TPU v5 lite": 16 * 2**30, "TPU v5e": 16 * 2**30,
    "TPU v4": 32 * 2**30, "TPU v5p": 95 * 2**30, "TPU v6e": 32 * 2**30,
}


def chip_peaks(device_kind: Optional[str]
               ) -> Optional[Tuple[float, float]]:
    """(peak_flops_per_s, peak_hbm_bytes_per_s) for a device kind, or
    ``None`` when the chip is not in the table (CPU/GPU hosts: their
    ledger rows are machine-local diagnostics, namespaced by ``backend``
    in every dump, and their MFU is reported absent rather than computed
    against a made-up peak — exactly like the ``cpu:``-namespaced metric
    keys the trajectory gate never pins)."""
    if not device_kind:
        return None
    for k, f in PEAK_FLOPS.items():
        if k in device_kind:
            return f, PEAK_HBM_BW[k]
    return None


def hbm_capacity(device_kind: Optional[str]) -> Optional[float]:
    if not device_kind:
        return None
    for k, v in HBM_BYTES.items():
        if k in device_kind:
            return v
    return None


# -- compiled-program analysis extraction ------------------------------------

#: memory_analysis() attribute -> row field. Every value is optional: a
#: backend that doesn't implement the stat yields None, never 0.
_MEMORY_FIELDS = (
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("temp_bytes", "temp_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
    ("generated_code_bytes", "generated_code_size_in_bytes"),
)


def analyze_compiled(compiled) -> Dict[str, Optional[float]]:
    """Extract {flops, bytes_accessed, argument/output/temp/alias/
    generated_code bytes} from a jax ``Compiled``.  Every key degrades to
    ``None`` independently: older jax returns cost_analysis as a
    one-element list, some backends return nothing, XLA reports -1 for
    "unknown" — none of those may crash serving or fabricate a zero."""
    out: Dict[str, Optional[float]] = {
        "flops": None, "bytes_accessed": None}
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — telemetry never takes serving down
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        for field, key in (("flops", "flops"),
                           ("bytes_accessed", "bytes accessed")):
            v = ca.get(key)
            if isinstance(v, (int, float)) and v > 0:
                out[field] = float(v)
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — same boundary
        ma = None
    for field, attr in _MEMORY_FIELDS:
        v = getattr(ma, attr, None) if ma is not None else None
        out[field] = float(v) if isinstance(v, (int, float)) and v >= 0 \
            else None
    return out


def ledger_id(key) -> str:
    """Short stable display id for a program-cache key: the session's
    ``kind@b<b>:<h>x<w>/it<iters>`` status format plus an 8-hex-char hash
    of the FULL key (fingerprint included), so two configs sharing a
    geometry still get distinct rows in traces and flight records."""
    digest = hashlib.sha1(repr(key).encode()).hexdigest()[:8]
    if (isinstance(key, tuple) and len(key) == 6
            and isinstance(key[0], str)):
        kind, b, h, w, iters, _fp = key
        return f"{kind}@b{b}:{h}x{w}/it{iters}#{digest}"
    head = key[0] if isinstance(key, tuple) and key else key
    return f"{head}#{digest}"


# -- the ledger ---------------------------------------------------------------

@dataclasses.dataclass
class LedgerRow:
    """One compiled program's compiler-derived account.  ``flops`` /
    ``bytes_accessed`` are the RAW compiler numbers (scan bodies counted
    once — see the module docstring); ``flops_est`` / ``bytes_est`` are
    the per-invocation estimates after ``scan_scale``, ``None`` when the
    structure makes an estimate dishonest."""

    id: str
    kind: str
    b: int = 1
    h: Optional[int] = None
    w: Optional[int] = None
    iters: int = 0
    scan_scale: Optional[int] = None
    backend: Optional[str] = None
    device_kind: Optional[str] = None
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    argument_bytes: Optional[float] = None
    output_bytes: Optional[float] = None
    temp_bytes: Optional[float] = None
    alias_bytes: Optional[float] = None
    generated_code_bytes: Optional[float] = None
    flops_est: Optional[float] = None
    bytes_est: Optional[float] = None

    @property
    def peak_hbm_bytes(self) -> Optional[float]:
        """Device-memory footprint while the program runs: arguments +
        outputs + temporaries minus aliased buffers. ``None`` when the
        backend reported no memory stats at all (an all-None row) —
        absent, not zero, so cache accounting can say "unknown"."""
        parts = [self.argument_bytes, self.output_bytes, self.temp_bytes]
        if all(p is None for p in parts):
            return None
        total = sum(p for p in parts if p is not None)
        return total - (self.alias_bytes or 0.0)

    def intensity(self) -> Optional[float]:
        """Arithmetic intensity flop/byte (scan scale cancels, so the raw
        compiler numbers are the honest numerator/denominator)."""
        if self.flops and self.bytes_accessed:
            return self.flops / self.bytes_accessed
        return None

    def roofline(self, peaks: Optional[Tuple[float, float]]
                 ) -> Optional[str]:
        """'compute-bound' / 'hbm-bound' against the chip ridge point;
        ``None`` off the table (CPU) or without compiler numbers."""
        inten = self.intensity()
        if peaks is None or inten is None:
            return None
        ridge = peaks[0] / peaks[1]
        return "compute-bound" if inten >= ridge else "hbm-bound"

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        d["peak_hbm_bytes"] = self.peak_hbm_bytes
        d["intensity"] = self.intensity()
        d["roofline"] = self.roofline(chip_peaks(self.device_kind))
        return d


def _derive_estimates(row: LedgerRow) -> None:
    if row.scan_scale is not None:
        if row.flops is not None:
            row.flops_est = row.flops * row.scan_scale
        if row.bytes_accessed is not None:
            row.bytes_est = row.bytes_accessed * row.scan_scale


class ProgramLedger:
    """Thread-safe map from the EXACT program-cache key to its
    :class:`LedgerRow`.  The session records at compile (warm) time and
    drops on LRU eviction; readers (``/healthz``, flight records, dumps)
    see a consistent snapshot."""

    def __init__(self):
        self._rows: Dict[object, LedgerRow] = {}
        self._lock = threading.Lock()

    def record(self, key, *, kind: str, b: int = 1,
               h: Optional[int] = None, w: Optional[int] = None,
               iters: int = 0, scan_scale: Optional[int] = None,
               analysis: Optional[Dict[str, Optional[float]]] = None,
               backend: Optional[str] = None,
               device_kind: Optional[str] = None) -> LedgerRow:
        row = LedgerRow(id=ledger_id(key), kind=kind, b=b, h=h, w=w,
                        iters=iters, scan_scale=scan_scale,
                        backend=backend, device_kind=device_kind)
        for field, value in (analysis or {}).items():
            if field in LedgerRow.__dataclass_fields__:
                setattr(row, field, value)
        _derive_estimates(row)
        with self._lock:
            self._rows[key] = row
        return row

    def annotate(self, key, **fields) -> Optional[LedgerRow]:
        """Attach out-of-band estimates to an existing row (``bench.py``
        writes its unrolled-slope ``flops_est`` here). Unknown keys are a
        no-op returning None — annotation is advisory telemetry."""
        with self._lock:
            row = self._rows.get(key)
            if row is None:
                return None
            for f, v in fields.items():
                if f in LedgerRow.__dataclass_fields__:
                    setattr(row, f, v)
            return row

    def drop(self, key) -> Optional[LedgerRow]:
        with self._lock:
            return self._rows.pop(key, None)

    def row(self, key) -> Optional[LedgerRow]:
        with self._lock:
            return self._rows.get(key)

    def rows(self) -> List[LedgerRow]:
        with self._lock:
            return list(self._rows.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def rows_by_id(self, ids: Iterable[str]) -> List[Dict]:
        wanted = set(ids)
        return [r.to_dict() for r in self.rows() if r.id in wanted]

    # -- the MFU join ------------------------------------------------------

    def attribution(self, registry, *, device_kind: Optional[str] = None,
                    peaks: Optional[Tuple[float, float]] = None) -> Dict:
        """Per-program-kind MFU/roofline: join the session-accumulated
        ``raft_program_flops_total`` / ``raft_program_hbm_bytes_total``
        counters with graftscope's ``raft_program_device_seconds_total``
        and the chip peak table.  Every output is ``None`` unless ALL of
        its inputs exist and are positive — zero device-seconds, a
        missing peak entry (CPU) or scan-opaque flops yield an absent
        MFU, never a division."""
        if peaks is None:
            peaks = chip_peaks(device_kind)
        kinds = {r.kind for r in self.rows()}
        kinds |= {labels.get("kind") for labels, _ in
                  registry.series("raft_program_device_seconds_total")}
        out: Dict[str, Dict] = {}
        for kind in sorted(k for k in kinds if k):
            flops = registry.value("raft_program_flops_total", kind=kind)
            hbm = registry.value("raft_program_hbm_bytes_total", kind=kind)
            secs = registry.value("raft_program_device_seconds_total",
                                  kind=kind)
            calls = registry.value("raft_program_calls_total", kind=kind)
            mfu = (flops / secs / peaks[0]
                   if peaks and flops > 0 and secs > 0 else None)
            bw_util = (hbm / secs / peaks[1]
                       if peaks and hbm > 0 and secs > 0 else None)
            roofline = None
            if peaks and flops > 0 and hbm > 0:
                roofline = ("compute-bound"
                            if flops / hbm >= peaks[0] / peaks[1]
                            else "hbm-bound")
            out[kind] = {"calls": calls, "device_seconds": secs,
                         "flops": flops or None, "hbm_bytes": hbm or None,
                         "mfu": mfu, "hbm_bw_util": bw_util,
                         "roofline": roofline}
        return out

    # -- dumps -------------------------------------------------------------

    def to_doc(self, *, cache_keys: Iterable = (),
               backend: Optional[str] = None,
               device_kind: Optional[str] = None,
               attribution: Optional[Dict] = None,
               cache_hbm: Optional[Dict] = None) -> Dict:
        """JSON-able dump + the completeness verdict the release gate
        enforces: every live cache key must have a ledger row."""
        cache_ids = [ledger_id(k) for k in cache_keys]
        with self._lock:
            have = {ledger_id(k) for k in self._rows}
            rows = [r.to_dict() for r in self._rows.values()]
        missing = sorted(i for i in cache_ids if i not in have)
        return {"schema": SCHEMA, "backend": backend,
                "device_kind": device_kind,
                "hbm_capacity_bytes": hbm_capacity(device_kind),
                "rows": rows, "cache": cache_ids, "missing": missing,
                "complete": not missing,
                "attribution": attribution or {},
                "cache_hbm": cache_hbm or {}}


def dump_path() -> Optional[str]:
    """The ``RAFT_LEDGER`` dump target (function-scope read — GL001):
    when the release gate exports it, the serve bench writes its
    session's ledger doc there for the gate's report step."""
    return os.environ.get("RAFT_LEDGER") or None


def save_doc(doc: Dict, path: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


# -- AOT wrapper for the train/eval steps ------------------------------------

class AotLedgerFn:
    """Wrap a jitted callable: the FIRST call lowers + compiles ahead of
    time to harvest the compiled program's analyses into the ledger, then
    EVERY call (first included) executes via plain jit dispatch.

    Why not execute the AOT executable directly, like the serving
    session does?  The train step donates (params, opt_state), and its
    output aliases identical rank-0 counters into one buffer (the GV105
    scalar exemption) — feeding that back through ``Compiled.__call__``
    is a hard XLA "donate the same buffer twice" error, while jit
    dispatch deduplicates donated buffers (measured on the real step).
    Serving programs donate nothing, which is why the session CAN run
    its AOT executables.  The jit call after the AOT compile re-traces
    but hits jax's in-process compilation cache (measured: ~7x cheaper
    than a fresh compile; the XLA-compile half is not paid twice).

    Not thread-safe by design: the train loop (its only caller) is
    single-threaded; the serving session does its own AOT under the
    program compile lock.
    """

    def __init__(self, jitted, ledger: ProgramLedger, key, *, kind: str,
                 iters: int = 0, scan_scale: Optional[int] = None):
        self._jitted = jitted
        self._ledger = ledger
        self._key = key
        self._kind = kind
        self._iters = iters
        self._scan_scale = scan_scale
        self._recorded = False

    def _record(self, args) -> None:
        import jax
        backend = jax.default_backend()
        device_kind = jax.devices()[0].device_kind
        try:
            compiled = self._jitted.lower(*args).compile()
            analysis = analyze_compiled(compiled)
        except Exception as e:  # noqa: BLE001 — telemetry-only compile
            # The AOT compile here is PURE telemetry (execution always
            # goes through jit dispatch, which compiles for itself), so
            # ANY failure degrades to an empty row instead of taking the
            # train loop down. Not hypothetical: on a multi-process CPU
            # pod the AOT path raises "Multiprocess computations aren't
            # implemented on the CPU backend" while jit dispatch trains
            # fine (caught live by tests/test_multihost.py). The serving
            # session is the opposite case — there the AOT executable IS
            # the execution path, so its compile errors must propagate to
            # the breaker.
            logger.warning(
                "ledger AOT compile unavailable for %s (%s: %s) — "
                "recording an empty row; training is unaffected",
                self._kind, type(e).__name__, e)
            analysis = {}
        self._ledger.record(self._key, kind=self._kind,
                            iters=self._iters, scan_scale=self._scan_scale,
                            analysis=analysis, backend=backend,
                            device_kind=device_kind)

    def __call__(self, *args):
        if not self._recorded:
            self._recorded = True
            self._record(args)
        return self._jitted(*args)


# -- CLI ----------------------------------------------------------------------

def _fmt_num(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if abs(v) >= 1e12:
        return f"{v / 1e12:.2f}T"
    if abs(v) >= 1e9:
        return f"{v / 1e9:.2f}G"
    if abs(v) >= 1e6:
        return f"{v / 1e6:.2f}M"
    return f"{v:.4g}"


def _fmt_bytes(v: Optional[float]) -> str:
    return "-" if v is None else f"{v / 2**20:.1f}MiB"


def load_doc(path: str) -> Dict:
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise ValueError(f"cannot read {path}: {e}") from e
    except ValueError as e:
        raise ValueError(f"{path} is not valid JSON: {e}") from e
    if not isinstance(doc, dict) or doc.get("schema") != SCHEMA or \
            not isinstance(doc.get("rows"), list):
        raise ValueError(
            f"{path} is not a schema-{SCHEMA} ledger dump "
            "({'schema': 1, 'rows': [...]})")
    # Element-level validation: a truncated/corrupted dump whose rows are
    # not id-carrying dicts must be exit 2 (malformed), not a misleading
    # exit-1 completeness failure with a traceback.
    for r in doc["rows"]:
        if not isinstance(r, dict) or not isinstance(r.get("id"), str):
            raise ValueError(
                f"{path}: malformed ledger row {r!r} (rows must be "
                "dicts carrying a string 'id')")
    return doc


def _cmd_report(args) -> int:
    doc = load_doc(args.ledger)
    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        print(f"ledger: {len(doc['rows'])} row(s), backend="
              f"{doc.get('backend')}, device={doc.get('device_kind')}")
        hdr = (f"{'program':<34} {'flops':>8} {'flops_est':>9} "
               f"{'bytes':>8} {'peak_hbm':>10} {'roofline':>13}")
        print(hdr)
        for r in sorted(doc["rows"], key=lambda r: r["id"]):
            print(f"{r['id']:<34} {_fmt_num(r.get('flops')):>8} "
                  f"{_fmt_num(r.get('flops_est')):>9} "
                  f"{_fmt_num(r.get('bytes_accessed')):>8} "
                  f"{_fmt_bytes(r.get('peak_hbm_bytes')):>10} "
                  f"{(r.get('roofline') or '-'):>13}")
        for kind, a in sorted((doc.get("attribution") or {}).items()):
            mfu = a.get("mfu")
            print(f"mfu[{kind}]: "
                  f"{f'{mfu:.2%}' if mfu is not None else 'absent'} "
                  f"({a.get('calls', 0):.0f} calls, "
                  f"{a.get('device_seconds', 0):.3f} device-s, "
                  f"{a.get('roofline') or 'roofline unknown'})")
        ch = doc.get("cache_hbm") or {}
        for bucket, v in sorted((ch.get("by_bucket") or {}).items()):
            print(f"cache_hbm[{bucket}]: {_fmt_bytes(v)}")
        if ch.get("total_bytes") is not None:
            cap = doc.get("hbm_capacity_bytes")
            of = f" of {_fmt_bytes(cap)}" if cap else ""
            print(f"cache_hbm[total]: {_fmt_bytes(ch['total_bytes'])}{of}")
    if doc.get("missing"):
        for m in doc["missing"]:
            print(f"FAIL: cached program {m} has no ledger row", flush=True)
        return 1
    print(f"ledger: complete ({len(doc.get('cache', []))} cached "
          "program(s) all have rows)")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m raft_stereo_tpu.obs.ledger",
        description=__doc__.split("\n\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("report", help="print a ledger dump; exit 1 when "
                       "any cached program lacks a row")
    r.add_argument("ledger")
    r.add_argument("--json", action="store_true")
    r.set_defaults(func=_cmd_report)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.func(args)
    except (ValueError, KeyError, TypeError) as e:
        # Malformed input can never read as a (mis)classified verdict.
        print(f"ledger: internal error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())

"""Structured request tracing: where did this request's deadline go?

Every admitted request gets a trace id and a :class:`RequestTrace` — an
ordered span timeline recorded host-side at **program boundaries only**
(admission, queue wait, upload, prepare, each advance tick, epilogue,
unpad, plus degrade/breaker decision events).  Spans never reach inside a
compiled program: the trace reads the session clock around device calls,
so GV103 (no host callbacks in traced programs) stays clean by
construction and the tracer costs nothing on the device.

Two recording targets, both bounded:

- an in-memory **ring** of the last N completed timelines (the /healthz
  debugging surface — ``tracer.last()`` answers "show me the previous
  request's breakdown" without any sink configured);
- an optional **JSONL sink** (``RAFT_TRACE=/path/file.jsonl``, read once
  at tracer construction — never at import time): one line per completed
  request, append-only, consumable by ``scratch/analyze_trace.py``-style
  offline tooling.

Span accounting is split into **tiling** spans and **concurrent** spans.
Tiling spans advance the trace cursor and partition the request's wall
time (queue_wait → prepare → advance… → epilogue → unpad), so their
summed durations reconcile with the reported end-to-end latency — exactly
(FakeClock) or up to scheduler-loop slack (RealClock).  Concurrent spans
(the background upload that overlaps a running segment) and zero-duration
events (breaker trips, degrade decisions) are recorded in the timeline
but excluded from the reconciliation sum.

The clock is injected (``faults.RealClock``/``FakeClock``), so span
arithmetic in tests is deterministic and instantaneous.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
from collections import deque
from typing import Dict, List, Optional

from raft_stereo_tpu.faults import RealClock

logger = logging.getLogger(__name__)

#: Default ring depth: enough recent timelines to debug a live incident,
#: bounded regardless of traffic.
DEFAULT_RING = 256


class Span:
    """One timeline interval. ``concurrent`` spans overlap tiling spans
    (background work) and never advance the trace cursor."""

    __slots__ = ("kind", "t0", "t1", "concurrent", "attrs")

    def __init__(self, kind: str, t0: float, t1: float,
                 concurrent: bool = False, attrs: Optional[Dict] = None):
        self.kind = kind
        self.t0 = t0
        self.t1 = t1
        self.concurrent = concurrent
        self.attrs = attrs or {}

    @property
    def duration(self) -> float:
        return self.t1 - self.t0

    def to_dict(self) -> Dict:
        d = {"kind": self.kind, "t0": self.t0, "t1": self.t1,
             "ms": (self.t1 - self.t0) * 1e3}
        if self.concurrent:
            d["concurrent"] = True
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


class RequestTrace:
    """Span timeline for one request, from admission to response.

    Mutated by whichever thread currently owns the request (submitter →
    scheduler/worker → uploader for its one concurrent span); hand-off
    happens through the service queue, which orders the accesses.
    ``finish()`` is idempotent — whoever resolves the response closes the
    trace, later calls are no-ops.
    """

    __slots__ = ("trace_id", "request_id", "t_start", "t_end", "spans",
                 "meta", "_clock", "_tracer", "_cursor", "_done")

    def __init__(self, tracer: "Tracer", trace_id: str,
                 request_id, t_start: float):
        self.trace_id = trace_id
        self.request_id = request_id
        self.t_start = t_start
        self.t_end: Optional[float] = None
        self.spans: List[Span] = []
        self.meta: Dict = {}
        self._clock = tracer.clock
        self._tracer = tracer
        self._cursor = t_start
        self._done = False

    # -- recording ---------------------------------------------------------

    def mark(self, kind: str, **attrs) -> None:
        """Close the interval from the cursor to now as one tiling span
        (the phase that just ended: admission, queue_wait, ...)."""
        now = self._clock.now()
        self.spans.append(Span(kind, self._cursor, now, attrs=attrs))
        self._cursor = now

    @contextlib.contextmanager
    def span(self, kind: str, **attrs):
        """Tiling span around a code block (device call, unpad, ...)."""
        t0 = self._clock.now()
        try:
            yield self
        finally:
            self.add_span(kind, t0, self._clock.now(), **attrs)

    def add_span(self, kind: str, t0: float, t1: float,
                 concurrent: bool = False, **attrs) -> None:
        """Record an explicit interval — the batched scheduler fans one
        device-call interval out to every row that rode the batch."""
        self.spans.append(Span(kind, t0, t1, concurrent=concurrent,
                               attrs=attrs))
        if not concurrent and t1 > self._cursor:
            self._cursor = t1

    def event(self, kind: str, **attrs) -> None:
        """Zero-duration decision point (breaker trip, degrade choice)."""
        now = self._clock.now()
        self.spans.append(Span(kind, now, now, concurrent=True,
                               attrs=attrs))

    def finish(self, status: str = "ok", **meta) -> None:
        if self._done:
            return
        self._done = True
        self.t_end = self._clock.now()
        self.meta["status"] = status
        self.meta.update({k: v for k, v in meta.items() if v is not None})
        self._tracer._record(self)

    # -- reporting ---------------------------------------------------------

    def summary(self) -> Dict:
        """Reconciliation view: total wall time vs the tiled partition."""
        t_end = self.t_end if self.t_end is not None else self._cursor
        tiled = sum(s.duration for s in self.spans if not s.concurrent)
        kinds: Dict[str, Dict] = {}
        for s in self.spans:
            k = kinds.setdefault(s.kind, {"count": 0, "ms": 0.0})
            k["count"] += 1
            k["ms"] += s.duration * 1e3
        return {"trace_id": self.trace_id,
                "total_ms": (t_end - self.t_start) * 1e3,
                "tiled_ms": tiled * 1e3,
                "kinds": kinds}

    def to_dict(self) -> Dict:
        return {"trace_id": self.trace_id,
                "request_id": self.request_id,
                "t_start": self.t_start,
                "t_end": self.t_end,
                "total_ms": ((self.t_end - self.t_start) * 1e3
                             if self.t_end is not None else None),
                "meta": dict(self.meta),
                "spans": [s.to_dict() for s in self.spans],
                "summary": self.summary()}


class _NullTrace:
    """Do-nothing trace: the disabled-tracing path is a handful of no-op
    method calls, no allocation, no clock reads (overhead-pinned in
    tests/test_obs.py)."""

    __slots__ = ()
    trace_id = None
    request_id = None
    spans: List[Span] = []

    def mark(self, kind: str, **attrs) -> None:
        pass

    @contextlib.contextmanager
    def span(self, kind: str, **attrs):
        yield self

    def add_span(self, kind: str, t0: float, t1: float,
                 concurrent: bool = False, **attrs) -> None:
        pass

    def event(self, kind: str, **attrs) -> None:
        pass

    def finish(self, status: str = "ok", **meta) -> None:
        pass

    def summary(self) -> Dict:
        return {"trace_id": None, "total_ms": 0.0, "tiled_ms": 0.0,
                "kinds": {}}


NULL_TRACE = _NullTrace()


class Tracer:
    """Trace-id source + bounded recorder (ring + optional JSONL sink).

    ``sink=None`` reads ``RAFT_TRACE`` once, here (a constructor is
    function scope — GL001's import-time-read class cannot recur); pass
    ``sink=False``-y empty string to force no sink regardless of env.
    """

    def __init__(self, clock=None, ring: int = DEFAULT_RING,
                 sink: Optional[str] = None, enabled: bool = True):
        self.clock = clock if clock is not None else RealClock()
        self.enabled = enabled
        if sink is None:
            sink = os.environ.get("RAFT_TRACE") or None
        self._sink_path = sink or None
        self._sink_file = None
        self._ring: "deque[Dict]" = deque(maxlen=ring)
        self._count = 0
        self._lock = threading.Lock()
        # Sink I/O gets its OWN lock: the JSONL write happens on the
        # request-completion path, and holding the tracer-wide lock (which
        # start_request takes on every admission) across a disk write
        # would head-of-line-block admissions behind a stalled filesystem.
        self._sink_lock = threading.Lock()

    def start_request(self, request_id=None) -> RequestTrace:
        """A fresh trace (or the no-op singleton when disabled). Trace ids
        are monotonic per tracer — grep-able across the ring and sink."""
        if not self.enabled:
            return NULL_TRACE  # type: ignore[return-value]
        with self._lock:
            n = self._count
            self._count = n + 1
        return RequestTrace(self, f"req-{n:06d}", request_id,
                            self.clock.now())

    def _record(self, trace: RequestTrace) -> None:
        doc = trace.to_dict()
        with self._lock:
            self._ring.append(doc)
            sink_path = self._sink_path
        if sink_path is None:
            return
        # Telemetry must never take serving down: a sink failure (bad
        # path, disk full) runs on the request-completion path — in
        # batched mode an escaped exception would kill the scheduler
        # thread and hang every pending Future. Log once, drop the sink,
        # keep serving (the in-memory ring is unaffected).
        try:
            line = json.dumps(doc, default=str, sort_keys=True) + "\n"
            with self._sink_lock:
                if self._sink_file is None:
                    # Line-buffered append: timelines survive crashes that
                    # never reach close() (engine/logger.py's promise).
                    self._sink_file = open(sink_path, "a", buffering=1)
                self._sink_file.write(line)
        except Exception:  # noqa: BLE001 — the telemetry/serving boundary
            logger.exception(
                "trace sink %s failed — disabling the JSONL sink "
                "(in-memory ring keeps recording)", sink_path)
            with self._lock:
                self._sink_path = None
            with self._sink_lock:
                if self._sink_file is not None:
                    try:
                        self._sink_file.close()
                    except OSError:
                        pass
                    self._sink_file = None

    # -- inspection --------------------------------------------------------

    def timelines(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def last(self) -> Optional[Dict]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def status(self) -> Dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "recorded": self._count,
                    "ring": len(self._ring),
                    "sink": self._sink_path}

    def close(self) -> None:
        with self._sink_lock:
            if self._sink_file is not None:
                self._sink_file.close()
                self._sink_file = None

"""graftfleet rollup — fold N per-instance ``/healthz`` documents into
ONE fleet-level health view (DESIGN.md "Fleet operations (r20)").

The fleet supervisor (``serve/fleet.py``) polls every instance's
``/healthz``; this module is the pure fold over those documents that
backs ``GET /fleet/healthz``.  It is deliberately arithmetic-only — no
sockets, no process state — so the aggregation contract is testable
without a single subprocess, and the supervisor stays the one owner of
liveness truth (a document here may be one probe interval stale; the
rollup labels each row with its instance uid so the reader can tell
which instance said what).

Aggregation rules (each chosen to keep the fleet number HONEST under
partial data):

- request outcome counts **sum** (the reconciliation surface the chaos
  storm checks against the router's own books);
- capacity ``headroom_rps`` **sums** across instances (independent
  devices serve independently) while ``saturation`` reports the **max**
  (the fleet is as saturated as its busiest member — averaging would
  hide one pegged instance behind three idle ones);
- ``fingerprint_id`` collects the distinct set: more than one entry
  means a rolling deploy is mid-flight (or failed half-way — the
  supervisor's generation field disambiguates);
- stream sessions / cache entries sum; uptime reports the min (the
  youngest instance bounds how warm the fleet can be).

Import-light like every obs module: stdlib only.
"""

from __future__ import annotations

from typing import Dict, List, Optional

#: /fleet/healthz document schema version.
FLEET_SCHEMA = 1


def _num(doc: Dict, *path, default=None):
    """Defensive nested read: a crashed instance's last document may be
    truncated or absent — a rollup that throws on one bad row would turn
    a single-instance failure into a fleet-health outage."""
    cur: object = doc
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return default
        cur = cur[key]
    return cur


def rollup(rows: List[Dict]) -> Dict:
    """Fold per-instance health rows into the fleet document.

    Each row is ``{"uid": ..., "state": ..., "doc": <instance /healthz
    or None>}`` — exactly what the supervisor holds per instance.  Rows
    whose ``doc`` is None (never probed, or dead before first probe)
    still count toward ``instances``/state tallies so the fleet size is
    never under-reported.
    """
    requests: Dict[str, int] = {}
    states: Dict[str, int] = {}
    fingerprints: List[str] = []
    headroom = 0.0
    headroom_seen = False
    saturation: Optional[float] = None
    stream_sessions = 0
    cache_entries = 0
    uptime_min: Optional[float] = None
    # graftpod: chips SUM across instances (each instance's mesh drives
    # its own devices — a 4-instance fleet of 2-chip meshes advertises
    # an 8-chip pod) and quarantined chips sum the same way.
    chips = 0
    chips_seen = False
    chips_quarantined = 0
    # graftheal: MTTR reports the MAX across instances (the fleet
    # recovered only when its slowest member did — averaging would hide
    # one slow recovery behind fast peers, the saturation argument
    # again); recovery events sum.
    mttr_last: Optional[float] = None
    heal_events = 0
    per_instance = []
    for row in rows:
        state = str(row.get("state", "unknown"))
        states[state] = states.get(state, 0) + 1
        doc = row.get("doc")
        entry = {"uid": row.get("uid"), "state": state}
        if isinstance(doc, dict):
            reqs = _num(doc, "requests", default={})
            for outcome, n in (reqs.items()
                               if isinstance(reqs, dict) else ()):
                requests[outcome] = requests.get(outcome, 0) + int(n)
                entry.setdefault("requests", {})[outcome] = int(n)
            fp = _num(doc, "fingerprint_id")
            if fp is not None:
                entry["fingerprint_id"] = fp
                if fp not in fingerprints:
                    fingerprints.append(fp)
            up = _num(doc, "uptime_s")
            if up is not None:
                entry["uptime_s"] = up
                uptime_min = up if uptime_min is None else min(
                    uptime_min, up)
            by_bucket = _num(doc, "capacity", "by_bucket", default={})
            inst_headroom = 0.0
            inst_seen = False
            for m in (by_bucket or {}).values():
                h = m.get("headroom_rps") if isinstance(m, dict) else None
                if h is not None:
                    inst_headroom += float(h)
                    inst_seen = True
            if inst_seen:
                headroom += inst_headroom
                headroom_seen = True
                entry["headroom_rps"] = inst_headroom
            ratio = _num(doc, "capacity", "saturation", "ratio")
            if ratio is not None:
                entry["saturation"] = ratio
                saturation = (float(ratio) if saturation is None
                              else max(saturation, float(ratio)))
            stream_sessions += int(
                _num(doc, "stream", "sessions", default=0) or 0)
            cache_entries += int(
                _num(doc, "cache", "entries", default=0) or 0)
            n_chips = _num(doc, "capacity", "chips", "n_data")
            if n_chips is not None:
                chips += int(n_chips)
                chips_seen = True
                entry["chips"] = int(n_chips)
                q = _num(doc, "capacity", "chips", "quarantined",
                         default=()) or ()
                chips_quarantined += len(q)
            m = _num(doc, "heal", "mttr", "last_s")
            if m is not None:
                entry["mttr_last_s"] = float(m)
                mttr_last = (float(m) if mttr_last is None
                             else max(mttr_last, float(m)))
            heal_events += int(
                _num(doc, "heal", "mttr", "events", default=0) or 0)
        per_instance.append(entry)
    return {
        "schema": FLEET_SCHEMA,
        "instances": len(rows),
        "states": states,
        "requests": requests,
        "fingerprints": fingerprints,
        "rolling": len(fingerprints) > 1,
        "headroom_rps": headroom if headroom_seen else None,
        "saturation": saturation,
        "chips": chips if chips_seen else None,
        "chips_quarantined": chips_quarantined if chips_seen else None,
        "mttr_last_s": mttr_last,
        "heal_events": heal_events,
        "stream_sessions": stream_sessions,
        "cache_entries": cache_entries,
        "uptime_min_s": uptime_min,
        "by_instance": per_instance,
    }

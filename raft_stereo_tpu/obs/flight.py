"""SLO flight recorder: when a request goes wrong, leave an artifact.

A p99 blowup, a breaker trip or a non-finite output on a live server
used to leave nothing behind but a counter increment — by the time an
operator looks, the ring buffer has rotated and the request's timeline
is gone.  The flight recorder persists a bounded set of **flight
records**: one JSON file per SLO-breaching request, written at response
resolution time by the service, containing

- the request's full graftscope span timeline (``obs/tracing.py``),
  degrade/breaker decision events included;
- the ledger rows of every program the request touched (spans carry the
  program's ledger id — see ``obs/ledger.py``);
- a registry snapshot and the breaker state at breach time;
- the response summary and the breach reason(s).

Contract (mirrors the ``RAFT_TRACE`` sink):

- armed by ``RAFT_FLIGHT_DIR`` (read ONCE, at construction — GL001's
  import-time class cannot recur) or an explicit argument; unarmed, every
  ``record()`` is a counted no-op;
- **bounded**: at most ``limit`` records live in the directory; the
  oldest (by the monotonic sequence number in the filename, which
  continues across restarts) are evicted first;
- **failure-isolated**: a sink failure (bad path, disk full) logs once,
  disables the recorder and never escapes into the serving thread — an
  exception here would kill the batch scheduler and hang every pending
  Future.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

#: Default bound on persisted flight records: enough to cover an incident
#: window, bounded regardless of how badly the SLO is burning.
DEFAULT_LIMIT = 32

_FLIGHT_RE = re.compile(r"^flight-(\d{6})-.*\.json$")


class FlightRecorder:
    def __init__(self, out_dir: Optional[str] = None, *,
                 limit: int = DEFAULT_LIMIT):
        if out_dir is None:
            out_dir = os.environ.get("RAFT_FLIGHT_DIR") or None
        if limit < 1:
            raise ValueError(f"flight-record limit must be >= 1, "
                             f"got {limit}")
        self._dir = out_dir
        self._limit = limit
        self._recorded = 0
        self._skipped = 0
        self._evicted = 0
        self._lock = threading.Lock()
        # Continue the sequence past any records a previous process left:
        # eviction order must stay oldest-first across restarts.
        self._seq = self._scan_seq() if out_dir else 0

    def _scan_seq(self) -> int:
        try:
            names = os.listdir(self._dir)
        except OSError:
            return 0
        seqs = [int(m.group(1)) for m in map(_FLIGHT_RE.match, names) if m]
        return max(seqs) + 1 if seqs else 0

    @property
    def enabled(self) -> bool:
        return self._dir is not None

    def record(self, doc: Dict, *, trace_id: Optional[str] = None
               ) -> Optional[str]:
        """Persist one flight record; returns its path, or ``None`` when
        unarmed or the sink just failed.  Never raises."""
        with self._lock:
            if self._dir is None:
                self._skipped += 1
                return None
            seq = self._seq
            self._seq += 1
            out_dir = self._dir
        name = f"flight-{seq:06d}-{trace_id or 'untraced'}.json"
        path = os.path.join(out_dir, name)
        try:
            os.makedirs(out_dir, exist_ok=True)
            tmp = path + ".tmp"
            # Flight records are written from the watchdog's bounce path
            # under _check_lock on purpose: the record must land before
            # the sweep releases (a crash right after the bounce must
            # not lose the evidence), the doc is byte-capped, and
            # _check_lock only serializes sweeps — the serving path
            # never waits on it.
            # graftlint: disable=GC204 (bounded flight dump on the watchdog path, not the serving path)
            with open(tmp, "w") as f:
                # graftlint: disable=GC204 (same bounded watchdog-path dump)
                json.dump(doc, f, default=str, sort_keys=True, indent=1)
                f.write("\n")
            # graftlint: disable=GC204 (atomic publish of the same dump)
            os.replace(tmp, path)
            self._evict(out_dir)
        except Exception:  # noqa: BLE001 — the telemetry/serving boundary
            logger.exception(
                "flight-record sink %s failed — disabling the recorder "
                "(serving continues, no further records)", out_dir)
            with self._lock:
                self._dir = None
            return None
        with self._lock:
            self._recorded += 1
        return path

    def _evict(self, out_dir: str) -> None:
        entries = sorted(n for n in os.listdir(out_dir)
                         if _FLIGHT_RE.match(n))
        excess = len(entries) - self._limit
        for name in entries[:max(0, excess)]:
            try:
                os.remove(os.path.join(out_dir, name))
                with self._lock:
                    self._evicted += 1
            except OSError:
                pass  # already gone (concurrent cleanup) — not a failure

    def records(self) -> List[str]:
        """Paths of the currently persisted records, oldest first."""
        with self._lock:
            out_dir = self._dir
        if out_dir is None:
            return []
        try:
            return [os.path.join(out_dir, n)
                    for n in sorted(os.listdir(out_dir))
                    if _FLIGHT_RE.match(n)]
        except OSError:
            return []

    def status(self) -> Dict:
        with self._lock:
            return {"enabled": self._dir is not None, "dir": self._dir,
                    "limit": self._limit, "recorded": self._recorded,
                    "evicted": self._evicted, "skipped": self._skipped}

"""graftdeck — the tick flight-deck: what did each scheduler tick DO?

The telemetry built so far answers "how long did this request take"
(tracing), "what did this program cost" (ledger) and "did we breach"
(flight) — but nothing records what the *scheduler* actually did tick by
tick, which is exactly where continuous-batching throughput goes to die:
partial batches, pad-row waste, idle gaps between ticks.  This module is
the operator-plane record of that loop:

- a **bounded ring** (default 1024, ``RAFT_DECK_TICKS``) of per-tick
  :class:`TickRecord` rows owned by the scheduler thread: tick seq,
  shape bucket, batch size, live-row occupancy, joins/exits/pad rows,
  the advance program's ledger id, steady host/device seconds (split
  exactly as ``raft_program_*_seconds_total`` splits them — the deck's
  per-tick device seconds reconcile with the counters and the trace
  span timeline, three-way and exactly under FakeClock), queue depth at
  tick start, and the scheduler generation;
- **sequential mode records too**: an invocation outside any open tick
  (the worker-pool path, direct ``session.infer``) lands as its own
  standalone row, so the reconciliation contract holds in both serving
  modes;
- ``GET /debug/ticks`` serves :meth:`TickDeck.doc` (bounded JSON), and
  ``python -m raft_stereo_tpu.obs.deck report`` renders the operator
  views offline: occupancy histogram, pad-waste by bucket, and the
  idle-gap analysis between ticks (the number that says whether the
  chip is starved by the host or busy);
- flight records link back here by **tick-seq range**: the scheduler
  stamps ``tick=<seq>`` on every fanned device span, so an SLO
  post-mortem names the exact ticks the request rode;
- :func:`thread_stacks` is the live-introspection partner of the PR 9
  watchdogs (``GET /debug/stacks``): an all-thread stack dump via
  ``sys._current_frames`` that names a hung invocation's parked frame
  while the watchdog is still counting down.

Threading contract: ``begin_tick``/``end_tick`` bracket one scheduler
tick on the calling thread (the open tick is thread-local, so a zombie
generation's tick can never corrupt a fresh generation's record);
``note_invocation`` accumulates into the calling thread's open tick or
appends a standalone row.  The ring itself is lock-guarded for the
``/debug/ticks`` readers.

Stdlib-only, no jax — importable from the linter's environment.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
from collections import deque
from typing import Dict, List, Optional

SCHEMA = 1

#: Default ring depth: at a few ticks per second this covers minutes of
#: scheduler history, bounded regardless of traffic.
DEFAULT_DECK_TICKS = 1024


def resolve_deck_ticks(value: Optional[int] = None) -> int:
    """Effective deck ring depth: explicit config wins, else
    ``RAFT_DECK_TICKS``, else 1024.  Telemetry sizing only (the
    HOST_ENV_KNOBS rationale) — no compiled program depends on it.
    A malformed value raises a ValueError NAMING the variable (the
    SLURM_CPUS_PER_TASK convention)."""
    if value is not None:
        return int(value)
    raw = os.environ.get("RAFT_DECK_TICKS", "").strip()
    if not raw:
        return DEFAULT_DECK_TICKS
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"RAFT_DECK_TICKS must be an integer, got {raw!r}") from None
    if n < 1:
        raise ValueError(f"RAFT_DECK_TICKS must be >= 1, got {n}")
    return n


@dataclasses.dataclass
class TickRecord:
    """One scheduler tick (``kind='tick'``) or one standalone sequential
    invocation (``kind=<program kind>``).  Time fields are SESSION-clock
    seconds; ``device_s``/``host_s`` cover steady invocations only and
    ``warm_s`` the compile-inclusive warming ones — the same split the
    ``raft_program_*_seconds_total`` counters use, which is what makes
    the three-way reconciliation an equality rather than a tolerance."""

    seq: int
    kind: str                      # 'tick' | program kind (standalone)
    t_start: float
    t_end: Optional[float] = None
    bucket: Optional[str] = None   # padded shape, "HxW"
    generation: Optional[int] = None
    queue_depth: Optional[int] = None  # pending joiners at tick start
    batch: int = 0                 # advance batch bucket (rows incl. pads)
    occupancy: int = 0             # live rows advanced
    joins: int = 0
    warm_joins: int = 0            # joins seeded via prepare_warm
    exits: int = 0
    converged: int = 0             # exits via the convergence monitor
    cache_hits: int = 0            # CUMULATIVE response-cache hits
    #                                (exact + near) at tick start — diff
    #                                two rows for the hit rate over a
    #                                window (graftrecall, serve/cache.py)
    pad_rows: int = 0
    iters: int = 0                 # refinement iters this tick advanced
    program: Optional[str] = None  # advance program's ledger id
    invocations: int = 0           # device calls inside this record
    chips: int = 1                 # mesh chips the device calls spanned
    #                                (graftpod; device_s stays the ONE
    #                                wall interval per invoke, never
    #                                multiplied by chips — the per-chip
    #                                view divides, obs/capacity.py)
    host_s: float = 0.0
    device_s: float = 0.0
    warm_s: float = 0.0

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


class TickDeck:
    """Bounded ring of :class:`TickRecord` rows + the thread-local
    open-tick accumulator the scheduler drives."""

    def __init__(self, clock=None, ticks: Optional[int] = None):
        if clock is None:
            from raft_stereo_tpu.faults import RealClock
            clock = RealClock()
        self._clock = clock
        self._ring_size = resolve_deck_ticks(ticks)
        self._ring: "deque[TickRecord]" = deque(maxlen=self._ring_size)
        self._seq = 0
        self._closed = 0   # records actually published to the ring —
        #                    dropped = closed - ringed, so an OPEN tick
        #                    (seq allocated, not yet ringed) can never
        #                    read as a spurious ring drop
        self._warm = 0     # CUMULATIVE records that carried compile-
        #                    inclusive warm time — monotone (unlike a
        #                    ring scan, which forgets as rows fall off),
        #                    so graftheal's "zero mid-request compiles
        #                    across a re-grow" pin is a two-read diff
        self._lock = threading.Lock()
        self._tl = threading.local()

    # -- recording ---------------------------------------------------------

    def _next_seq(self) -> int:
        with self._lock:
            seq = self._seq
            self._seq = seq + 1
        return seq

    def begin_tick(self, *, bucket: str, generation: Optional[int] = None,
                   queue_depth: Optional[int] = None) -> TickRecord:
        """Open one scheduler tick on the calling thread.  The record is
        private to this thread until :meth:`end_tick` publishes it to the
        ring, so /debug/ticks readers never see a half-written row."""
        rec = TickRecord(seq=self._next_seq(), kind="tick",
                         t_start=self._clock.now(), bucket=bucket,
                         generation=generation, queue_depth=queue_depth)
        self._tl.open = rec
        return rec

    def end_tick(self, rec: TickRecord) -> None:
        rec.t_end = self._clock.now()
        if getattr(self._tl, "open", None) is rec:
            self._tl.open = None
        with self._lock:
            self._ring.append(rec)
            self._closed += 1
            if rec.warm_s > 0:
                self._warm += 1

    def current(self) -> Optional[TickRecord]:
        """The calling thread's open tick, if any (the session's invoke
        uses this to decide tick-accumulate vs standalone row)."""
        return getattr(self._tl, "open", None)

    def note_invocation(self, *, kind: str, program: str, b: int, h: int,
                        w: int, t0: float, t1: float, host_s: float,
                        device_s: float, warming: bool,
                        chips: int = 1) -> Optional[int]:
        """One device invocation's timing.  Inside an open tick (the
        scheduler thread) it accumulates; outside (sequential workers,
        direct ``session.infer``) it records a standalone row and
        returns its seq so the caller can stamp ``tick=<seq>`` on the
        matching trace span.  ``chips`` is the mesh span of THIS
        invocation; an open tick takes the max (all of one tick's calls
        ride one mesh, but a quarantine between programs must surface
        the wider span, never hide it)."""
        open_tick = getattr(self._tl, "open", None)
        if open_tick is not None:
            open_tick.invocations += 1
            open_tick.chips = max(open_tick.chips, int(chips))
            if warming:
                open_tick.warm_s += host_s + device_s
            else:
                open_tick.host_s += host_s
                open_tick.device_s += device_s
            return None
        rec = TickRecord(seq=self._next_seq(), kind=kind, t_start=t0,
                         t_end=t1, bucket=f"{h}x{w}", batch=b,
                         occupancy=b, program=program, invocations=1,
                         chips=int(chips))
        if warming:
            rec.warm_s = host_s + device_s
        else:
            rec.host_s = host_s
            rec.device_s = device_s
        with self._lock:
            self._ring.append(rec)
            self._closed += 1
            if rec.warm_s > 0:
                self._warm += 1
        return rec.seq

    # -- reporting ---------------------------------------------------------

    def snapshot(self, n: Optional[int] = None) -> List[Dict]:
        """The newest ``n`` (default: all ringed) completed records,
        oldest first — the bounded /debug/ticks payload."""
        with self._lock:
            rows = list(self._ring)
        if n is not None:
            rows = rows[-max(1, int(n)):]
        return [r.to_dict() for r in rows]

    def status(self) -> Dict:
        with self._lock:
            ringed = len(self._ring)
            recorded = self._seq
            closed = self._closed
            warm = self._warm
        return {"ring": self._ring_size, "recorded": recorded,
                "dropped": max(0, closed - ringed),
                "warm_records": warm}

    def doc(self, n: Optional[int] = None) -> Dict:
        """The /debug/ticks document: bounded by construction (the ring)
        and further by ``n``."""
        return {"schema": SCHEMA, **self.status(),
                "ticks": self.snapshot(n)}


# ---------------------------------------------------------------------------
# Live debug introspection: all-thread stack dump (GET /debug/stacks).
# ---------------------------------------------------------------------------

#: Bounds on the stack dump — the endpoint must stay cheap and bounded
#: even on a process with many handler threads and deep stacks.
STACKS_MAX_THREADS = 64
STACKS_MAX_FRAMES = 32


def thread_stacks(max_threads: int = STACKS_MAX_THREADS,
                  max_frames: int = STACKS_MAX_FRAMES) -> Dict:
    """Bounded all-thread stack dump via ``sys._current_frames`` — the
    natural partner of the PR 9 watchdogs: while a hung device
    invocation is still inside its deadline, this names the exact frame
    the victim thread is parked in (acceptance-pinned against an
    injected device hang).  Read-only: no thread is interrupted, the
    frames are snapshotted and immediately released."""
    import traceback
    names = {t.ident: (t.name, t.daemon) for t in threading.enumerate()}
    frames = sys._current_frames()
    n_threads = len(frames)
    threads: List[Dict] = []
    try:
        for ident, frame in list(frames.items())[:max_threads]:
            name, daemon = names.get(ident, (None, None))
            stack = traceback.extract_stack(frame)[-max_frames:]
            threads.append({
                "ident": ident,
                "name": name,
                "daemon": daemon,
                "current": ident == threading.get_ident(),
                "frames": [{"file": f.filename, "line": f.lineno,
                            "function": f.name} for f in stack],
            })
    finally:
        del frames  # drop the frame references promptly
    return {"schema": SCHEMA, "thread_count": n_threads,
            "truncated": n_threads > max_threads,
            "threads": threads}


# ---------------------------------------------------------------------------
# Report CLI: `python -m raft_stereo_tpu.obs.deck report <doc.json|URL|->`
# ---------------------------------------------------------------------------


class DeckError(ValueError):
    """Malformed deck document — the CLI maps this to exit 2 (a corrupt
    dump can never read as a clean report)."""


def _load_doc(target: str) -> Dict:
    try:
        if target == "-":
            raw = sys.stdin.read()
        elif target.startswith(("http://", "https://")):
            from urllib.request import urlopen
            with urlopen(target, timeout=10) as resp:
                raw = resp.read().decode("utf-8")
        else:
            with open(target) as f:
                raw = f.read()
    except OSError as e:
        raise DeckError(f"cannot read {target}: {e}") from e
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise DeckError(f"{target} is not valid JSON: {e}") from e
    if not isinstance(doc, dict) or not isinstance(doc.get("ticks"), list):
        raise DeckError(
            f"{target} is not a deck document "
            "({'schema': 1, 'ticks': [...]} — save GET /debug/ticks)")
    for t in doc["ticks"]:
        if not isinstance(t, dict) or "seq" not in t or "t_start" not in t:
            raise DeckError(f"malformed tick record: {t!r}")
    return doc


def _pct(sample: List[float], p: float) -> Optional[float]:
    if not sample:
        return None
    s = sorted(sample)
    return s[min(len(s) - 1, int(p * len(s)))]


def report(doc: Dict, out=None) -> Dict:
    """Render the operator views of one deck document and return the
    computed summary (the CLI prints; tests assert on the dict)."""
    out = out or sys.stdout
    ticks = [t for t in doc["ticks"] if t.get("kind") == "tick"]
    standalone = [t for t in doc["ticks"] if t.get("kind") != "tick"]
    print(f"deck: {len(doc['ticks'])} record(s) "
          f"({len(ticks)} scheduler tick(s), {len(standalone)} "
          f"standalone invocation(s)), {doc.get('dropped', 0)} older "
          f"dropped from the ring", file=out)

    # Occupancy histogram: live rows per advancing tick.  Every field
    # read below is .get-defaulted: a hand-trimmed or future-schema doc
    # must degrade to partial output, never a KeyError traceback that
    # escapes the DeckError -> rc 2 contract.
    occ: Dict[int, int] = {}
    for t in ticks:
        if t.get("batch", 0) > 0:
            rows_live = int(t.get("occupancy", 0))
            occ[rows_live] = occ.get(rows_live, 0) + 1
    total_adv = sum(occ.values())
    print("occupancy histogram (live rows -> ticks):", file=out)
    for rows in sorted(occ):
        frac = occ[rows] / total_adv
        print(f"  {rows:4d}: {occ[rows]:6d}  {'#' * int(40 * frac)}",
              file=out)
    if not occ:
        print("  (no advancing ticks recorded)", file=out)
    occ_mean = (sum(r * c for r, c in occ.items()) / total_adv
                if total_adv else None)

    # Pad waste by shape bucket: dead rows / total rows advanced.
    waste: Dict[str, List[int]] = {}
    for t in ticks:
        if t.get("batch", 0) > 0:
            w = waste.setdefault(str(t.get("bucket")), [0, 0])
            w[0] += t.get("pad_rows", 0)
            w[1] += t.get("batch", 0)
    print("pad waste by bucket (pad rows / batch rows):", file=out)
    for bucket in sorted(waste):
        pads, rows = waste[bucket]
        print(f"  {bucket}: {pads}/{rows} = {pads / rows:.1%}", file=out)
    if not waste:
        print("  (no advancing ticks recorded)", file=out)

    # Mesh span (graftpod): ticks whose device calls rode a >1-chip mesh.
    mesh_ticks = [t for t in ticks if int(t.get("chips", 1)) > 1]
    if mesh_ticks:
        print(f"mesh ticks: {len(mesh_ticks)} of {len(ticks)} spanned "
              f"{max(int(t.get('chips', 1)) for t in mesh_ticks)} chip(s)",
              file=out)

    # Response-cache hit rate over the ring window (graftrecall):
    # cache_hits is cumulative at tick start, so last - first is the
    # hits served while these ticks ran.
    ch = [int(t.get("cache_hits", 0)) for t in ticks]
    cache_window = (ch[-1] - ch[0]) if len(ch) >= 2 else 0
    if any(ch):
        served = sum(t.get("exits", 0) for t in ticks)
        print(f"response-cache hits over the ring window: {cache_window} "
              f"(vs {served} computed exits"
              + (f", hit frac {cache_window / (cache_window + served):.1%}"
                 if cache_window + served else "") + ")", file=out)

    # Idle-gap analysis: host time between one tick's end and the next
    # tick's start — the is-the-chip-starved number.
    gaps: List[float] = []
    seq_sorted = sorted((t for t in ticks if t.get("t_end") is not None),
                        key=lambda t: t["t_start"])
    for prev, cur in zip(seq_sorted, seq_sorted[1:]):
        gaps.append(max(0.0, cur["t_start"] - prev["t_end"]))
    busy = sum((t["t_end"] - t["t_start"]) for t in seq_sorted)
    print("idle gaps between ticks:", file=out)
    if gaps:
        print(f"  n={len(gaps)}  total_idle={sum(gaps):.4f}s  "
              f"total_busy={busy:.4f}s  "
              f"idle_frac={sum(gaps) / max(1e-12, sum(gaps) + busy):.1%}",
              file=out)
        print(f"  p50={_pct(gaps, 0.5):.4f}s  p99={_pct(gaps, 0.99):.4f}s"
              f"  max={max(gaps):.4f}s", file=out)
    else:
        print("  (fewer than two completed ticks)", file=out)

    return {"occupancy_hist": {str(k): v for k, v in sorted(occ.items())},
            "occupancy_mean": occ_mean,
            "pad_waste": {b: (p / r if r else 0.0)
                          for b, (p, r) in waste.items()},
            "mesh_ticks": len(mesh_ticks),
            "cache_hits_window": cache_window,
            "idle_gaps": {"n": len(gaps), "total_s": sum(gaps),
                          "busy_s": busy}}


def _cmd_report(args) -> int:
    report(_load_doc(args.target))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m raft_stereo_tpu.obs.deck",
        description=__doc__.split("\n\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser(
        "report",
        help="occupancy histogram, pad-waste by bucket, idle-gap "
             "analysis from a saved GET /debug/ticks document")
    r.add_argument("target",
                   help="path to a deck JSON document, an http(s) URL "
                        "(the live /debug/ticks endpoint), or '-' for "
                        "stdin")
    r.set_defaults(func=_cmd_report)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.func(args)
    except DeckError as e:
        print(f"deck: internal error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())

"""Metrics registry: the ONE truth behind /healthz and /metrics.

Before this module the serving stack carried three hand-rolled metric
stores — ``session.py``'s ``_metrics`` dict, ``scheduler.py``'s ``_m`` +
occupancy counter + tick-latency deque, ``service.py``'s request
``Counter`` + latency deque — each with its own lock, its own percentile
math, and its own /healthz folding code.  This registry replaces all
three: counters, gauges and bounded reservoir histograms registered by
name (+ label set), rendered either as the plain dicts /healthz already
serves (``snapshot()`` / ``series()``) or as Prometheus text exposition
(``render_prometheus()``) so a scrape target costs one method call.

Design points:

- **bounded by construction**: histograms keep a fixed-size sample — a
  sliding window of the newest N (the latency default: percentiles must
  react to a FRESH regression on a long-running server) or a uniform
  lifetime reservoir (Vitter's algorithm R, deterministic per-instrument
  seed) — so latency tracking is O(1) memory at any request count; the
  deques they replace were bounded too, but every new call site had to
  remember to bound its own; here the bound is the type;
- **get-or-create**: ``counter(name, **labels)`` returns the existing
  instrument for an existing (name, labels) pair — a scheduler rebuilt on
  service restart keeps accumulating instead of double-registering;
  re-registering a name as a different instrument type is an error;
- **stdlib only, no jax**: importable from the linter's environment and
  from host-side tooling.

Percentile semantics match the deques this replaces byte-for-byte at
equal sample counts: ``sorted(sample)[min(n-1, int(p*n))]`` — /healthz
numbers cannot shift just because the store changed.
"""

from __future__ import annotations

import random
import re
import threading
import time
import zlib
from typing import Dict, Iterable, List, Optional, Tuple

#: Captured once at first import: the registry's view of "when this
#: process started" (standard exposition practice —
#: ``process_start_time_seconds`` lets a scraper detect restarts and
#: rate-window counters correctly). Close enough to exec time for any
#: serving process, with no /proc parsing or third-party dependency.
_PROCESS_START_S = time.time()

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Default reservoir size for histograms — matches the 512-sample sliding
#: windows the serving layer used before the registry existed.
DEFAULT_RESERVOIR = 512


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats render as integers so
    counters read naturally; everything else as repr (full precision)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    """Exposition-format label value escaping: backslash FIRST (or the
    escapes it introduces would be re-escaped), then newline and quote —
    a hostile label value must round-trip through a scraper, not corrupt
    the line protocol (golden-pinned in tests/test_obs.py)."""
    return v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r"\"")


def _escape_help(v: str) -> str:
    """HELP-line escaping per the exposition format: backslash and
    newline only (quotes are legal in help text)."""
    return v.replace("\\", r"\\").replace("\n", r"\n")


class Counter:
    """Monotonic float counter (``inc`` only — a value that can go down
    is a :class:`Gauge`)."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-size sample + exact count/sum/min/max. Two sampling modes,
    both O(size) memory forever (the long-run memory pin in
    tests/test_obs.py):

    - ``"window"`` (the latency default): the most RECENT ``size``
      observations — byte-identical semantics to the sliding deques this
      replaced, so /healthz p50/p99 keep reacting to a fresh latency
      regression on a long-running server (a lifetime-uniform sample
      would dilute a new regression to invisibility after enough
      history);
    - ``"reservoir"``: Vitter's algorithm R, an unbiased uniform sample
      over ALL observations — the right view for lifetime distributions.
      The RNG is seeded from the instrument identity (crc32, not the
      salted ``hash``) so a replayed test sees the same sample on every
      run.
    """

    __slots__ = ("name", "labels", "size", "mode", "_sample", "_count",
                 "_sum", "_min", "_max", "_rng", "_lock")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 size: int = DEFAULT_RESERVOIR, mode: str = "window"):
        if size < 1:
            raise ValueError(f"histogram {name}: reservoir size must be "
                             f">= 1, got {size}")
        if mode not in ("window", "reservoir"):
            raise ValueError(f"histogram {name}: unknown mode {mode!r}")
        self.name = name
        self.labels = labels
        self.size = size
        self.mode = mode
        self._sample: List[float] = []
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._rng = random.Random(zlib.crc32(repr((name, labels)).encode()))
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            if len(self._sample) < self.size:
                self._sample.append(v)
            elif self.mode == "window":
                # ring overwrite: the sample is always the newest `size`
                self._sample[(self._count - 1) % self.size] = v
            else:
                j = self._rng.randrange(self._count)
                if j < self.size:
                    self._sample[j] = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def n(self) -> int:
        """Current sample size (== count until the reservoir saturates) —
        the ``n`` the /healthz latency document reports."""
        with self._lock:
            return len(self._sample)

    def percentile(self, p: float) -> Optional[float]:
        """``sorted(sample)[min(n-1, int(p*n))]`` — the exact formula the
        pre-registry sliding windows used, so /healthz p50/p99 are
        byte-identical at equal sample counts."""
        with self._lock:
            sample = sorted(self._sample)
        if not sample:
            return None
        return sample[min(len(sample) - 1, int(p * len(sample)))]

    def stats(self) -> Dict:
        with self._lock:
            return {"count": self._count, "sum": self._sum,
                    "min": self._min, "max": self._max,
                    "sample_n": len(self._sample)}


class MetricsRegistry:
    """Named instrument store with label support and two renderings.

    One registry per serving process (the session owns it; service and
    scheduler share it), so /healthz and /metrics describe the same
    counters by construction.
    """

    #: Prometheus summary quantiles rendered for every histogram.
    QUANTILES = (0.5, 0.9, 0.99)

    def __init__(self):
        self._instruments: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                                object] = {}
        self._meta: Dict[str, Tuple[type, str]] = {}  # name -> (type, help)
        self._lock = threading.Lock()

    # -- registration ------------------------------------------------------

    def _get(self, cls, name: str, help: str,
             labels: Dict[str, str], **extra):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"invalid label name {k!r} on {name}")
        lab = tuple(sorted((k, str(v)) for k, v in labels.items()))
        key = (name, lab)
        with self._lock:
            inst = self._instruments.get(key)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(inst).__name__}, not {cls.__name__}")
                return inst
            prev = self._meta.get(name)
            if prev is not None and prev[0] is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{prev[0].__name__}, not {cls.__name__}")
            inst = cls(name, lab, **extra)
            self._instruments[key] = inst
            if prev is None or (help and not prev[1]):
                self._meta[name] = (cls, help or (prev[1] if prev else ""))
            return inst

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  reservoir: int = DEFAULT_RESERVOIR,
                  mode: str = "window", **labels) -> Histogram:
        return self._get(Histogram, name, help, labels, size=reservoir,
                         mode=mode)

    def set_build_info(self, **labels) -> None:
        """Standard exposition identity: ``raft_build_info`` (value
        always 1 — the information is the LABELS: config fingerprint,
        python/jax versions, backend) plus
        ``raft_process_start_time_seconds``, so every scrape identifies
        exactly what is running and when it came up.  Get-or-create like
        every other instrument: re-registering the same identity is a
        no-op, a new identity (fresh session) adds its own series."""
        self.gauge(
            "raft_build_info",
            "identity of the running build/config (value is always 1; "
            "the labels carry the information)",
            **{k: str(v) for k, v in labels.items()}).set(1.0)
        self.gauge(
            "raft_process_start_time_seconds",
            "unix time this process started (metrics-module import "
            "time)").set(_PROCESS_START_S)

    # -- queries -----------------------------------------------------------

    def series(self, name: str) -> List[Tuple[Dict[str, str], float]]:
        """All (labels, value) pairs of one counter/gauge family — the
        /healthz folding primitive (e.g. the request-outcome map)."""
        with self._lock:
            insts = [i for (n, _), i in self._instruments.items()
                     if n == name]
        return [(dict(i.labels), i.value) for i in insts
                if isinstance(i, (Counter, Gauge))]

    def value(self, name: str, **labels) -> float:
        """Value of one counter/gauge, 0.0 when never registered."""
        lab = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            inst = self._instruments.get((name, lab))
        if inst is None:
            return 0.0
        if isinstance(inst, Histogram):
            raise TypeError(f"{name} is a histogram; use series/stats")
        return inst.value

    def snapshot(self) -> Dict:
        """Plain-dict dump of every instrument (JSON-able; the /healthz
        derivation surface)."""
        with self._lock:
            items = sorted(self._instruments.items())
            meta = dict(self._meta)
        out: Dict = {}
        for (name, lab), inst in items:
            fam = out.setdefault(name, {
                "type": meta[name][0].__name__.lower(),
                "help": meta[name][1], "series": []})
            entry: Dict = {"labels": dict(lab)}
            if isinstance(inst, Histogram):
                entry.update(inst.stats())
                entry["p50"] = inst.percentile(0.50)
                entry["p99"] = inst.percentile(0.99)
            else:
                entry["value"] = inst.value
            fam["series"].append(entry)
        return out

    # -- Prometheus exposition --------------------------------------------

    @staticmethod
    def _label_str(labels: Iterable[Tuple[str, str]]) -> str:
        parts = [f'{k}="{_escape_label(v)}"' for k, v in labels]
        return "{%s}" % ",".join(parts) if parts else ""

    def render_prometheus(self) -> str:
        """Text exposition format (version 0.0.4): counters and gauges as
        themselves, reservoir histograms as summaries (quantile series +
        ``_sum``/``_count``)."""
        with self._lock:
            items = sorted(self._instruments.items())
            meta = dict(self._meta)
        lines: List[str] = []
        seen_header = set()
        for (name, lab), inst in items:
            if name not in seen_header:
                seen_header.add(name)
                cls, help_text = meta[name]
                kind = {"Counter": "counter", "Gauge": "gauge",
                        "Histogram": "summary"}[cls.__name__]
                if help_text:
                    lines.append(f"# HELP {name} {_escape_help(help_text)}")
                lines.append(f"# TYPE {name} {kind}")
            if isinstance(inst, Histogram):
                for q in self.QUANTILES:
                    v = inst.percentile(q)
                    qlab = lab + (("quantile", _fmt(q)),)
                    lines.append(
                        f"{name}{self._label_str(qlab)} "
                        f"{_fmt(v) if v is not None else 'NaN'}")
                lines.append(f"{name}_sum{self._label_str(lab)} "
                             f"{_fmt(inst.sum)}")
                lines.append(f"{name}_count{self._label_str(lab)} "
                             f"{_fmt(inst.count)}")
            else:
                lines.append(
                    f"{name}{self._label_str(lab)} {_fmt(inst.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

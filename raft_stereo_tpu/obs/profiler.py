"""On-demand ``jax.profiler`` windows for a live serving process.

``bench.py`` already wraps one frame in ``jax.profiler.trace`` for
offline attribution; a server needs the same capture **on demand**,
against live traffic, without a restart.  :class:`ProfilerWindow` wraps
``jax.profiler.start_trace``/``stop_trace`` behind a guarded toggle:

- the output directory comes from ``RAFT_PROFILE_DIR`` (read once, at
  construction — never at import time) or an explicit argument; with
  neither, the window is **disabled** and ``start()`` is a recorded
  no-op — an operator can always poke the endpoint safely;
- windows are serialized (``start`` while active is refused), counted,
  and visible in /healthz via ``status()``.

The profiler captures device activity for everything the process runs
during the window, so one window around N requests gives the op-level
device timeline that the host-side span traces (obs/tracing.py)
deliberately do not claim to know.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, Optional


class ProfilerWindow:
    def __init__(self, out_dir: Optional[str] = None):
        if out_dir is None:
            out_dir = os.environ.get("RAFT_PROFILE_DIR") or None
        self.out_dir = out_dir
        self._active = False
        self._windows = 0
        self._refused = 0
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.out_dir is not None

    def start(self) -> bool:
        """Open a capture window. Returns False (and counts the refusal)
        when disabled or already active — never raises at the operator."""
        with self._lock:
            if self.out_dir is None or self._active:
                self._refused += 1
                return False
            self._active = True
        import jax
        try:
            jax.profiler.start_trace(self.out_dir)
        except Exception:
            with self._lock:
                self._active = False
            raise
        return True

    def stop(self) -> Optional[str]:
        """Close the window; returns the output dir (None if no window
        was open). The stop is CLAIMED under the lock (flag cleared
        before the profiler call) so two racing stop() calls cannot both
        reach ``jax.profiler.stop_trace`` — the loser returns None."""
        with self._lock:
            if not self._active:
                return None
            self._active = False
        import jax
        try:
            jax.profiler.stop_trace()
        finally:
            with self._lock:
                self._windows += 1
        return self.out_dir

    @contextlib.contextmanager
    def window(self):
        opened = self.start()
        try:
            yield opened
        finally:
            if opened:
                self.stop()

    def status(self) -> Dict:
        with self._lock:
            return {"enabled": self.out_dir is not None,
                    "dir": self.out_dir,
                    "active": self._active,
                    "windows": self._windows,
                    "refused": self._refused}

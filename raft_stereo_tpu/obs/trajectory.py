"""The consolidated perf-trajectory gate: one file, every headline metric.

The repo's perf record was scattered: ``bench.py`` pins fps/chip via
checksum bands, ``scratch/bench_serve.py`` prints requests/s,
``scratch/bench_train.py`` prints steps/s — and only the first was
release-gated.  ROADMAP item 5 names the consequence: a serving or
training regression sails through a gate that only watches the forward
pass.  This module closes that:

- every bench **emits** its headline metric into ONE ``TRAJECTORY.json``
  (schema below) when ``RAFT_TRAJECTORY=/path`` is exported — the gate
  exports it for all three benches, so the file is the merged perf
  artifact of a gate run (gitignored, echoed on failure, mirroring
  ``analysis_report.json``);
- ``trajectory_bands.json`` (committed) **pins a band per metric**:
  ``{"value": <pinned>, "rel_band": 0.2}`` means the metric may not fall
  below ``pinned * (1 - rel_band)``; an explicit ``"min"`` overrides the
  derived floor.  A value ABOVE ``pinned * (1 + rel_band)`` is a note
  (re-pin the improvement), never a failure;
- ``check`` fails (exit 1) when ANY emitted entry with a pinned band is
  below its floor — fps/chip, requests/s and steps/s are now one gate;
- pin lifecycle copies ``bench.py``'s checksum ceremony: an existing band
  is only moved by an explicit re-pin; a MISSING band is recorded only
  under the gate's loud ``--autopin`` opt-in (TPU runs only — CPU numbers
  are machine-local and namespaced, see :func:`metric_key`), and
  recording never overwrites.

Metric keys are backend-namespaced exactly like the bench checksum pins:
a laptop run can never satisfy — or poison — a chip band.

CLI (also a release-gate step)::

    python -m raft_stereo_tpu.obs.trajectory check TRAJECTORY.json \
        --bands trajectory_bands.json [--autopin]
    python -m raft_stereo_tpu.obs.trajectory show TRAJECTORY.json

Exit codes mirror the analysis CLI: 0 in-band, 1 out-of-band, 2 internal
error (a malformed trajectory can never read as "clean").
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

SCHEMA = 1

#: Default regression band: 20% below the pinned value fails. Wide enough
#: for run-to-run jitter on a dedicated chip (BENCH_r0* history moves
#: single digits), tight enough that a real regression (a dead fast path
#: is 2x+) cannot hide.
DEFAULT_REL_BAND = 0.20


class TrajectoryError(ValueError):
    """Malformed trajectory/bands file — the CLI maps this to exit 2."""


def metric_key(metric: str, backend: Optional[str] = None) -> str:
    """Backend-namespaced metric key (bench.py's pin-key convention):
    bare on TPU, ``cpu:``/``gpu:``-prefixed elsewhere."""
    if backend is None or backend == "tpu":
        return metric
    return f"{backend}:{metric}"


def _empty() -> Dict:
    return {"schema": SCHEMA, "entries": []}


def load(path: str) -> Dict:
    """Load a trajectory file; a missing file is an empty trajectory, a
    present-but-malformed one is an error (never silently reset — the
    bench pin-file lesson)."""
    if not os.path.exists(path):
        return _empty()
    try:
        with open(path) as f:
            doc = json.load(f)
    except ValueError as e:
        raise TrajectoryError(f"{path} is not valid JSON: {e}") from e
    if not isinstance(doc, dict) or doc.get("schema") != SCHEMA or \
            not isinstance(doc.get("entries"), list):
        raise TrajectoryError(
            f"{path} is not a schema-{SCHEMA} trajectory "
            "({'schema': 1, 'entries': [...]})")
    return doc


def _atomic_write(path: str, doc: Dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def emit(metric: str, value: float, unit: str, *,
         backend: Optional[str] = None, source: Optional[str] = None,
         extra: Optional[Dict] = None,
         path: Optional[str] = None) -> Optional[Dict]:
    """Append one trajectory entry to ``path`` (default: the
    ``RAFT_TRAJECTORY`` env target; unset -> no-op, returns None) and
    return the entry written.  Benches call this right after printing
    their JSON line; outside a gate run it costs one env read."""
    if path is None:
        path = os.environ.get("RAFT_TRAJECTORY") or None
    if not path:
        return None
    doc = load(path)
    entry: Dict = {"metric": metric_key(metric, backend),
                   "value": float(value), "unit": unit}
    if backend is not None:
        entry["backend"] = backend
    if source is not None:
        entry["source"] = source
    if extra:
        entry["extra"] = extra
    doc["entries"].append(entry)
    _atomic_write(path, doc)
    return entry


# -- bands ------------------------------------------------------------------

def load_bands(path: str) -> Dict:
    if not os.path.exists(path):
        return {"schema": SCHEMA, "bands": {}}
    try:
        with open(path) as f:
            doc = json.load(f)
    except ValueError as e:
        raise TrajectoryError(f"{path} is not valid JSON: {e}") from e
    if not isinstance(doc, dict) or not isinstance(doc.get("bands"), dict):
        raise TrajectoryError(
            f"{path} is not a bands file ({{'schema': 1, 'bands': ...}})")
    return doc


def band_floor(band: Dict) -> float:
    """The failure threshold of one band: explicit ``min`` wins, else
    ``value * (1 - rel_band)``. A band with neither is malformed."""
    if "min" in band:
        return float(band["min"])
    if "value" not in band:
        raise TrajectoryError(
            f"band {band!r} has neither 'value' nor 'min' — no floor can "
            "be derived")
    return float(band["value"]) * (1.0 - float(
        band.get("rel_band", DEFAULT_REL_BAND)))


#: Extra keys autopin copies from an entry into its band: the device
#: ledger's diagnostic account (graftscope-device, DESIGN.md r12). On a
#: later out-of-band failure these pins let ``check`` say WHY: flops
#: changed => the compiled program itself changed; flops same but the
#: metric fell => same program, slower wall clock (machine/env drift).
DIAGNOSTIC_EXTRAS = ("flops", "bytes", "mfu")

#: Relative flops drift below which the program counts as "unchanged"
#: for the diagnosis (compiler reassociation jitter, not a regression).
FLOPS_DRIFT_RTOL = 0.02


def _diagnose(entry: Dict, band: Dict) -> str:
    """One-line failure attribution from the ledger extras (always
    produced — absence of telemetry is itself stated, never silent)."""
    e = entry.get("extra") or {}
    b = band.get("extra") or {}
    ef, bf = e.get("flops"), b.get("flops")
    if isinstance(ef, (int, float)) and isinstance(bf, (int, float)) and bf:
        drift = (ef - bf) / abs(bf)
        if abs(drift) > FLOPS_DRIFT_RTOL:
            return (f"diagnosis: program flops changed "
                    f"{bf:.4g} -> {ef:.4g} ({drift:+.1%}) — the compiled "
                    "program itself changed; suspect a model/lowering "
                    "regression, not the machine")
        return ("diagnosis: flops unchanged but the metric fell — same "
                "program, slower wall clock; suspect machine/env drift "
                "(backend flags, contention, thermal)")
    return ("diagnosis: no pinned flops extra for this metric — emit the "
            "device-ledger extras (obs/ledger.py) and re-pin to enable "
            "program-vs-machine attribution")


@dataclasses.dataclass
class CheckResult:
    failures: List[str]
    notes: List[str]
    unpinned: List[str]
    checked: int = 0

    @property
    def ok(self) -> bool:
        return not self.failures


def check(doc: Dict, bands_doc: Dict) -> CheckResult:
    """Every emitted entry with a pinned band must sit above its floor."""
    bands = bands_doc.get("bands", {})
    res = CheckResult([], [], [])
    for entry in doc.get("entries", []):
        metric = entry.get("metric")
        value = entry.get("value")
        if not isinstance(metric, str) or not isinstance(value, (int, float)):
            raise TrajectoryError(f"malformed trajectory entry: {entry!r}")
        band = bands.get(metric)
        if band is None:
            res.unpinned.append(metric)
            continue
        res.checked += 1
        floor = band_floor(band)
        # A min-only band (explicit floor, no pinned center) is legal:
        # it gates the downside and opts out of the upward re-pin note.
        pinned = band.get("value")
        if value < floor:
            ref = (f"pinned {float(pinned):.4f}, band "
                   f"{band.get('rel_band', DEFAULT_REL_BAND):.0%}"
                   if pinned is not None else "explicit min")
            res.failures.append(
                f"{metric}: {value:.4f} {entry.get('unit', '')} is below "
                f"the pinned floor {floor:.4f} ({ref}) — a perf "
                "regression; if intentional, re-pin trajectory_bands.json "
                "explicitly | " + _diagnose(entry, band))
        elif pinned is not None and value > float(pinned) * (1.0 + float(
                band.get("rel_band", DEFAULT_REL_BAND))):
            res.notes.append(
                f"{metric}: {value:.4f} exceeds the pinned band upward "
                f"(pinned {float(pinned):.4f}) — re-pin to lock in the "
                "improvement")
    return res


def autopin(doc: Dict, bands_doc: Dict,
            rel_band: float = DEFAULT_REL_BAND) -> List[str]:
    """Record a band for every UNPINNED entry (never moves an existing
    one — recording is the only way a band is born, re-pinning is a
    deliberate edit).  Returns the metrics pinned.  CPU-namespaced keys
    are skipped: a shared-runner CPU number is machine noise, not a
    floor worth enforcing."""
    bands = bands_doc.setdefault("bands", {})
    pinned: List[str] = []
    for entry in doc.get("entries", []):
        metric = entry["metric"]
        if metric in bands or ":" in metric:
            continue
        bands[metric] = {"value": float(entry["value"]),
                         "rel_band": rel_band,
                         "unit": entry.get("unit", "")}
        # Pin the device-ledger diagnostics alongside the value: a later
        # out-of-band failure can then attribute itself (program flops
        # changed vs machine drift) instead of just failing.
        extras = {k: (entry.get("extra") or {}).get(k)
                  for k in DIAGNOSTIC_EXTRAS
                  if isinstance((entry.get("extra") or {}).get(k),
                                (int, float))}
        if extras:
            bands[metric]["extra"] = extras
        pinned.append(metric)
    return pinned


# -- CLI --------------------------------------------------------------------

def _cmd_check(args) -> int:
    doc = load(args.trajectory)
    bands_doc = load_bands(args.bands)
    if args.autopin:
        newly = autopin(doc, bands_doc, rel_band=args.rel_band)
        if newly:
            _atomic_write(args.bands, bands_doc)
            for m in newly:
                print(f"trajectory: PINNED (new metric) {m} = "
                      f"{bands_doc['bands'][m]['value']:.4f} "
                      f"(band {args.rel_band:.0%}) — now enforced",
                      file=sys.stderr)
    if not bands_doc.get("bands"):
        # The gate passes vacuously with an empty bands file (it has
        # been empty since the gate was born — no on-chip --autopin run
        # yet). Say so LOUDLY in the gate output instead of printing a
        # clean-looking "0 out of band": a gate that checks nothing must
        # not read like a gate that checked everything.
        print("trajectory: WARNING: 0 bands pinned — gate is vacuous "
              "until the first on-chip --autopin")
    res = check(doc, bands_doc)
    for n in res.notes:
        print(f"note: {n}", file=sys.stderr)
    for m in sorted(set(res.unpinned)):
        print(f"unpinned: {m} (no band; --autopin records one on a TPU "
              "gate run)", file=sys.stderr)
    for f in res.failures:
        print(f"FAIL: {f}")
    print(f"trajectory: {len(doc['entries'])} entr"
          f"{'y' if len(doc['entries']) == 1 else 'ies'}, "
          f"{res.checked} checked against bands, "
          f"{len(res.failures)} out of band")
    return 1 if res.failures else 0


def _cmd_show(args) -> int:
    doc = load(args.trajectory)
    for e in doc["entries"]:
        src = f"  [{e['source']}]" if e.get("source") else ""
        print(f"{e['metric']}: {e['value']} {e.get('unit', '')}{src}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m raft_stereo_tpu.obs.trajectory",
        description=__doc__.split("\n\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("check", help="gate a trajectory against bands")
    c.add_argument("trajectory")
    c.add_argument("--bands", required=True)
    c.add_argument("--autopin", action="store_true",
                   help="record bands for unpinned non-namespaced metrics "
                        "(never overwrites; the gate's TPU-only ceremony)")
    c.add_argument("--rel-band", type=float, default=DEFAULT_REL_BAND)
    c.set_defaults(func=_cmd_check)
    s = sub.add_parser("show", help="print a trajectory")
    s.add_argument("trajectory")
    s.set_defaults(func=_cmd_show)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.func(args)
    except TrajectoryError as e:
        print(f"trajectory: internal error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())

"""Capacity & saturation model — how far is this service from the wall?

The scheduler's EMA cost table (per (program, batch bucket), warmed
steady-state only — PR 5) already knows what one tick costs; the deck
(obs/deck.py) knows how busy the device has actually been.  This module
turns those two trusted sources into the operator numbers the pod-scale
and MFU arcs will be steered by:

- **per-bucket theoretical requests/s**: a full-quality request costs
  ``prepare + segments x advance + epilogue`` at some batch bucket
  ``b``, amortized across the ``b`` rows riding it — so the bucket's
  ceiling is ``b / (e_prep + segments * e_adv + e_epi)``.  Every batch
  bucket with a warmed advance estimate is scored and the best wins
  (sequential deployments score ``1 / e_full`` the same way).  Missing
  estimates make the component 0 and flag the row ``partial`` — an
  honest under-informed ceiling, never a fabricated one; a bucket with
  no advance/full estimate at all reports ``None``;
- **live saturation**: device-busy fraction over a sliding window
  (default 60 s, ``RAFT_CAPACITY_WINDOW_MS``) computed from the deck's
  per-record steady+warm device seconds vs wall time — 1.0 means the
  device never idled, the distance to 1.0 is the admission headroom;
- **headroom gauges**: ``raft_capacity_headroom{bucket=}`` publishes
  ``theoretical_rps x (1 - saturation)`` — requests/s of remaining
  capacity — plus ``raft_capacity_saturation``; the same document rides
  ``/healthz`` (``capacity`` block) and the serve bench emits it into
  ``TRAJECTORY.json`` so gate runs pin predicted-vs-measured
  requests/s side by side.

Pure functions over plain rows — stdlib-only, no jax, no session import
(the session adapts its estimate table into ``rows``)."""

from __future__ import annotations

import os
from typing import Dict, List, Optional

SCHEMA = 1

#: Default saturation sliding window.
DEFAULT_WINDOW_S = 60.0


def resolve_capacity_window_s(value: Optional[float] = None) -> float:
    """Effective saturation window in seconds: explicit config wins,
    else ``RAFT_CAPACITY_WINDOW_MS``, else 60 s.  Telemetry windowing
    only (HOST_ENV_KNOBS) — no compiled program depends on it."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_CAPACITY_WINDOW_MS", "").strip()
    if not raw:
        return DEFAULT_WINDOW_S
    try:
        ms = float(raw)
    except ValueError:
        raise ValueError(
            f"RAFT_CAPACITY_WINDOW_MS must be a number, "
            f"got {raw!r}") from None
    if ms <= 0:
        raise ValueError(
            f"RAFT_CAPACITY_WINDOW_MS must be positive, got {ms}")
    return ms / 1e3


def model(rows: List[Dict], *, segments: int,
          valid_iters: int) -> Dict:
    """Theoretical requests/s per shape bucket from warmed EMA rows.

    ``rows``: ``[{kind, b, h, w, iters, est}]`` — the session's latency
    EMA table (steady-state seconds per invocation, warmups excluded by
    construction).  Returns ``{"by_bucket": {...}, "best_rps": ...}``.
    """
    by_shape: Dict[str, Dict] = {}
    for r in rows:
        bucket = f"{r['h']}x{r['w']}"
        by_shape.setdefault(bucket, {})[(r["kind"], r["b"])] = r["est"]

    out: Dict[str, Dict] = {}
    for bucket, ests in by_shape.items():
        candidates: List[Dict] = []
        # Batched serving: score every batch bucket with an advance EMA.
        for (kind, b), e_adv in ests.items():
            if kind != "advance" or e_adv is None:
                continue
            e_prep = ests.get(("prepare", b))
            e_epi = ests.get(("epilogue", b))
            per_batch = ((e_prep or 0.0) + segments * e_adv
                         + (e_epi or 0.0))
            if per_batch <= 0:
                continue
            candidates.append({
                "mode": "batched", "batch": b,
                "rps": b / per_batch,
                "seconds_per_request": per_batch / b,
                "partial": e_prep is None or e_epi is None,
                "components": {"prepare": e_prep,
                               "advance_per_segment": e_adv,
                               "epilogue": e_epi,
                               "segments": segments},
            })
        # Sequential serving: the single-scan full program...
        e_full = ests.get(("full", 1))
        if e_full:
            candidates.append({
                "mode": "sequential", "batch": 1, "rps": 1.0 / e_full,
                "seconds_per_request": e_full, "partial": False,
                "components": {"full": e_full},
            })
        # ...or the segmented prepare + k x segment path.
        e_seg = ests.get(("segment", 1))
        if e_seg:
            e_prep = ests.get(("prepare", 1))
            per_req = (e_prep or 0.0) + segments * e_seg
            candidates.append({
                "mode": "sequential_segmented", "batch": 1,
                "rps": 1.0 / per_req, "seconds_per_request": per_req,
                "partial": e_prep is None,
                "components": {"prepare": e_prep,
                               "segment_per_segment": e_seg,
                               "segments": segments},
            })
        best = max(candidates, key=lambda c: c["rps"], default=None)
        out[bucket] = (dict(best) if best is not None
                       else {"mode": None, "rps": None, "partial": True,
                             "components": {}})
    best_rps = max((m["rps"] for m in out.values()
                    if m["rps"] is not None), default=None)
    return {"schema": SCHEMA, "segments": segments,
            "valid_iters": valid_iters, "by_bucket": out,
            "best_rps": best_rps}


def saturation(deck_rows: List[Dict], *, now: float,
               window_s: float = DEFAULT_WINDOW_S) -> Optional[Dict]:
    """Device-busy fraction over the sliding window, from deck records.

    Busy time is each record's steady ``device_s`` plus compile-inclusive
    ``warm_s`` (a compiling device is not idle), clipped proportionally
    where a record straddles the window edge.  The denominator is the
    window span actually covered by history (``min(window, now - first
    record)``), so a young server is not diluted to near-zero.  Returns
    ``None`` when there is no history — absence, never a fabricated 0.

    With CONCURRENT submitters (sequential mode, ``workers >= 2``) the
    host-measured device intervals of different threads can overlap
    even though the one device serializes them, so the raw busy sum can
    exceed the wall window.  ``ratio`` is clamped to 1.0 — a saturation
    gauge must keep its "distance to 1.0 is the headroom" meaning — and
    the unclamped evidence stays visible as ``busy_s`` / ``covered_s``.
    """
    w0 = now - window_s
    busy = 0.0
    earliest: Optional[float] = None
    for t in deck_rows:
        t1 = t.get("t_end")
        if t1 is None or t1 <= w0:
            continue
        t0 = min(t["t_start"], t1)
        if earliest is None or t0 < earliest:
            earliest = t0
        span = t1 - t0
        frac = 1.0
        if span > 0:
            frac = max(0.0, min(t1, now) - max(t0, w0)) / span
        busy += (t.get("device_s", 0.0) + t.get("warm_s", 0.0)) * frac
    if earliest is None:
        return None
    covered = min(window_s, max(1e-12, now - max(earliest, w0)))
    return {"ratio": min(1.0, busy / covered), "busy_s": busy,
            "window_s": window_s, "covered_s": covered}


def headroom_recovered(pre: Optional[float], post: Optional[float], *,
                       tol: float = 0.10) -> Optional[bool]:
    """graftheal's recovery acceptance test as arithmetic: did summed
    ``headroom_rps`` return to within ``tol`` of its pre-fault value
    after a re-admission?  ``None`` in = ``None`` out (capacity EMAs
    not warmed — absence, never a fabricated verdict); a zero pre-fault
    headroom recovers trivially (there was nothing to restore).  Shared
    by the chaos storms and the release-gate trajectory extras so the
    in-test and in-gate definitions of "recovered" cannot drift."""
    if pre is None or post is None:
        return None
    if pre <= 0:
        return True
    return post >= pre * (1.0 - tol)


def saturation_per_chip(deck_rows: List[Dict], n_chips: int, *, now: float,
                        window_s: float = DEFAULT_WINDOW_S) -> List[Dict]:
    """Per-chip device-busy fractions over the sliding window (graftpod).

    A mesh invocation's device window covers ALL of its chips at once
    (one wall interval, the PR 12 reconciliation contract — never
    multiplied by the span), so each record's ``device_s + warm_s``
    counts toward chips ``0 .. chips-1``: the mesh always packs the
    leading chips of the device list, so a 2-chip record busies chips 0
    and 1 while chips 2+ idle.  Same window-edge clipping and covered
    denominator as :func:`saturation`; a chip with no history reports
    ``ratio: None`` — absence, never a fabricated 0.
    """
    w0 = now - window_s
    busy = [0.0] * max(1, int(n_chips))
    earliest = [None] * max(1, int(n_chips))
    for t in deck_rows:
        t1 = t.get("t_end")
        if t1 is None or t1 <= w0:
            continue
        t0 = min(t["t_start"], t1)
        span = t1 - t0
        frac = 1.0
        if span > 0:
            frac = max(0.0, min(t1, now) - max(t0, w0)) / span
        dt = (t.get("device_s", 0.0) + t.get("warm_s", 0.0)) * frac
        for chip in range(min(len(busy), max(1, int(t.get("chips", 1))))):
            busy[chip] += dt
            if earliest[chip] is None or t0 < earliest[chip]:
                earliest[chip] = t0
    out: List[Dict] = []
    for chip in range(len(busy)):
        if earliest[chip] is None:
            out.append({"chip": chip, "ratio": None, "busy_s": 0.0})
            continue
        covered = min(window_s,
                      max(1e-12, now - max(earliest[chip], w0)))
        out.append({"chip": chip,
                    "ratio": min(1.0, busy[chip] / covered),
                    "busy_s": busy[chip]})
    return out

"""graftscope — unified telemetry for the serving/training stack.

Four pieces, one contract (DESIGN.md "Observability (r11)"):

- :mod:`~raft_stereo_tpu.obs.metrics` — the metrics registry
  (counters / gauges / bounded reservoir histograms) that is the single
  truth behind ``/healthz`` and the Prometheus-text ``/metrics`` view;
- :mod:`~raft_stereo_tpu.obs.tracing` — per-request span timelines
  (trace id at admission; host-side spans at program boundaries only),
  ring-buffered and optionally JSONL-sunk via ``RAFT_TRACE``;
- :mod:`~raft_stereo_tpu.obs.profiler` — on-demand ``jax.profiler``
  windows (``RAFT_PROFILE_DIR``);
- :mod:`~raft_stereo_tpu.obs.trajectory` — the consolidated
  perf-trajectory gate (``TRAJECTORY.json`` + pinned bands) folding
  fps/chip, requests/s and steps/s into one release-gate verdict.

Import-light: nothing here imports jax at module scope (the registry and
trajectory tooling run in the linter's jax-free environment).
"""

from raft_stereo_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                         MetricsRegistry)
from raft_stereo_tpu.obs.profiler import ProfilerWindow
from raft_stereo_tpu.obs.tracing import (NULL_TRACE, RequestTrace, Span,
                                         Tracer)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "ProfilerWindow",
    "NULL_TRACE", "RequestTrace", "Span", "Tracer",
]

"""graftscope — unified telemetry for the serving/training stack.

Four pieces, one contract (DESIGN.md "Observability (r11)"):

- :mod:`~raft_stereo_tpu.obs.metrics` — the metrics registry
  (counters / gauges / bounded reservoir histograms) that is the single
  truth behind ``/healthz`` and the Prometheus-text ``/metrics`` view;
- :mod:`~raft_stereo_tpu.obs.tracing` — per-request span timelines
  (trace id at admission; host-side spans at program boundaries only),
  ring-buffered and optionally JSONL-sunk via ``RAFT_TRACE``;
- :mod:`~raft_stereo_tpu.obs.profiler` — on-demand ``jax.profiler``
  windows (``RAFT_PROFILE_DIR``);
- :mod:`~raft_stereo_tpu.obs.trajectory` — the consolidated
  perf-trajectory gate (``TRAJECTORY.json`` + pinned bands) folding
  fps/chip, requests/s and steps/s into one release-gate verdict;
- :mod:`~raft_stereo_tpu.obs.ledger` — graftscope-device: the
  compiler-derived cost/memory ledger per compiled program, the chip
  peak flops/bandwidth tables, per-program-kind MFU attribution and the
  ``obs.ledger report`` CLI (DESIGN.md "Device observability (r12)");
- :mod:`~raft_stereo_tpu.obs.flight` — the SLO flight recorder: bounded
  per-breach artifacts (timeline + ledger rows + registry snapshot)
  persisted to ``RAFT_FLIGHT_DIR``;
- :mod:`~raft_stereo_tpu.obs.deck` — graftdeck: the tick flight-deck
  (bounded per-tick scheduler records, ``RAFT_DECK_TICKS``), the
  ``obs.deck report`` CLI and the all-thread stack dump behind
  ``GET /debug/stacks`` (DESIGN.md "Operator plane (r15)");
- :mod:`~raft_stereo_tpu.obs.usage` — per-tenant usage accounting
  (requests/outcomes, exactly-partitioned device seconds, ledger flops,
  wire bytes) under the PR 10 bounded-label discipline;
- :mod:`~raft_stereo_tpu.obs.capacity` — the capacity & saturation
  model: per-bucket theoretical requests/s off the warmed EMA cost
  table, device-busy fraction off the deck, headroom gauges.

Import-light: nothing here imports jax at module scope (the registry and
trajectory tooling run in the linter's jax-free environment).
"""

# obs.ledger is deliberately NOT imported here (same as obs.trajectory
# and obs.deck): all three are `python -m` entry points, and importing
# them from the package __init__ would trip runpy's already-in-sys.modules
# warning on every CLI invocation. Import them by module path.
from raft_stereo_tpu.obs.flight import FlightRecorder
from raft_stereo_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                         MetricsRegistry)
from raft_stereo_tpu.obs.profiler import ProfilerWindow
from raft_stereo_tpu.obs.tracing import (NULL_TRACE, RequestTrace, Span,
                                         Tracer)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "ProfilerWindow", "FlightRecorder",
    "NULL_TRACE", "RequestTrace", "Span", "Tracer",
]

"""Supervised self-healing serving (DESIGN.md "Supervision & self-healing
(r13)").

The breaker ladder (serve/guard.py) survives *kernel* failures, but
nothing supervised the threads and device calls the ladder rides on: a
hung TPU invocation parks the scheduler thread forever, a crashed tick
loop or uploader strands every pending Future, and the only shutdown
path was a cooperative ``stop()`` no signal ever triggered.  This module
adds the missing supervision layer, host-side only — no compiled program
changes, nothing here ever reaches a trace:

- :class:`InvocationWatch` — a bounded registry of in-flight device
  invocations.  ``InferenceSession.invoke`` brackets every device call
  with ``begin``/``end``; the supervisor classifies an invocation as a
  **device hang** when its age exceeds ``max(EMA x factor, floor)``
  (``floor`` = ``RAFT_WATCHDOG_MS``; warming invocations, which include
  the XLA compile, get ``floor x warm_factor`` instead — a cold TPU
  compile is minutes, not a hang);
- :class:`Heartbeat` — staleness tracking for the scheduler tick loop
  (stamped once per loop iteration) plus a crash record: the loop
  wrapper marks the heartbeat dead with the exception that killed the
  thread, so a **crashed tick loop** is detected by state, not by
  polling ``Thread.is_alive`` races;
- :class:`Supervisor` — the monitor: a daemon thread (real-time poll)
  plus a synchronous :meth:`Supervisor.check_now` that tests and the
  chaos harness drive deterministically.  Every detection is a
  :class:`WatchdogTrip` counted in
  ``raft_watchdog_trips_total{kind=}``; the response is ONE call into
  ``StereoService._bounce`` — retire the scheduler generation, re-admit
  the harvested in-flight rows from their original (still-held) inputs
  under the retry budget, and leave a flight record naming the reason.

Clock discipline: all deadline arithmetic runs on the SESSION clock
(``faults.FakeClock`` in tests — zero real sleeping in the watchdog
math); only the monitor thread's poll interval is wall time, and tests
bypass it entirely via ``check_now``.

Knobs (read here, function scope — GL001's import-time class cannot
recur; registered in ``analysis/knobs.py`` ``SERVE_ENV_KNOBS`` with the
stays-out-of-the-fingerprint rationale):

- ``RAFT_WATCHDOG_MS``   — hang-deadline floor; ``0`` (the library
  default) disarms supervision.  ``serve_stereo.py`` defaults it ON.
- ``RAFT_RETRY_BUDGET``  — bounded re-admissions per request (default 2).
- ``RAFT_DRAIN_GRACE_MS``— graceful-drain hard deadline (default 10 s).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

#: Steady-state hang deadline = max(EMA x FACTOR, floor): a segment that
#: takes 4x its moving estimate is stuck, not slow.
WATCHDOG_FACTOR = 4.0

#: Warming invocations include the XLA compile (minutes on TPU): their
#: hang deadline is floor x WARM_FACTOR, never the steady-state rule.
WATCHDOG_WARM_FACTOR = 120.0

#: Tick-loop staleness threshold, in floors: the loop beats once per
#: iteration (~ms), so a heartbeat this old with work pending and no
#: in-flight device call means the loop is stuck outside a device call.
STALL_FACTOR = 4.0

DEFAULT_WATCHDOG_MS = 0.0      # disarmed unless configured (env or CLI)
DEFAULT_RETRY_BUDGET = 2
DEFAULT_DRAIN_GRACE_MS = 10_000.0


def _parse_number(name: str, raw: str, cast):
    """Parse one supervision env knob's value.  A malformed value raises
    a ValueError NAMING the variable (the SLURM_CPUS_PER_TASK convention
    from data/loader.py) instead of a bare ``int()``/``float()``
    traceback that never says which env var to fix.  The ``os.environ``
    read itself stays LITERAL at each resolve_* site so GL001/GL002 can
    see it — reading through a name parameter here would blind the
    registry cross-check."""
    try:
        return cast(raw)
    except ValueError:
        raise ValueError(
            f"{name} must be a number, got {raw!r}") from None


def resolve_watchdog_ms(value: Optional[float] = None) -> float:
    """Effective watchdog floor in ms: explicit config wins, else
    ``RAFT_WATCHDOG_MS``, else disarmed (0).  Host-side scheduling only —
    never part of any program fingerprint."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_WATCHDOG_MS", "").strip()
    if not raw:
        return DEFAULT_WATCHDOG_MS
    return _parse_number("RAFT_WATCHDOG_MS", raw, float)


def resolve_retry_budget(value: Optional[int] = None) -> int:
    """Effective per-request retry budget: explicit config wins, else
    ``RAFT_RETRY_BUDGET``, else 2."""
    if value is not None:
        return int(value)
    raw = os.environ.get("RAFT_RETRY_BUDGET", "").strip()
    if not raw:
        return DEFAULT_RETRY_BUDGET
    return _parse_number("RAFT_RETRY_BUDGET", raw, int)


def resolve_drain_grace_ms(value: Optional[float] = None) -> float:
    """Effective graceful-drain hard deadline in ms: explicit config
    wins, else ``RAFT_DRAIN_GRACE_MS``, else 10 s."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_DRAIN_GRACE_MS", "").strip()
    if not raw:
        return DEFAULT_DRAIN_GRACE_MS
    return _parse_number("RAFT_DRAIN_GRACE_MS", raw, float)


@dataclasses.dataclass(frozen=True)
class InFlight:
    """One registered device invocation (a snapshot row — the watch hands
    out copies, never its mutable state)."""

    token: int
    program: str           # ledger id of the program being invoked
    kind: str              # program kind (full/prepare/advance/...)
    warming: bool          # first invocation: compile-inclusive
    est: Optional[float]   # latency EMA for this program, if recorded
    t0: float              # session-clock start time


@dataclasses.dataclass(frozen=True)
class WatchdogTrip:
    """One watchdog detection.  ``kind`` is the metrics label
    (``raft_watchdog_trips_total{kind=}``) and selects the failure code
    budget-exhausted requests carry (``device_hang`` for hangs,
    ``scheduler_restarted`` for everything else)."""

    kind: str      # 'device_hang' | 'tick_crashed' | 'tick_stalled'
                   # | 'uploader_dead' | 'uploader_stalled'
    reason: str    # human-readable one-liner (flight records, logs)
    detail: Dict = dataclasses.field(default_factory=dict)


class InvocationWatch:
    """Bounded registry of in-flight device invocations.

    ``invoke`` calls ``begin``/``end`` around every device call; the
    supervisor reads ``active()``/``overdue()``.  All state is mutated
    under one lock — a begin/end pair costs two dict ops, nothing else
    (the disabled-supervision path pays this too; it is nanoseconds
    against a device call).
    """

    def __init__(self, clock):
        self._clock = clock
        self._lock = threading.Lock()
        self._active: Dict[int, InFlight] = {}
        self._next = 0
        self._total = 0

    def begin(self, program: str, kind: str, *, warming: bool,
              est: Optional[float]) -> int:
        with self._lock:
            token = self._next
            self._next = token + 1
            self._total += 1
            self._active[token] = InFlight(
                token=token, program=program, kind=kind, warming=warming,
                est=est, t0=self._clock.now())
        return token

    def end(self, token: int) -> None:
        with self._lock:
            self._active.pop(token, None)

    @property
    def count(self) -> int:
        with self._lock:
            return len(self._active)

    @property
    def total(self) -> int:
        with self._lock:
            return self._total

    def active(self) -> List[InFlight]:
        with self._lock:
            return list(self._active.values())

    @staticmethod
    def allowed_s(inv: InFlight, floor_s: float,
                  factor: float = WATCHDOG_FACTOR,
                  warm_factor: float = WATCHDOG_WARM_FACTOR) -> float:
        """The hang deadline for one invocation: warming (compile-
        inclusive) gets the warm grace; steady calls get
        ``max(EMA x factor, floor)`` — EMA-less steady calls (estimate
        evicted) fall back to the floor alone."""
        if inv.warming:
            return floor_s * warm_factor
        if inv.est is None:
            return floor_s
        return max(inv.est * factor, floor_s)

    def overdue(self, now: float, floor_s: float,
                factor: float = WATCHDOG_FACTOR,
                warm_factor: float = WATCHDOG_WARM_FACTOR
                ) -> List[Tuple[InFlight, float, float]]:
        """Every in-flight invocation past its hang deadline, as
        ``(invocation, age_s, allowed_s)`` rows."""
        out = []
        for inv in self.active():
            allowed = self.allowed_s(inv, floor_s, factor, warm_factor)
            age = now - inv.t0
            if age > allowed:
                out.append((inv, age, allowed))
        return out


class Heartbeat:
    """Liveness stamp + crash record for one supervised loop thread."""

    def __init__(self, name: str, clock):
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._t_last = clock.now()
        self._died: Optional[BaseException] = None

    def beat(self) -> None:
        with self._lock:
            self._t_last = self._clock.now()

    def mark_dead(self, exc: BaseException) -> None:
        with self._lock:
            self._died = exc

    @property
    def died(self) -> Optional[BaseException]:
        with self._lock:
            return self._died

    def age(self, now: Optional[float] = None) -> float:
        if now is None:
            now = self._clock.now()
        with self._lock:
            return now - self._t_last


class Supervisor:
    """The watchdog monitor for one :class:`StereoService` generation
    lineage.

    Owns nothing but detection: every response action (bouncing the
    scheduler generation, re-admitting rows, failing budget-exhausted
    requests) goes through ``service._bounce``, so the service keeps
    single ownership of its lifecycle state.  ``check_now`` is the
    synchronous entry point tests and the chaos harness drive; the
    monitor thread merely calls it on a real-time poll.
    """

    def __init__(self, service, *, watchdog_s: float,
                 factor: float = WATCHDOG_FACTOR,
                 warm_factor: float = WATCHDOG_WARM_FACTOR,
                 stall_factor: float = STALL_FACTOR,
                 poll_s: Optional[float] = None):
        if watchdog_s <= 0:
            raise ValueError(f"Supervisor needs a positive watchdog "
                             f"floor, got {watchdog_s}")
        self._service = service
        self._session = service.session
        self._clock = self._session.clock
        self.watchdog_s = float(watchdog_s)
        self.factor = factor
        self.warm_factor = warm_factor
        self.stall_factor = stall_factor
        # Poll a quarter of the floor: a hang is detected within ~1.25
        # floors worst case, and an idle monitor costs a few wakeups/s.
        self.poll_s = (poll_s if poll_s is not None
                       else min(0.5, max(0.01, self.watchdog_s / 4)))
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # check_now is callable from the monitor thread, tests and the
        # chaos pump concurrently; one check at a time, losers skip (the
        # next poll re-checks) rather than queueing up duplicate bounces.
        self._check_lock = threading.Lock()
        # Tokens of invocations already bounced for: a REAL device hang
        # never calls watch.end(), so without this memory every sweep
        # would re-detect the same wedged invocation and bounce each
        # fresh, healthy generation in a poll-period storm.  Pruned
        # against the live set each sweep (bounded by true leaks).
        self._hang_tripped: set = set()
        reg = service.registry
        self.registry = reg
        self._m_checks = reg.counter(
            "raft_watchdog_checks_total", "supervisor sweeps run")
        self._last_check = self._clock.now()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Supervisor":
        if self._thread is None or not self._thread.is_alive():
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, name="stereo-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.poll_s):
            try:
                self.check_now()
            except Exception:  # noqa: BLE001 — the monitor must survive
                logger.exception("watchdog sweep failed; next poll retries")

    # -- detection ---------------------------------------------------------

    def check_now(self) -> List[WatchdogTrip]:
        """One synchronous watchdog sweep: detect, count, respond.
        Returns the trips found (empty = healthy).  Concurrent callers
        skip instead of stacking duplicate bounces."""
        if not self._check_lock.acquire(blocking=False):
            return []
        try:
            # _check_lock IS held here — taken by the non-blocking
            # acquire above; try/finally instead of `with` is what lets
            # concurrent sweeps skip instead of queueing (graftlock
            # models the finally-release region as held, so this needs
            # no suppression).
            return self._check_locked()
        finally:
            self._check_lock.release()

    def _check_locked(self) -> List[WatchdogTrip]:
        self._m_checks.inc()
        now = self._clock.now()
        self._last_check = now
        trips: List[WatchdogTrip] = []

        # 1. Hung device invocation: wall-clock deadline on every invoke.
        hung = self._session.watch.overdue(
            now, self.watchdog_s, self.factor, self.warm_factor)
        self._hang_tripped &= {
            inv.token for inv in self._session.watch.active()}
        for inv, age, allowed in hung:
            if inv.token in self._hang_tripped:
                continue  # already bounced for this one; a real hang
                #           never ends and must not bounce every fresh
                #           healthy generation on every sweep
            self._hang_tripped.add(inv.token)
            trips.append(WatchdogTrip(
                "device_hang",
                f"device invocation {inv.kind} ({inv.program}) in flight "
                f"{age:.3f}s > allowed {allowed:.3f}s",
                detail={"kind": inv.kind, "program": inv.program,
                        "age_s": age, "allowed_s": allowed,
                        "warming": inv.warming}))

        doc = self._service.supervised_state()
        if doc is not None:
            hb = doc["heartbeat"]
            sched = doc["scheduler"]
            thread_alive = doc["thread_alive"]

            # 2. Crashed tick loop: the loop wrapper records the killing
            # exception (state, not an is_alive race).
            died = hb.died if hb is not None else None
            if died is not None or (not thread_alive and not doc["stopping"]):
                trips.append(WatchdogTrip(
                    "tick_crashed",
                    f"scheduler tick loop died: "
                    f"{type(died).__name__ if died else 'thread exited'}"
                    f"{f': {died}' if died else ''}",
                    detail={"error": str(died) if died else None}))
            # 3. Stalled tick loop: heartbeat stale with work pending and
            # NO in-flight device call (an in-flight call is the device
            # hang's territory — double-tripping one stuck tick would
            # burn two retries for one fault).
            elif (hb is not None and sched is not None and sched.has_work
                    and not hung and self._session.watch.count == 0
                    and hb.age(now) > self.watchdog_s * self.stall_factor):
                trips.append(WatchdogTrip(
                    "tick_stalled",
                    f"scheduler heartbeat stale {hb.age(now):.3f}s with "
                    f"work pending",
                    detail={"age_s": hb.age(now)}))

            # 4. Dead or wedged uploader: its joiners' uploads can never
            # complete (a wedged one is otherwise invisible — the tick
            # loop keeps beating while run_tick finds nothing uploaded).
            uploader = sched.uploader if sched is not None else None
            if uploader is not None and not any(
                    t.kind == "tick_crashed" for t in trips):
                dead = uploader.dead
                busy = uploader.busy_since
                if dead is not None or not uploader.alive:
                    trips.append(WatchdogTrip(
                        "uploader_dead",
                        f"uploader thread dead: "
                        f"{dead if dead is not None else 'thread exited'}",
                        detail={"error": str(dead) if dead else None}))
                elif busy is not None and now - busy > \
                        self.watchdog_s * self.stall_factor:
                    trips.append(WatchdogTrip(
                        "uploader_stalled",
                        f"uploader busy {now - busy:.3f}s on one "
                        f"transfer — wedged host->device path",
                        detail={"age_s": now - busy}))

        for trip in trips:
            self.registry.counter(
                "raft_watchdog_trips_total",
                "watchdog detections by kind", kind=trip.kind).inc()
            logger.warning("watchdog trip [%s]: %s", trip.kind, trip.reason)
        if trips:
            self._service._bounce(trips)
        return trips

    # -- reporting ---------------------------------------------------------

    def status(self) -> Dict:
        return {
            "armed": self._thread is not None and self._thread.is_alive(),
            "floor_ms": self.watchdog_s * 1e3,
            "factor": self.factor,
            "warm_factor": self.warm_factor,
            "poll_ms": self.poll_s * 1e3,
            "last_check_age_s": self._clock.now() - self._last_check,
            "in_flight": [dataclasses.asdict(i)
                          for i in self._session.watch.active()],
        }


def drain_deadline(grace_s: float) -> float:
    """Wall-clock drain deadline.  Drain is an *operational* action
    (SIGTERM from an orchestrator): its hard deadline runs on real time
    even when the serving clock is fake — a FakeClock drain would
    otherwise never time out."""
    return time.monotonic() + grace_s


def drain_expired(deadline: float) -> bool:
    return time.monotonic() >= deadline

"""graftrecall — content-addressed response cache (ROADMAP item 5).

Heavy real traffic is repetitive: fixed rigs re-see the same scenes,
adjacent requests barely differ.  Every repeat previously paid the full
device cost of a cold forward even though the serving stack already had
everything needed to answer it for free.  This module is the two-tier
answer — the cheapest requests/s multiplier in the repo, because a hit
costs ZERO device seconds:

- **exact tier**: key = sha256 of the PADDED input pair bytes + the
  session's live program fingerprint + the serving tier (``valid_iters``)
  + the sanitized tenant → the stored response contract, served straight
  from a byte-accounted host-RAM LRU (``RAFT_CACHE_BYTES``; optional
  ``RAFT_CACHE_DIR`` disk spill for evicted entries).  Bit-identical to a
  recompute BY CONSTRUCTION: only cold, full-quality responses are ever
  deposited (a warm-seeded or degraded output is not the cold program's
  bytes and is refused), and the fingerprint folded into every key means
  a config change or breaker trip can never serve a stale program's
  output — the same staleness discipline as the compile cache (PR 3).
  Hits are labeled ``cache:exact`` and move no device counter, no deck
  row and no usage nanosecond (the PR 12 three-way reconciliation delta
  is exactly 0 on a hit — test-pinned);

- **near tier**: a cheap block-mean perceptual signature over the padded
  left image (``SIG_GRID`` x ``SIG_GRID`` grayscale block means, ~1 KiB)
  → nearest stored neighbor of the SAME tenant/shape/fingerprint within
  an L1 threshold (``RAFT_CACHE_NEAR_TOL`` gray levels; 0 = tier fully
  disabled) → the request's ``coords1`` is seeded from the neighbor's
  held 1/8-res x-only disparity through the EXISTING ``prepare_warm``
  program kind (graftstream's x-only warm-start contract — no new
  compiled programs, no stream session required).  Near hits ride the
  normal serving path and exit through the PR 13 per-row convergence
  monitor unchanged, labeled ``warm:cache:<iters actually run>`` —
  honest iteration counts, never a claimed-exact answer;

- **lifecycle discipline** (the StreamManager mirror): bounded global
  byte budget with LRU eviction, per-tenant sub-caps with OWN-LRU
  eviction (a tenant at its cap evicts its own oldest entry, never
  another tenant's), lazy TTL sweep on the session clock
  (``RAFT_CACHE_TTL_MS``, FakeClock-drivable), deposit-before-resolve
  (a client that reads response N and resubmits the same frame is
  guaranteed a hit), ``drop_all()`` on service stop/drain, and hostile
  tenant churn provably unable to grow host memory or ``/metrics`` —
  entry count is bounded by the byte budget, metric labels ride the
  obs/usage.py first-come bound, and byte accounting keys on the RAW
  sanitized tenant so isolation never depends on the label.

Tenancy is part of the KEY, not an optimization: tenant A's scene is
never served to tenant B, even for bit-identical uploads — a response
cache that leaked across tenants would be a data-exfiltration oracle
(upload a guessed image, observe the hit).

Pod serving (graftpod, DESIGN.md r21): this cache stays ONE host-side
store ABOVE all N chips of a data mesh.  The keys fold in the program
FINGERPRINT, which is deliberately mesh-independent (the mesh extent
re-keys compiled programs via a trailing cache-key component, like the
batch bucket ``b`` — analysis/knobs.py HOST_ENV_KNOBS rationale), so a
hit deposited by a 1-chip serve answers an 8-chip serve and vice versa:
sharding the batch dim never changes the response bytes' contract, and
splitting the cache per chip would only divide its hit rate by N.

Memory bound: one full-res (2016x2976) entry holds the float32 disparity
(~24 MiB) + the 1/8-res seed (~0.4 MiB) + a 1 KiB signature, so the
default 256 MiB budget holds ~10 full-res scenes or thousands of
VGA-class ones; the gauge ``raft_cache_bytes`` is the accounted truth.

Stdlib + numpy only, no jax — the cache is pure host state.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import logging
import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from raft_stereo_tpu.obs.tracing import NULL_TRACE
from raft_stereo_tpu.obs.usage import sanitize_tenant
# ONE named-ValueError parser for env knobs (the SLURM_CPUS_PER_TASK
# convention) — the ``os.environ`` reads stay LITERAL at each resolve_*
# site below so GL002's registry cross-check can see them.
from raft_stereo_tpu.serve.supervise import _parse_number

logger = logging.getLogger(__name__)

#: Host-RAM budget the CLI defaults to (serve_stereo.py --cache_bytes).
#: The LIBRARY default is 0 = disabled — the watchdog stance (PR 9):
#: embedded sessions and test rigs must opt in, production CLIs default
#: it on.
DEFAULT_CACHE_BYTES = 256 << 20

#: Idle entries expire after this long on the session clock: a rig that
#: went away must not pin stale scenes until eviction pressure arrives.
DEFAULT_CACHE_TTL_MS = 600_000.0

#: Near-tier L1 threshold in gray levels over the block-mean signature;
#: 0 disables the tier entirely (no signature scan, no seed stamping).
DEFAULT_CACHE_NEAR_TOL = 0.0

#: Perceptual-signature grid: the padded left image reduces to this many
#: grayscale block means per side (padded shapes are multiples of 32, so
#: the grid always divides evenly enough to crop losslessly).
SIG_GRID = 16

#: Bound on the near-tier candidate scan (MRU-first): the linear scan
#: must stay cheap even when the byte budget holds thousands of tiny
#: entries.  Candidates beyond this are simply not considered — bounded
#: work beats an exhaustive nearest-neighbor search on the serving path.
NEAR_SCAN_BOUND = 512

#: Fixed per-entry bookkeeping charge (key tuple, dict slots, OrderedDict
#: node) folded into the byte accounting so a hostile flood of tiny
#: entries cannot grow host memory past the budget on overheads alone.
ENTRY_OVERHEAD = 512


def resolve_cache_bytes(value: Optional[int] = None) -> int:
    """Effective host-RAM budget in bytes: explicit config wins, else
    ``RAFT_CACHE_BYTES``, else 0 (disabled — the library default; the
    serving CLI defaults it to :data:`DEFAULT_CACHE_BYTES`).  Host-side
    response storage only — no compiled program depends on it
    (HOST_ENV_KNOBS rationale)."""
    if value is not None:
        return int(value)
    raw = os.environ.get("RAFT_CACHE_BYTES", "").strip()
    if not raw:
        return 0
    n = _parse_number("RAFT_CACHE_BYTES", raw, int)
    if n < 0:
        raise ValueError(f"RAFT_CACHE_BYTES must be >= 0, got {n}")
    return n


def resolve_cache_ttl_ms(value: Optional[float] = None) -> float:
    """Effective entry TTL in ms: explicit config wins, else
    ``RAFT_CACHE_TTL_MS``, else 10 minutes."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_CACHE_TTL_MS", "").strip()
    if not raw:
        return DEFAULT_CACHE_TTL_MS
    ttl = _parse_number("RAFT_CACHE_TTL_MS", raw, float)
    if ttl <= 0:
        raise ValueError(f"RAFT_CACHE_TTL_MS must be > 0, got {ttl}")
    return ttl


def resolve_cache_near_tol(value: Optional[float] = None) -> float:
    """Effective near-tier threshold (gray levels over the block-mean
    signature): explicit config wins, else ``RAFT_CACHE_NEAR_TOL``, else
    0 = disabled.  A HOST-side comparison only — the threshold never
    reaches a trace (the seed it hands out feeds the existing
    ``prepare_warm`` program unchanged), so it stays out of the program
    fingerprint exactly like ``RAFT_CONVERGE_TOL``."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_CACHE_NEAR_TOL", "").strip()
    if not raw:
        return DEFAULT_CACHE_NEAR_TOL
    tol = _parse_number("RAFT_CACHE_NEAR_TOL", raw, float)
    if tol < 0:
        raise ValueError(f"RAFT_CACHE_NEAR_TOL must be >= 0, got {tol}")
    return tol


def resolve_cache_dir(value: Optional[str] = None) -> Optional[str]:
    """Effective disk-spill directory: explicit config wins, else
    ``RAFT_CACHE_DIR``, else None (RAM only).  Exact-tier entries
    evicted from RAM spill here (bounded by the same byte budget again,
    oldest-file pruning) and are promoted back on a later exact match —
    the near tier deliberately scans RAM only."""
    if value is not None:
        return str(value) or None
    raw = os.environ.get("RAFT_CACHE_DIR", "").strip()
    return raw or None


def block_signature(padded_left: np.ndarray) -> np.ndarray:
    """The near tier's perceptual signature: ``SIG_GRID x SIG_GRID``
    grayscale block means over the padded left image — cheap (one mean
    reduction), shift-tolerant at the block scale, and 1 KiB to hold.
    Input is the canonical padded ``(1, H, W, 3)`` float32 array."""
    g = np.asarray(padded_left, dtype=np.float32)[0].mean(axis=2)
    h, w = g.shape
    bh, bw = max(1, h // SIG_GRID), max(1, w // SIG_GRID)
    gh, gw = min(SIG_GRID, h), min(SIG_GRID, w)
    g = g[:bh * gh, :bw * gw]
    return g.reshape(gh, bh, gw, bw).mean(axis=(1, 3)).astype(np.float32)


def signature_distance(a: np.ndarray, b: np.ndarray) -> float:
    """Mean absolute block-mean difference, in gray levels (the unit
    ``RAFT_CACHE_NEAR_TOL`` is expressed in)."""
    if a.shape != b.shape:
        return float("inf")
    return float(np.abs(a - b).mean())


class CacheEntry:
    """One stored cold full-quality response.  Immutable once deposited
    (hits serve copies); bookkeeping fields mutate only under the
    cache's lock."""

    __slots__ = ("key", "tenant", "label", "sig", "disparity", "flow",
                 "padded_shape", "iters", "nbytes", "created", "last_used")

    def __init__(self, key: Tuple, tenant: str, label: str,
                 sig: np.ndarray, disparity: np.ndarray,
                 flow: Optional[np.ndarray],
                 padded_shape: Optional[Tuple[int, int]],
                 iters: int, now: float):
        self.key = key
        self.tenant = tenant
        self.label = label
        self.sig = sig
        self.disparity = disparity
        self.flow = flow
        self.padded_shape = padded_shape
        self.iters = iters
        self.nbytes = (int(disparity.nbytes) + int(sig.nbytes)
                       + (int(flow.nbytes) if flow is not None else 0)
                       + ENTRY_OVERHEAD)
        self.created = now
        self.last_used = now


class ResponseCache:
    """Two-tier, bounded, tenant-isolated response cache over one
    :class:`~raft_stereo_tpu.serve.session.InferenceSession`.

    Protocol (all on the request dict, so bounces/retries carry it for
    free — the StreamManager's stance):

    - :meth:`admit` (service admission, after validation): computes the
      exact key + perceptual signature, stamps ``request["_cache_key"]``
      / ``_cache_sig``, and EITHER returns a complete served response
      (exact hit, ``cache:exact``) or stamps the near-tier warm seed
      (``_flow_init`` + ``_cache_warm`` + a default ``_converge_tol``)
      and returns None;
    - the serving path attaches the computed response's 1/8-res flow as
      ``request["_cache_flow"]`` / ``_cache_shape`` (the scheduler does
      this for every batched exit; the sequential path does when it runs
      the segmented composition);
    - :meth:`deposit` (response resolution, BEFORE the Future resolves)
      stores cold full-quality responses back — warm-seeded, degraded,
      failed or fingerprint-stale responses are refused, which is what
      makes every exact hit bit-identical to a cold recompute.
    """

    def __init__(self, session, *, max_bytes: Optional[int] = None,
                 ttl_ms: Optional[float] = None,
                 near_tol: Optional[float] = None,
                 cache_dir: Optional[str] = None,
                 per_tenant_bytes: Optional[int] = None,
                 default_converge_tol: Optional[float] = None,
                 registry=None):
        self.session = session
        self.registry = registry if registry is not None else \
            session.registry
        self.max_bytes = resolve_cache_bytes(max_bytes)
        self.ttl_s = resolve_cache_ttl_ms(ttl_ms) / 1e3
        self.near_tol = resolve_cache_near_tol(near_tol)
        self.dir = resolve_cache_dir(cache_dir)
        # Per-tenant sub-cap: an eighth of the global budget (>= 1 byte),
        # the quota/stream stance — generous for a real rig, bounding for
        # an adversary.  A tenant may always hold at least ONE entry (its
        # own-LRU eviction empties its account first), so a sub-cap below
        # one entry degrades to "one scene per tenant", never to a tenant
        # that can cache nothing.
        self.per_tenant = (int(per_tenant_bytes)
                           if per_tenant_bytes is not None
                           else max(1, self.max_bytes // 8))
        # Default convergence tolerance stamped on near-seeded requests
        # that carry none of their own (the service passes its stream
        # default so both warm-start flavors exit by one rule).
        self.default_converge_tol = default_converge_tol
        self._lock = threading.Lock()
        self._table: "OrderedDict[Tuple, CacheEntry]" = OrderedDict()
        self._total_bytes = 0
        self._tenant_bytes: Dict[str, int] = {}   # RAW sanitized tenant
        self._label_bytes: Dict[str, int] = {}    # bounded metric label
        # Disk-spill state, guarded by its OWN lock: file IO must never
        # serialize behind the RAM table's serving-path lock.
        self._disk_lock = threading.Lock()
        self._disk_bytes = 0
        reg = self.registry
        self._c_hits = reg.counter(
            "raft_cache_hits_total",
            "exact-tier response-cache hits (zero device seconds)")
        self._c_misses = reg.counter(
            "raft_cache_misses_total",
            "response-cache lookups that found no exact entry")
        self._c_near = reg.counter(
            "raft_cache_near_hits_total",
            "near-tier warm-start seeds handed out (prepare_warm rides "
            "the request)")
        self._c_evicted = reg.counter(
            "raft_cache_evictions_total",
            "entries evicted by the byte budget or a tenant sub-cap")
        self._c_expired = reg.counter(
            "raft_cache_expired_total", "entries expired by TTL")
        self._c_deposits = reg.counter(
            "raft_cache_deposits_total",
            "cold full-quality responses stored")
        self._c_refused = reg.counter(
            "raft_cache_deposits_refused_total",
            "deposits refused (warm-seeded, degraded, fingerprint-stale "
            "or oversize) — refusal is the bit-exactness guarantee")
        self._c_disk_hits = reg.counter(
            "raft_cache_disk_hits_total",
            "exact hits served by promoting a spilled entry from "
            "RAFT_CACHE_DIR")
        self._c_spills = reg.counter(
            "raft_cache_spills_total",
            "evicted entries spilled to RAFT_CACHE_DIR")
        self._g_bytes = reg.gauge(
            "raft_cache_bytes",
            "accounted bytes held by the response cache (bounded by "
            "RAFT_CACHE_BYTES)")
        self._g_entries = reg.gauge(
            "raft_cache_entries", "live response-cache entries")
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)
            with self._disk_lock:
                self._disk_bytes = sum(
                    e.stat().st_size for e in os.scandir(self.dir)
                    if e.is_file() and e.name.endswith(".npz"))

    # -- properties --------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    @property
    def wants_flow(self) -> bool:
        """Whether the serving path should produce (and attach) the
        1/8-res flow for deposits: only the near tier consumes it, so a
        near_tol of 0 keeps the sequential path on its classic route."""
        return self.enabled and self.near_tol > 0

    @property
    def hits_cumulative(self) -> int:
        """Exact + near hits served so far — the deck tick column."""
        return int(self._c_hits.value) + int(self._c_near.value)

    # -- key material ------------------------------------------------------

    def _key_for(self, tenant: str, ph: int, pw: int,
                 digest: str) -> Tuple:
        # The LIVE fingerprint: a breaker trip or config change re-keys
        # every lookup AND every deposit instantly — a stale program's
        # output is structurally unreachable (the PR 3 staleness class,
        # applied to responses).  valid_iters is the serving tier: two
        # sessions at different iteration budgets never share an answer.
        return (tenant, ph, pw, int(self.session.cfg.valid_iters),
                self.session.fingerprint_id(), digest)

    # -- the request protocol ----------------------------------------------

    def admit(self, request: Dict) -> Optional[Dict]:
        """One validated request (arrays already canonical): exact-tier
        lookup, near-tier seed stamping.  Returns a complete served
        response on an exact hit, None otherwise.  Never raises on the
        serving path — a cache bug must degrade to a miss, not a failed
        request."""
        if not self.enabled:
            return None
        try:
            return self._admit(request)
        except Exception:  # noqa: BLE001 — the cache must fail open
            logger.exception("response-cache admit failed — serving as "
                             "a miss")
            return None

    def _admit(self, request: Dict) -> Optional[Dict]:
        tenant = sanitize_tenant(request.get("tenant"))
        label = self.session.usage.label(tenant)
        trace = request.get("_trace") or NULL_TRACE
        left, right = request["left"], request["right"]
        padder = self.session.padder_for(left.shape)
        ph, pw = padder.padded_shape
        # Deliberate trade-off: this pad is a second full-frame copy on
        # the miss path (the uploader/stream path pads the same pair
        # again later), but attaching the padded arrays to the request
        # for reuse would pin ~2x the host RAM per QUEUED request for
        # its whole queue wait — compute is cheap and flat, resident
        # memory under backlog is not.
        lp, rp = padder.pad_np(left, right)
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(lp).tobytes())
        h.update(np.ascontiguousarray(rp).tobytes())
        key = self._key_for(tenant, ph, pw, h.hexdigest())
        sig = block_signature(lp)
        request["_cache_key"] = key
        request["_cache_sig"] = sig
        now = self.session.clock.now()
        t0 = now
        with self._lock:
            self._sweep(now)
            entry = self._touch(key, now)
        if entry is None and self.dir:
            entry = self._disk_lookup(key, tenant, label, now)
        if entry is not None:
            self._c_hits.inc()
            self.session.usage.note_cache(label, exact=True)
            trace.event("cache", tier="exact",
                        age_s=now - entry.created)
            if request.get("_stream") is not None and \
                    entry.flow is not None:
                # A stream member hitting the exact tier still keeps its
                # session warm: the entry's held flow rides the request
                # into the service's stream deposit hook.
                request["_cache_stream_flow"] = entry.flow
                request["_cache_stream_shape"] = entry.padded_shape
            return {
                "status": "ok",
                "quality": "cache:exact",
                "disparity": entry.disparity.copy(),
                "iters": entry.iters,
                "elapsed_ms": (self.session.clock.now() - t0) * 1e3,
                "deadline_missed": False,
            }
        self._c_misses.inc()
        self.session.usage.note_cache(label, miss=True)
        # Near tier: only when armed, and never over a stream session's
        # own seed (the previous frame of the SAME stream is a strictly
        # better prior than any neighbor).
        if self.near_tol > 0 and request.get("_flow_init") is None:
            neighbor, dist = self._nearest(tenant, ph, pw, key[4], sig)
            if neighbor is not None:
                request["_flow_init"] = neighbor.flow
                request["_cache_warm"] = True
                if request.get("_converge_tol") is None and \
                        self.default_converge_tol is not None:
                    request["_converge_tol"] = self.default_converge_tol
                self._c_near.inc()
                self.session.usage.note_cache(label, near=True)
                trace.event("cache", tier="near", distance=dist,
                            tol=self.near_tol)
        return None

    def _nearest(self, tenant: str, ph: int, pw: int, fp: str,
                 sig: np.ndarray):
        """Bounded MRU-first scan for the nearest same-tenant, same-
        bucket, same-fingerprint entry holding a seed.  RAM only (disk
        entries are exact-tier material)."""
        with self._lock:
            candidates = [e for e in reversed(self._table.values())
                          if e.tenant == tenant and e.flow is not None
                          and e.key[1] == ph and e.key[2] == pw
                          and e.key[4] == fp][:NEAR_SCAN_BOUND]
        best, best_d = None, float("inf")
        for e in candidates:
            d = signature_distance(sig, e.sig)
            if d < best_d:
                best, best_d = e, d
        if best is not None and best_d <= self.near_tol:
            return best, best_d
        return None, best_d

    def deposit(self, request: Dict, resp: Dict) -> None:
        """Store one resolved response — BEFORE its Future resolves, so
        an immediate resubmission of the same frame is guaranteed a hit.
        Runs on the response-resolution path for both serving modes and
        must never raise.  Only COLD (no warm seed), FULL-quality, ok
        responses under the LIVE fingerprint are stored: everything else
        is refused and counted — refusal is what makes every exact hit
        bit-identical to a cold recompute by construction."""
        key = request.get("_cache_key")
        flow = request.pop("_cache_flow", None)
        shape = request.pop("_cache_shape", None)
        if not self.enabled or key is None:
            return
        try:
            self._deposit(request, resp, key, flow, shape)
        except Exception:  # noqa: BLE001 — the cache must fail open
            logger.exception("response-cache deposit failed — entry "
                             "dropped")

    def _deposit(self, request: Dict, resp: Dict, key: Tuple,
                 flow, shape) -> None:
        if resp.get("status") != "ok" or resp.get("quality") != "full" \
                or request.get("_flow_init") is not None:
            self._c_refused.inc()
            return
        if key[4] != self.session.fingerprint_id():
            # The program set changed (breaker trip) between admission
            # and resolution: this output came from a program the key
            # does not describe — refuse, never poison.
            self._c_refused.inc()
            return
        sig = request.get("_cache_sig")
        if sig is None:
            self._c_refused.inc()
            return
        disparity = np.array(resp["disparity"], dtype=np.float32,
                             copy=True)
        flow_arr = (np.array(flow, dtype=np.float32, copy=True)
                    if flow is not None else None)
        tenant = key[0]
        label = self.session.usage.label(tenant)
        now = self.session.clock.now()
        entry = CacheEntry(key, tenant, label, np.asarray(sig), disparity,
                           flow_arr,
                           tuple(shape) if shape is not None else None,
                           int(resp.get("iters", 0)), now)
        if entry.nbytes > self.max_bytes:
            self._c_refused.inc()
            return
        with self._lock:
            self._sweep(now)
            if self._touch(key, now) is not None:
                # Re-deposit of a live entry (two identical cold
                # requests racing): refresh recency, keep the bytes.
                return
            evicted = self._store(entry)
        self._c_deposits.inc()
        self._note_evictions(evicted)

    def _note_evictions(self, evicted: List[CacheEntry]) -> None:
        """Post-eviction accounting shared by every path that calls
        ``_store``: global + per-tenant counters, and the disk spill —
        a victim must be persisted (and counted to its owner) whether
        the pressure came from a deposit or a disk promotion."""
        if not evicted:
            return
        self._c_evicted.inc(len(evicted))
        for e in evicted:
            self.registry.counter(
                "raft_tenant_cache_evictions_total",
                "response-cache evictions by owning tenant "
                "(first-come-bounded labels)", tenant=e.label).inc()
        if self.dir:
            for e in evicted:
                self._spill(e)

    # -- table maintenance (caller holds self._lock — the StreamManager
    # -- lock-held-helper discipline GL004 enforces: every mutation of
    # -- the table/byte books lives in these bare helpers) -----------------

    def _touch(self, key: Tuple, now: float) -> Optional[CacheEntry]:
        entry = self._table.get(key)
        if entry is not None:
            self._table.move_to_end(key)
            entry.last_used = now
        return entry

    def _store(self, entry: CacheEntry) -> List[CacheEntry]:
        evicted = self._make_room(entry)
        self._table[entry.key] = entry
        self._account(entry, +1)
        self._publish_gauges()
        return evicted

    def _account(self, entry: CacheEntry, sign: int) -> None:
        self._total_bytes += sign * entry.nbytes
        for book, k in ((self._tenant_bytes, entry.tenant),
                        (self._label_bytes, entry.label)):
            n = book.get(k, 0) + sign * entry.nbytes
            if n <= 0:
                book.pop(k, None)
            else:
                book[k] = n
        # A fully-drained label publishes 0, never a stale sum (the
        # cache-HBM gauge discipline from PR 8).
        self.registry.gauge(
            "raft_tenant_cache_bytes",
            "response-cache bytes held per tenant label",
            tenant=entry.label).set(self._label_bytes.get(entry.label, 0))

    def _drop(self, key: Tuple) -> Optional[CacheEntry]:
        entry = self._table.pop(key, None)
        if entry is not None:
            self._account(entry, -1)
        return entry

    def _sweep(self, now: float) -> None:
        expired = [k for k, e in self._table.items()
                   if now - e.last_used > self.ttl_s]
        for k in expired:
            self._drop(k)
        if expired:
            self._c_expired.inc(len(expired))
            self._publish_gauges()

    def _make_room(self, entry: CacheEntry) -> List[CacheEntry]:
        """Own-LRU tenant eviction first (a tenant at its sub-cap must
        never displace another tenant's entries), then the global LRU.
        Returns the evicted entries (for counting + disk spill)."""
        evicted: List[CacheEntry] = []
        while self._tenant_bytes.get(entry.tenant, 0) + entry.nbytes \
                > self.per_tenant:
            victim = next((k for k, e in self._table.items()
                           if e.tenant == entry.tenant), None)
            if victim is None:
                break  # sub-cap below one entry: one scene still allowed
            evicted.append(self._drop(victim))
        while self._total_bytes + entry.nbytes > self.max_bytes \
                and self._table:
            victim = next(iter(self._table))
            evicted.append(self._drop(victim))
        return [e for e in evicted if e is not None]

    def _publish_gauges(self) -> None:
        self._g_bytes.set(self._total_bytes)
        self._g_entries.set(len(self._table))

    def _clear(self) -> int:
        n = len(self._table)
        for label in list(self._label_bytes):
            self.registry.gauge(
                "raft_tenant_cache_bytes",
                "response-cache bytes held per tenant label",
                tenant=label).set(0)
        self._table.clear()
        self._tenant_bytes.clear()
        self._label_bytes.clear()
        self._total_bytes = 0
        self._publish_gauges()
        return n

    # -- disk spill (RAFT_CACHE_DIR) ---------------------------------------

    #: Per-process monotonic suffix for spill temp files.  Two caches
    #: sharing one RAFT_CACHE_DIR (a fleet of instances, or two caches
    #: in one process) may spill the SAME key concurrently; a fixed
    #: "<path>.tmp" name would let writer B's open() truncate the file
    #: writer A is mid-np.savez on, and A's os.replace would then
    #: publish B's torn bytes under the final name.  pid + counter makes
    #: every tmp name unique, so each os.replace publishes only its own
    #: complete payload (last full write wins — both are valid entries
    #: for the same key).  Deliberately NOT ending in ".npz": the disk
    #: accounting scans and _prune_disk must never count or load an
    #: in-progress tmp.
    _TMP_SEQ = itertools.count()

    def _path_for(self, key: Tuple) -> str:
        name = hashlib.sha256(repr(key).encode()).hexdigest()
        return os.path.join(self.dir, f"{name}.npz")

    def _spill(self, entry: CacheEntry) -> None:
        """Persist one evicted exact-tier entry; bounded by the SAME
        byte budget again on disk (oldest-mtime pruning).  Spill
        failures disable nothing — the entry is simply gone, a miss."""
        path = self._path_for(entry.key)
        try:
            tmp = f"{path}.{os.getpid()}.{next(self._TMP_SEQ)}.tmp"
            payload: Dict[str, np.ndarray] = {
                "disparity": entry.disparity,
                "sig": entry.sig,
                "meta": np.frombuffer(json.dumps({
                    "key": repr(entry.key),
                    "iters": entry.iters,
                    "created": entry.created,
                    "padded_shape": (list(entry.padded_shape)
                                     if entry.padded_shape else None),
                }).encode(), dtype=np.uint8),
            }
            if entry.flow is not None:
                payload["flow"] = entry.flow
            # The spill write IS the cache's disk tier doing its job;
            # bounce-path deposits (watchdog resolving scheduled rows
            # under _check_lock) accept the bounded write — _check_lock
            # serializes sweeps only, never the serving path.
            # graftlint: disable=GC204 (disk-tier spill; watchdog sweep tolerates bounded IO)
            with open(tmp, "wb") as f:
                np.savez(f, **payload)
            # graftlint: disable=GC204 (atomic publish of the same spill)
            os.replace(tmp, path)
        except OSError:
            logger.warning("cache spill to %s failed", path,
                           exc_info=True)
            return
        self._c_spills.inc()
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        with self._disk_lock:
            self._disk_account(size)
            self._prune_disk()

    def _disk_account(self, delta: int) -> None:
        # Caller holds self._disk_lock (the lock-held-helper discipline:
        # every _disk_bytes mutation lives here or in _prune_disk).
        self._disk_bytes = max(0, self._disk_bytes + delta)

    def _prune_disk(self) -> None:
        # Caller holds self._disk_lock.
        if self._disk_bytes <= self.max_bytes:
            return
        try:
            files = sorted(
                (e for e in os.scandir(self.dir)
                 if e.is_file() and e.name.endswith(".npz")),
                key=lambda e: e.stat().st_mtime)
        except OSError:
            return
        for e in files:
            if self._disk_bytes <= self.max_bytes:
                break
            try:
                size = e.stat().st_size
                os.unlink(e.path)
                self._disk_bytes -= size
            except OSError:
                continue

    def _disk_lookup(self, key: Tuple, tenant: str, label: str,
                     now: float) -> Optional[CacheEntry]:
        """RAM-miss fallback: load a spilled entry, verify its key and
        TTL, promote it back into RAM.  Any malformation is a miss."""
        path = self._path_for(key)
        try:
            if not os.path.exists(path):
                return None
            with np.load(path) as z:
                meta = json.loads(bytes(z["meta"]).decode())
                if meta.get("key") != repr(key):
                    return None  # hash collision / foreign file: a miss
                if now - float(meta.get("created", now)) > self.ttl_s:
                    size = os.path.getsize(path)
                    os.unlink(path)
                    with self._disk_lock:
                        self._disk_account(-size)
                    return None
                disparity = np.array(z["disparity"], dtype=np.float32)
                sig = np.array(z["sig"], dtype=np.float32)
                flow = (np.array(z["flow"], dtype=np.float32)
                        if "flow" in z.files else None)
                shape = meta.get("padded_shape")
        except Exception:  # noqa: BLE001 — a corrupt spill is a miss
            logger.warning("corrupt cache spill %s ignored", path,
                           exc_info=True)
            return None
        entry = CacheEntry(key, tenant, label, sig, disparity, flow,
                           tuple(shape) if shape else None,
                           int(meta.get("iters", 0)), now)
        entry.created = float(meta.get("created", now))
        if entry.nbytes > self.max_bytes:
            # Spilled under a larger budget than the current one (e.g. a
            # restart with a smaller --cache_bytes): serve this hit ONCE
            # but never promote — the RAM byte-budget invariant
            # (raft_cache_bytes <= RAFT_CACHE_BYTES) holds
            # unconditionally, the deposit path's oversize refusal
            # mirrored here.
            self._c_disk_hits.inc()
            return entry
        with self._lock:
            evicted = ([] if key in self._table
                       else self._store(entry))
        self._note_evictions(evicted)
        self._c_disk_hits.inc()
        return entry

    # -- lifecycle ---------------------------------------------------------

    def drop_all(self) -> int:
        """Service stop/drain: every RAM entry dies, gauges read 0.
        Disk spill survives deliberately — RAFT_CACHE_DIR exists to warm
        a RESTART, and the fingerprint folded into every key already
        guarantees a config-changed restart can never read a stale
        entry."""
        with self._lock:
            return self._clear()

    # -- reporting ---------------------------------------------------------

    def status(self) -> Dict:
        """The /healthz ``cache`` block — bounded by construction (the
        per-tenant byte map is summarized, never enumerated: entry
        counts are budget-bounded but tenant NAMES are attacker-chosen)."""
        with self._lock:
            entries = len(self._table)
            total = self._total_bytes
            tenants = len(self._tenant_bytes)
        hits = int(self._c_hits.value)
        misses = int(self._c_misses.value)
        doc = {
            "enabled": self.enabled,
            "max_bytes": self.max_bytes,
            "per_tenant_bytes": self.per_tenant,
            "ttl_ms": self.ttl_s * 1e3,
            "near_tol": self.near_tol,
            "entries": entries,
            "bytes": total,
            "tenants": tenants,
            "hits": hits,
            "misses": misses,
            "near_hits": int(self._c_near.value),
            "hit_ratio": (hits / (hits + misses)
                          if hits + misses else None),
            "evictions": int(self._c_evicted.value),
            "expired": int(self._c_expired.value),
            "deposits": int(self._c_deposits.value),
            "deposits_refused": int(self._c_refused.value),
        }
        if self.dir:
            with self._disk_lock:
                doc["disk"] = {"dir": self.dir,
                               "bytes": self._disk_bytes,
                               "spills": int(self._c_spills.value),
                               "hits": int(self._c_disk_hits.value)}
        return doc

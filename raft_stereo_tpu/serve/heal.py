"""graftheal — recovery-plane pacing knobs (DESIGN.md "The recovery
plane (r22)").

The PR 3..17 arc built graceful *degradation* at every layer — breaker
rungs trip to plain XLA, mesh chips quarantine and the mesh shrinks,
fleet slots exhaust their restart budget and go dark — but every one of
those ladders was one-way for the session/generation lifetime.  This
module holds the shared pacing knobs for the half-open probation state
machine that re-engages all three (serve/guard.py rungs,
serve/session.py chips, serve/fleet.py slots).

All of these are HOST-side recovery *pacing*: when a probe is allowed
to run, how many flaps are tolerated, how fast a restart budget
refills.  None of them ever shapes a compiled program — the re-engaged
configuration is keyed exactly the way tripping keyed it (the trip set
/ mesh epoch are already in the program-cache key projection), so these
knobs live in ``HOST_ENV_KNOBS``, never in any program fingerprint.

Knobs (explicit config wins, else env, else default — the resolve_*
convention from serve/supervise.py, with its named-ValueError parser):

- ``RAFT_HEAL``             — master switch; default ON.  ``0`` is the
  kill switch that restores the one-way PR 3..17 semantics exactly.
- ``RAFT_HEAL_BACKOFF_MS``  — initial probation backoff per rung/chip
  (default 30 s).  Doubles on every failed probe.
- ``RAFT_HEAL_BACKOFF_MAX_MS`` — backoff doubling cap (default 480 s).
- ``RAFT_HEAL_FLAP_CAP``    — chip re-admissions tolerated per window
  before the chip is permanently quarantined (default 2).
- ``RAFT_HEAL_WINDOW_MS``   — the flap-counting window (default 600 s).
- ``RAFT_HEAL_REFILL_MS``   — fleet restart-budget decay: one restart
  charge is refunded per this interval (default 60 s).

Clock discipline: every deadline here runs on the owning component's
session clock (``faults.FakeClock`` in tests/storms), except the fleet
refill which rides the fleet's ``time.monotonic`` clock seam — the
fleet supervisor has no FakeClock and its tests inject tiny refill
intervals instead.
"""

from __future__ import annotations

import os
from typing import Optional

# ONE named-ValueError parser for env knobs (the SLURM_CPUS_PER_TASK
# convention) — the ``os.environ`` reads stay LITERAL at each
# resolve_* site below so GL002's registry cross-check can see them.
from raft_stereo_tpu.serve.supervise import _parse_number

#: Recovery is ON by default: the kill switch is ``RAFT_HEAL=0``.
DEFAULT_HEAL_ENABLED = True

#: First probation backoff: a transient 30 s fault (the motivating
#: preemption hiccup) gets exactly one backoff period before the first
#: half-open probe.
DEFAULT_HEAL_BACKOFF_MS = 30_000.0

#: Backoff doubling cap: 30 s * 2^4 = 480 s — a persistently failing
#: probe settles at one canary per 8 minutes, which is noise against
#: serving but still finds an eventually-cleared fault within minutes.
DEFAULT_HEAL_BACKOFF_MAX_MS = 480_000.0

#: Chip flap cap: K re-admissions per window, then permanently out.  A
#: mesh re-grow is an epoch bump (re-keyed programs, re-warm) — a chip
#: flapping faster than this would thrash epochs into a recompile
#: storm, which is worse than serving shrunk.
DEFAULT_HEAL_FLAP_CAP = 2

#: The flap-counting window (session clock).
DEFAULT_HEAL_WINDOW_MS = 600_000.0

#: Fleet restart-budget decay: one charge refunded per interval, so an
#: exhausted slot re-enters probation (one relaunch at a time) instead
#: of staying dark until the next deploy.
DEFAULT_HEAL_REFILL_MS = 60_000.0


def resolve_heal_enabled(value: Optional[bool] = None) -> bool:
    """Effective recovery-plane switch: explicit config wins, else
    ``RAFT_HEAL`` (``0`` disables), else ON.  The kill switch restores
    the one-way degradation semantics bit-for-bit — no probes, no
    refills, no re-admissions."""
    if value is not None:
        return bool(value)
    raw = os.environ.get("RAFT_HEAL", "").strip()
    if not raw:
        return DEFAULT_HEAL_ENABLED
    return raw != "0"


def resolve_heal_backoff_ms(value: Optional[float] = None) -> float:
    """Effective initial probation backoff in ms: explicit config wins,
    else ``RAFT_HEAL_BACKOFF_MS``, else 30 s."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_HEAL_BACKOFF_MS", "").strip()
    if not raw:
        return DEFAULT_HEAL_BACKOFF_MS
    ms = _parse_number("RAFT_HEAL_BACKOFF_MS", raw, float)
    if ms <= 0:
        raise ValueError(f"RAFT_HEAL_BACKOFF_MS must be > 0, got {ms}")
    return ms


def resolve_heal_backoff_max_ms(value: Optional[float] = None) -> float:
    """Effective backoff doubling cap in ms: explicit config wins, else
    ``RAFT_HEAL_BACKOFF_MAX_MS``, else 480 s."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_HEAL_BACKOFF_MAX_MS", "").strip()
    if not raw:
        return DEFAULT_HEAL_BACKOFF_MAX_MS
    ms = _parse_number("RAFT_HEAL_BACKOFF_MAX_MS", raw, float)
    if ms <= 0:
        raise ValueError(
            f"RAFT_HEAL_BACKOFF_MAX_MS must be > 0, got {ms}")
    return ms


def resolve_heal_flap_cap(value: Optional[int] = None) -> int:
    """Effective chip flap cap: explicit config wins, else
    ``RAFT_HEAL_FLAP_CAP``, else 2.  ``0`` means a quarantined chip is
    never re-admitted (quarantine stays one-way while rung/slot healing
    remains armed)."""
    if value is not None:
        return int(value)
    raw = os.environ.get("RAFT_HEAL_FLAP_CAP", "").strip()
    if not raw:
        return DEFAULT_HEAL_FLAP_CAP
    cap = _parse_number("RAFT_HEAL_FLAP_CAP", raw, int)
    if cap < 0:
        raise ValueError(f"RAFT_HEAL_FLAP_CAP must be >= 0, got {cap}")
    return cap


def resolve_heal_window_ms(value: Optional[float] = None) -> float:
    """Effective flap-counting window in ms: explicit config wins, else
    ``RAFT_HEAL_WINDOW_MS``, else 600 s."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_HEAL_WINDOW_MS", "").strip()
    if not raw:
        return DEFAULT_HEAL_WINDOW_MS
    ms = _parse_number("RAFT_HEAL_WINDOW_MS", raw, float)
    if ms <= 0:
        raise ValueError(f"RAFT_HEAL_WINDOW_MS must be > 0, got {ms}")
    return ms


def resolve_heal_refill_ms(value: Optional[float] = None) -> float:
    """Effective fleet restart-budget refill interval in ms: explicit
    config wins, else ``RAFT_HEAL_REFILL_MS``, else 60 s."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_HEAL_REFILL_MS", "").strip()
    if not raw:
        return DEFAULT_HEAL_REFILL_MS
    ms = _parse_number("RAFT_HEAL_REFILL_MS", raw, float)
    if ms <= 0:
        raise ValueError(f"RAFT_HEAL_REFILL_MS must be > 0, got {ms}")
    return ms

"""InferenceSession: params + config + a bounded cache of compiled shapes.

The eval CLIs compile one program per padded shape and die on the first
kernel failure; a server cannot. The session owns:

- **shape bucketing**: every admitted pair is padded with ``InputPadder``
  onto a multiple-of-``bucket`` shape, so arbitrary request sizes collapse
  onto a handful of compiled programs (``bucket=32`` reproduces the
  reference per-shape padding exactly — same formula — while still sharing
  programs between requests that round to the same shape);
- **an LRU-bounded compile cache** keyed by *(program kind, padded shape,
  iteration count, full config fingerprint)* — the fingerprint covers every
  forward-relevant config field plus the effective kernel env switches
  (circuit-breaker trips are projected into those two, so an effective
  trip re-keys), so two configs differing only in (say)
  ``corr_implementation`` can never share a program (regression-pinned in
  tests/test_serve.py);
- **per-bucket compile locks**: two concurrent first requests for one
  bucket compile once, requests for different buckets don't serialize
  behind each other's compiles (tracing itself is serialized — env-switch
  reads at trace time are process-global);
- **output validation**: a non-finite disparity is a structured
  ``InferenceFailed('nonfinite_output')``, never a silently served frame;
- **the circuit breaker** (serve/guard.py): a classified kernel failure
  trips one fallback rung, the session rebuilds and retries the same
  request; an optional startup **parity canary** checks one bucketed
  forward against the plain-XLA program inside the pinned drift band.

All hooks are plan-driven (``faults.ServeFaultPlan``), so every recovery
path here is CPU-testable with deterministic injected faults.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

from raft_stereo_tpu.analysis.knobs import ENV_KNOBS as _ENV_KNOBS
from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.faults import (RealClock, ServeFaultPlan, ServeFaults,
                                    poison_disparity)
from raft_stereo_tpu.obs.capacity import resolve_capacity_window_s
from raft_stereo_tpu.obs.deck import TickDeck
from raft_stereo_tpu.obs.flight import FlightRecorder
from raft_stereo_tpu.obs.ledger import (ProgramLedger, analyze_compiled,
                                        hbm_capacity, ledger_id)
from raft_stereo_tpu.obs.metrics import MetricsRegistry
from raft_stereo_tpu.obs.profiler import ProfilerWindow
from raft_stereo_tpu.obs.tracing import NULL_TRACE, Tracer
from raft_stereo_tpu.obs.usage import DEFAULT_TENANT, UsageAccountant
from raft_stereo_tpu.ops.padder import InputPadder
from raft_stereo_tpu.serve.guard import (KernelCircuitBreaker, CANARY_ATOL,
                                         CANARY_RTOL, is_kernel_failure)
from raft_stereo_tpu.serve.heal import (resolve_heal_backoff_max_ms,
                                        resolve_heal_backoff_ms,
                                        resolve_heal_enabled,
                                        resolve_heal_flap_cap,
                                        resolve_heal_window_ms)
from raft_stereo_tpu.serve.supervise import InvocationWatch, _parse_number
from raft_stereo_tpu.serve.validate import AdmissionConfig, validate_pair

logger = logging.getLogger(__name__)

# _ENV_KNOBS (analysis/knobs.py ENV_KNOBS): the env switches whose
# trace-time values shape the compiled program — part of every cache key so
# a flipped switch (breaker trip or operator export) can never be served a
# stale program. ONE registry shared with serve/guard.py and the GL002
# linter, instead of three hand-synced lists.

# Tracing mutates process-global env (the kernel kill switches are read at
# trace time), so traces are serialized even across buckets.
_TRACE_LOCK = threading.Lock()


class SessionError(RuntimeError):
    """Structured serving failure; ``code`` is machine-readable."""

    def __init__(self, code: str, message: str):
        self.code = code
        super().__init__(message)


class InferenceFailed(SessionError):
    """The forward ran but its result cannot be served (non-finite
    disparity), or every fallback rung failed (``ladder_exhausted``)."""


class DeadlineExceeded(SessionError):
    def __init__(self, message: str):
        super().__init__("deadline_exceeded", message)


# -- pod-scale serving knobs (graftpod) -------------------------------------
#
# The data-mesh extent is resolved HERE, once per session, and then rides
# the program-cache KEY as an explicit trailing component (like the batch
# bucket ``b``) — NOT the config fingerprint.  Mesh shape changes the
# compiled program (the PR 3 stale-program class), so it must re-key; but
# ``fingerprint_id()`` deliberately stays mesh-independent so the PR 14
# response cache (fingerprint-keyed, host-side) remains ONE cache above
# all chips (DESIGN r18/r21).

def resolve_serve_mesh_data(value: Optional[int] = None) -> int:
    """Effective ``data``-mesh extent (chips one session drives): explicit
    config wins, else ``RAFT_SERVE_MESH_DATA``, else 1 (single-device, the
    pre-pod behavior, byte-identical keys)."""
    if value is not None:
        n = int(value)
    else:
        raw = os.environ.get("RAFT_SERVE_MESH_DATA", "").strip()
        if not raw:
            return 1
        n = _parse_number("RAFT_SERVE_MESH_DATA", raw, int)
    if n < 1:
        raise ValueError(f"RAFT_SERVE_MESH_DATA must be >= 1, got {n}")
    return n


def resolve_mesh_fallback() -> bool:
    """The mesh kill switch: ``RAFT_SERVE_MESH_FALLBACK=1`` forces a
    session back to n_data=1 regardless of config/env — the same
    operator-escape contract every kernel kill switch honors.  Host-side
    only (it selects whether mesh-keyed programs exist at all, it never
    changes what any one compiled program computes)."""
    raw = os.environ.get("RAFT_SERVE_MESH_FALLBACK", "").strip()
    return raw not in ("", "0", "false", "False")


@dataclasses.dataclass(frozen=True)
class SessionConfig:
    """Serving knobs, orthogonal to the model config.

    valid_iters: refinement iterations for an undegraded request.
    segments: how many host-visible chunks a deadline-carrying request
        splits ``valid_iters`` into (must divide it). Between segments the
        degrade policy checks the budget and can return best-so-far.
    bucket: pad request shapes up to multiples of this (a multiple of 32);
        32 == the reference per-shape padding formula.
    max_programs: LRU bound on cached compiled programs. With
        ``max_batch > 1`` the effective bound is raised to fit one fully
        warm shape bucket (prepare/prepare_warm/advance/epilogue at
        every batch bucket) — a smaller bound would evict the warmup's
        own programs and recompile per tick.
    warmup_shapes: (H, W) image shapes whose full-scan programs compile at
        construction, so first requests don't pay the compile.
    warmup_segmented: also pre-compile the prepare/segment programs for
        each warmup shape (deadline-serving deployments want this).
    canary: run the startup parity canary (fast path vs plain XLA within
        the pinned drift band; mismatch trips the breaker).
    canary_shape / canary_iters: geometry of the canary forward (small and
        cheap by default; iteration count does not change which kernels
        engage).
    allow_half_res: let the degrade policy drop to half resolution when
        the budget cannot fit even one full-res segment.
    max_batch: device-batch ceiling for the continuous-batching scheduler
        (1 = the PR 3 sequential path, no batched programs compiled).
    batch_buckets: the batch sizes programs compile at (each request batch
        pads up to the smallest bucket that fits — pad rows are dead
        carries). Empty = the RAFT_BATCH_BUCKETS env override if set, else
        powers of two up to ``max_batch``. Bounding the bucket set bounds
        the compile count exactly like shape bucketing does.
    mesh_data: chips this session drives over the ``data`` mesh axis
        (graftpod). None = the RAFT_SERVE_MESH_DATA env override, else 1
        (single-device, the pre-pod path). With n_data > 1 the batched
        programs compile under ``parallel/mesh.make_mesh`` with the
        leading batch dim sharded; batch buckets round up to multiples of
        n_data (the pad rows land in the existing dead-carry accounting).
        RAFT_SERVE_MESH_FALLBACK=1 forces 1 (the pod kill switch).
    """

    valid_iters: int = 32
    segments: int = 4
    bucket: int = 32
    max_programs: int = 8
    warmup_shapes: Tuple[Tuple[int, int], ...] = ()
    warmup_segmented: bool = False
    canary: bool = False
    canary_shape: Tuple[int, int] = (64, 96)
    canary_iters: int = 2
    allow_half_res: bool = True
    max_batch: int = 1
    batch_buckets: Tuple[int, ...] = ()
    mesh_data: Optional[int] = None
    # graftheal (r22): recovery-plane master switch. None = the RAFT_HEAL
    # env override, else ON.  False restores the one-way PR 3..17
    # degradation semantics exactly (no probation, no re-admission, no
    # refill).  Host-side pacing only — never part of any fingerprint.
    heal: Optional[bool] = None
    admission: AdmissionConfig = dataclasses.field(
        default_factory=AdmissionConfig)

    def __post_init__(self):
        if self.bucket % 32:
            raise ValueError(f"bucket must be a multiple of 32, "
                             f"got {self.bucket}")
        if self.valid_iters % self.segments:
            raise ValueError(
                f"segments ({self.segments}) must divide valid_iters "
                f"({self.valid_iters})")
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.batch_buckets:
            bb = tuple(self.batch_buckets)
            if list(bb) != sorted(set(bb)) or bb[0] < 1:
                raise ValueError(
                    f"batch_buckets must be strictly increasing positive "
                    f"ints, got {bb}")
        if self.mesh_data is not None and self.mesh_data < 1:
            raise ValueError(
                f"mesh_data must be >= 1, got {self.mesh_data}")


@dataclasses.dataclass
class InferenceResult:
    """One served disparity field with an honest quality label."""

    disparity: np.ndarray        # (H, W) float32, positive disparity
    quality: str                 # 'full' | 'reduced_iters:<k>' | 'half_res'
    iters: int                   # refinement iterations actually run
    elapsed_s: float
    padded_shape: Tuple[int, int]
    deadline_missed: bool = False

    @property
    def degraded(self) -> bool:
        return self.quality != "full"


class _Program:
    """One cached compiled program + its first-call lock. ``env`` is the
    switch set the program must be TRACED under — the canary's plain-XLA
    reference carries all-off switches regardless of the session's own.
    ``compiled`` is the AOT executable produced at warm time (so its
    ``cost_analysis``/``memory_analysis`` feed the program ledger with
    zero extra compiles); ``None`` means the warming path fell back to
    plain jit dispatch (``fn``)."""

    __slots__ = ("key", "fn", "kind", "env", "warmed", "lock", "compiled",
                 "ledger_id", "mesh")

    def __init__(self, key, fn, kind, env):
        self.key = key
        self.fn = fn
        self.kind = kind
        self.env = dict(env)
        self.warmed = False
        self.lock = threading.Lock()
        self.compiled = None
        self.ledger_id = ledger_id(key)
        # graftpod: mesh-sharded programs carry a trailing
        # ("mesh", n_data, epoch) key component (see cache_key) — parsed
        # once here so invoke() can pick shardings without re-inspecting
        # the tuple shape on every call.  None = single-device program.
        self.mesh = key[6] if len(key) > 6 else None


@contextlib.contextmanager
def _env_overrides(env: Dict[str, Optional[str]]):
    """Export a FULLY RESOLVED switch set for the duration of a trace.
    ``None`` means "unset" (several switches distinguish unset from empty),
    so the trace provably sees exactly the values its program was keyed
    under — even if another thread mutated the process env meanwhile."""
    old = {k: os.environ.get(k) for k in env}
    for k, v in env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def resolve_env(overrides: Dict[str, str],
                base: Optional[Dict[str, Optional[str]]] = None
                ) -> Dict[str, Optional[str]]:
    """A full kernel-switch mapping: the breaker override where present,
    the ``base`` snapshot otherwise (``None`` value = unset; ``base=None``
    reads the live process env). Both the cache key and the trace use THIS
    mapping, so a program can never be keyed under one switch set and
    traced under another. The session passes its construction-time env
    snapshot as ``base`` — another thread's in-flight ``_env_overrides``
    (which temporarily mutates the process env around a trace) can then
    never bleed into a concurrent key. Override keys outside
    ``_ENV_KNOBS`` are kept, never dropped — a ladder rung with a new env
    var must actually reach the trace."""
    keys = tuple(_ENV_KNOBS) + tuple(k for k in overrides
                                     if k not in _ENV_KNOBS)
    if base is None:
        base = {k: os.environ.get(k) for k in keys}
    return {k: (overrides[k] if k in overrides else base.get(k))
            for k in keys}


def config_fingerprint(cfg: RAFTStereoConfig,
                       env: Dict[str, str]) -> Tuple:
    """Every forward-relevant degree of freedom, hashable.

    All config dataclass fields (not a hand-picked subset — a new field is
    conservative-by-default in the key) and the effective value of each
    kernel env switch (pass a :func:`resolve_env` mapping to pin one
    snapshot). The breaker trip set is deliberately NOT part of the key:
    ``breaker.apply`` projects every trip into cfg/env, so two trip sets
    with the same projection compile the same program — keying on the
    projection lets them share it (e.g. the canary's plain-XLA reference
    survives a ladder walk instead of recompiling per trip).
    """
    cfg_part = tuple(sorted(
        (f.name, repr(getattr(cfg, f.name)))
        for f in dataclasses.fields(cfg)))
    if set(env) >= set(_ENV_KNOBS):  # already a resolve_env snapshot
        env_part = tuple(sorted(env.items()))
    else:
        env_part = tuple(sorted(resolve_env(env).items()))
    return cfg_part, env_part


# Session counters (obs/metrics.py registry): the short names /healthz has
# always reported, mapped to their Prometheus series. ONE table so
# ``metrics()`` (the legacy dict view) and the /metrics exposition can
# never drift.
_SESSION_COUNTERS = {
    "compiles": "programs built (jit closures created)",
    "evictions": "programs evicted from the LRU cache",
    "requests_ok": "requests served with a finite disparity",
    "requests_failed": "requests that raised (all serving modes)",
    "degraded": "served requests whose quality label was not 'full'",
    "nonfinite_outputs": "forwards whose disparity failed validation",
    "rebuilds": "breaker-driven session rebuilds (one rung down)",
}


# Every serving program kind the session can compile — ONE list shared by
# `_build_fn` and the graftverify trace registry
# (analysis/trace/registry.py), which traces each kind at pinned shapes so
# the GV checkers walk exactly the programs serving would compile.
#
# "prepare_warm" is the streaming warm-start seam (serve/stream.py): the
# same encoder half as "prepare" plus a flow_init operand seeding
# ``coords1 = coords0 + flow_init``.  It is a DIFFERENT traced program
# (extra operand, extra adds), so it is a separate kind with its own
# cache rows, ledger rows and warmup entry — reusing the cold key would
# be exactly the PR 3 stale-program bug class.  The flow operand is
# x-only (the program bakes in a zero y channel), which preserves the
# flow-y == 0 invariant the fused motion encoder relies on — so warm and
# cold carries share ONE advance program and one epilogue, and
# prepare_warm is the only new program a stream costs.
PROGRAM_KINDS = ("full", "prepare", "prepare_warm", "segment", "advance",
                 "epilogue")

# Scan-scale declaration per kind for the program ledger (obs/ledger.py):
# XLA cost analysis counts a scan body ONCE regardless of trip count, so
# kinds whose whole body rides the refinement scan scale by their
# iteration count, scan-free kinds scale by 1, and "full" (encoders +
# scan + epilogue in one program) declares None — no per-invocation flop
# estimate is honest for it, so its MFU reports absent rather than ~32x
# wrong ("segment" includes one mask-head pass per call, so its scaled
# estimate slightly overcounts that head; documented in DESIGN.md r12).
SCAN_SCALE = {"full": None, "prepare": 1, "prepare_warm": 1,
              "segment": "iters", "advance": "iters", "epilogue": 1}


def build_program(kind: str, cfg, iters: int):
    """The RAW (unjitted) python callable for one serving program kind.

    This is the traceable entry-point registry's view of the session: the
    session jits exactly this callable (``_build_fn``), so a jaxpr of
    ``build_program(kind, ...)`` at a padded shape IS the program the
    serving cache would compile — graftverify's checkers (GV101-GV104)
    walk these, and any drift between serving and analysis is structurally
    impossible because there is one builder.
    """
    import jax.numpy as jnp
    from raft_stereo_tpu.models import (raft_stereo_epilogue,
                                        raft_stereo_forward,
                                        raft_stereo_prepare,
                                        raft_stereo_segment,
                                        raft_stereo_segment_carry)
    if kind == "full":
        # The exact program engine/evaluate.make_eval_forward compiles
        # (flow plus a checksum whose host fetch is the completion
        # barrier) — byte-identical serving vs the eval/demo path.
        def fwd(p, image1, image2):
            _, flow_up = raft_stereo_forward(
                p, cfg, image1, image2, iters=iters, test_mode=True)
            return flow_up, jnp.sum(flow_up.astype(jnp.float32))
        return fwd
    if kind == "prepare":
        def prep(p, image1, image2):
            # 1-tuple so every program returns a tuple (invoke()'s
            # fetch iterates outputs; the carry dict is one output).
            return (raft_stereo_prepare(p, cfg, image1, image2),)
        return prep
    if kind == "prepare_warm":
        # Streaming warm start: seed coords1 from the previous frame's
        # 1/8-res disparity. ``flow_x`` is x-only ``(b, h/f, w/f, 1)``;
        # the zero y channel is constructed IN the program, so the
        # carried flow's y component is exactly 0 — the invariant that
        # lets warm carries ride the same advance/epilogue programs as
        # cold ones (see models/raft_stereo.py raft_stereo_prepare).
        # With an all-zero flow_x this computes coords0 + 0.0, which is
        # bit-identical to the cold prepare's coords0 (pinned in
        # tests/test_stream.py).
        def prep_warm(p, image1, image2, flow_x):
            flow_init = jnp.concatenate(
                [flow_x.astype(jnp.float32), jnp.zeros_like(flow_x)],
                axis=-1)
            return (raft_stereo_prepare(p, cfg, image1, image2,
                                        flow_init=flow_init),)
        return prep_warm
    if kind == "segment":
        def seg(p, state):
            state, _, flow_up = raft_stereo_segment(
                p, cfg, state, iters=iters)
            return state, flow_up, jnp.sum(flow_up.astype(jnp.float32))
        return seg
    if kind == "advance":
        # The continuous-batching tick: advance the whole device batch
        # WITHOUT the mask-head epilogue (exiting rows pay it once, in
        # the batched "epilogue" program). The per-row coords sums are
        # the host fetch that doubles as the completion barrier; the
        # per-row delta-flow norm (last iteration's mean |delta_x|)
        # rides the same fetch — the convergence monitor the streaming
        # early exit compares against RAFT_CONVERGE_TOL on the HOST, so
        # the tolerance never shapes this program (and stays out of the
        # fingerprint by construction).
        def adv(p, state):
            state, dnorm = raft_stereo_segment_carry(p, cfg, state,
                                                     iters=iters)
            rowsum = jnp.sum(state["coords1"].astype(jnp.float32),
                             axis=(1, 2, 3))
            return state, rowsum, dnorm
        return adv
    if kind == "epilogue":
        # Mask head + convex upsample for a batch of exiting carries —
        # one stacked round trip for every row that finished this tick.
        # The x-only low-res flow rides along (tiny next to flow_up:
        # 1/64th the pixels) — it is the next frame's warm-start seed,
        # held host-side per stream session (serve/stream.py).
        def epi(p, state):
            flow_low, flow_up = raft_stereo_epilogue(p, cfg, state)
            return flow_up, flow_low[..., :1].astype(jnp.float32)
        return epi
    raise ValueError(f"unknown program kind {kind!r}")


class InferenceSession:
    """Owns params + config; admits arbitrary pairs, serves disparity."""

    def __init__(self, params, cfg: RAFTStereoConfig,
                 session_cfg: Optional[SessionConfig] = None, *,
                 breaker: Optional[KernelCircuitBreaker] = None,
                 fault_plan: Optional[ServeFaultPlan] = None,
                 clock=None, registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None,
                 ledger: Optional[ProgramLedger] = None,
                 flight: Optional[FlightRecorder] = None):
        import jax
        self._jax = jax
        self.cfg = session_cfg or SessionConfig()
        self.clock = clock if clock is not None else RealClock()
        # graftscope (obs/): ONE registry + tracer per serving process —
        # service and scheduler share these, so /healthz, /metrics and the
        # span timelines describe the same counters by construction.
        self.registry = registry if registry is not None else \
            MetricsRegistry()
        self.tracer = tracer if tracer is not None else \
            Tracer(clock=self.clock)
        self.profiler = ProfilerWindow()  # RAFT_PROFILE_DIR, read once
        # graftscope-device (obs/ledger.py, obs/flight.py): the program
        # ledger records every compiled program's compiler-derived
        # cost/memory account; the flight recorder persists SLO-breaching
        # requests' timelines (RAFT_FLIGHT_DIR, read once, here).
        self.ledger = ledger if ledger is not None else ProgramLedger()
        self.flight = flight if flight is not None else FlightRecorder()
        # graftdeck (obs/deck.py, obs/usage.py, obs/capacity.py): the
        # tick flight-deck ring (RAFT_DECK_TICKS, read once here), the
        # per-tenant usage accountant sharing the one registry, and the
        # saturation window for the capacity model.  All host-side
        # telemetry — no compiled program depends on any of it.
        self.deck = TickDeck(clock=self.clock)
        self.usage = UsageAccountant(self.registry)
        self._capacity_window_s = resolve_capacity_window_s()
        # Thread-local usage-attribution context: the scheduler binds the
        # tenant labels of every row riding a device call; the sequential
        # worker binds its one request's tenant; unbound steady invokes
        # (direct session.infer) attribute to the "default" tenant so the
        # per-tenant device-seconds partition stays exhaustive.
        self._usage_tl = threading.local()
        self._backend = jax.default_backend()
        try:
            self._device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 — diagnostics label only
            self._device_kind = None
        # Per-shape-bucket cache-HBM gauges last published (so a bucket
        # whose programs all evicted reads 0, not a stale sum). Mutated
        # only under _hbm_lock.
        self._hbm_lock = threading.Lock()
        self._hbm_buckets: set = set()
        self._ctr = {
            name: self.registry.counter(f"raft_session_{name}_total", help)
            for name, help in _SESSION_COUNTERS.items()}
        self._params = params
        self._base_cfg = cfg
        # Kernel switches are captured ONCE, here: every cache key and
        # every trace resolves against this snapshot (plus breaker
        # overrides), so concurrent _env_overrides windows and operator
        # env flips mid-process can never skew a key. Changing switches
        # means a new session (or tripping the breaker).
        self._env_base: Dict[str, Optional[str]] = {
            k: os.environ.get(k) for k in _ENV_KNOBS}
        # graftpod: the data-mesh plane.  n_data is resolved ONCE here
        # (kill switch > explicit config > RAFT_SERVE_MESH_DATA > 1) and
        # rides the program-cache KEY as a trailing component, never the
        # config fingerprint — see resolve_serve_mesh_data's rationale.
        # ``_mesh_base_n`` is the construction-time extent; quarantining a
        # hung chip shrinks the live mesh to the largest divisor of the
        # base extent that fits the surviving chips (divisors of the base
        # still divide every rounded batch bucket) and bumps the epoch,
        # re-keying the mesh programs (old ones age out of the LRU — the
        # PR 3 stale-program discipline).
        self._mesh = None
        self._mesh_n = 1
        self._mesh_epoch = 0
        self._mesh_devices: list = []
        self._quarantined: set = set()
        self._mesh_shardings: Dict[int, Tuple] = {}
        self._mesh_params: Dict[int, object] = {}
        # RLock: quarantine_chip rebuilds the mesh while holding it, and
        # _build_mesh re-takes it so every mutation site is guarded.
        self._mesh_lock = threading.RLock()
        self._mesh_base_n = (1 if resolve_mesh_fallback()
                             else resolve_serve_mesh_data(self.cfg.mesh_data))
        if self._mesh_base_n > 1:
            devices = list(jax.devices())
            if self._mesh_base_n > len(devices):
                raise ValueError(
                    f"mesh_data {self._mesh_base_n} exceeds the "
                    f"{len(devices)} available {self._backend} device(s)")
            # The POD is the first base_n devices — probes, per-chip
            # capacity rows and the quarantine shrink all index into this
            # list, so it must be exactly the chips the mesh spans, not
            # every device the host can see (a spare chip is a deliberate
            # redeploy, not a silent failover target).
            self._mesh_devices = devices[:self._mesh_base_n]
            self._build_mesh(self._mesh_devices, self._mesh_base_n)
        # Batch-bucket ladder for continuous batching, resolved ONCE here
        # (SessionConfig value > RAFT_BATCH_BUCKETS env > powers of two up
        # to max_batch). Batch size is an EXPLICIT cache-key component, so
        # this knob never needs to ride the config fingerprint — it only
        # selects which batch sizes get compiled, not what any one
        # compiled program computes (analysis/knobs.py SERVE_ENV_KNOBS).
        self._batch_buckets = self._resolve_batch_buckets()
        # Effective LRU bound: continuous batching keeps prepare/
        # prepare_warm/advance/epilogue warm at EVERY batch bucket for a
        # shape — with the sequential default (8) a max_batch=8 warmup
        # would evict its own programs and the scheduler would recompile
        # per tick, forever.  One fully-warm shape bucket is the floor
        # (FOUR kinds per bucket since graftstream added prepare_warm —
        # the old 3-per-bucket floor would have let the warmup evict its
        # own first programs again); operators serving many shapes raise
        # max_programs themselves.
        self._max_programs = self.cfg.max_programs
        if self.cfg.max_batch > 1:
            self._max_programs = max(
                self.cfg.max_programs, 4 * len(self._batch_buckets) + 2)
        elif self.cfg.warmup_segmented:
            # Sequential deadline serving warms full + prepare/segment
            # (+ the half-res pair) + the b=1 streaming trio
            # (prepare_warm/advance/epilogue) per shape = up to 8
            # programs; the default bound of 8 would let the warmup
            # evict its own first program.  One fully warm sequential
            # shape bucket plus headroom is the floor.
            self._max_programs = max(self.cfg.max_programs, 10)
        # The ladder/knob-registry sync check lives in the breaker's
        # constructor now (guard.py imports the same ENV_KNOBS registry);
        # resolve_env additionally keeps unknown override keys, so a rung
        # whose env var drifted out of the registry still reaches the
        # trace correctly — it just won't key untripped programs.
        self.breaker = breaker or KernelCircuitBreaker()
        self.breaker.bind_registry(self.registry)
        # graftheal (r22): recovery-plane pacing, resolved ONCE here
        # (explicit SessionConfig.heal > RAFT_HEAL > on).  The breaker's
        # probation deadlines ride THIS session's clock — FakeClock in
        # tests/storms, so every heal test is instantaneous and exact.
        self._heal_enabled = resolve_heal_enabled(self.cfg.heal)
        self._heal_backoff_s = resolve_heal_backoff_ms() / 1e3
        self._heal_backoff_max_s = resolve_heal_backoff_max_ms() / 1e3
        self._heal_flap_cap = resolve_heal_flap_cap()
        self._heal_window_s = resolve_heal_window_ms() / 1e3
        self.breaker.configure_heal(
            enabled=self._heal_enabled, clock=self.clock,
            backoff_s=self._heal_backoff_s,
            backoff_max_s=self._heal_backoff_max_s)
        # Per-chip probation state (chip -> {backoff_s, deadline, probes,
        # readmitted: [session-clock times], permanent, quarantined_at}),
        # mutated only under _mesh_lock; and the MTTR record the heal
        # sweeps publish (fault-injected -> capacity restored).
        self._chip_heal: Dict[int, Dict] = {}
        self._heal_mttr: Dict = {"last_s": None, "events": 0}
        self.faults = ServeFaults(fault_plan, clock=self.clock)
        # graftguard (serve/supervise.py): every device invocation is
        # bracketed in this watch so a supervisor can classify a hung
        # call (age > max(EMA x factor, floor)) without the session
        # knowing any watchdog policy.
        self.watch = InvocationWatch(self.clock)
        self._cache: "OrderedDict[Tuple, _Program]" = OrderedDict()
        self._cache_lock = threading.Lock()
        self._key_locks: Dict[Tuple, threading.Lock] = {}
        self._estimates: Dict[Tuple, float] = {}
        self._est_lock = threading.Lock()
        self._canary_state = {"enabled": self.cfg.canary, "ran": False,
                              "passed": None, "attempts": 0}
        self._run_cfg, self._env = self.breaker.apply(cfg)
        # Scrape identity (standard exposition practice): every /metrics
        # scrape names the config fingerprint, runtime versions and
        # backend it came from, plus the process start time.
        import platform
        self.registry.set_build_info(
            fingerprint=self.fingerprint_id(),
            python=platform.python_version(),
            jax=getattr(jax, "__version__", "unknown"),
            backend=self._backend)
        self.start()

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        """Warm the configured buckets and run the parity canary. Called
        from the constructor; safe to call again after ``breaker.reset()``.
        Kernel failures here already walk the fallback ladder — a session
        whose fast paths are broken comes up degraded, not dead."""
        for (h, w) in self.cfg.warmup_shapes:
            self._warm_shape(h, w)
        if self.cfg.canary:
            self._run_canary()

    def _rebuild(self, why: str) -> None:
        """Project the new trip set onto the run config. Cached programs
        keyed under the old fingerprint become unreachable (and age out of
        the LRU) — they are never served for the new config."""
        self._run_cfg, self._env = self.breaker.apply(self._base_cfg)
        self._ctr["rebuilds"].inc()
        logger.warning("session rebuilt one rung down (%s); tripped=%s",
                       why, list(self.breaker.tripped_names))

    def _breaker_retry(self, exc: Exception, phase: str,
                       traces=()) -> None:
        """Classify a kernel failure, trip the rung, rebuild — or give up
        with a structured error when the ladder is exhausted. ``traces``
        are the request timelines riding the failed program (one for the
        sequential path, every batch row for the scheduler) — the trip is
        a decision event on each."""
        path = self.breaker.classify(exc)
        if path is None:
            raise InferenceFailed(
                "ladder_exhausted",
                f"plain-XLA program still failing: {exc}") from exc
        self.breaker.trip(path.name, phase, exc)
        for trace in traces:
            trace.event("breaker_trip", rung=path.name, phase=phase)
        self._rebuild(f"{path.name}: {exc}")

    # -- padding / bucketing ----------------------------------------------

    def padder_for(self, shape) -> InputPadder:
        return InputPadder(shape, divis_by=32, bucket=self.cfg.bucket)

    def _resolve_batch_buckets(self) -> Tuple[int, ...]:
        buckets = tuple(self.cfg.batch_buckets)
        if not buckets:
            spec = os.environ.get("RAFT_BATCH_BUCKETS", "").strip()
            if spec:
                try:  # named error, not a bare int() traceback (cf. the
                    # PR 4 SLURM_CPUS_PER_TASK fix — same env-parsing class)
                    buckets = tuple(sorted({int(p) for p in spec.split(",")
                                            if p.strip()}))
                except ValueError:
                    raise ValueError(
                        f"RAFT_BATCH_BUCKETS must be comma-separated "
                        f"positive ints, got {spec!r}") from None
                if not buckets or buckets[0] < 1:
                    raise ValueError(
                        f"RAFT_BATCH_BUCKETS must be positive ints, "
                        f"got {spec!r}")
            else:
                buckets, b = [], 1
                while b < self.cfg.max_batch:
                    buckets.append(b)
                    b *= 2
                buckets = tuple(buckets) + (self.cfg.max_batch,)
        # Cap at max_batch but always keep one bucket that covers it.
        capped = tuple(b for b in buckets if b < self.cfg.max_batch)
        covering = min((b for b in buckets if b >= self.cfg.max_batch),
                       default=self.cfg.max_batch)
        buckets = capped + (covering,)
        if self._mesh_n > 1:
            # graftpod: every batch bucket rounds UP to a multiple of the
            # mesh extent so the leading dim always shards evenly (the
            # `local_batch_rows` divisibility rule); the extra rows are
            # ordinary dead-carry pads and land in the scheduler's
            # existing `pad_rows` accounting, never in occupancy.
            n = self._mesh_n
            buckets = tuple(sorted({-(-b // n) * n for b in buckets}))
        return buckets

    @property
    def batch_buckets(self) -> Tuple[int, ...]:
        return self._batch_buckets

    def batch_bucket(self, n: int) -> int:
        """Smallest registered batch bucket that fits ``n`` rows."""
        for b in self._batch_buckets:
            if b >= n:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest batch bucket "
            f"{self._batch_buckets[-1]} (max_batch={self.cfg.max_batch})")

    # -- pod mesh (graftpod) ----------------------------------------------

    def _build_mesh(self, devices, n: int) -> None:
        """(Re)build the live data mesh over ``devices`` at extent ``n``
        for the CURRENT epoch, replicate params onto it, and cache the
        epoch's shardings.  Single-device programs keep riding the
        original ``self._params`` so the n_data=1 path stays byte-for-byte
        the pre-pod path."""
        from raft_stereo_tpu.parallel.mesh import (batch_sharding,
                                                   make_mesh, replicated)
        mesh = make_mesh(n, 1, devices)
        rep = replicated(mesh)
        with self._mesh_lock:  # reentrant from quarantine_chip
            self._mesh = mesh
            self._mesh_n = n
            self._mesh_shardings[self._mesh_epoch] = (batch_sharding(mesh),
                                                      rep)
            # Params replicated per mesh epoch: an old epoch's in-flight
            # invocation still finds its own params/shardings (bounded —
            # the epoch only bumps on a chip quarantine).
            self._mesh_params[self._mesh_epoch] = self._jax.device_put(
                self._params, rep)

    @property
    def mesh_active(self) -> bool:
        return self._mesh is not None

    @property
    def mesh_chips(self) -> int:
        """Chips the live mesh spans (1 = single-device serving)."""
        return self._mesh_n if self._mesh is not None else 1

    def probe_chips(self, timeout_s: float = 2.0) -> Tuple[int, ...]:
        """Probe every non-quarantined chip of the base mesh with a tiny
        transfer + ``block_until_ready`` on a daemon thread each; a chip
        whose probe does not complete within ``timeout_s`` is hung.
        Returns the hung chip ordinals (indices into the construction-time
        device list).  The ``faults.on_chip_probe`` hook runs INSIDE each
        probe thread so chaos plans can park exactly one chip's probe the
        way ``on_invoke`` parks a device call."""
        if self._mesh is None and not self._mesh_devices:
            return ()
        done: Dict[int, bool] = {}

        def _probe(i: int, dev) -> None:
            try:
                self.faults.on_chip_probe(i)
                x = self._jax.device_put(np.zeros((), np.float32), dev)
                x.block_until_ready()
                done[i] = True
            except Exception:  # noqa: BLE001 — a failed probe IS a hang
                done[i] = False

        threads = []
        for i, dev in enumerate(self._mesh_devices):
            if i in self._quarantined:
                continue
            t = threading.Thread(target=_probe, args=(i, dev),
                                 name=f"chip-probe-{i}", daemon=True)
            t.start()
            threads.append((i, t))
        deadline = self.clock.now() + timeout_s
        for i, t in threads:
            # Chip probes are deadline-bounded and run from the bounce
            # path (under _check_lock by design: one recovery at a
            # time); a wedged probe thread is exactly what the deadline
            # caps.
            # graftlint: disable=GC203 (deadline-capped probe join on the serialized bounce path)
            t.join(timeout=max(0.05, deadline - self.clock.now()))
        return tuple(i for i, t in threads
                     if t.is_alive() or not done.get(i, False))

    def quarantine_chip(self, chip: int) -> bool:
        """Take one hung chip out of the live mesh: shrink the mesh to
        the largest divisor of the base extent that fits the surviving
        chips (divisors keep every rounded batch bucket evenly sharded)
        and bump the mesh epoch, re-keying the mesh programs.  Returns
        False when the chip was already quarantined / out of range."""
        with self._mesh_lock:
            if chip in self._quarantined or \
                    not (0 <= chip < len(self._mesh_devices)):
                return False
            self._quarantined.add(chip)
            if self._heal_enabled:
                # graftheal: arm (or re-arm) this chip's probation.  A
                # RE-quarantine doubles the backoff (capped) and counts
                # against the flap cap — a chip flapping past the cap
                # inside the window goes permanently out (an epoch bump
                # per flap would thrash the mesh programs into a
                # recompile storm, which is worse than serving shrunk).
                now = self.clock.now()
                st = self._chip_heal.get(chip)
                if st is None:
                    self._chip_heal[chip] = {
                        "backoff_s": self._heal_backoff_s,
                        "deadline": now + self._heal_backoff_s,
                        "probes": 0, "readmitted": [],
                        "permanent": False, "quarantined_at": now}
                else:
                    st["quarantined_at"] = now
                    st["backoff_s"] = min(st["backoff_s"] * 2.0,
                                          self._heal_backoff_max_s)
                    st["deadline"] = now + st["backoff_s"]
                    window = [t for t in st["readmitted"]
                              if now - t <= self._heal_window_s]
                    if len(window) >= self._heal_flap_cap \
                            and not st["permanent"]:
                        st["permanent"] = True
                        logger.error(
                            "chip %d re-quarantined after %d "
                            "re-admissions in the flap window — "
                            "permanently out", chip, len(window))
                        self.registry.counter(
                            "raft_heal_chips_permanent_total",
                            "chips permanently quarantined by the flap "
                            "cap").inc()
            healthy = [d for i, d in enumerate(self._mesh_devices)
                       if i not in self._quarantined]
            new_n = max((d for d in range(1, self._mesh_base_n + 1)
                         if self._mesh_base_n % d == 0
                         and d <= len(healthy)), default=1)
            self._mesh_epoch += 1
            if not healthy:
                # Every chip gone: serving will fail loudly downstream —
                # never silently route onto a quarantined chip.
                self._mesh = None
                self._mesh_n = 1
                logger.error("all %d mesh chips quarantined",
                             len(self._mesh_devices))
                return True
            # Even a 1-chip remainder keeps a (1,1) mesh: placement must
            # land on a HEALTHY chip, and the default device might be the
            # quarantined one.
            self._build_mesh(healthy[:new_n], new_n)
            logger.warning(
                "quarantined chip %d; mesh now %d chip(s) (epoch %d, "
                "quarantined=%s)", chip, new_n, self._mesh_epoch,
                sorted(self._quarantined))
            self.registry.counter(
                "raft_mesh_chips_quarantined_total",
                "chips removed from the live data mesh").inc()
            self.registry.gauge(
                "raft_mesh_chips",
                "chips the live data mesh spans").set(new_n)
            return True

    def mesh_status(self) -> Dict:
        """The /healthz + /debug/config ``mesh`` block (bounded: one row
        per construction-time chip)."""
        with self._mesh_lock:
            return {
                "enabled": self._mesh is not None,
                "n_data": self.mesh_chips,
                "base_n_data": self._mesh_base_n,
                "epoch": self._mesh_epoch,
                "quarantined": sorted(self._quarantined),
                "devices": [
                    {"chip": i, "kind": getattr(d, "device_kind", None),
                     "quarantined": i in self._quarantined}
                    for i, d in enumerate(self._mesh_devices)],
            }

    # -- recovery plane (graftheal r22) ------------------------------------

    def probe_quarantined(self, chips: Tuple[int, ...],
                          timeout_s: float = 2.0) -> Tuple[int, ...]:
        """Probe exactly the given quarantined chips (tiny transfer +
        ``block_until_ready`` on a daemon thread each, the
        ``probe_chips`` recipe) and return the subset that FAILED.  The
        ``faults.on_chip_probe`` hook runs inside each probe thread, so
        a transient chaos fault whose window has cleared passes and a
        still-wedged chip keeps failing."""
        done: Dict[int, bool] = {}

        def _probe(i: int, dev) -> None:
            try:
                self.faults.on_chip_probe(i)
                x = self._jax.device_put(np.zeros((), np.float32), dev)
                x.block_until_ready()
                done[i] = True
            except Exception:  # noqa: BLE001 — a failed probe IS a hang
                done[i] = False

        threads = []
        for i in chips:
            if not (0 <= i < len(self._mesh_devices)):
                continue
            t = threading.Thread(target=_probe,
                                 args=(i, self._mesh_devices[i]),
                                 name=f"chip-heal-probe-{i}", daemon=True)
            t.start()
            threads.append((i, t))
        deadline = self.clock.now() + timeout_s
        for i, t in threads:
            t.join(timeout=max(0.05, deadline - self.clock.now()))
        return tuple(i for i, t in threads
                     if t.is_alive() or not done.get(i, False))

    def readmit_chip(self, chip: int) -> bool:
        """Re-grow the mesh onto one probe-verified chip: flap-cap
        check, un-quarantine, recompute the extent (largest divisor of
        the base fitting the healthy set), bump the epoch, rebuild the
        mesh — then RE-WARM the re-keyed mesh programs before returning,
        so no row ever routes onto a cold epoch (the PR 5
        mid-request-compile class).  Returns False when the chip is not
        quarantined, healing is off, or the flap cap fired."""
        with self._mesh_lock:
            if not self._heal_enabled or chip not in self._quarantined:
                return False
            st = self._chip_heal.get(chip)
            now = self.clock.now()
            if st is None or st["permanent"]:
                return False
            window = [t for t in st["readmitted"]
                      if now - t <= self._heal_window_s]
            if len(window) >= self._heal_flap_cap:
                st["permanent"] = True
                self.registry.counter(
                    "raft_heal_chips_permanent_total",
                    "chips permanently quarantined by the flap cap").inc()
                return False
            self._quarantined.discard(chip)
            st["readmitted"] = window + [now]
            # The fault class that cleared is not the one that re-trips:
            # a LATER quarantine starts back at the base backoff (then
            # doubles per flap).
            st["backoff_s"] = self._heal_backoff_s
            healthy = [d for i, d in enumerate(self._mesh_devices)
                       if i not in self._quarantined]
            new_n = max((d for d in range(1, self._mesh_base_n + 1)
                         if self._mesh_base_n % d == 0
                         and d <= len(healthy)), default=1)
            self._mesh_epoch += 1
            self._build_mesh(healthy[:new_n], new_n)
            logger.warning(
                "re-admitted chip %d; mesh now %d chip(s) (epoch %d, "
                "quarantined=%s)", chip, new_n, self._mesh_epoch,
                sorted(self._quarantined))
            self.registry.counter(
                "raft_heal_chips_readmitted_total",
                "chips re-admitted to the live data mesh").inc()
            self.registry.gauge(
                "raft_mesh_chips",
                "chips the live data mesh spans").set(new_n)
            mttr = now - st["quarantined_at"]
            self._heal_mttr = {"last_s": mttr,
                               "events": self._heal_mttr["events"] + 1}
            self.registry.gauge(
                "raft_heal_mttr_seconds",
                "last fault-injected -> capacity-restored interval "
                "(session clock)").set(mttr)
        # Re-warm the new epoch's mesh-keyed programs OUTSIDE the mesh
        # lock (compiles are slow; quarantine from another thread must
        # not block behind them) but BEFORE returning — the heal sweep
        # is synchronous, so no request routes onto the grown mesh
        # until the warmup-LRU floor holds the new programs.
        if self.cfg.max_batch > 1:
            for (h, w) in self.cfg.warmup_shapes:
                self._warm_shape(h, w)
        return True

    def heal_mesh(self, probe_timeout_s: float = 2.0) -> Dict:
        """One recovery sweep over quarantined chips: probe every chip
        whose probation deadline elapsed, re-admit the passers, double
        the backoff of the failers.  Returns
        ``{"probed", "readmitted", "failed"}`` chip lists."""
        out: Dict = {"probed": [], "readmitted": [], "failed": []}
        if not self._heal_enabled or self._heal_flap_cap < 1:
            return out
        now = self.clock.now()
        with self._mesh_lock:
            candidates = []
            for c in sorted(self._quarantined):
                st = self._chip_heal.get(c)
                if st is None or st["permanent"] or now < st["deadline"]:
                    continue
                # Hand-out pushes the deadline one backoff out, so a
                # concurrent sweep cannot double-probe this chip.
                st["probes"] += 1
                st["deadline"] = now + st["backoff_s"]
                candidates.append(c)
        if not candidates:
            return out
        out["probed"] = list(candidates)
        failed = set(self.probe_quarantined(tuple(candidates),
                                            timeout_s=probe_timeout_s))
        for c in candidates:
            ok = c not in failed and self.readmit_chip(c)
            self.registry.counter(
                "raft_heal_chip_probes_total",
                "quarantined-chip probation probes by outcome",
                result=("passed" if ok else "failed")).inc()
            if ok:
                out["readmitted"].append(c)
                continue
            out["failed"].append(c)
            if c in failed:
                with self._mesh_lock:
                    st = self._chip_heal.get(c)
                    if st is not None:
                        st["backoff_s"] = min(st["backoff_s"] * 2.0,
                                              self._heal_backoff_max_s)
                        st["deadline"] = (self.clock.now()
                                          + st["backoff_s"])
        return out

    def heal_breaker(self) -> Optional[Dict]:
        """One half-open canary probe of the most-recently-tripped
        eligible rung (strict reverse trip order — the breaker only ever
        nominates the last trip).  The CANDIDATE projection (current
        trips minus the rung) runs against the plain-XLA reference
        within the pinned drift band, WITHOUT touching serving state; a
        pass untrips + rebuilds + re-warms before any traffic routes on
        the re-engaged rung, a fail re-trips with doubled backoff.
        Returns None when no rung is eligible."""
        name = self.breaker.heal_candidate()
        if name is None:
            return None
        out: Dict = {"rung": name, "passed": False}
        cand = tuple(n for n in self.breaker.tripped_names if n != name)
        cand_cfg, cand_env = self.breaker.apply(self._base_cfg,
                                                tripped=cand)
        h, w = self.cfg.canary_shape
        padder = self.padder_for((h, w, 3))
        rng = np.random.default_rng(1234)
        left = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
        right = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
        iters = self.cfg.canary_iters
        ok = False
        try:
            fast = self._run_full(padder, left, right, iters=iters,
                                  cfg=cand_cfg, env=cand_env)
            ref_cfg, ref_env = self.breaker.plain_xla_cfg(self._base_cfg)
            if (self._fingerprint(cand_cfg, cand_env) ==
                    self._fingerprint(ref_cfg, ref_env)):
                # Candidate IS plain XLA (every other rung tripped):
                # finite output is the whole parity statement.
                ok = bool(np.isfinite(fast).all())
            else:
                ref = self._run_full(padder, left, right, iters=iters,
                                     cfg=ref_cfg, env=ref_env)
                ok = bool(np.isfinite(fast).all()
                          and np.isfinite(ref).all()
                          and np.allclose(fast, ref, rtol=CANARY_RTOL,
                                          atol=CANARY_ATOL))
        except Exception as e:  # noqa: BLE001 — filtered just below
            if not is_kernel_failure(e):
                raise
            # The probe's own kernel failure is a failed canary, never a
            # ladder walk: the rung under probation is the suspect.
            out["error"] = str(e)
        self.registry.counter(
            "raft_heal_rung_probes_total",
            "half-open breaker canary probes by rung and outcome",
            rung=name, result=("passed" if ok else "failed")).inc()
        if ok:
            self.breaker.untrip(name)
            # Untripping re-keys exactly as tripping did: re-project the
            # trip set, then RE-WARM before routing (same rebuild
            # counter — /healthz sees the walk back up the ladder).
            self._run_cfg, self._env = self.breaker.apply(self._base_cfg)
            self._ctr["rebuilds"].inc()
            logger.warning(
                "heal: rung %s re-engaged after a passing canary; "
                "tripped=%s", name, list(self.breaker.tripped_names))
            for (wh, ww) in self.cfg.warmup_shapes:
                self._warm_shape(wh, ww)
            out["passed"] = True
        else:
            # Re-trip doubles the probation backoff (guard.py trip()) and
            # increments the rung's trip count with the heal reason —
            # pinned visible on /healthz.
            self.breaker.trip(name, "heal_canary_failed")
        return out

    def heal_status(self) -> Dict:
        """The /healthz ``heal`` block: pacing knobs, per-rung and
        per-chip probation state, MTTR.  Bounded by construction (one
        row per ladder rung / construction-time chip)."""
        with self._mesh_lock:
            now = self.clock.now()
            chips = {}
            for chip, st in sorted(self._chip_heal.items()):
                quarantined = chip in self._quarantined
                chips[str(chip)] = {
                    "quarantined": quarantined,
                    "permanent": st["permanent"],
                    "backoff_ms": st["backoff_s"] * 1e3,
                    "probes": st["probes"],
                    "readmissions": len(st["readmitted"]),
                    "eligible_in_s": (
                        max(0.0, st["deadline"] - now)
                        if quarantined and not st["permanent"] else None),
                }
            mttr = dict(self._heal_mttr)
        return {
            "enabled": self._heal_enabled,
            "backoff_ms": self._heal_backoff_s * 1e3,
            "backoff_max_ms": self._heal_backoff_max_s * 1e3,
            "flap_cap": self._heal_flap_cap,
            "window_ms": self._heal_window_s * 1e3,
            "breaker": self.breaker.heal_status(),
            "chips": chips,
            "mttr": mttr,
        }

    def _shard_args(self, prog: _Program, args):
        """Canonically re-``device_put`` a mesh program's operands every
        call: leading-dim-``b`` leaves onto the batch sharding, everything
        else replicated.  AOT ``Compiled`` executables require their exact
        input shardings, and the scheduler's host-side gathers between
        ticks (np carries, fresh uploads) arrive unsharded — a
        ``device_put`` onto an array already holding the target sharding
        is a no-op, so the steady path pays nothing."""
        shardings = self._mesh_shardings.get(prog.mesh[2])
        if shardings is None:  # epoch retired mid-flight: run as keyed
            return args
        batch_sh, rep = shardings
        b = prog.key[1]
        put = self._jax.device_put

        def _place(x):
            if getattr(x, "ndim", 0) >= 1 and x.shape[0] == b:
                return put(x, batch_sh)
            return put(x, rep)

        return tuple(self._jax.tree.map(_place, a) for a in args)

    def _params_for(self, prog: _Program):
        """The params copy a program must see: the epoch-replicated set
        for mesh programs, the original single-device set otherwise."""
        if prog.mesh is not None:
            p = self._mesh_params.get(prog.mesh[2])
            if p is not None:
                return p
        return self._params

    # -- program cache ----------------------------------------------------

    def _resolve(self, env: Dict[str, str]) -> Dict[str, Optional[str]]:
        return resolve_env(env, self._env_base)

    def _fingerprint(self, cfg=None, env=None) -> Tuple:
        env = env if env is not None else self._env
        if not (set(env) >= set(_ENV_KNOBS)):
            env = self._resolve(env)
        return config_fingerprint(
            cfg if cfg is not None else self._run_cfg, env)

    def cache_key(self, kind: str, h: int, w: int, iters: int,
                  cfg=None, env=None, b: int = 1) -> Tuple:
        # ``b`` is the batch bucket: jit would happily re-specialize one
        # cached program on a new leading dim, but that silent recompile
        # would dodge the warmed flag and corrupt the latency EMA (batched
        # segments have batch-dependent cost) — so batch is part of the
        # key and callers always pad rows up to a registered bucket.
        key = (kind, b, h, w, iters, self._fingerprint(cfg, env))
        if self._mesh is not None and b % self._mesh_n == 0:
            # graftpod: the mesh extent changes the compiled program
            # (sharded lowering — the PR 3 stale-program class), so it
            # re-keys — as a TRAILING component, appended only when the
            # mesh is live and the bucket shards evenly, so single-device
            # keys stay byte-identical and every positional consumer of
            # key[:6] (ledger ids, capacity's k[5] fingerprint filter,
            # the status render) is untouched.  The epoch rides along so
            # a post-quarantine mesh can never be served a pre-quarantine
            # program.  The config FINGERPRINT stays mesh-independent on
            # purpose: the PR 14 response cache keys on it and must stay
            # ONE host-side cache above all chips (DESIGN r18/r21).
            key = key + (("mesh", self._mesh_n, self._mesh_epoch),)
        return key

    def fingerprint_id(self) -> str:
        """Short stable hash of the CURRENT run fingerprint (config
        fields + effective kernel switches) — the /debug/config and
        ``raft_build_info`` identity.  An effective breaker trip changes
        it, exactly like the cache keys it summarizes."""
        import hashlib
        return hashlib.sha256(
            repr(self._fingerprint()).encode()).hexdigest()[:12]

    # -- per-tenant usage attribution (obs/usage.py) -----------------------

    @contextlib.contextmanager
    def usage_riders(self, labels):
        """Bind the tenant labels of the rows riding the next device
        call(s) on THIS thread: ``invoke`` partitions each steady
        invocation's device seconds (and ledger flops) exactly across
        them.  The scheduler binds its batch's labels per device call;
        the sequential worker binds its one request's label; nesting
        restores the previous binding."""
        prev = getattr(self._usage_tl, "labels", None)
        self._usage_tl.labels = list(labels) or None
        try:
            yield
        finally:
            self._usage_tl.labels = prev

    def _build_fn(self, kind: str, cfg, iters: int):
        return self._jax.jit(build_program(kind, cfg, iters))

    def get_program(self, kind: str, h: int, w: int, iters: int,
                    cfg=None, env=None, b: int = 1) -> _Program:
        """Fetch-or-compile under the per-bucket lock; LRU-bounded.

        The kernel switch set is resolved ONCE here (breaker overrides ∪
        live env) and that same snapshot both keys the program and is
        exported around its trace — key and trace cannot diverge."""
        cfg = cfg if cfg is not None else self._run_cfg
        env = env if env is not None else self._env
        trace_env = self._resolve(env)
        key = self.cache_key(kind, h, w, iters, cfg, trace_env, b=b)
        with self._cache_lock:
            prog = self._cache.get(key)
            if prog is not None:
                self._cache.move_to_end(key)
                return prog
            lock = self._key_locks.setdefault(key, threading.Lock())
        with lock:
            with self._cache_lock:  # double-checked: loser of the race
                prog = self._cache.get(key)
                if prog is not None:
                    self._cache.move_to_end(key)
                    return prog
            try:
                self.faults.on_build()  # injected compile failure fires here
                fn = self._build_fn(kind, cfg, iters)
            except Exception as e:
                setattr(e, "_raft_phase", "compile_failure")
                with self._cache_lock:
                    # the key never reaches the cache, so its lock entry
                    # would otherwise leak for the process lifetime
                    self._key_locks.pop(key, None)
                raise
            self._ctr["compiles"].inc()
            prog = _Program(key, fn, kind, trace_env)
            evicted_keys = []
            with self._cache_lock:
                self._cache[key] = prog
                while len(self._cache) > self._max_programs:
                    old_key, _ = self._cache.popitem(last=False)
                    self._key_locks.pop(old_key, None)
                    with self._est_lock:
                        self._estimates.pop(old_key, None)
                    evicted_keys.append(old_key)
            if evicted_keys:
                self._ctr["evictions"].inc(len(evicted_keys))
                for old_key in evicted_keys:
                    # The eviction line names the ledger row being
                    # dropped: operators correlating a recompile storm
                    # with /healthz can see WHAT left and how much HBM it
                    # was holding.
                    row = self.ledger.drop(old_key)
                    peak = row.peak_hbm_bytes if row is not None else None
                    logger.info(
                        "evicted program %s from the LRU cache "
                        "(peak HBM %s)", ledger_id(old_key),
                        f"{peak / 2**20:.1f} MiB" if peak else "unknown")
                self._refresh_cache_hbm()
            return prog

    def has_program(self, kind: str, h: int, w: int, iters: int,
                    b: int = 1) -> bool:
        """Whether this program is already compiled (no side effects) —
        the degrade policy refuses to route a deadline request onto a
        cold bucket whose compile would dwarf the budget."""
        key = self.cache_key(kind, h, w, iters, b=b)
        with self._cache_lock:
            prog = self._cache.get(key)
        return prog is not None and prog.warmed

    def _aot_compile(self, prog: _Program, args, params=None):
        """Lower + compile one program ahead of time and record its
        compiler-derived account (cost_analysis / memory_analysis) in the
        program ledger.  MUST run inside the caller's trace lock with the
        program's switch set exported (the lowering reads env at trace
        time).  Real compile failures propagate to the breaker exactly as
        they did from the first jit call; only AOT *API* skew
        (TypeError/AttributeError/NotImplementedError from the
        lower/compile plumbing itself) falls back to plain jit dispatch —
        the ledger row then carries no compiler numbers, which every
        downstream consumer treats as "absent", never as zero."""
        if prog.compiled is not None:
            return prog.compiled
        kind, b, h, w, iters = prog.key[:5]
        scale = SCAN_SCALE.get(kind)

        def record(analysis: Dict) -> None:
            self.ledger.record(
                prog.key, kind=kind, b=b, h=h, w=w, iters=iters,
                scan_scale=(iters if scale == "iters" else scale),
                analysis=analysis, backend=self._backend,
                device_kind=self._device_kind)

        try:
            compiled = prog.fn.lower(
                params if params is not None else self._params,
                *args).compile()
        except (TypeError, AttributeError, NotImplementedError) as e:
            logger.warning(
                "AOT compile unavailable for %s (%s: %s) — using jit "
                "dispatch; its ledger row has no compiler numbers",
                prog.ledger_id, type(e).__name__, e)
            record({})
            return prog.fn
        except Exception:
            # A REAL compile failure propagates to the breaker exactly as
            # before — but the _Program is already cached, and a rebuild
            # leaves it lingering in the LRU. Record an empty row first
            # so ledger completeness keeps reflecting the cache: a server
            # healthily degraded one rung down must not false-fail the
            # report gate over the rung that refused to compile.
            record({})
            raise
        prog.compiled = compiled
        record(analyze_compiled(compiled))
        return compiled

    def invoke(self, prog: _Program, *args,
               trace=NULL_TRACE) -> Tuple[np.ndarray, ...]:
        """Run a cached program, fetch results to host, apply fault hooks.

        The first invocation (which triggers the actual XLA compile under
        jit) holds the program's compile lock and the global trace lock
        with the program's OWN switch set exported, so concurrent first
        requests for one bucket compile once and trace-time env reads see
        the switches this program was keyed under (the breaker's overrides
        for serving programs; all-off for the canary reference).

        ``trace`` (a :class:`~raft_stereo_tpu.obs.tracing.RequestTrace`)
        gets one span per invocation, named by program kind — the
        sequential path's per-segment timeline. The batched scheduler
        passes no trace here; it fans the interval out to every row
        itself.
        """
        # Array outputs come back as host numpy (the fetch doubles as the
        # completion barrier); dict outputs (the segment carry) stay on
        # device — they only ever feed the next segment.
        def fetch(out):
            return tuple(o if isinstance(o, dict) else np.asarray(o)
                         for o in out)

        was_warm = prog.warmed
        t0 = self.clock.now()
        t_disp = t0
        # Supervision bracket: the invocation is registered for the
        # watchdog's whole device window (including the injected-hang
        # hook below, which models a hung device call parked INSIDE the
        # bracket).  Post-invocation bookkeeping (metrics, injected slow
        # forwards) happens after end() — a merely slow forward can
        # never read as a hang.
        token = self.watch.begin(prog.ledger_id, prog.kind,
                                 warming=not was_warm,
                                 est=self.estimate(prog.key))
        try:
            self.faults.on_invoke()
            params = self._params_for(prog)
            if prog.mesh is not None:
                # graftpod: mesh programs get their operands canonically
                # re-placed every call (leading-dim rows over the data
                # axis, the rest replicated) — the AOT executable requires
                # its exact input shardings, and the placement cost rides
                # the host_s side of the split (it happens before
                # t_disp), so device seconds stay the dispatch-to-fetch
                # wall interval — counted ONCE per invoke, never x chips.
                args = self._shard_args(prog, args)
            if not prog.warmed:
                with prog.lock:
                    with _TRACE_LOCK, _env_overrides(prog.env):
                        # AOT lower+compile (not jit dispatch): the same
                        # one compile the first jit call would pay, but
                        # the Compiled handle stays in hand so its
                        # cost/memory analyses feed the program ledger.
                        fn = self._aot_compile(prog, args, params)
                        raw = fn(params, *args)
                        t_disp = self.clock.now()
                        out = fetch(raw)
                    prog.warmed = True
                self._refresh_cache_hbm()
            else:
                raw = (prog.compiled if prog.compiled is not None
                       else prog.fn)(params, *args)
                t_disp = self.clock.now()
                out = fetch(raw)
        except Exception as e:
            if not hasattr(e, "_raft_phase"):
                setattr(e, "_raft_phase", "runtime_failure")
            raise
        finally:
            self.watch.end(token)
        ordinal = self.faults.on_forward()
        t_end = self.clock.now()  # includes any injected device time
        # ONE host/device split shared by the counters, the tick deck
        # and the per-tenant usage partition — using the same two floats
        # everywhere is what makes the deck/counter/usage reconciliation
        # an equality, not three nearly-equal measurements.
        host_s = max(0.0, t_disp - t0)
        device_s = max(0.0, t_end - t_disp)
        _, b_key, h_key, w_key = prog.key[:4]
        # Chips this invocation spanned — from the program's OWN key (a
        # quarantine between compile and invoke must not relabel it).
        chips = prog.mesh[1] if prog.mesh is not None else 1
        self.registry.counter(
            "raft_program_calls_total",
            "device-program invocations by kind", kind=prog.kind).inc()
        if was_warm:
            # The warming invocation's time includes the XLA compile
            # (minutes on TPU) — feeding it into the latency EMA would
            # make the degrade policy reject/halve requests for dozens of
            # calls after every cold bucket. Only steady-state runs count.
            self._record_time(prog.key, t_end - t0)
            # Device-vs-host split per program kind: dispatch up to the
            # async call's return is host work (python + jit call
            # overhead); from there to the completed host fetch is device
            # execution + transfer (the fetch IS the completion barrier).
            self.registry.counter(
                "raft_program_host_seconds_total",
                "host-side dispatch time by program kind",
                kind=prog.kind).inc(host_s)
            self.registry.counter(
                "raft_program_device_seconds_total",
                "device wait (dispatch-to-fetch) by program kind",
                kind=prog.kind).inc(device_s)
            # The MFU join's numerator: ledger flop/byte estimates
            # accumulated per kind, steady-state only (warmups are
            # excluded from device seconds, so they must be excluded here
            # too or the ratio lies). Scan-opaque rows (flops_est None,
            # e.g. "full") accumulate nothing — their MFU reports absent.
            row = self.ledger.row(prog.key)
            if row is not None and row.flops_est:
                self.registry.counter(
                    "raft_program_flops_total",
                    "ledger-estimated flops executed by program kind",
                    kind=prog.kind).inc(row.flops_est)
            if row is not None and row.bytes_est:
                self.registry.counter(
                    "raft_program_hbm_bytes_total",
                    "ledger-estimated HBM bytes moved by program kind",
                    kind=prog.kind).inc(row.bytes_est)
            # Per-tenant attribution (obs/usage.py): partition this
            # steady invocation's device seconds + ledger flops exactly
            # across the bound rider labels (scheduler-bound batch rows,
            # the sequential worker's one tenant, or "default" for a
            # direct session caller) — warmups excluded, matching the
            # device-seconds counter, so tenant sums reconcile with the
            # program totals.
            # The fallback routes through label() like every bound
            # path, so 'default' is registered in the first-come set
            # (tenants_tracked counts it) and shares the bound
            # discipline instead of bypassing it.
            labels = getattr(self._usage_tl, "labels", None) \
                or [self.usage.label(DEFAULT_TENANT)]
            self.usage.add_device(
                labels, device_s,
                flops=(row.flops_est if row is not None else None))
            tick_seq = self.deck.note_invocation(
                kind=prog.kind, program=prog.ledger_id, b=b_key,
                h=h_key, w=w_key, t0=t0, t1=t_end, host_s=host_s,
                device_s=device_s, warming=False, chips=chips)
            attrs = {"program": prog.ledger_id}
            if tick_seq is not None:
                # Standalone (sequential) deck row: the span links to it
                # the same way scheduler spans link to their tick seq.
                attrs["tick"] = tick_seq
            trace.add_span(prog.kind, t0, t_end, **attrs)
        else:
            self.registry.counter(
                "raft_program_warmup_seconds_total",
                "first-invocation (compile-inclusive) time by kind",
                kind=prog.kind).inc(max(0.0, t_end - t0))
            self.deck.note_invocation(
                kind=prog.kind, program=prog.ledger_id, b=b_key,
                h=h_key, w=w_key, t0=t0, t1=t_end, host_s=host_s,
                device_s=device_s, warming=True, chips=chips)
            trace.add_span(prog.kind, t0, t_end, warming=True,
                           program=prog.ledger_id)
        if self.faults.poisoned(ordinal):
            flow_i = {"full": 0, "segment": 1, "epilogue": 0}.get(prog.kind)
            if flow_i is not None:
                out = (out[:flow_i] + (poison_disparity(out[flow_i]),)
                       + out[flow_i + 1:])
        return out

    # -- latency estimates (EMA per program) ------------------------------

    def _record_time(self, key: Tuple, dt: float) -> None:
        with self._est_lock:
            prev = self._estimates.get(key)
            self._estimates[key] = dt if prev is None else (
                0.7 * prev + 0.3 * dt)

    def estimate(self, key: Tuple) -> Optional[float]:
        with self._est_lock:
            return self._estimates.get(key)

    # -- serving ----------------------------------------------------------

    def infer(self, left, right, *, deadline: Optional[float] = None,
              budget_s: Optional[float] = None,
              allow_half_res: Optional[bool] = None,
              prevalidated: bool = False,
              trace=NULL_TRACE) -> InferenceResult:
        """Serve one stereo pair.

        ``deadline`` is absolute on the session clock; ``budget_s`` is
        relative sugar. With neither, the full ``valid_iters`` single-scan
        program runs. With a deadline, the refinement runs in segments and
        the degrade policy may return a reduced-iteration or half-res
        field (quality-labeled). Raises :class:`~raft_stereo_tpu.serve.
        validate.InputRejected`, :class:`DeadlineExceeded` or
        :class:`InferenceFailed`; any disparity returned is finite.
        """
        try:
            return self._infer(left, right, deadline=deadline,
                               budget_s=budget_s,
                               allow_half_res=allow_half_res,
                               prevalidated=prevalidated, trace=trace)
        except Exception:
            self._ctr["requests_failed"].inc()
            raise

    def _infer(self, left, right, *, deadline: Optional[float],
               budget_s: Optional[float],
               allow_half_res: Optional[bool],
               prevalidated: bool = False,
               trace=NULL_TRACE) -> InferenceResult:
        from raft_stereo_tpu.serve import degrade

        t_start = self.clock.now()
        if deadline is None and budget_s is not None:
            deadline = t_start + budget_s
        if not prevalidated:
            # ``prevalidated`` lets the service layer (which validates at
            # admission, before queueing) skip the second O(N) finite scan
            # + float32 copies; the arrays must then already be the
            # canonical (1, H, W, 3) float32 form validate_pair returns.
            left, right = validate_pair(left, right, self.cfg.admission)
        if deadline is not None and t_start >= deadline:
            raise DeadlineExceeded("deadline already expired on arrival")
        orig_h, orig_w = left.shape[1], left.shape[2]
        padder = self.padder_for(left.shape)
        half = (self.cfg.allow_half_res
                if allow_half_res is None else allow_half_res)

        last_exc: Optional[Exception] = None
        for _ in range(len(self.breaker.ladder) + 1):
            try:
                if deadline is None:
                    flow = self._run_full(padder, left, right, trace=trace)
                    out = degrade.Outcome(flow, "full", self.cfg.valid_iters,
                                          False)
                else:
                    out = degrade.run_with_deadline(
                        self, padder, left, right, deadline,
                        allow_half_res=half, trace=trace)
                break
            except Exception as e:  # noqa: BLE001 — filtered just below
                if isinstance(e, SessionError) or not is_kernel_failure(e):
                    raise
                last_exc = e
                self._breaker_retry(
                    e, getattr(e, "_raft_phase", "runtime_failure"),
                    traces=(trace,))
                padder = self.padder_for(left.shape)  # unchanged, explicit
                continue
        else:
            raise InferenceFailed(
                "ladder_exhausted",
                f"breaker retries exhausted: {last_exc}") from last_exc

        with trace.span("unpad"):
            disparity = self._finish(out.flow_padded, padder, out.quality,
                                     orig_h, orig_w)
        elapsed = self.clock.now() - t_start
        self._ctr["requests_ok"].inc()
        if out.quality != "full":
            self._ctr["degraded"].inc()
        return InferenceResult(
            disparity=disparity, quality=out.quality, iters=out.iters,
            elapsed_s=elapsed, padded_shape=padder.padded_shape,
            deadline_missed=out.deadline_missed)

    def _run_full(self, padder: InputPadder, left: np.ndarray,
                  right: np.ndarray, iters: Optional[int] = None,
                  cfg=None, env=None, trace=NULL_TRACE) -> np.ndarray:
        """Single-scan forward on the padded bucket; returns padded flow."""
        iters = iters if iters is not None else self.cfg.valid_iters
        lp, rp = padder.pad_np(left, right)
        ph, pw = padder.padded_shape
        prog = self.get_program("full", ph, pw, iters, cfg, env)
        flow_up, _checksum = self.invoke(prog, lp, rp, trace=trace)
        return flow_up

    def _finish(self, flow_padded: np.ndarray, padder: InputPadder,
                quality: str, orig_h: int, orig_w: int) -> np.ndarray:
        """Unpad, validate, convert to positive disparity."""
        if quality == "half_res":
            # degrade.py already restored full resolution and unpadded.
            flow = flow_padded
        else:
            flow = padder.unpad_np(flow_padded)
        flow = flow[0, ..., 0]
        if flow.shape != (orig_h, orig_w):
            raise InferenceFailed(
                "internal", f"output shape {flow.shape} != input "
                f"({orig_h}, {orig_w})")
        if not np.isfinite(flow).all():
            self._ctr["nonfinite_outputs"].inc()
            raise InferenceFailed(
                "nonfinite_output",
                "disparity contains NaN/Inf — refusing to serve it")
        return -flow

    # -- warmup / canary --------------------------------------------------

    def _warm_shape(self, h: int, w: int) -> None:
        """Compile (and once-run, on zeros) the programs for one bucket,
        walking the breaker ladder on failure instead of dying."""
        padder = self.padder_for((h, w, 3))
        zeros = np.zeros((1, h, w, 3), np.float32)
        for _ in range(len(self.breaker.ladder) + 1):
            try:
                self._run_full(padder, zeros, zeros)
                if self.cfg.warmup_segmented and self.cfg.max_batch == 1:
                    # Sequential-only: the batched scheduler never runs
                    # the b=1 "segment" program nor the half-res degrade
                    # route, so warming them with max_batch > 1 would be
                    # minutes of dead compiles per shape (_warm_batched
                    # below covers every program the scheduler uses).
                    from raft_stereo_tpu.serve import degrade
                    degrade.warm_segmented(self, padder, zeros)
                    # The sequential streaming path (serve/stream.py
                    # stream_infer) runs b=1 prepare_warm/advance/
                    # epilogue — warm them too, or the first stream
                    # frame of a deadline-serving deployment pays up to
                    # three XLA compiles mid-request (the same contract
                    # _warm_batched honors for the scheduler).
                    self._warm_stream_sequential(padder, zeros)
                if self.cfg.max_batch > 1:
                    self._warm_batched(padder, zeros)
                return
            except Exception as e:  # noqa: BLE001 — filtered just below
                if not is_kernel_failure(e):
                    raise
                self._breaker_retry(
                    e, getattr(e, "_raft_phase", "runtime_failure"))
        raise InferenceFailed("ladder_exhausted",
                              f"warmup for bucket {h}x{w} never succeeded")

    def _warm_stream_sequential(self, padder: InputPadder,
                                zeros: np.ndarray) -> None:
        """Compile (and once-run) the b=1 streaming programs for one
        shape bucket — prepare_warm, advance, epilogue — the set
        :func:`raft_stereo_tpu.serve.stream.stream_infer` drives in
        sequential mode.  (The cold ``prepare`` is already warm from
        ``degrade.warm_segmented``.)"""
        import jax.numpy as jnp
        m = self.cfg.valid_iters // self.cfg.segments
        ph, pw = padder.padded_shape
        lp, rp = padder.pad_np(zeros, zeros)
        factor = self._run_cfg.downsample_factor
        warm = self.get_program("prepare_warm", ph, pw, 0)
        fz = jnp.zeros((1, ph // factor, pw // factor, 1), jnp.float32)
        (state,) = self.invoke(warm, lp, rp, fz)
        adv = self.get_program("advance", ph, pw, m)
        state, _, _ = self.invoke(adv, state)
        epi = self.get_program("epilogue", ph, pw, 0)
        self.invoke(epi, state)

    def _warm_batched(self, padder: InputPadder, zeros: np.ndarray) -> None:
        """Compile (and once-run) the continuous-batching programs for one
        shape bucket at every batch bucket — prepare, advance, epilogue —
        so the scheduler's first ticks don't pay compiles. The warming
        invocations are excluded from the latency EMAs per (program, batch
        bucket), exactly like the sequential warmups."""
        import jax.numpy as jnp
        m = self.cfg.valid_iters // self.cfg.segments
        ph, pw = padder.padded_shape
        lp, rp = padder.pad_np(zeros, zeros)
        factor = self._run_cfg.downsample_factor
        for b in self._batch_buckets:
            lb = jnp.concatenate([jnp.asarray(lp)] * b, axis=0)
            rb = jnp.concatenate([jnp.asarray(rp)] * b, axis=0)
            prep = self.get_program("prepare", ph, pw, 0, b=b)
            (state,) = self.invoke(prep, lb, rb)
            # The streaming warm-start entry (serve/stream.py) — its own
            # program kind, so it gets its own warmup: the first warm
            # join of a stream must not pay a compile mid-stream.
            warm = self.get_program("prepare_warm", ph, pw, 0, b=b)
            fz = jnp.zeros((b, ph // factor, pw // factor, 1),
                           jnp.float32)
            self.invoke(warm, lb, rb, fz)
            adv = self.get_program("advance", ph, pw, m, b=b)
            state, _, _ = self.invoke(adv, state)
            epi = self.get_program("epilogue", ph, pw, 0, b=b)
            self.invoke(epi, state)

    def _run_canary(self) -> None:
        """One bucketed forward, fast path vs plain XLA, within the pinned
        drift band. A mismatch is a silently-wrong kernel: trip a rung,
        rebuild, re-check — by the bottom rung fast == reference and the
        canary passes trivially."""
        h, w = self.cfg.canary_shape
        padder = self.padder_for((h, w, 3))
        rng = np.random.default_rng(1234)
        left = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
        right = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
        iters = self.cfg.canary_iters
        self._canary_state["ran"] = True
        for _ in range(len(self.breaker.ladder) + 1):
            self._canary_state["attempts"] += 1
            try:
                fast = self._run_full(padder, left, right, iters=iters)
                ref_cfg, ref_env = self.breaker.plain_xla_cfg(self._base_cfg)
                if (self._fingerprint() ==
                        self._fingerprint(ref_cfg, ref_env)):
                    # Already at plain XLA — the reference program IS the
                    # serving program.
                    ok = bool(np.isfinite(fast).all())
                else:
                    ref = self._run_full(padder, left, right, iters=iters,
                                         cfg=ref_cfg, env=ref_env)
                    ok = (np.isfinite(fast).all() and np.isfinite(ref).all()
                          and np.allclose(fast, ref, rtol=CANARY_RTOL,
                                          atol=CANARY_ATOL))
            except Exception as e:  # noqa: BLE001 — filtered just below
                if not is_kernel_failure(e):
                    raise
                self._breaker_retry(
                    e, getattr(e, "_raft_phase", "runtime_failure"))
                continue
            if ok:
                self._canary_state["passed"] = True
                return
            path = self.breaker.classify(
                RuntimeError("canary parity mismatch"))
            if path is None:
                self._canary_state["passed"] = False
                raise InferenceFailed(
                    "canary_failed",
                    "parity canary failing at plain XLA (non-finite "
                    "reference output)")
            self.breaker.trip(path.name, "canary_mismatch")
            self._rebuild(f"canary mismatch -> tripped {path.name}")
        self._canary_state["passed"] = False
        raise InferenceFailed("canary_failed", "canary never converged")

    # -- device ledger / HBM accounting -----------------------------------

    def ledger_key_id(self, kind: str, h: int, w: int, iters: int,
                      b: int = 1) -> str:
        """Ledger display id of the program this (kind, geometry, batch)
        resolves to under the CURRENT run config — the scheduler stamps
        it on its fanned spans so flight records can join a request's
        timeline to the exact ledger rows it rode."""
        return ledger_id(self.cache_key(kind, h, w, iters, b=b))

    def _cache_hbm_parts(self) -> Tuple[Dict[str, float], float, int]:
        """(by_bucket, total, unknown_rows): summed ledger peak-HBM of
        the currently cached programs per shape bucket. Programs whose
        backend reported no memory stats count as ``unknown_rows`` and
        contribute nothing — absence is visible, never a fabricated 0."""
        with self._cache_lock:
            progs = list(self._cache.values())
        by_bucket: Dict[str, float] = {}
        total, unknown = 0.0, 0
        for prog in progs:
            row = self.ledger.row(prog.key)
            peak = row.peak_hbm_bytes if row is not None else None
            if peak is None:
                unknown += 1
                continue
            bucket = f"{prog.key[2]}x{prog.key[3]}"
            by_bucket[bucket] = by_bucket.get(bucket, 0.0) + peak
            total += peak
        return by_bucket, total, unknown

    def cache_hbm(self) -> Dict:
        """The /healthz cache-HBM document: will the warm set fit one
        chip (ROADMAP item 1's precondition before multiplying by N)."""
        by_bucket, total, unknown = self._cache_hbm_parts()
        return {"by_bucket": by_bucket, "total_bytes": total,
                "unknown_rows": unknown,
                "hbm_capacity_bytes": hbm_capacity(self._device_kind)}

    def _refresh_cache_hbm(self) -> None:
        """Publish the per-bucket cache-HBM gauges after a warm or an
        eviction; a bucket whose programs all evicted reads 0, never a
        stale sum."""
        by_bucket, total, _ = self._cache_hbm_parts()
        with self._hbm_lock:
            stale = self._hbm_buckets - set(by_bucket)
            self._hbm_buckets = set(by_bucket)
        for bucket in stale:
            self.registry.gauge(
                "raft_cache_hbm_bytes",
                "summed peak HBM of cached programs by shape bucket",
                bucket=bucket).set(0.0)
        for bucket, v in by_bucket.items():
            self.registry.gauge(
                "raft_cache_hbm_bytes",
                "summed peak HBM of cached programs by shape bucket",
                bucket=bucket).set(v)
        self.registry.gauge(
            "raft_cache_hbm_total_bytes",
            "summed peak HBM of every cached program").set(total)

    def attribution(self, peaks=None) -> Dict:
        """Per-program-kind MFU/roofline (the ledger ⋈ registry join) and
        publish the non-absent MFUs as gauges. ``peaks`` overrides the
        chip table (tests inject synthetic peaks on CPU)."""
        doc = self.ledger.attribution(self.registry,
                                      device_kind=self._device_kind,
                                      peaks=peaks)
        for kind, a in doc.items():
            if a["mfu"] is not None:
                self.registry.gauge(
                    "raft_program_mfu",
                    "model flops utilization by program kind "
                    "(ledger flops / device seconds / chip peak)",
                    kind=kind).set(a["mfu"])
        return doc

    def ledger_doc(self) -> Dict:
        """The dumpable device-ledger artifact (``obs.ledger report``):
        rows + cache completeness + attribution + cache-HBM accounting."""
        with self._cache_lock:
            keys = list(self._cache)
        return self.ledger.to_doc(
            cache_keys=keys, backend=self._backend,
            device_kind=self._device_kind,
            attribution=self.attribution(), cache_hbm=self.cache_hbm())

    # -- capacity & saturation model (obs/capacity.py) ---------------------

    def capacity_status(self) -> Dict:
        """The /healthz ``capacity`` block: per-bucket theoretical
        requests/s from the warmed EMA cost table, live device
        saturation from the tick deck, and the headroom gauges
        published as a side effect (``raft_capacity_headroom{bucket=}``
        = theoretical rps x (1 - saturation);
        ``raft_capacity_saturation``)."""
        from raft_stereo_tpu.obs import capacity as cap
        with self._est_lock:
            ests = dict(self._estimates)
        # Only rows keyed under the CURRENT run fingerprint feed the
        # model: after a breaker trip the old rung's EMA entries linger
        # until eviction, and capacity must describe the programs that
        # would actually serve — not whichever stale row dict order
        # happens to surface last.  Same for iteration counts: only the
        # canonical per-kind iters (the serving paths' own values) are
        # modeled, so e.g. a short-iters canary "full" program cannot
        # overwrite the serving "full" estimate.
        fp = self._fingerprint()
        m_iters = self.cfg.valid_iters // self.cfg.segments
        kind_iters = {"full": self.cfg.valid_iters, "prepare": 0,
                      "prepare_warm": 0, "segment": m_iters,
                      "advance": m_iters, "epilogue": 0}
        rows = [{"kind": k[0], "b": k[1], "h": k[2], "w": k[3],
                 "iters": k[4], "est": v} for k, v in ests.items()
                if k[5] == fp and kind_iters.get(k[0]) == k[4]]
        doc = cap.model(rows, segments=self.cfg.segments,
                        valid_iters=self.cfg.valid_iters)
        sat = cap.saturation(self.deck.snapshot(),
                             now=self.clock.now(),
                             window_s=self._capacity_window_s)
        doc["saturation"] = sat
        ratio = sat["ratio"] if sat is not None else None
        if ratio is not None:
            self.registry.gauge(
                "raft_capacity_saturation",
                "device-busy fraction over the sliding capacity window "
                "(1.0 = the device never idled)").set(ratio)
        for bucket, m in doc["by_bucket"].items():
            if m.get("rps") is None:
                continue
            headroom = m["rps"] * max(0.0, 1.0 - (ratio or 0.0))
            m["headroom_rps"] = headroom
            self.registry.gauge(
                "raft_capacity_headroom",
                "estimated remaining requests/s by shape bucket "
                "(theoretical rps x (1 - saturation))",
                bucket=bucket).set(headroom)
        if self._mesh_base_n > 1:
            # graftpod: the admission plane goes per-chip.  A mesh
            # invocation's device window covers all its chips at once, so
            # each chip's busy fraction counts the windows whose chip span
            # included it; occupancy and headroom divide by the chip count
            # (rows shard evenly by construction, pads excluded).
            mesh = self.mesh_status()
            per_chip = cap.saturation_per_chip(
                self.deck.snapshot(), len(self._mesh_devices),
                now=self.clock.now(), window_s=self._capacity_window_s)
            best = max((m.get("headroom_rps") or 0.0
                        for m in doc["by_bucket"].values()), default=None)
            for row in per_chip:
                chip = row["chip"]
                row["quarantined"] = chip in self._quarantined
                # graftheal: distinguish a chip in probation (eligible
                # for re-admission on its backoff clock) from one the
                # flap cap retired for good.
                st = self._chip_heal.get(chip)
                if row["quarantined"] and st is not None:
                    row["permanent"] = st["permanent"]
                row["headroom_rps"] = (
                    0.0 if row["quarantined"] else
                    None if best is None else best / max(1, self.mesh_chips))
                self.registry.gauge(
                    "raft_capacity_chip_saturation",
                    "device-busy fraction over the capacity window, "
                    "per mesh chip", chip=str(chip)).set(
                        row["ratio"] if row["ratio"] is not None else 0.0)
            doc["chips"] = {"n_data": mesh["n_data"],
                            "base_n_data": mesh["base_n_data"],
                            "quarantined": mesh["quarantined"],
                            "per_chip": per_chip}
        return doc

    # -- debug introspection (GET /debug/config) ---------------------------

    def config_doc(self) -> Dict:
        """The session half of /debug/config: resolved knob snapshot,
        fingerprint, breaker trips, batch-bucket ladder, program-cache
        contents.  Read-only and bounded (the cache is LRU-bounded, the
        env snapshot is the registry key set)."""
        with self._cache_lock:
            programs = [{"id": p.ledger_id, "warmed": p.warmed,
                         "aot": p.compiled is not None}
                        for p in self._cache.values()]
        env = self._resolve(self._env)
        return {
            "fingerprint": self.fingerprint_id(),
            "backend": self._backend,
            "device_kind": self._device_kind,
            "session_cfg": dataclasses.asdict(self.cfg),
            "env_knobs": {k: env.get(k) for k in sorted(env)},
            "breaker": self.breaker.status(),
            "batch_buckets": list(self._batch_buckets),
            "max_programs": self._max_programs,
            "programs": programs,
            "mesh": self.mesh_status(),
            "deck": self.deck.status(),
            "capacity_window_s": self._capacity_window_s,
        }

    # -- reporting --------------------------------------------------------

    def count_request(self, ok: bool, degraded: bool = False,
                      nonfinite: bool = False) -> None:
        """Fold one externally-served request (the continuous-batching
        scheduler resolves its own responses) into the session counters,
        so /healthz sees one truth regardless of serving mode."""
        if ok:
            self._ctr["requests_ok"].inc()
            if degraded:
                self._ctr["degraded"].inc()
        else:
            self._ctr["requests_failed"].inc()
            if nonfinite:
                self._ctr["nonfinite_outputs"].inc()

    def metrics(self) -> Dict:
        """The legacy short-name counter dict — every value read straight
        off the registry (/healthz numbers ARE registry numbers)."""
        return {k: int(c.value) for k, c in self._ctr.items()}

    def status(self) -> Dict:
        with self._cache_lock:
            cached = [f"{k[0]}@b{k[1]}:{k[2]}x{k[3]}/it{k[4]}"
                      + (f"/mesh{k[6][1]}" if len(k) > 6 else "")
                      for k in self._cache]
        return {
            "bucket": self.cfg.bucket,
            "valid_iters": self.cfg.valid_iters,
            "segments": self.cfg.segments,
            "max_batch": self.cfg.max_batch,
            "batch_buckets": list(self._batch_buckets),
            "mesh": self.mesh_status(),
            "programs": {"cached": cached,
                         "capacity": self._max_programs,
                         **{k: v for k, v in self.metrics().items()
                            if k in ("compiles", "evictions")}},
            "breaker": self.breaker.status(),
            "canary": dict(self._canary_state),
            "counts": {k: v for k, v in self.metrics().items()
                       if k not in ("compiles", "evictions")},
            "profiler": self.profiler.status(),
            "tracing": self.tracer.status(),
            "ledger": {"rows": len(self.ledger),
                       "device_kind": self._device_kind,
                       "backend": self._backend,
                       "cache_hbm": self.cache_hbm(),
                       "attribution": self.attribution()},
            "flight": self.flight.status(),
            "deck": self.deck.status(),
            "usage": self.usage.status(),
        }

"""graftfleet — the fleet supervisor (DESIGN.md "Fleet operations
(r20)").

Fifteen PRs built a single process that survives almost anything: the
breaker ladder eats kernel failures, the PR 9 watchdog bounces hung
generations, the PR 13 stream table warm-starts video, the PR 14 cache
spills to disk and survives a restart.  But ONE process is still one
process: a ``kill -9`` stops serving, a config change means downtime,
and nothing routes on health.  This module is the assembly — a
supervisor that owns N ``serve_stereo`` instances as subprocesses and
turns them into an operable service:

- **launch & handshake** — each instance binds ``--http_port 0`` and
  prints the ``RAFT_HTTP_PORT=<n>`` readiness line after its warmup
  compiles finish; the supervisor reads it from the child's stdout (a
  dedicated reader thread per instance — the pipe is drained forever so
  a chatty child can never wedge on a full pipe);
- **health routing** — a probe loop GETs every instance's ``/healthz``;
  placement weight is the capacity block's summed ``headroom_rps``
  (theoretical rps x (1 - saturation), obs/capacity.py) and a saturated
  instance (ratio >= SATURATION_BACKPRESSURE) is skipped while any
  unsaturated peer exists.  ``X-Raft-Session`` stream affinity pins a
  session to one instance (the held 1/8-res seed lives in THAT
  process's stream table) and is handed off — eagerly re-pinned — the
  moment its instance drains or dies;
- **preemption-proof serving** — a dead process (``poll()``), a hung
  one (consecutive probe failures) or a sick one (scheduler heartbeat
  dead in its own health block — the PR 9 supervision surface) is
  removed from rotation; its in-flight forwards fail STRUCTURED (the
  proxy's bounded socket ops turn connection loss into a JSON 502/503,
  never a hung client socket) and a replacement is launched into the
  same slot with the same ``RAFT_CACHE_DIR``, so the PR 14 disk spill
  carries the warm exact-tier across the death;
- **zero-downtime rolling deploys** — ``deploy()`` bumps the
  generation, launches the new instance BESIDE the old one per slot,
  waits for the new warmup handshake, shifts routing (and hands off
  pinned sessions), then SIGTERM-drains the old under
  ``RAFT_DRAIN_GRACE_MS`` with a counted SIGKILL escalation when the
  grace expires;
- **bounded self-healing** — every launch retry and death replacement
  consumes one unit of the per-slot ``RAFT_FLEET_RESTART_BUDGET``
  (reset each generation); an exhausted slot is reported DEGRADED in
  ``/fleet/healthz`` instead of crash-looping the fleet.

Everything is host-side orchestration: no compiled program, fingerprint
or cache-key changes.  The fleet's own metrics live in a private
registry (``raft_fleet_{instances,restarts,reroutes,draining}_total``
...) rendered at ``GET /fleet/metrics``; ``GET /fleet/healthz`` is the
obs/fleet.py rollup of the instances' own documents plus the router's
books — the per-instance ledger of forwarded requests the chaos storm
reconciles against each instance's ``raft_requests_total``.

Knobs (read at function scope; registered in ``analysis/knobs.py``
``HOST_ENV_KNOBS`` — pure fleet topology, never in any fingerprint):

- ``RAFT_FLEET_INSTANCES``         — fleet width (default 2);
- ``RAFT_FLEET_RESTART_BUDGET``    — per-slot launch retries + death
  replacements per generation before the slot degrades (default 3);
- ``RAFT_FLEET_PROBE_MS``          — health-probe period (default
  500 ms; <= 0 disables the background prober — tests drive
  :meth:`FleetSupervisor.poke` deterministically);
- ``RAFT_FLEET_WARMUP_TIMEOUT_MS`` — readiness-handshake deadline per
  launch attempt (default 600 s — a cold TPU warmup is minutes);
- ``RAFT_HEAL`` / ``RAFT_HEAL_REFILL_MS`` (serve/heal.py) — the
  recovery plane: restart budgets REFILL on a decay clock (one charge
  refunded per refill interval), so a degraded slot re-enters probation
  — one budget-charged, probe-verified relaunch per refill — instead of
  staying dark until the next deploy.  ``RAFT_HEAL=0`` restores the
  one-way per-generation budget exactly.

Testability: :class:`FleetConfig.command` injects the instance argv —
tier-1 tests launch a stdlib stub that speaks the same handshake and
health schema in milliseconds; only the release gate
(``scratch/chaos_fleet.py``) pays for real ``serve_stereo.py``
children.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from raft_stereo_tpu.obs.fleet import rollup
from raft_stereo_tpu.obs.metrics import MetricsRegistry
from raft_stereo_tpu.serve.heal import (resolve_heal_enabled,
                                        resolve_heal_refill_ms)
from raft_stereo_tpu.serve.supervise import (_parse_number,
                                             resolve_drain_grace_ms)

logger = logging.getLogger(__name__)

DEFAULT_FLEET_INSTANCES = 2
DEFAULT_FLEET_RESTART_BUDGET = 3
DEFAULT_FLEET_PROBE_MS = 500.0
DEFAULT_FLEET_WARMUP_TIMEOUT_MS = 600_000.0

#: Consecutive /healthz probe failures before a live-but-unresponsive
#: process is declared hung and replaced (one blip — a GC pause, a probe
#: racing a bounce — must not cost a warm instance).
PROBE_FAIL_THRESHOLD = 3

#: Saturation ratio at which an instance stops taking NEW placements
#: while any less-saturated peer exists (backpressure, not ejection: a
#: busy instance is healthy, it is just full).
SATURATION_BACKPRESSURE = 0.98

#: Bound on the session-affinity table: LRU-evicted beyond this many
#: pinned sessions.  An evicted session is not broken — its next frame
#: re-pins (possibly elsewhere) and warm-joins there after one cold
#: frame; the bound exists because session ids are client-chosen bytes
#: (hostile-input discipline: no unbounded dict keyed by the wire).
AFFINITY_MAX = 4096

#: stdout lines kept per instance for the death report.
LINES_KEEP = 30


def resolve_fleet_instances(value: Optional[int] = None) -> int:
    """Fleet width: explicit config wins, else ``RAFT_FLEET_INSTANCES``,
    else 2.  Floor of 1 — a zero-instance fleet serves nothing and a
    misconfigured '0' should degrade to single-instance, not to outage."""
    if value is not None:
        return max(1, int(value))
    raw = os.environ.get("RAFT_FLEET_INSTANCES", "").strip()
    if not raw:
        return DEFAULT_FLEET_INSTANCES
    return max(1, _parse_number("RAFT_FLEET_INSTANCES", raw, int))


def resolve_fleet_restart_budget(value: Optional[int] = None) -> int:
    """Per-slot, per-generation launch/replacement budget: explicit
    config wins, else ``RAFT_FLEET_RESTART_BUDGET``, else 3."""
    if value is not None:
        return int(value)
    raw = os.environ.get("RAFT_FLEET_RESTART_BUDGET", "").strip()
    if not raw:
        return DEFAULT_FLEET_RESTART_BUDGET
    return _parse_number("RAFT_FLEET_RESTART_BUDGET", raw, int)


def resolve_fleet_probe_ms(value: Optional[float] = None) -> float:
    """Health-probe period in ms: explicit config wins, else
    ``RAFT_FLEET_PROBE_MS``, else 500.  <= 0 disables the background
    prober (deterministic tests drive ``poke()`` directly)."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_FLEET_PROBE_MS", "").strip()
    if not raw:
        return DEFAULT_FLEET_PROBE_MS
    return _parse_number("RAFT_FLEET_PROBE_MS", raw, float)


def resolve_fleet_warmup_timeout_ms(value: Optional[float] = None
                                    ) -> float:
    """Per-attempt readiness deadline in ms: explicit config wins, else
    ``RAFT_FLEET_WARMUP_TIMEOUT_MS``, else 600 s."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_FLEET_WARMUP_TIMEOUT_MS", "").strip()
    if not raw:
        return DEFAULT_FLEET_WARMUP_TIMEOUT_MS
    return _parse_number("RAFT_FLEET_WARMUP_TIMEOUT_MS", raw, float)


@dataclasses.dataclass(frozen=True)
class InstanceSpec:
    """What one launch attempt is asked to become."""
    slot: int
    generation: int
    args: Tuple[str, ...] = ()


def default_command(spec: InstanceSpec) -> List[str]:
    """The production argv: ``serve_stereo.py --http_port 0`` + the
    fleet's pass-through args.  Port 0 (kernel-assigned) is mandatory —
    N instances on one host cannot share a configured port, and the
    handshake line reports whatever was bound."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return [sys.executable, os.path.join(root, "serve_stereo.py"),
            "--http_port", "0", *spec.args]


@dataclasses.dataclass
class FleetConfig:
    """Fleet topology + per-instance launch recipe.

    ``None`` fields defer to their ``RAFT_FLEET_*`` knob at
    :class:`FleetSupervisor` construction (the resolve_* precedence:
    explicit config > env > default — same contract as supervise.py).
    """
    instances: Optional[int] = None
    restart_budget: Optional[int] = None
    probe_ms: Optional[float] = None
    warmup_timeout_ms: Optional[float] = None
    #: Old-generation / dead-instance drain grace; defers to the PR 9
    #: RAFT_DRAIN_GRACE_MS contract (supervise.resolve_drain_grace_ms).
    drain_grace_ms: Optional[float] = None
    #: Extra argv appended to every instance launch (model size, cache
    #: flags...).  Changing it via deploy() is the rolling-deploy input.
    instance_args: Tuple[str, ...] = ()
    #: Extra environment for instances (merged over os.environ).
    instance_env: Optional[Dict[str, str]] = None
    #: Shared RAFT_CACHE_DIR: set it and every instance (including
    #: replacements after a death) spills/restores the PR 14 exact tier
    #: from the same directory — the warm state that survives a kill -9.
    cache_dir: Optional[str] = None
    #: argv factory — tests inject a stub here.
    command: Callable[[InstanceSpec], List[str]] = default_command
    #: Per-forward socket deadline: the "never a hung client socket"
    #: bound.  Generous because a first-of-its-bucket request compiles
    #: inline on the instance.
    forward_timeout_s: float = 600.0
    #: Probe socket deadline (short: a healthy /healthz answers in ms).
    probe_timeout_s: float = 5.0
    #: Backoff base between launch retries (attempt k sleeps k * this).
    restart_backoff_s: float = 0.25
    #: Fleet ingress body cap (same hostile-input stance as http.py).
    body_max: int = 64 << 20
    #: graftheal: recovery-plane master switch for THIS supervisor
    #: (None -> RAFT_HEAL -> on).  Off = per-generation budgets are
    #: one-way, degraded slots stay dark until the next deploy.
    heal: Optional[bool] = None
    #: graftheal: restart-budget decay interval — one spent charge is
    #: refunded per interval on the fleet's monotonic clock
    #: (None -> RAFT_HEAL_REFILL_MS -> 60 s).  Tests inject tiny values
    #: here; the fleet has no FakeClock seam by design (its children
    #: are real processes on real time).
    restart_refill_ms: Optional[float] = None


class FleetInstance:
    """One owned subprocess: launch, handshake, probe, drain, books."""

    def __init__(self, spec: InstanceSpec, uid: str, argv: List[str],
                 env: Dict[str, str]):
        self.spec = spec
        self.uid = uid
        self.argv = argv
        self.env = env
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.state = "launching"  # -> ready -> draining -> dead
        self.ready = threading.Event()
        self.fail_streak = 0
        self.last_doc: Optional[Dict] = None
        self.routed = 0           # placement tie-break (least-routed)
        self.lines: deque = deque(maxlen=LINES_KEEP)
        self._reader: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def launch(self) -> None:
        self.proc = subprocess.Popen(
            self.argv, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=self.env,
            start_new_session=True)
        # Reads the child's stdout until the pipe dies with the
        # process: reap()/kill() end it by killing the child, and
        # joining a reader blocked on a live pipe would hang forever.
        # graftlint: disable=GC206 (reader ends when reap/kill closes the pipe)
        self._reader = threading.Thread(
            target=self._drain_stdout, name=f"fleet-stdout-{self.uid}",
            daemon=True)
        self._reader.start()

    def _drain_stdout(self) -> None:
        """Read the child's stdout FOREVER: the handshake line arms
        ``ready``; everything after is kept in a bounded ring for the
        death report.  Never returning the pipe to the kernel unread is
        the no-wedge invariant — a child that logs after ready must not
        block on a full pipe because its supervisor stopped listening."""
        assert self.proc is not None and self.proc.stdout is not None
        try:
            for line in self.proc.stdout:
                line = line.rstrip("\n")
                self.lines.append(line)
                if line.startswith("RAFT_HTTP_PORT="):
                    try:
                        self.port = int(line.split("=", 1)[1])
                    except ValueError:
                        continue
                    self.ready.set()
        except (OSError, ValueError):
            pass  # pipe died with the process — poll() is the truth

    def wait_ready(self, timeout_s: float) -> bool:
        """Await the handshake; False on timeout OR child death (the
        died-during-warmup satellite case — poll() breaks the wait early
        so a crash costs one poll interval, not the full warmup grace)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            # deploy() reaches this wait while holding _deploy_lock on
            # purpose: warmup is part of the one-rollout-at-a-time
            # critical section, and _deploy_lock is never taken on the
            # serving path.
            # graftlint: disable=GC203 (warmup wait inside the one-deploy-at-a-time mutex)
            if self.ready.wait(timeout=0.05):
                self.state = "ready"
                return True
            if self.proc is not None and self.proc.poll() is not None:
                self.state = "dead"
                return False
        return False

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def endpoint(self) -> Optional[str]:
        if self.port is None:
            return None
        return f"127.0.0.1:{self.port}"

    # -- health ------------------------------------------------------------

    def probe(self, timeout_s: float) -> Tuple[bool, Optional[str]]:
        """One /healthz GET.  Returns (healthy, reason-if-not); stores
        the document (the routing weight + rollup input) on success.  A
        200 whose own supervision block says the scheduler heartbeat
        died is UNHEALTHY — the PR 9 watchdog surface is part of the
        fleet's liveness truth, not just socket reachability."""
        if not self.alive:
            return False, "process dead"
        if self.port is None:
            return False, "no handshake"
        import http.client
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", self.port, timeout=timeout_s)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                body = resp.read()
            finally:
                conn.close()
        except OSError as e:
            return False, f"probe failed: {e}"
        if resp.status != 200:
            return False, f"healthz status {resp.status}"
        try:
            doc = json.loads(body)
        except ValueError:
            return False, "healthz not json"
        self.last_doc = doc
        hb = (doc.get("supervision") or {}).get("heartbeats") or {}
        if hb.get("scheduler_alive") is False or hb.get(
                "scheduler_died"):
            return False, "scheduler heartbeat dead"
        return True, None

    def weight(self) -> Optional[float]:
        """Placement weight: summed per-bucket ``headroom_rps`` from the
        last health document (None until capacity EMAs warm — the router
        treats unknown as average, not as zero, so a fresh instance is
        not starved out of ever warming)."""
        doc = self.last_doc or {}
        buckets = ((doc.get("capacity") or {}).get("by_bucket") or {})
        total, seen = 0.0, False
        for m in buckets.values():
            h = m.get("headroom_rps") if isinstance(m, dict) else None
            if h is not None:
                total += float(h)
                seen = True
        return total if seen else None

    def saturation(self) -> Optional[float]:
        doc = self.last_doc or {}
        sat = (doc.get("capacity") or {}).get("saturation") or {}
        return sat.get("ratio")

    def chips(self) -> Optional[int]:
        """graftpod: this instance's live data-mesh width from its last
        health document (None = single-device or never probed).  The
        per-bucket headroom the router weighs by already reflects the
        whole mesh's throughput — this accessor exists so the fleet
        rollup and /fleet/healthz advertise N-chip capacity per slot."""
        doc = self.last_doc or {}
        chips = (doc.get("capacity") or {}).get("chips") or {}
        n = chips.get("n_data")
        return int(n) if n is not None else None

    # -- teardown ----------------------------------------------------------

    def begin_drain(self) -> None:
        self.state = "draining"
        if self.alive:
            try:
                self.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass

    def reap(self, grace_s: float) -> bool:
        """Wait out the drain grace; SIGKILL on overrun.  Returns True
        when the child exited within grace (clean drain)."""
        if self.proc is None:
            self.state = "dead"
            return True
        try:
            self.proc.wait(timeout=max(0.0, grace_s))
            clean = True
        except subprocess.TimeoutExpired:
            clean = False
            self.kill()
        self.state = "dead"
        return clean

    def kill(self) -> None:
        self.state = "dead"
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass
            try:
                # Reaping a killed child under _deploy_lock is the
                # rollout's own cleanup; the serving path never waits on
                # this lock.
                # graftlint: disable=GC203 (bounded reap inside the deploy mutex)
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass


def _structured(status: int, code: str, message: str,
                retry_after_s: Optional[float] = None) -> Tuple[
                    int, str, bytes, Dict[str, str]]:
    """A fleet-originated response in the wire error schema (same
    status/code/message JSON the instance ingress sends) — the client
    cannot tell proxy-level failures from instance-level ones by shape,
    only by code."""
    body = json.dumps({"status": "rejected" if status == 503 else "error",
                       "code": code, "message": message}).encode()
    headers = {}
    if retry_after_s is not None:
        headers["Retry-After"] = str(int(retry_after_s))
    return status, "application/json", body, headers


class FleetSupervisor:
    """Owns the instances, the routing table and the books."""

    def __init__(self, cfg: Optional[FleetConfig] = None):
        self.cfg = cfg or FleetConfig()
        self.n = resolve_fleet_instances(self.cfg.instances)
        self.restart_budget = resolve_fleet_restart_budget(
            self.cfg.restart_budget)
        self.probe_s = resolve_fleet_probe_ms(self.cfg.probe_ms) / 1e3
        self.warmup_timeout_s = resolve_fleet_warmup_timeout_ms(
            self.cfg.warmup_timeout_ms) / 1e3
        self.drain_grace_s = resolve_drain_grace_ms(
            self.cfg.drain_grace_ms) / 1e3
        self.registry = MetricsRegistry()
        self._c_instances = self.registry.counter(
            "raft_fleet_instances_total", "instance launches (every "
            "attempt, including warmup retries and replacements)")
        self._c_restarts = self.registry.counter(
            "raft_fleet_restarts_total",
            "replacement launches after an instance died or failed "
            "warmup (first launches are not restarts)")
        self._c_reroutes = self.registry.counter(
            "raft_fleet_reroutes_total",
            "requests and pinned sessions moved off a dead/draining "
            "instance")
        self._c_draining = self.registry.counter(
            "raft_fleet_draining_total", "instances SIGTERM-drained")
        self._c_kills = self.registry.counter(
            "raft_fleet_kill_escalations_total",
            "drains that exceeded the grace and were SIGKILLed")
        self._c_heal_relaunch = self.registry.counter(
            "raft_heal_slot_relaunches_total",
            "degraded-slot probation relaunches after a restart-budget "
            "refill (graftheal)")
        self._g_generation = self.registry.gauge(
            "raft_fleet_generation", "current deploy generation")
        self._g_ready = self.registry.gauge(
            "raft_fleet_ready", "instances currently in rotation")
        self._lock = threading.RLock()
        self._slots: List[Optional[FleetInstance]] = [None] * self.n
        self._retired: List[FleetInstance] = []
        self._affinity: "OrderedDict[str, str]" = OrderedDict()
        self._books: Dict[str, Dict] = {}
        self._spent: Dict[int, int] = {}   # slot -> budget used this gen
        # graftheal: restart-budget decay.  _refill_last[slot] is the
        # monotonic instant up to which refunds were accounted — armed
        # at a slot's first charge, advanced in whole refill intervals.
        self.heal_enabled = resolve_heal_enabled(self.cfg.heal)
        self.refill_s = resolve_heal_refill_ms(
            self.cfg.restart_refill_ms) / 1e3
        self._refill_last: Dict[int, float] = {}
        self._generation = 0
        self._uid_seq = 0
        self._args = tuple(self.cfg.instance_args)
        self._env = dict(self.cfg.instance_env or {})
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None
        self._deploy_lock = threading.Lock()
        self._started = time.monotonic()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetSupervisor":
        with self._lock:
            self._generation = 1
        self._g_generation.set(1.0)
        for slot in range(self.n):
            inst = self._launch_slot(slot, self._generation)
            with self._lock:
                self._slots[slot] = inst
        self._publish_ready()
        if self.probe_s > 0:
            self._prober = threading.Thread(
                target=self._probe_loop, name="fleet-prober", daemon=True)
            self._prober.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=10)
        with self._lock:
            insts = [i for i in self._slots if i is not None]
            self._slots = [None] * self.n
        for inst in insts:
            inst.begin_drain()
            self._c_draining.inc()
        for inst in insts:
            if not inst.reap(self.drain_grace_s):
                self._c_kills.inc()
        with self._lock:
            retired, self._retired = self._retired, []
        for inst in retired:
            inst.kill()
        self._publish_ready()

    def __enter__(self) -> "FleetSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- launch ------------------------------------------------------------

    def _instance_env(self) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self._env)
        if self.cfg.cache_dir is not None:
            env["RAFT_CACHE_DIR"] = self.cfg.cache_dir
        return env

    def _effective_spent_locked(self, slot: int) -> int:
        """The slot's spent budget AFTER decay refunds (graftheal).
        Caller holds ``self._lock``.  With healing off (or a
        non-positive refill) this is exactly the raw per-generation
        counter — the one-way PR 16 semantics.  Refunds are accounted
        in whole refill intervals on the fleet's monotonic clock and
        folded back into ``_spent``, so every reader (charging,
        relaunch eligibility, /fleet/healthz) sees one truth."""
        with self._lock:  # re-entrant: callers already hold it
            spent = self._spent.get(slot, 0)
            if not self.heal_enabled or self.refill_s <= 0:
                return spent
            last = self._refill_last.get(slot)
            if last is None:
                return spent
            now = time.monotonic()
            refunds = int((now - last) / self.refill_s)
            if refunds > 0:
                self._refill_last[slot] = last + refunds * self.refill_s
                if spent > 0:
                    spent = max(0, spent - refunds)
                    self._spent[slot] = spent
            return spent

    def _launch_slot(self, slot: int, generation: int,
                     replacement: bool = False
                     ) -> Optional[FleetInstance]:
        """Launch one slot to readiness under the slot's remaining
        budget.  Every warmup retry — and, with ``replacement=True``,
        the relaunch after an in-service death — consumes one unit of
        the slot's per-generation budget and counts a restart; an
        exhausted budget returns None (the DEGRADED slot — the fleet
        serves on, smaller) instead of crash-looping."""
        spec = InstanceSpec(slot=slot, generation=generation,
                            args=self._args)
        first = True
        while not self._stop.is_set():
            spent = 0
            if not first or replacement:
                with self._lock:
                    spent = self._effective_spent_locked(slot)
                    if spent < self.restart_budget:
                        self._spent[slot] = spent + 1
                        # Arm the decay clock at the first live charge.
                        self._refill_last.setdefault(
                            slot, time.monotonic())
                if spent >= self.restart_budget:
                    logger.warning(
                        "fleet slot %d: restart budget (%d) exhausted in "
                        "generation %d — slot degraded", slot,
                        self.restart_budget, generation)
                    return None
                self._c_restarts.inc()
            if not first:
                # Linear backoff, attempt-scaled: enough to let a
                # transient (port exhaustion, OOM reclaim) clear, short
                # enough that tests with a ~0 base stay fast.
                # deploy() holds _deploy_lock across the whole rollout
                # BY DESIGN — one deploy at a time; backoff inside it
                # only delays that deploy, and the serving plane's
                # _lock is NOT held across this sleep.
                # graftlint: disable=GC203 (backoff under the one-deploy-at-a-time mutex only)
                time.sleep(self.cfg.restart_backoff_s * (spent + 1))
            first = False
            with self._lock:
                self._uid_seq += 1
                uid = f"i{slot}-g{generation}-{self._uid_seq}"
            inst = FleetInstance(spec, uid, list(self.cfg.command(spec)),
                                 self._instance_env())
            try:
                inst.launch()
            except OSError as e:
                logger.warning("fleet slot %d: launch failed: %s",
                               slot, e)
                continue
            self._c_instances.inc()
            with self._lock:
                self._books[uid] = {"sent": 0, "answered": 0,
                                    "undelivered": 0, "by_status": {}}
            if inst.wait_ready(self.warmup_timeout_s):
                logger.info("fleet slot %d: %s ready on port %s",
                            slot, uid, inst.port)
                return inst
            # Died during warmup or never handshook within the grace:
            # make sure it is gone, then retry under the budget.
            inst.kill()
            logger.warning(
                "fleet slot %d: %s failed warmup (%s); last output: %s",
                slot, uid,
                "died" if not inst.alive else "handshake timeout",
                list(inst.lines)[-3:])
        return None

    # -- probing / self-healing --------------------------------------------

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_s):
            try:
                self.poke()
            except Exception:
                logger.exception("fleet probe pass failed")

    def poke(self) -> None:
        """One synchronous probe pass over every slot — the prober
        thread's body, exposed so tests (and the chaos storm) can drive
        detection deterministically."""
        with self._lock:
            live = [(slot, inst) for slot, inst in enumerate(self._slots)
                    if inst is not None]
        for slot, inst in live:
            if inst.state != "ready":
                continue
            healthy, reason = inst.probe(self.cfg.probe_timeout_s)
            if healthy:
                inst.fail_streak = 0
                continue
            inst.fail_streak += 1
            process_gone = not inst.alive
            if not process_gone and \
                    inst.fail_streak < PROBE_FAIL_THRESHOLD and \
                    reason != "scheduler heartbeat dead":
                continue
            logger.warning("fleet slot %d: %s unhealthy (%s, streak "
                           "%d) — replacing", slot, inst.uid, reason,
                           inst.fail_streak)
            inst.kill()
            self._unpin_all(inst.uid)
            replacement = self._launch_slot(slot, self._generation,
                                            replacement=True)
            with self._lock:
                if self._slots[slot] is inst:
                    self._slots[slot] = replacement
            self._publish_ready()
        # graftheal: degraded-slot probation.  A slot that exhausted its
        # budget went dark (None); once the decay clock has refunded a
        # charge, it gets ONE budget-charged, handshake-verified
        # relaunch — naturally paced at one attempt per refill interval
        # because the attempt re-spends the refunded charge.  The
        # silent pre-check keeps an exhausted slot from logging a
        # budget warning on every probe pass.
        if self.heal_enabled and not self._stop.is_set():
            with self._lock:
                gen = self._generation
                degraded = [
                    slot for slot, inst in enumerate(self._slots)
                    if inst is None
                    and self._effective_spent_locked(slot)
                    < self.restart_budget]
            for slot in degraded:
                inst = self._launch_slot(slot, gen, replacement=True)
                if inst is None:
                    continue
                adopted = False
                with self._lock:
                    if self._slots[slot] is None:
                        self._slots[slot] = inst
                        adopted = True
                if not adopted:
                    # A concurrent deploy() re-filled the slot while we
                    # were warming our probe instance — ours loses.
                    inst.kill()
                    continue
                self._c_heal_relaunch.inc()
                logger.warning(
                    "fleet slot %d: degraded slot re-entered service "
                    "as %s after a restart-budget refill", slot,
                    inst.uid)
        self._publish_ready()

    def _publish_ready(self) -> None:
        with self._lock:
            ready = sum(1 for i in self._slots
                        if i is not None and i.state == "ready")
        self._g_ready.set(float(ready))

    # -- routing -----------------------------------------------------------

    def _routable(self, exclude: Tuple[str, ...] = ()
                  ) -> List[FleetInstance]:
        with self._lock:
            return [i for i in self._slots
                    if i is not None and i.state == "ready" and i.alive
                    and i.uid not in exclude]

    def _pick(self, exclude: Tuple[str, ...] = ()
              ) -> Optional[FleetInstance]:
        """Headroom-weighted placement: among routable instances, prefer
        unsaturated ones, then the highest headroom; unknown headroom
        (capacity EMAs not warmed) ranks as the average of the known
        ones so fresh instances still take traffic.  Ties break to the
        least-routed (deterministic round-robin, no RNG)."""
        candidates = self._routable(exclude)
        if not candidates:
            return None
        unsaturated = [i for i in candidates
                       if (i.saturation() or 0.0) <
                       SATURATION_BACKPRESSURE]
        pool = unsaturated or candidates
        known = [w for w in (i.weight() for i in pool) if w is not None]
        fallback = (sum(known) / len(known)) if known else 1.0

        def rank(inst: FleetInstance) -> Tuple[float, int]:
            w = inst.weight()
            return (-(w if w is not None else fallback), inst.routed)

        return min(pool, key=rank)

    def _session_key(self, raw: Optional[str]) -> Optional[str]:
        if not raw:
            return None
        return raw[:128]

    def _unpin_all(self, uid: str) -> None:
        """Hand off every session pinned to a retiring/dead instance:
        eagerly re-pin to a routable peer (counted as reroutes).  The
        next frame runs cold THERE and the stream warm-joins from then
        on — the session survives, the seed is rebuilt (the held
        1/8-res flow died with the old process's stream table)."""
        with self._lock:
            moving = [s for s, u in self._affinity.items() if u == uid]
        for sess in moving:
            target = self._pick(exclude=(uid,))
            with self._lock:
                if target is None:
                    self._affinity.pop(sess, None)
                else:
                    self._affinity[sess] = target.uid
            self._c_reroutes.inc()

    def _route(self, session: Optional[str],
               exclude: Tuple[str, ...] = ()) -> Optional[FleetInstance]:
        sess = self._session_key(session)
        if sess is not None:
            with self._lock:
                pinned = self._affinity.get(sess)
            if pinned is not None and pinned not in exclude:
                for inst in self._routable():
                    if inst.uid == pinned:
                        return inst
                # Pinned instance left rotation between frames: fall
                # through to a fresh pick and count the handoff.
                self._c_reroutes.inc()
        inst = self._pick(exclude)
        if inst is not None and sess is not None:
            with self._lock:
                self._affinity[sess] = inst.uid
                self._affinity.move_to_end(sess)
                while len(self._affinity) > AFFINITY_MAX:
                    self._affinity.popitem(last=False)
        return inst

    # -- forwarding --------------------------------------------------------

    def forward(self, headers: Dict[str, str], body: bytes
                ) -> Tuple[int, str, bytes, Dict[str, str]]:
        """Proxy one POST /v1/stereo.  Connection loss mid-exchange is
        counted against the instance's books as ``undelivered`` and the
        request is retried ONCE on a different instance (stereo
        inference is pure — a duplicate execution is wasted flops, not
        corruption); with no peers left the client gets a structured
        503/502, never a dangling socket."""
        import http.client
        session = headers.get("X-Raft-Session")
        tried: Tuple[str, ...] = ()
        for _attempt in range(2):
            inst = self._route(session, exclude=tried)
            if inst is None:
                return _structured(
                    503, "no_healthy_instance",
                    "no fleet instance is in rotation",
                    retry_after_s=1.0)
            with self._lock:
                book = self._books[inst.uid]
                book["sent"] += 1
                inst.routed += 1
            fwd_headers = {
                k: v for k, v in headers.items()
                if k.lower() == "content-type" or
                k.lower().startswith("x-raft-")}
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", inst.port,
                    timeout=self.cfg.forward_timeout_s)
                try:
                    conn.request("POST", "/v1/stereo", body=body,
                                 headers=fwd_headers)
                    resp = conn.getresponse()
                    payload = resp.read()
                    status = resp.status
                    ctype = resp.getheader("Content-Type",
                                           "application/json")
                    extra = {}
                    retry_after = resp.getheader("Retry-After")
                    if retry_after:
                        extra["Retry-After"] = retry_after
                finally:
                    conn.close()
            except OSError:
                # The instance vanished mid-exchange (the kill -9 case).
                with self._lock:
                    book["undelivered"] += 1
                self._c_reroutes.inc()
                tried = tried + (inst.uid,)
                continue
            with self._lock:
                book["answered"] += 1
                key = str(status)
                book["by_status"][key] = book["by_status"].get(key, 0) + 1
            return status, ctype, payload, extra
        return _structured(
            502, "instance_lost",
            "the serving instance was lost mid-request and its peer "
            "retry also failed; safe to retry", retry_after_s=1.0)

    # -- rolling deploy ----------------------------------------------------

    def deploy(self, instance_args: Optional[Sequence[str]] = None,
               instance_env: Optional[Dict[str, str]] = None) -> Dict:
        """Zero-downtime roll to a new instance recipe.

        Per slot, strictly: launch the NEW generation beside the old,
        await its warmup handshake, shift routing (hand off pinned
        sessions), SIGTERM-drain the old under the grace (SIGKILL
        escalation counted).  A slot whose new instance cannot reach
        readiness within the (fresh) budget KEEPS its old instance and
        aborts the remainder of the roll — half a fleet on the new
        fingerprint and half on the old is recoverable (deploy again);
        half a fleet dead is an outage."""
        with self._deploy_lock:
            with self._lock:
                if instance_args is not None:
                    self._args = tuple(instance_args)
                if instance_env is not None:
                    self._env = dict(instance_env)
                self._generation += 1
                gen = self._generation
                self._spent = {}   # fresh budget per generation
                self._refill_last = {}  # fresh decay clock too
            self._g_generation.set(float(gen))
            report: Dict = {"generation": gen, "slots": [],
                            "completed": True}
            for slot in range(self.n):
                with self._lock:
                    old = self._slots[slot]
                new = self._launch_slot(slot, gen)
                if new is None:
                    report["slots"].append(
                        {"slot": slot, "rolled": False,
                         "kept": old.uid if old is not None else None})
                    report["completed"] = False
                    break
                with self._lock:
                    self._slots[slot] = new
                self._publish_ready()
                report["slots"].append({"slot": slot, "rolled": True,
                                        "new": new.uid,
                                        "old": (old.uid if old is not None
                                                else None)})
                if old is not None:
                    self._retire(old)
            return report

    def _retire(self, inst: FleetInstance) -> None:
        """Take one instance out of rotation and drain it in the
        background: routing shifted first (sessions handed off), THEN
        SIGTERM — in-flight requests it already accepted run to their
        segment-boundary exits inside the PR 9 drain grace."""
        inst.begin_drain()
        self._c_draining.inc()
        self._unpin_all(inst.uid)
        with self._lock:
            self._retired.append(inst)

        def _reap() -> None:
            if not inst.reap(self.drain_grace_s):
                self._c_kills.inc()
            with self._lock:
                if inst in self._retired:
                    self._retired.remove(inst)

        # Bounded fire-and-forget: _reap ends within drain_grace_s by
        # construction — reap() escalates to SIGKILL at the deadline —
        # and stop()'s sweep re-reaps anything still in _retired, so no
        # reap thread outlives the supervisor.
        # graftlint: disable=GC206 (bounded by drain_grace_s; stop() re-reaps _retired)
        threading.Thread(target=_reap, name=f"fleet-reap-{inst.uid}",
                         daemon=True).start()

    # -- status ------------------------------------------------------------

    def books(self) -> Dict[str, Dict]:
        """The router's per-instance ledger (by instance uid): requests
        sent, answered (a complete HTTP response was read back — the
        count that must reconcile with the instance's own
        ``raft_requests_total``), undelivered (connection lost
        mid-exchange), and the answered-by-HTTP-status split."""
        with self._lock:
            return {uid: {"sent": b["sent"], "answered": b["answered"],
                          "undelivered": b["undelivered"],
                          "by_status": dict(b["by_status"])}
                    for uid, b in self._books.items()}

    def status(self) -> Dict:
        """The GET /fleet/healthz document: supervisor state + the
        obs/fleet.py rollup of every instance's own last health doc +
        the router's books."""
        with self._lock:
            rows = []
            degraded = 0
            for slot, inst in enumerate(self._slots):
                # graftheal satellite: every slot row carries its live
                # budget position — decay refunds included — so an
                # operator watching /fleet/healthz sees a degraded
                # slot's budget_remaining climb back above zero before
                # its probation relaunch fires.
                spent = self._effective_spent_locked(slot)
                budget = {"restarts_spent": spent,
                          "budget_remaining": max(
                              0, self.restart_budget - spent)}
                if inst is None:
                    degraded += 1
                    rows.append({"uid": None, "slot": slot,
                                 "state": "degraded", "doc": None,
                                 **budget})
                    continue
                rows.append({"uid": inst.uid, "slot": slot,
                             "state": inst.state, "doc": inst.last_doc,
                             "chips": inst.chips(), **budget})
            draining = len(self._retired)
            affinity = len(self._affinity)
        doc = rollup(rows)
        # graftpod: advertise the pod's summed chip count as a gauge so
        # an operator scraping /fleet/metrics sees capacity shrink when
        # an instance quarantines a chip.
        if doc.get("chips") is not None:
            self.registry.gauge(
                "raft_fleet_chips",
                "data-mesh chips advertised across the fleet"
            ).set(doc["chips"])
        doc.update({
            "generation": self._generation,
            "restart_budget": self.restart_budget,
            "heal": {
                "enabled": self.heal_enabled,
                "refill_ms": self.refill_s * 1e3,
                "slot_relaunches_total": int(self.registry.value(
                    "raft_heal_slot_relaunches_total")),
            },
            "degraded_slots": degraded,
            "draining": draining,
            "pinned_sessions": affinity,
            "uptime_s": time.monotonic() - self._started,
            "books": self.books(),
            "counters": {
                "instances_total": int(self.registry.value(
                    "raft_fleet_instances_total")),
                "restarts_total": int(self.registry.value(
                    "raft_fleet_restarts_total")),
                "reroutes_total": int(self.registry.value(
                    "raft_fleet_reroutes_total")),
                "draining_total": int(self.registry.value(
                    "raft_fleet_draining_total")),
                "kill_escalations_total": int(self.registry.value(
                    "raft_fleet_kill_escalations_total")),
            },
        })
        for row, slot_doc in zip(doc["by_instance"], rows):
            row["slot"] = slot_doc["slot"]
            row["restarts_spent"] = slot_doc["restarts_spent"]
            row["budget_remaining"] = slot_doc["budget_remaining"]
        return doc

    def metrics_text(self) -> str:
        return self.registry.render_prometheus()


# -- fleet ingress ---------------------------------------------------------

class _FleetHandler(BaseHTTPRequestHandler):
    """The fleet's thin wire surface: forward POST /v1/stereo, answer
    the two fleet-plane GETs.  Deliberately much smaller than the
    instance ingress (serve/http.py) — multipart parsing, decode
    offload, quotas and per-tenant accounting all happen ON the
    instance; the fleet only moves bytes and owns placement.  What it
    does share is the structured-error stance: every failure path
    writes a JSON body with a stable code."""

    supervisor: "FleetSupervisor" = None  # type: ignore[assignment]
    protocol_version = "HTTP/1.1"
    #: Per-read socket timeout (BaseHTTPRequestHandler honors this via
    #: the connection's settimeout) — a client trickling its request
    #: line cannot pin a handler thread forever.
    timeout = 30.0

    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        logger.debug("fleet-http %s — " + fmt,
                     self.client_address[0], *args)

    def _send(self, status: int, ctype: str, body: bytes,
              extra: Optional[Dict[str, str]] = None) -> None:
        try:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionError, OSError):
            self.close_connection = True

    def _send_structured(self, status: int, code: str,
                         message: str) -> None:
        s, ctype, body, extra = _structured(status, code, message)
        self._send(s, ctype, body, extra)

    def send_error(self, code, message=None, explain=None):
        # http.server's own parse failures route here: keep them JSON.
        self._send_structured(int(code), f"http_{int(code)}",
                              message or "request rejected")
        self.close_connection = True

    def do_GET(self):  # noqa: N802 — stdlib handler naming
        path = self.path.split("?", 1)[0]
        if path in ("/fleet/healthz", "/healthz"):
            body = json.dumps(self.supervisor.status(),
                              default=str).encode()
            return self._send(200, "application/json", body)
        if path == "/fleet/metrics":
            return self._send(200, "text/plain; version=0.0.4",
                              self.supervisor.metrics_text().encode())
        self._send_structured(404, "not_found",
                              f"no fleet route {path!r}")

    def do_POST(self):  # noqa: N802 — stdlib handler naming
        path = self.path.split("?", 1)[0]
        if path != "/v1/stereo":
            return self._send_structured(404, "not_found",
                                         f"no fleet route {path!r}")
        try:
            length = int(self.headers.get("Content-Length", ""))
        except ValueError:
            return self._send_structured(
                411, "length_required",
                "POST /v1/stereo requires Content-Length")
        if length > self.supervisor.cfg.body_max:
            return self._send_structured(
                413, "body_too_large",
                f"body {length} bytes exceeds the fleet cap "
                f"{self.supervisor.cfg.body_max}")
        try:
            body = self.rfile.read(length)
        except (OSError, ConnectionError):
            self.close_connection = True
            return
        if len(body) != length:
            self.close_connection = True
            return self._send_structured(
                400, "truncated_body",
                "connection closed before Content-Length bytes arrived")
        status, ctype, payload, extra = self.supervisor.forward(
            {k: v for k, v in self.headers.items()}, body)
        self._send(status, ctype, payload, extra)


class _FleetServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class FleetFrontend:
    """The fleet's listening socket.  Construction binds (port 0 is
    final before :meth:`start`), so a supervisor-of-supervisors could
    apply the same handshake discipline one level up."""

    def __init__(self, supervisor: FleetSupervisor,
                 host: str = "127.0.0.1", port: int = 0):
        self.supervisor = supervisor
        handler = type("BoundFleetHandler", (_FleetHandler,),
                       {"supervisor": supervisor})
        self._server = _FleetServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def host(self) -> str:
        return self._server.server_address[0]

    def start(self) -> "FleetFrontend":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                kwargs={"poll_interval": 0.05},
                name="fleet-http-listener", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "FleetFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
